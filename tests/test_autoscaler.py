"""Serve autoscaler tests (serve/autoscaler.py; ISSUE 16,
docs/protocol.md "Serve autoscaler").

The load-bearing claims, in test order:

* **hysteresis + cooldown units** (synthetic telemetry, injected
  clock) — a load flapping AT a watermark trips exactly one action per
  cooldown window; the band between the watermarks is a hold; shed
  deltas and p99-over-deadline force a high crossing regardless of the
  queue; the min/max replica bounds turn verdicts into ``bounded``
  non-actions; a failed action (the ``autoscale.action`` fault site)
  never half-scales and does NOT consume the cooldown — it retries on a
  later tick;
* **scale-down drain barrier** (real fleet, live traffic) — a direct
  ``scale_in`` under concurrent requests loses ZERO requests: the
  victim leaves the ring first, the per-model rollout's drain barrier
  waits out every pinned in-flight request, and only then may the
  victim daemon be stopped;
* **load-spike flagship** (real fleet + real traffic, the autoscaler's
  own thread) — offered load triples and the fleet scales itself up
  with ZERO operator action while p99 stays under the deadline; the
  load falls away and the fleet drains itself back down, still without
  a single failed request.
"""

import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu.serve import (
    DataPlaneDaemon,
    ModelFleet,
)
from spark_rapids_ml_tpu.serve.autoscaler import AutoScaler
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.faults import FaultPlan

pytestmark = pytest.mark.autoscale

D = 16


@pytest.fixture(autouse=True)
def _no_plan_leaks():
    yield
    faults.deactivate()
    assert faults.active_plan() is None


def _counter(name, **labels):
    snap = metrics_mod.snapshot()
    total = 0.0
    for s in (snap.get(name) or {}).get("samples", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            total += float(s.get("value", 0.0))
    return total


# ---------------------------------------------------------------------------
# synthetic-telemetry units: a fake fleet, a hand-cranked clock
# ---------------------------------------------------------------------------


class _FakeReplica:
    def __init__(self, key):
        self.key, self.alive, self.health = key, True, {}

    def load(self):
        return 0.0


class _FakeTable:
    def __init__(self, n):
        self._r = [_FakeReplica(f"10.0.0.{i}:7000") for i in range(n)]

    def replicas(self):
        return list(self._r)


class _FakeFleet:
    """Counts scale actions; mutates its replica set like the real one."""

    def __init__(self, n):
        self.table = _FakeTable(n)
        self.outs = []
        self.ins = []
        self.drained = True

    def scale_out(self, endpoint):
        r = _FakeReplica(str(endpoint))
        self.table._r.append(r)
        self.outs.append(str(endpoint))
        return {"replica": r.key, "replicas": len(self.table._r)}

    def scale_in(self, key=None):
        victim = self.table._r.pop()
        self.ins.append(victim.key)
        return {
            "replica": victim.key, "drained": self.drained,
            "rollouts": {}, "replicas": len(self.table._r),
        }


def _scaler(fleet, sample, clock, **kw):
    """An AutoScaler on synthetic telemetry: ``sample`` is a mutable
    dict the test edits between ticks; replicas always tracks the fake
    fleet so the load signal divides by live capacity."""
    kw.setdefault("high_watermark", 5.0)
    kw.setdefault("low_watermark", 1.0)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("tick_s", 0.01)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 8)
    counter = iter(range(10 ** 6))

    def telemetry():
        return dict(sample, replicas=len(fleet.table.replicas()))

    return AutoScaler(
        fleet, spawn=lambda: f"10.0.1.{next(counter)}:7000",
        telemetry=telemetry, clock=clock, **kw,
    )


def test_hold_band_between_watermarks_never_acts():
    """The hysteresis band: any load strictly between the watermarks is
    a hold — no crossing, no action, however long it persists."""
    fleet = _FakeFleet(2)
    t = [0.0]
    sample = {"queued": 6.0, "sheds_total": 0.0, "p99_s": None}  # load 3.0
    sc = _scaler(fleet, sample, lambda: t[0])
    for _ in range(20):
        d = sc.tick()
        assert d["verdict"] == "hold" and d["action"] == "none"
        t[0] += 1.0
    assert fleet.outs == [] and fleet.ins == []


def test_flap_at_watermark_one_action_per_cooldown_window():
    """THE hysteresis claim: a load flapping right at the high watermark
    every tick produces exactly ONE scale action per cooldown window —
    crossings and decisions keep counting, the fleet is not churned."""
    fleet = _FakeFleet(1)
    t = [0.0]
    sample = {"queued": 0.0, "sheds_total": 0.0, "p99_s": None}
    sc = _scaler(fleet, sample, lambda: t[0],
                 high_watermark=5.0, low_watermark=1.0, cooldown_s=10.0)
    dec0 = _counter("srml_autoscale_decisions_total")
    # 30 seconds of one-second ticks, flapping across the watermark:
    # windows [0,10), [10,20), [20,30) may each act at most once.
    actions = []
    for i in range(30):
        n = len(fleet.table.replicas())
        # flap: above the high watermark on even ticks, below on odd —
        # scaled by capacity so growth does not quench the signal
        sample["queued"] = 6.0 * n if i % 2 == 0 else 0.5 * n
        d = sc.tick()
        if d["action"] in ("scale_up", "scale_down"):
            actions.append((t[0], d["action"]))
        t[0] += 1.0
    assert len(actions) == 3, actions  # one per 10s window, not one per flap
    for (t1, _), (t2, _) in zip(actions, actions[1:]):
        assert t2 - t1 >= 10.0
    # pressure stayed visible while the controller held
    assert _counter("srml_autoscale_decisions_total") - dec0 == 30


def test_sheds_force_scale_up_regardless_of_queue():
    """A positive shed delta means requests are ALREADY refused — the
    verdict is up even when the instantaneous queue reads empty."""
    fleet = _FakeFleet(2)
    t = [0.0]
    sample = {"queued": 0.0, "sheds_total": 5.0, "p99_s": None}
    sc = _scaler(fleet, sample, lambda: t[0])
    d = sc.tick()  # first tick only baselines the shed counter
    assert d["verdict"] == "down"  # load 0 <= low with no delta yet
    t[0] += 11.0
    sample["sheds_total"] = 9.0
    d = sc.tick()
    assert d["verdict"] == "up" and d["reason"] == "sheds"
    assert d["action"] == "scale_up"
    assert len(fleet.outs) == 1


def test_p99_over_deadline_forces_scale_up():
    fleet = _FakeFleet(2)
    t = [0.0]
    sample = {"queued": 4.0, "sheds_total": 0.0, "p99_s": 0.9}  # load 2: hold
    sc = _scaler(fleet, sample, lambda: t[0], p99_deadline_s=0.5)
    d = sc.tick()
    assert d["verdict"] == "up" and d["reason"] == "p99"
    assert len(fleet.outs) == 1
    # deadline unset (the default 0.0) ignores p99 entirely
    fleet2 = _FakeFleet(2)
    sc2 = _scaler(fleet2, dict(sample), lambda: t[0], p99_deadline_s=0.0)
    assert sc2.tick()["verdict"] == "hold"


def test_replica_bounds_turn_verdicts_into_bounded():
    """max_replicas caps growth and min_replicas floors shrinkage: the
    verdict stands (pressure stays visible) but no action fires and no
    cooldown is consumed."""
    fleet = _FakeFleet(2)
    t = [0.0]
    sample = {"queued": 100.0, "sheds_total": 0.0, "p99_s": None}
    sc = _scaler(fleet, sample, lambda: t[0], max_replicas=2, min_replicas=2)
    b0 = _counter("srml_autoscale_actions_total", outcome="bounded")
    d = sc.tick()
    assert d["verdict"] == "up" and d["action"] == "bounded"
    sample["queued"] = 0.0
    d = sc.tick()
    assert d["verdict"] == "down" and d["action"] == "bounded"
    assert fleet.outs == [] and fleet.ins == []
    assert _counter("srml_autoscale_actions_total", outcome="bounded") \
        - b0 == 2
    assert sc.cooldown_remaining() == 0.0


def test_action_fault_never_half_scales_and_retries():
    """The autoscale.action fault site sits between decide and act: a
    refused action leaves the fleet EXACTLY as it was, counts an error,
    does NOT consume the cooldown, and the next tick retries."""
    fleet = _FakeFleet(1)
    t = [0.0]
    sample = {"queued": 50.0, "sheds_total": 0.0, "p99_s": None}
    sc = _scaler(fleet, sample, lambda: t[0])
    err0 = _counter("srml_autoscale_actions_total", outcome="error")
    plan = FaultPlan(seed=7).rule("autoscale.action", "refuse", times=1)
    with faults.active(plan):
        d = sc.tick()
    assert plan.fired.get("autoscale.action") == 1
    assert d["action"] == "error"
    assert fleet.outs == [] and len(fleet.table.replicas()) == 1
    assert _counter("srml_autoscale_actions_total", outcome="error") \
        - err0 == 1
    assert sc.cooldown_remaining() == 0.0  # failure must not gate the retry
    d = sc.tick()  # same clock instant: the retry needs no waiting
    assert d["action"] == "scale_up" and len(fleet.outs) == 1


def test_drain_callback_only_after_full_drain():
    """scale_in reporting drained=False means pinned requests are still
    in flight on the victim — releasing its host THEN would drop them,
    so the drain hook must not run."""
    released = []
    fleet = _FakeFleet(3)
    t = [0.0]
    sample = {"queued": 0.0, "sheds_total": 0.0, "p99_s": None}
    sc = _scaler(fleet, sample, lambda: t[0], drain=released.append)
    fleet.drained = False
    d = sc.tick()
    assert d["action"] == "scale_down" and released == []
    t[0] += 11.0
    fleet.drained = True
    d = sc.tick()
    assert d["action"] == "scale_down" and len(released) == 1
    assert released[0] == fleet.ins[-1]


@pytest.mark.gossip
def test_orphaned_rollout_intent_adopted_after_drain_horizon():
    """Crash-safe rollouts' closed loop (serve/gossip.py): a gossiped
    rollout intent OLDER than the drain horizon was orphaned by a dead
    controller — the autoscaler's tick adopts it through
    ``resume_rollout``. A younger intent belongs to a live controller
    and is left alone; fleets without the gossip plane are skipped
    (the other units here never trip this path)."""
    from spark_rapids_ml_tpu import config

    fleet = _FakeFleet(2)
    horizon = float(config.get("fleet_drain_timeout_s"))
    intents = {
        "orphan": {"model": "orphan", "from_version": 1, "to_version": 2,
                   "phase": "flipped", "by": "ctl-dead",
                   "at": time.time() - horizon - 60.0},
        "young": {"model": "young", "from_version": 1, "to_version": 2,
                  "phase": "registering", "by": "ctl-live",
                  "at": time.time()},
    }
    fleet.table.intents = lambda: dict(intents)
    calls = []
    fleet.resume_rollout = lambda model: (
        calls.append(model) or {"action": "completed", "model": model,
                                "version": 2}
    )
    t = [0.0]
    sample = {"queued": 4.0, "sheds_total": 0.0, "p99_s": None}  # hold band
    sc = _scaler(fleet, sample, lambda: t[0])
    metrics_mod.reset()
    sc.tick()
    assert calls == ["orphan"]
    assert _counter("srml_autoscale_actions_total",
                    action="resume_rollout", outcome="ok") == 1.0


def test_inverted_watermarks_rejected():
    with pytest.raises(ValueError, match="hysteresis"):
        _scaler(_FakeFleet(1), {}, time.monotonic,
                high_watermark=1.0, low_watermark=2.0)


def test_status_feeds_the_operator_panel():
    fleet = _FakeFleet(2)
    t = [0.0]
    sample = {"queued": 100.0, "sheds_total": 0.0, "p99_s": None}
    sc = _scaler(fleet, sample, lambda: t[0])
    sc.tick()
    st = sc.status()
    assert st["high_watermark"] == 5.0 and st["low_watermark"] == 1.0
    assert st["replicas"] == 3  # the tick scaled 2 → 3
    assert st["last_decision"]["verdict"] == "up"
    assert st["last_action"]["action"] == "scale_up"
    assert st["cooldown_remaining_s"] == 10.0
    # the gauges the tools/top panel renders from are live too
    snap = metrics_mod.snapshot()
    for g in ("srml_autoscale_replicas", "srml_autoscale_load",
              "srml_autoscale_cooldown_seconds", "srml_autoscale_watermark",
              "srml_autoscale_last_decision"):
        assert snap.get(g), f"{g} missing from the registry"


def test_top_renders_autoscaler_panel():
    """tools.top grows an autoscaler panel: last decision, load vs the
    high/low watermarks, replica count, cooldown remaining, and action
    tallies — all from the snapshot alone, no live scaler handle."""
    from spark_rapids_ml_tpu.tools.top import render

    fleet = _FakeFleet(2)
    t = [0.0]
    sample = {"queued": 100.0, "sheds_total": 0.0, "p99_s": None}
    sc = _scaler(fleet, sample, lambda: t[0])
    ups0 = _counter("srml_autoscale_actions_total",
                    action="scale_up", outcome="ok")
    sc.tick()  # up verdict → scale_up ok
    out = render({"id": "d0"}, metrics_mod.snapshot())
    panel = [ln for ln in out.splitlines() if ln.startswith("autoscaler")]
    assert panel, "autoscaler panel missing from tools.top render"
    head = panel[0]
    assert "decision up" in head
    assert "(low 1.00 / high 5.00)" in head
    assert "replicas 3" in head
    assert "cooldown 10.0s" in head
    tally = f"scale_up/ok:{int(ups0) + 1}"
    assert any(tally in ln for ln in out.splitlines())
    # a snapshot with no autoscale series renders no dead panel
    quiet = render({"id": "d0"}, {})
    assert not any(ln.startswith("autoscaler") for ln in quiet.splitlines())


# ---------------------------------------------------------------------------
# real fleet: scale-out seeding, the scale-in drain barrier
# ---------------------------------------------------------------------------


@pytest.fixture
def pca_arrays(rng, mesh8):
    from spark_rapids_ml_tpu.models.pca import PCA

    basis = rng.normal(size=(D, D)) * np.logspace(0, -1.5, D)
    data = rng.normal(size=(400, D)) @ basis
    m = PCA(mesh=mesh8).setK(3).fit({"features": data})
    q = rng.normal(size=(12, D))
    return {
        "arrays": m._model_data(),
        "q": q,
        "ref": np.asarray(m.transform_matrix(q)["output"]),
    }


def test_scale_out_newcomer_is_warm_before_first_request(mesh8, pca_arrays):
    """Admission is the flip: every active model version is registered
    and warmed on the newcomer BEFORE it joins the ring, so the first
    routed request never hits a no-such-model repair window."""
    from spark_rapids_ml_tpu.serve.client import DataPlaneClient

    d0 = DataPlaneDaemon(mesh=mesh8).start()
    d1 = DataPlaneDaemon(mesh=mesh8).start()
    try:
        with ModelFleet([d0.address]) as fleet:
            fleet.register("m", "pca", pca_arrays["arrays"], version=1)
            res = fleet.scale_out(d1.address)
            assert res["replicas"] == 2 and res["models"] == ["m"]
            # the newcomer already holds the versioned registration
            with DataPlaneClient(*d1.address) as c:
                assert c.model_exists("m@v1")
            # and serves bitwise-correct answers through the router
            with fleet.client() as fc:
                for i in range(12):
                    out = fc.transform("m", pca_arrays["q"],
                                       route_key=f"k{i}")
                    assert np.array_equal(
                        np.asarray(out["output"]), pca_arrays["ref"]
                    )
                assert sorted(fc.stats) == sorted(
                    fleet.table.ring.members
                )  # both replicas took traffic
    finally:
        d0.stop()
        d1.stop()


def test_scale_in_under_live_traffic_drops_nothing(mesh8, pca_arrays):
    """The drain barrier under fire: concurrent clients keep routing
    while a replica is retired. Every request — including those pinned
    in flight to the victim — must succeed with the bitwise answer;
    the victim daemon stays up until scale_in reports drained."""
    daemons = [DataPlaneDaemon(mesh=mesh8).start() for _ in range(2)]
    errors = []
    answers = [0]
    stop = threading.Event()
    try:
        with ModelFleet([d.address for d in daemons]) as fleet:
            fleet.register("m", "pca", pca_arrays["arrays"], version=1)

            def pound(i):
                try:
                    with fleet.client() as fc:
                        j = 0
                        while not stop.is_set():
                            out = fc.transform(
                                "m", pca_arrays["q"],
                                route_key=f"c{i}-{j}",
                            )
                            if not np.array_equal(
                                np.asarray(out["output"]),
                                pca_arrays["ref"],
                            ):
                                raise AssertionError("wrong answer")
                            j += 1
                        answers[0] += j
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [
                threading.Thread(target=pound, args=(i,)) for i in range(4)
            ]
            for th in threads:
                th.start()
            time.sleep(0.3)  # requests genuinely in flight
            res = fleet.scale_in()
            assert res["drained"] is True, res
            assert res["replicas"] == 1
            victim = next(
                d for d in daemons
                if f"{d.address[0]}:{d.address[1]}" == res["replica"]
            )
            time.sleep(0.3)  # traffic continues on the shrunken fleet
            stop.set()
            for th in threads:
                th.join(timeout=30)
            victim.stop()  # only AFTER the drain barrier held
        assert errors == [], errors[:3]
        assert answers[0] > 0
    finally:
        stop.set()
        for d in daemons:
            d.stop()


# ---------------------------------------------------------------------------
# the load-spike flagship
# ---------------------------------------------------------------------------


def test_flagship_load_spike_scales_itself_zero_drops(mesh8, pca_arrays):
    """ISSUE 16's serving acceptance: offered load triples and the
    AUTOSCALER — not an operator — grows the fleet; p99 stays under the
    deadline; when the load falls away the fleet drains itself back
    down; and across the whole episode, including the scale-down,
    not one request fails."""
    daemons = {}

    def spawn():
        d = DataPlaneDaemon(mesh=mesh8).start()
        key = f"{d.address[0]}:{d.address[1]}"
        daemons[key] = d
        return d.address

    released = []

    def drain(key):
        released.append(key)
        d = daemons.pop(key, None)
        if d is not None:
            d.stop()

    first = spawn()
    level = [2]  # offered concurrency, the telemetry's load signal
    errors = []
    lat = []
    lat_lock = threading.Lock()
    stop = threading.Event()
    with ModelFleet([first]) as fleet:
        fleet.register("m", "pca", pca_arrays["arrays"], version=1)

        def telemetry():
            live = [r for r in fleet.table.replicas() if r.alive]
            return {
                "replicas": len(live),
                "queued": float(level[0]),
                "busy": 0,
                "sheds_total": 0.0,
                "p99_s": None,
            }

        scaler = AutoScaler(
            fleet, spawn, drain,
            high_watermark=1.5, low_watermark=0.75,
            cooldown_s=0.2, tick_s=0.05,
            min_replicas=1, max_replicas=3,
            telemetry=telemetry,
        )

        def pound(i):
            try:
                with fleet.client() as fc:
                    j = 0
                    while not stop.is_set():
                        if i >= level[0]:  # offered load follows `level`
                            time.sleep(0.01)
                            continue
                        t0 = time.perf_counter()
                        out = fc.transform(
                            "m", pca_arrays["q"], route_key=f"c{i}-{j}"
                        )
                        dt = time.perf_counter() - t0
                        if not np.array_equal(
                            np.asarray(out["output"]), pca_arrays["ref"]
                        ):
                            raise AssertionError("wrong answer")
                        with lat_lock:
                            lat.append(dt)
                        j += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=pound, args=(i,)) for i in range(6)
        ]
        for th in threads:
            th.start()

        def live_count():
            return len([r for r in fleet.table.replicas() if r.alive])

        def wait_for(n, timeout=20.0):
            t0 = time.monotonic()
            while live_count() != n:
                if time.monotonic() - t0 > timeout:
                    raise AssertionError(
                        f"fleet never reached {n} replicas "
                        f"(at {live_count()}): {scaler.status()}"
                    )
                time.sleep(0.05)

        try:
            with scaler:  # the control loop runs itself — no operator
                wait_for(2)  # load 2 / 1 replica = 2.0 >= 1.5 → grow
                level[0] = 6  # the spike: offered load triples
                wait_for(3)  # 6/2 = 3.0 → grow to the ceiling
                time.sleep(0.5)  # serve the spike at full width
                level[0] = 1  # the spike passes
                wait_for(1)  # 1/3, 1/2 <= 0.75 → drain back down
                time.sleep(0.3)  # traffic survives the shrunken fleet
        finally:
            stop.set()
            for th in threads:
                th.join(timeout=30)
    try:
        assert errors == [], errors[:3]
        assert len(released) == 2 and len(daemons) == 1
        assert len(lat) > 0
        lat.sort()
        p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)]
        assert p99 < 2.0, f"p99 {p99:.3f}s blew the deadline"
        # the episode is journaled as metrics, not just asserted here
        assert _counter("srml_autoscale_actions_total", action="scale_up",
                        outcome="ok") >= 2
        assert _counter("srml_autoscale_actions_total", action="scale_down",
                        outcome="ok") >= 2
    finally:
        for d in daemons.values():
            d.stop()
