"""Distributed tracing: the additive ``trace_ctx`` wire field end-to-end.

The PR 3 journal gave each PROCESS its own span trees; this suite pins
the cross-process stitch (docs/protocol.md "trace_ctx"): the client
stamps its innermost journal frame on every request, the daemon adopts
it around the dispatched op, and one fit — driver + executors + N
daemons — journals a SINGLE tree that ``tools/trace.py`` merges into a
Chrome-trace JSON. The flagship here is the acceptance criterion: a
sparksim two-daemon fit whose daemon-side spans are children of the
driver's fit span in the merged trace.

The field is additive: a pre-tracing client never sends it (the byte
streams in tests/fixtures/*.bin replay unchanged — test_protocol_golden
is the authority), and a daemon receiving it with the journal off does
nothing.
"""

import json
import socket

import numpy as np
import pytest

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon
from spark_rapids_ml_tpu.serve import protocol
from spark_rapids_ml_tpu.spark import estimator as spark_est
from spark_rapids_ml_tpu.spark.estimator import SparkPCA
from spark_rapids_ml_tpu.tools import trace
from spark_rapids_ml_tpu.utils import journal

from sparksim import SimDataFrame, SimSparkSession, simdf_from_numpy

spark_est.register_dataframe_type(SimDataFrame)


@pytest.fixture(autouse=True)
def _closed_journal():
    """Every test starts and ends with the journal file handles closed
    (reads see complete lines; no cross-test handle reuse)."""
    journal.close()
    yield
    journal.close()


def _addr(daemon) -> str:
    return f"{daemon.address[0]}:{daemon.address[1]}"


# ---------------------------------------------------------------------------
# wire-level: the field is additive
# ---------------------------------------------------------------------------


def test_raw_request_with_trace_ctx_is_accepted(mesh8):
    """A v1 request carrying the additive field is served normally even
    with the journal off — unknown-to-the-op extra keys must never
    reject (the additive-field contract every PR 2–5 op relies on)."""
    with DataPlaneDaemon(mesh=mesh8) as d:
        with socket.create_connection(d.address, timeout=5.0) as s:
            protocol.send_json(s, {
                "v": 1, "op": "ping",
                "trace_ctx": {"run": "ab" * 8, "span": "cd" * 8},
            })
            resp = protocol.recv_json(s)
    assert resp["ok"] is True


def test_client_outside_any_run_stamps_nothing(mesh8, tmp_path):
    """No journal frame → no trace_ctx on the wire → the daemon's op
    span roots itself (the PR 3 standalone behavior, and the reason the
    golden transcripts replay byte-identically)."""
    p = tmp_path / "daemon.jsonl"
    with DataPlaneDaemon(mesh=mesh8) as d:
        with config.option("run_journal", str(p)):
            with DataPlaneClient(*d.address) as c:
                c.feed("solo", np.ones((8, 3)), algo="pca")
    journal.close()
    spans = [e for e in journal.read(str(p)) if e.get("event") == "phase"]
    ops = [e for e in spans if e["name"] == "daemon.feed"]
    assert ops, f"daemon.feed span missing from {spans}"
    assert all(e["parent_id"] is None for e in ops)


def test_stop_joins_connection_threads_flushing_trailing_writes(
    mesh8, tmp_path
):
    """``stop()`` must WAIT for connection threads: the op span's journal
    line (and the request's metrics) are written AFTER the ack is sent,
    so a stop() that returns while a connection thread is still unwinding
    races every stopped-then-inspect sequence — this very suite read
    journal files the moment the daemon scope closed and flaked when the
    trailing write lost the race. After the scope exits, the span line is
    on disk and no connection thread survives."""
    import threading

    before = {
        t for t in threading.enumerate()
        if t.name.startswith("srml-dataplane-")
    }
    p = tmp_path / "flush.jsonl"
    with DataPlaneDaemon(mesh=mesh8) as d:
        with config.option("run_journal", str(p)):
            with DataPlaneClient(*d.address) as c:
                c.feed("flush", np.ones((8, 3)), algo="pca")
    # No sleep, no close(): the write must already have landed.
    leftovers = [
        t for t in threading.enumerate()
        if t.name.startswith("srml-dataplane-") and t not in before
    ]
    assert not leftovers, f"connection threads outlived stop(): {leftovers}"
    journal.close()
    names = [
        e["name"] for e in journal.read(str(p)) if e.get("event") == "phase"
    ]
    assert "daemon.feed" in names


def test_daemon_op_span_parents_into_the_callers_frame(mesh8, tmp_path):
    """The core stitch: a client op issued inside a driver-side span
    lands the daemon's op span (and every model-phase span under it)
    in the SAME run, parented to the caller's span."""
    p = tmp_path / "both.jsonl"
    ids = {}
    with DataPlaneDaemon(mesh=mesh8) as d:
        with config.option("run_journal", str(p)):
            with DataPlaneClient(*d.address) as c:
                with journal.run("fit") as run_id:
                    ids["run"] = run_id
                    with journal.span("feed pass") as span_id:
                        ids["span"] = span_id
                        c.feed("job", np.ones((16, 4)), algo="pca")
    journal.close()
    events = journal.read(str(p))
    (op_span,) = [
        e for e in events
        if e.get("event") == "phase" and e["name"] == "daemon.feed"
    ]
    assert op_span["run_id"] == ids["run"]
    assert op_span["parent_id"] == ids["span"]
    assert op_span["job"] == "job"


def test_unjournaled_ops_stay_quiet(mesh8, tmp_path):
    """Liveness probes and scrapes (ping/health/metrics/model_status)
    must not bury the fit tree under polling noise."""
    p = tmp_path / "quiet.jsonl"
    with DataPlaneDaemon(mesh=mesh8) as d:
        with config.option("run_journal", str(p)):
            with DataPlaneClient(*d.address) as c:
                with journal.run("fit"):
                    c.ping()
                    c.health()
    journal.close()
    names = {
        e["name"] for e in journal.read(str(p))
        if e.get("event") == "phase"
    }
    assert not any(n.startswith("daemon.") for n in names), names


def test_fixed_trace_ctx_ctor_arg_wins(mesh8, tmp_path):
    """The executor path: a client constructed with an explicit
    trace_ctx (the driver frame captured into the task closure) stamps
    THAT context even though its own thread never opened a journal
    run."""
    p = tmp_path / "exec.jsonl"
    ctx = {"run": "12" * 8, "span": "34" * 8}
    with DataPlaneDaemon(mesh=mesh8) as d:
        with config.option("run_journal", str(p)):
            with DataPlaneClient(*d.address, trace_ctx=ctx) as c:
                c.feed("job", np.ones((8, 3)), algo="pca")
    journal.close()
    (op_span,) = [
        e for e in journal.read(str(p))
        if e.get("event") == "phase" and e["name"] == "daemon.feed"
    ]
    assert op_span["run_id"] == ctx["run"]
    assert op_span["parent_id"] == ctx["span"]


# ---------------------------------------------------------------------------
# flagship: sparksim two-daemon fit → one merged Chrome trace
# ---------------------------------------------------------------------------


def test_two_daemon_fit_merges_into_one_chrome_trace(rng, mesh8, tmp_path):
    """Acceptance criterion: a sparksim fit across TWO daemons journals
    driver + daemon spans that ``tools.trace`` stitches into a single
    tree — every daemon-side span a descendant of the driver's fit span
    — and emits as Chrome-trace JSON."""
    x = rng.integers(-8, 9, size=(800, 16)).astype(np.float64)
    p = tmp_path / "fit.jsonl"
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        with config.option("run_journal", str(p)):
            session = SimSparkSession(
                {"spark.srml.daemon.address": _addr(a)}
            )
            env_plan = {
                pid: {"SRML_DAEMON_ADDRESS": _addr(b)} for pid in (2, 3)
            }
            df = simdf_from_numpy(x, n_partitions=4, session=session,
                                  env_plan=env_plan)
            SparkPCA().setInputCol("features").setK(4).fit(df)
    journal.close()

    events = trace.load([str(p)])
    fit_runs = [
        e for e in events
        if e.get("event") == "run_end" and e["name"] == "fit"
    ]
    assert len(fit_runs) == 1
    run_id = fit_runs[0]["run_id"]

    # Both daemons served ops, and every daemon span joined the fit run.
    daemon_spans = [
        e for e in events
        if e.get("event") == "phase" and e["name"].startswith("daemon.")
    ]
    assert {e["name"] for e in daemon_spans} >= {"daemon.feed",
                                                 "daemon.finalize"}
    assert all(e["run_id"] == run_id for e in daemon_spans)

    # The stitched tree has ONE root (the fit), with every daemon span a
    # descendant of it.
    (root,) = trace.tree(events)
    assert root.name == "fit"

    def collect(node, out):
        for c in node.children:
            out.append(c)
            collect(c, out)
        return out

    names_in_tree = [n.name for n in collect(root, [])]
    for e in daemon_spans:
        assert e["name"] in names_in_tree
    assert sum(1 for n in names_in_tree if n.startswith("daemon.")) == len(
        daemon_spans
    )

    # And the CLI emits loadable Chrome-trace JSON carrying those spans.
    out = tmp_path / "trace.json"
    assert trace.main([str(p), "--out", str(out)]) == 0
    obj = json.loads(out.read_text())
    xs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    assert {"fit", "daemon.feed", "daemon.finalize"} <= {e["name"] for e in xs}
    for e in xs:
        if e["name"].startswith("daemon."):
            assert e["args"]["run_id"] == run_id

    # The flame summary names both sides of the wire.
    text = trace.flame(events)
    assert "fit" in text and "daemon.feed" in text


def test_knn_fit_pool_thread_clients_stay_in_the_fit_tree(rng, mesh8,
                                                          tmp_path):
    """The sharded-KNN build runs its per-daemon finalizes (and the
    cross-shard quantizer sampling) on POOL threads whose journal stack
    is empty — the estimator must hand them the driver's fit frame
    explicitly, or the fit's heaviest daemon spans (index builds,
    sample_rows) orphan out of the trace."""
    from spark_rapids_ml_tpu.spark.estimator import (
        SparkApproximateNearestNeighbors,
    )

    x = rng.normal(size=(400, 8))
    p = tmp_path / "knn.jsonl"
    with DataPlaneDaemon(ttl=600.0) as a, DataPlaneDaemon(ttl=600.0) as b:
        with config.option("run_journal", str(p)):
            session = SimSparkSession(
                {"spark.srml.daemon.address": _addr(a)}
            )
            env_plan = {
                pid: {"SRML_DAEMON_ADDRESS": _addr(b)} for pid in (2, 3)
            }
            df = simdf_from_numpy(x, n_partitions=4, session=session,
                                  env_plan=env_plan)
            model = (
                SparkApproximateNearestNeighbors()
                .setK(3).setNlist(4).setNprobe(4)
                .fit(df)
            )
        # Outside the journal scope: release's drop_model ops are not
        # part of the fit and must not appear in the trace at all.
        model.release()
    journal.close()
    events = trace.load([str(p)])
    fit_runs = [
        e for e in events
        if e.get("event") == "run_end" and e["name"] == "fit"
    ]
    assert len(fit_runs) == 1
    run_id = fit_runs[0]["run_id"]
    daemon_spans = [
        e for e in events
        if e.get("event") == "phase" and e["name"].startswith("daemon.")
    ]
    names = {e["name"] for e in daemon_spans}
    assert {"daemon.feed", "daemon.sample_rows", "daemon.finalize"} <= names
    strays = [
        (e["name"], e["run_id"]) for e in daemon_spans
        if e["run_id"] != run_id
    ]
    assert strays == [], f"daemon spans outside the fit run: {strays}"
