"""Nearest neighbors — placeholder, implemented in the breadth pass."""

from spark_rapids_ml_tpu.core.params import Estimator, Model


class NearestNeighbors(Estimator):
    _uid_prefix = "NearestNeighbors"


class NearestNeighborsModel(Model):
    _uid_prefix = "NearestNeighborsModel"


class ApproximateNearestNeighbors(Estimator):
    _uid_prefix = "ApproximateNearestNeighbors"


class ApproximateNearestNeighborsModel(Model):
    _uid_prefix = "ApproximateNearestNeighborsModel"
