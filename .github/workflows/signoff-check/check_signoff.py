#!/usr/bin/env python3
"""Fail unless every commit in the PR carries a DCO `Signed-off-by:` trailer.

Policy-CI parity with the reference's signoff checker (SURVEY.md §2.5); own
implementation: stdlib-only, reads the PR commit list from the GitHub API.
"""

import json
import os
import re
import sys
import urllib.request

SIGNOFF = re.compile(r"^Signed-off-by: .+ <.+@.+>$", re.MULTILINE)


def api(url: str, token: str):
    req = urllib.request.Request(url)
    req.add_header("Authorization", f"Bearer {token}")
    req.add_header("Accept", "application/vnd.github+json")
    with urllib.request.urlopen(req) as resp:
        return json.load(resp)


def main() -> int:
    token = os.environ["GITHUB_TOKEN"]
    repo = os.environ["REPO"]
    pr = os.environ["PR_NUMBER"]
    commits = []
    page = 1
    while True:
        batch = api(
            f"https://api.github.com/repos/{repo}/pulls/{pr}/commits"
            f"?per_page=100&page={page}",
            token,
        )
        commits.extend(batch)
        if len(batch) < 100:
            break
        page += 1
    missing = [
        c["sha"][:12]
        for c in commits
        # merge commits (>1 parent) are machine-generated — standard DCO
        # checkers exempt them, and the auto-merge forward PRs rely on it
        if len(c.get("parents", [])) <= 1
        and not SIGNOFF.search(c["commit"]["message"])
    ]
    if missing:
        print(f"commits missing Signed-off-by: {', '.join(missing)}")
        print("sign your work: git commit -s (see CONTRIBUTING.md)")
        return 1
    print(f"all {len(commits)} commits signed off")
    return 0


if __name__ == "__main__":
    sys.exit(main())
