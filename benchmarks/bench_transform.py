"""PCA.transform p50 latency — the second BASELINE.json headline metric.

The reference's transform re-uploads the PC matrix host→device on every
batch (rapidsml_jni.cu:85 — flagged in SURVEY.md §3.2 as the optimization
target); here the PC matrix is device-resident across batches and the
per-batch work is one (batch, d) × (d, k) MXU GEMM.

Baseline: an A100 cuML batch transform at 65536×2048 × 2048×32 is ~8.6
GFLOP ≈ 0.08 ms of GEMM plus per-batch PC upload (~0.25 ms for 0.5 MB
over PCIe effective ~2 GB/s with launch overhead) ≈ 0.35 ms. vs_baseline =
baseline_p50 / our_p50 (higher is better, >1 beats the A100 path).

Measurement notes (so the number stays comparable across rounds): the
measured path is this framework's quantize-on-ingest design — bf16 inputs,
f32 accumulation — against the reference's f32 path; the dtype is in the
metric name. The p50 is the per-batch *device* latency via slope_dt, which
subtracts the dev tunnel's fixed ~90 ms host round-trip (a harness
artifact, not TPU serving cost); the A100 baseline's per-batch PC upload is
kept in the baseline because eliminating it (device-resident PC) is a real
architectural difference, not a harness one.
"""

import os
import sys

if __package__ in (None, ""):  # direct script run: python benchmarks/bench_*.py
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BASELINE_P50_MS = 0.35

D = int(os.environ.get("SRML_BENCH_D", 2048))
K = int(os.environ.get("SRML_BENCH_K", 32))
BATCH = int(os.environ.get("SRML_BENCH_BATCH_ROWS", 65536))
CALLS = int(os.environ.get("SRML_BENCH_CALLS", 200))


def main() -> None:
    from benchmarks import setup_platform

    setup_platform()
    import jax
    import jax.numpy as jnp

    from benchmarks import emit

    rng = np.random.default_rng(0)
    # Ingest-cast to bfloat16 (the framework's quantize-on-ingest design):
    # the batch GEMM is HBM-bound at these shapes, so halving the bytes
    # halves the latency; accumulation stays float32.
    pc = jnp.asarray(rng.normal(size=(D, K)), dtype=jnp.bfloat16)
    x = jnp.asarray(rng.normal(size=(BATCH, D)), dtype=jnp.bfloat16)

    @jax.jit
    def transform(pc, x):
        return jax.lax.dot_general(
            x, pc, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @jax.jit
    def transform_bf16_out(pc, x):
        # bf16 output writes (f32 accumulation unchanged): halves the
        # store bytes. At this shape the op is LOAD-bound (k ≪ d: the
        # (batch, d) bf16 read is ~268 MB vs an 8.4 MB f32 store), so
        # the roofline gain is ~1.5% — measured to close VERDICT r3 #7.
        return jax.lax.dot_general(
            x, pc, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(jnp.bfloat16)

    # Per-batch device latency via the two-point slope: chained batches in
    # one sync window, so the tunnel's fixed ~90 ms host round-trip (a dev
    # harness artifact, not TPU serving latency) cancels out of the p50.
    from benchmarks import slope_dt, sync

    def make_run(fn):
        def run(n):
            out = None
            for _ in range(n):
                out = fn(pc, x)
            sync(out)
            return out
        return run

    run, run_bf16 = make_run(transform), make_run(transform_bf16_out)
    for r in (run, run_bf16):  # warm / compile both sizes, outside samples
        r(CALLS)
        r(2 * CALLS)
    # Interleave the two arms (same-run A/B: chip drift discipline).
    lat, lat_bf16 = [], []
    for _ in range(9):
        lat.append(slope_dt(run, CALLS, 2 * CALLS, warm=False) * 1e3)
        lat_bf16.append(slope_dt(run_bf16, CALLS, 2 * CALLS, warm=False) * 1e3)
    p50 = float(np.percentile(lat, 50))
    p50_bf16 = float(np.percentile(lat_bf16, 50))
    # HBM roofline at this shape (v5e 819 GB/s): read x (batch·d·2B) +
    # pc, write out (batch·k·4B or ·2B).
    bytes_f32 = BATCH * D * 2 + D * K * 2 + BATCH * K * 4
    bytes_bf16 = BATCH * D * 2 + D * K * 2 + BATCH * K * 2
    daemon_extras = _daemon_serving_p50(rng)
    emit(
        f"pca_transform_p50_ms_batch{BATCH}_d{D}_k{K}_bf16",
        p50,
        "ms",
        BASELINE_P50_MS / p50,
        bf16_out_p50_ms=round(p50_bf16, 4),
        roofline_ms=round(bytes_f32 / 819e9 * 1e3, 4),
        roofline_bf16_out_ms=round(bytes_bf16 / 819e9 * 1e3, 4),
        hbm_efficiency=round(bytes_f32 / 819e9 * 1e3 / p50, 4),
        **daemon_extras,
    )


def _daemon_serving_p50(rng) -> dict:
    """End-to-end daemon ``transform`` round-trip p50 (Arrow IPC over
    loopback TCP + host→device + GEMM + device→host) — the path Spark
    executors actually take (VERDICT r2 #1 asked for this number next to
    the device-only p50).

    Measured at a smaller batch than the device-only metric: on the dev
    harness, host→device crosses the axon tunnel at single-digit MB/s, so
    a 512 MB batch would measure the tunnel, not the serving stack. The
    ``daemon_tunneled`` flag marks runs where that applies (same
    heuristic as bench_ingest).
    """
    import time

    from spark_rapids_ml_tpu.models.pca import PCAModel
    from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon

    d_rows = int(os.environ.get("SRML_BENCH_DAEMON_ROWS", 4096))
    model = PCAModel(
        pc=rng.normal(size=(D, K)), mean=np.zeros(D),
        explained_variance=np.ones(K) / K,
    )
    xb = rng.normal(size=(d_rows, D)).astype(np.float32)
    with DataPlaneDaemon() as daemon:
        with DataPlaneClient(*daemon.address) as c:
            c.ensure_model("bench-pca", "pca", model._model_data())
            c.transform("bench-pca", xb)  # warm: compile + device residency
            lats = []
            for _ in range(9):
                t0 = time.perf_counter()
                c.transform("bench-pca", xb)
                lats.append((time.perf_counter() - t0) * 1e3)
    p50 = float(np.percentile(lats, 50))
    # crude tunnel detection: a local host→device path moves this batch in
    # well under a PCIe-class millisecond budget; the tunnel takes 100s of ms
    bps = xb.nbytes / (p50 / 1e3)
    return {
        "daemon_p50_ms": round(p50, 3),
        "daemon_batch_rows": d_rows,
        "daemon_tunneled": bool(bps < 1e9),
    }


if __name__ == "__main__":
    main()
