"""Stitch run-journal files into one distributed trace.

One fit writes journal lines from several processes: the driver's
``journal.run`` + phase spans, each executor task's client ops, and every
daemon's ``daemon.<op>`` spans — all carrying the same ``run_id`` because
the client stamps its frame as an additive ``trace_ctx`` on every wire op
and the daemon adopts it (docs/protocol.md). This tool merges one or more
journal files (processes may share a file via O_APPEND, or write their
own) and emits:

* **Chrome-trace JSON** (``--out trace.json``): complete ``X`` events on
  (pid, tid) tracks — loads in ``chrome://tracing`` or Perfetto
  (https://ui.perfetto.dev). The queryable successor of the reference's
  Nsight-only NVTX ranges.
* **a text flame summary** (default to stdout): the span tree aggregated
  by name-path, with total seconds, call counts, and the share of the
  root — ``why is fit flat`` as a terminal one-liner.

Usage::

    python -m spark_rapids_ml_tpu.tools.trace journal.jsonl [more.jsonl ...] \
        [--out trace.json] [--run RUN_ID] [--flame]
    python -m spark_rapids_ml_tpu.tools.trace --fleet HOST:PORT [--flame]

Three kinds of source, freely mixable:

* **journal files** — rotated segments (``journal.jsonl.1`` …) are
  folded in transparently (utils/journal.py ``segments``);
* **incident bundles** — a flight-recorder dump
  (``state_dir/incidents/incident-*.json``, utils/flight.py) loads as a
  trace source through its ``events`` list, so a daemon that died five
  minutes ago stitches into the tree like a live one;
* **the fleet itself** — ``--fleet HOST:PORT`` needs ONE gossip seed
  and ZERO filesystem access: it pulls the seed's FleetView
  (``gossip_pull``), then drains every live replica's in-memory span
  ring over the wire (``trace_pull``), and stitches the union.

Merged events sort by ``(ts, pid, seq)`` — the per-process monotonic
``seq`` breaks wall-clock ties, so the merge order is stable no matter
how many processes share a timestamp. Spans whose ``parent_id`` is not
in the merged set (a daemon span whose parent lives in a journal file
you did not pass) root at their run — the tree degrades, it never drops
events.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Optional

from spark_rapids_ml_tpu.utils import flight, journal

#: Events that appear in the trace: phases and run_ends carry durations;
#: marks become instants. run_start is the run_end's open bracket — it
#: carries no duration, so it is used only to name the run.
_SPAN_EVENTS = ("phase", "run_end")


def _sort_key(e: Dict[str, Any]):
    """Stable merge order: wall clock, then pid, then the per-process
    monotonic ``seq`` — two events stamped in the same clock tick by the
    same process keep their emission order."""
    return (
        float(e.get("ts", 0.0)),
        int(e.get("pid", 0)),
        int(e.get("seq", 0)),
    )


def _load_source(path: str) -> List[Dict[str, Any]]:
    """One source file → its events: an incident bundle (a single JSON
    object with ``kind: srml_incident_bundle``) contributes its
    ``events`` list; anything else is read as a journal file, rotated
    segments included."""
    try:
        bundle = flight.load_bundle(path)
    except (ValueError, OSError):
        return journal.read(str(path))
    events = bundle.get("events")
    return [e for e in events if isinstance(e, dict)] \
        if isinstance(events, list) else []


def load(paths: Iterable[str]) -> List[Dict[str, Any]]:
    """Merge journal files and/or incident bundles into one event list,
    sorted by ``(ts, pid, seq)``."""
    events: List[Dict[str, Any]] = []
    for p in paths:
        events.extend(_load_source(str(p)))
    events.sort(key=_sort_key)
    return events


def fleet_load(
    seed: str,
    token: Optional[str] = None,
    timeout: float = 5.0,
) -> List[Dict[str, Any]]:
    """Drain the whole fleet's span rings from ONE gossip seed — zero
    filesystem access. ``gossip_pull`` on the seed names every replica;
    each up-replica answers ``trace_pull`` with its in-memory journal
    ring. A replica that dies mid-drain is skipped (its spans may still
    arrive via the others' rings or an incident bundle); duplicate
    addresses collapse by server id."""
    from spark_rapids_ml_tpu.serve.client import DataPlaneClient
    from spark_rapids_ml_tpu.spark.daemon_session import _parse_addr

    with DataPlaneClient(
        *_parse_addr(seed), token=token, timeout=timeout, max_op_attempts=1,
    ) as c:
        view = c.gossip_pull()
    addrs: Dict[str, str] = {}  # server_id → addr (view wins over seed)
    for sid, rec in (view.get("replicas") or {}).items():
        if rec.get("liveness") == "up" and rec.get("addr"):
            addrs[str(sid)] = str(rec["addr"])
    if not addrs:  # pre-gossip daemon: the seed is the whole "fleet"
        addrs[""] = seed
    events: List[Dict[str, Any]] = []
    for sid in sorted(addrs):
        try:
            with DataPlaneClient(
                *_parse_addr(addrs[sid]), token=token,
                timeout=timeout, max_op_attempts=1,
            ) as c:
                pulled = c.trace_pull()
        except Exception as e:
            print(f"trace: replica {addrs[sid]} unreachable: {e}",
                  file=sys.stderr)
            continue
        evs = pulled.get("events")
        if isinstance(evs, list):
            events.extend(ev for ev in evs if isinstance(ev, dict))
    events.sort(key=_sort_key)
    return events


def runs(events: List[Dict[str, Any]]) -> Dict[str, str]:
    """run_id → run name for every run that appears in the events.
    Runs seen only through adopted spans (their run_start/run_end lives
    in a journal file not passed) list as ``?``."""
    out: Dict[str, str] = {}
    for e in events:
        rid = e.get("run_id")
        if not rid:
            continue
        if e.get("event") in ("run_start", "run_end"):
            out[rid] = str(e.get("name", "?"))
        else:
            out.setdefault(rid, "?")
    return out


def _filter_run(
    events: List[Dict[str, Any]], run_id: Optional[str]
) -> List[Dict[str, Any]]:
    if run_id is None:
        return events
    return [e for e in events if e.get("run_id") == run_id]


def chrome_trace(
    events: List[Dict[str, Any]], run_id: Optional[str] = None
) -> Dict[str, Any]:
    """Merged events → a Chrome-trace/Perfetto JSON object.

    ``X`` (complete) events for phases and runs, ``i`` (instant) events
    for marks; ``ts``/``dur`` in microseconds as the format requires;
    tracks are the journal's (pid, tid). Extra journal fields ride in
    ``args`` so nothing recorded is lost in the conversion."""
    events = _filter_run(events, run_id)
    out: List[Dict[str, Any]] = []
    seen_tracks = set()
    for e in events:
        ev = e.get("event")
        base = {
            "name": str(e.get("name", "?")),
            "pid": int(e.get("pid", 0)),
            "tid": int(e.get("tid", e.get("pid", 0))),
            "ts": float(e.get("ts", 0.0)) * 1e6,
            "cat": ev or "?",
            "args": {
                k: v for k, v in e.items()
                if k not in ("ts", "pid", "tid", "event", "name")
            },
        }
        seen_tracks.add((base["pid"], base["tid"]))
        if ev in _SPAN_EVENTS:
            out.append({
                **base, "ph": "X",
                "dur": float(e.get("duration_s", 0.0)) * 1e6,
            })
        elif ev == "mark":
            out.append({**base, "ph": "i", "s": "t"})
        # run_start: subsumed by its run_end X event.
    for pid, tid in sorted(seen_tracks):
        out.append({
            "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": f"pid {pid} / tid {tid}"},
        })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


class Node:
    """One span in the stitched tree (spans only — marks are leaves of
    convenience, they carry no duration)."""

    __slots__ = ("event", "children")

    def __init__(self, event: Dict[str, Any]):
        self.event = event
        self.children: List["Node"] = []

    @property
    def span_id(self) -> Optional[str]:
        return self.event.get("span_id")

    @property
    def name(self) -> str:
        return str(self.event.get("name", "?"))

    @property
    def duration_s(self) -> float:
        return float(self.event.get("duration_s", 0.0))


def tree(
    events: List[Dict[str, Any]], run_id: Optional[str] = None
) -> List[Node]:
    """Stitch spans into parent→children trees; returns the roots.

    A span parents to the node owning its ``parent_id`` — REGARDLESS of
    which process/file it came from; that is the whole point of the
    trace_ctx stamp. Orphans (parent span not in the merged set) become
    roots rather than vanishing."""
    events = _filter_run(events, run_id)
    nodes = [Node(e) for e in events if e.get("event") in _SPAN_EVENTS]
    by_span: Dict[str, Node] = {}
    for n in nodes:
        sid = n.span_id
        if sid:
            # A replayed op can journal the same span name twice; last
            # write wins for identity, both still render as children.
            by_span.setdefault(sid, n)
    roots: List[Node] = []
    for n in nodes:
        parent = n.event.get("parent_id")
        p = by_span.get(parent) if parent else None
        if p is not None and p is not n:
            p.children.append(n)
        else:
            roots.append(n)
    for n in nodes:
        n.children.sort(key=lambda c: _sort_key(c.event))
    roots.sort(key=lambda r: _sort_key(r.event))
    return roots


def flame(
    events: List[Dict[str, Any]], run_id: Optional[str] = None
) -> str:
    """Text flame summary: the span tree aggregated by name-path.

    Sibling spans with the same name fold into one line (count ×, total
    seconds, % of their root) — 384 identical feed passes read as one
    line, not 384. Multi-process paths show ``pid@`` so a daemon-side
    span is visibly remote."""
    roots = tree(events, run_id)
    lines: List[str] = []

    def total(node: Node) -> float:
        return node.duration_s

    def walk(nodes: List[Node], depth: int, root_s: float) -> None:
        groups: Dict[str, List[Node]] = {}
        for n in nodes:
            groups.setdefault(n.name, []).append(n)
        ordered = sorted(
            groups.items(), key=lambda kv: -sum(total(n) for n in kv[1])
        )
        for name, group in ordered:
            secs = sum(total(n) for n in group)
            pids = sorted({int(n.event.get("pid", 0)) for n in group})
            where = f" [pid {','.join(str(p) for p in pids)}]" if depth else ""
            pct = f" {100 * secs / root_s:5.1f}%" if root_s > 0 else ""
            count = f" x{len(group)}" if len(group) > 1 else ""
            lines.append(
                f"{'  ' * depth}{name:<{max(1, 36 - 2 * depth)}}"
                f" {secs:9.3f}s{pct}{count}{where}"
            )
            children = [c for n in group for c in n.children]
            if children:
                walk(children, depth + 1, root_s)

    for root in roots:
        root_s = total(root) or sum(c.duration_s for c in root.children)
        walk([root], 0, root_s)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spark_rapids_ml_tpu.tools.trace",
        description="Merge run-journal files into a Chrome trace and/or "
        "a text flame summary.",
    )
    ap.add_argument(
        "journals", nargs="*",
        help="journal .jsonl file(s) and/or incident bundle .json file(s)",
    )
    ap.add_argument(
        "--fleet", metavar="HOST:PORT",
        help="pull the whole fleet's spans over the wire from ONE gossip "
        "seed (gossip_pull + trace_pull per replica) — no files needed; "
        "mixes with file sources",
    )
    ap.add_argument(
        "--token", default=os.environ.get("SRML_DAEMON_TOKEN"),
        help="shared-secret daemon token for --fleet (default: "
        "$SRML_DAEMON_TOKEN)",
    )
    ap.add_argument("--out", "-o", help="write Chrome-trace JSON here")
    ap.add_argument("--run", help="restrict to one run_id")
    ap.add_argument(
        "--flame", action="store_true",
        help="print the flame summary (default when --out is not given)",
    )
    ap.add_argument(
        "--list-runs", action="store_true",
        help="print run_id → name and exit",
    )
    args = ap.parse_args(argv)
    if not args.journals and not args.fleet:
        ap.error("no sources: pass journal/bundle files and/or --fleet")

    events = load(args.journals)
    if args.fleet:
        events.extend(fleet_load(args.fleet, token=args.token))
        events.sort(key=_sort_key)
    if not events:
        print("no journal events found", file=sys.stderr)
        return 1
    if args.list_runs:
        for rid, name in sorted(runs(events).items()):
            n = sum(1 for e in events if e.get("run_id") == rid)
            print(f"{rid}  {name}  ({n} events)")
        return 0
    if args.out:
        obj = chrome_trace(events, args.run)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(obj, f)
        print(
            f"wrote {len(obj['traceEvents'])} trace events to {args.out} "
            "(load in chrome://tracing or https://ui.perfetto.dev)"
        )
    if args.flame or not args.out:
        print(flame(events, args.run))
    return 0


if __name__ == "__main__":
    sys.exit(main())
