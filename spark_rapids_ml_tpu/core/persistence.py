"""Model/estimator persistence: params JSON + data Parquet.

Reproduces the Spark ML on-disk contract the reference uses
(RapidsPCA.scala:193-228 — ``DefaultParamsWriter.saveMetadata`` + a
single-partition Parquet ``data`` dir; reload via ``loadMetadata`` +
``getAndSetParams``):

    path/
      metadata/part-00000     <- one JSON object (class, uid, params, defaults)
      data/part-00000.parquet <- model payload (fitted arrays), when a Model

A model saved by this framework is layout-compatible in spirit: params land
in the same metadata JSON shape (``class``/``timestamp``/``uid``/``paramMap``/
``defaultParamMap``) so tooling that inspects Spark ML metadata can read it.
"""

from __future__ import annotations

import importlib
import json
import os
import time
from typing import Any, Dict, Optional

import numpy as np

try:
    import pyarrow as pa
    import pyarrow.parquet as pq
except ImportError:  # pragma: no cover
    pa = None
    pq = None


def _json_default(value: Any):
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    if isinstance(value, np.ndarray):
        return value.tolist()
    raise TypeError(f"not JSON-serializable: {type(value)}")


class MLWriter:
    """write() handle: ``model.write().overwrite().save(path)``."""

    def __init__(self, instance):
        self._instance = instance
        self._overwrite = False

    def overwrite(self) -> "MLWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        if os.path.exists(path):
            if not self._overwrite:
                raise FileExistsError(
                    f"path {path} already exists; use write().overwrite().save()"
                )
            import shutil

            shutil.rmtree(path)
        os.makedirs(path)
        DefaultParamsWriter.save_metadata(self._instance, path)
        payload = getattr(self._instance, "_model_data", None)
        if callable(payload):
            data = payload()
            if data:
                _write_data(path, data)


class MLReader:
    def __init__(self, cls):
        self._cls = cls

    def load(self, path: str):
        return DefaultParamsReader.load_instance(path, expected_cls=self._cls)


def _write_data(path: str, data: Dict[str, np.ndarray]) -> None:
    data_dir = os.path.join(path, "data")
    os.makedirs(data_dir, exist_ok=True)
    if pa is not None:
        # Arrays stored as single-row table: each fitted tensor is one cell
        # (list for 1-D, list-of-list kept flat + shape column for >=2-D).
        cols: Dict[str, Any] = {}
        shapes: Dict[str, Any] = {}
        for name, arr in data.items():
            arr = np.asarray(arr)
            shapes[name] = list(arr.shape)
            cols[name] = [arr.reshape(-1).tolist()]
        cols["__shapes__"] = [json.dumps(shapes)]
        table = pa.table(cols)
        pq.write_table(table, os.path.join(data_dir, "part-00000.parquet"))
    else:  # pragma: no cover - numpy fallback
        np.savez(os.path.join(data_dir, "part-00000.npz"), **data)


def _read_data(path: str) -> Optional[Dict[str, np.ndarray]]:
    data_dir = os.path.join(path, "data")
    if not os.path.isdir(data_dir):
        return None
    pq_path = os.path.join(data_dir, "part-00000.parquet")
    if pa is not None and os.path.exists(pq_path):
        table = pq.read_table(pq_path)
        shapes = json.loads(table.column("__shapes__")[0].as_py())
        out = {}
        for name, shape in shapes.items():
            flat = np.asarray(table.column(name)[0].as_py(), dtype=np.float64)
            out[name] = flat.reshape(shape)
        return out
    npz_path = os.path.join(data_dir, "part-00000.npz")  # pragma: no cover
    if os.path.exists(npz_path):  # pragma: no cover
        with np.load(npz_path) as z:
            return {k: z[k] for k in z.files}
    return None


class DefaultParamsWriter:
    @staticmethod
    def save_metadata(instance, path: str, extra: Optional[Dict[str, Any]] = None) -> None:
        cls = type(instance)
        meta = {
            "class": f"{cls.__module__}.{cls.__qualname__}",
            "timestamp": int(time.time() * 1000),
            "sparkVersion": "tpu-native",
            "uid": instance.uid,
            "paramMap": {p.name: v for p, v in instance._paramMap.items()},
            "defaultParamMap": {p.name: v for p, v in instance._defaultParamMap.items()},
        }
        if extra:
            meta.update(extra)
        meta_dir = os.path.join(path, "metadata")
        os.makedirs(meta_dir, exist_ok=True)
        with open(os.path.join(meta_dir, "part-00000"), "w") as f:
            json.dump(meta, f, default=_json_default)
        # Spark writes an empty _SUCCESS marker per saved dir.
        open(os.path.join(meta_dir, "_SUCCESS"), "w").close()


class DefaultParamsReader:
    @staticmethod
    def load_metadata(path: str) -> Dict[str, Any]:
        with open(os.path.join(path, "metadata", "part-00000")) as f:
            return json.load(f)

    @staticmethod
    def load_instance(path: str, expected_cls=None):
        meta = DefaultParamsReader.load_metadata(path)
        module_name, _, cls_name = meta["class"].rpartition(".")
        module = importlib.import_module(module_name)
        cls = getattr(module, cls_name)
        if expected_cls is not None and not issubclass(cls, expected_cls):
            raise TypeError(
                f"saved class {meta['class']} is not a {expected_cls.__name__}"
            )
        data = _read_data(path)
        if data is not None and hasattr(cls, "_from_model_data"):
            instance = cls._from_model_data(meta["uid"], data)
        else:
            instance = cls(uid=meta["uid"]) if cls._accepts_uid() else cls()
            instance.uid = meta["uid"]
        for name, value in meta.get("defaultParamMap", {}).items():
            if instance.hasParam(name):
                instance.setDefault(**{name: value})
        for name, value in meta.get("paramMap", {}).items():
            if instance.hasParam(name):
                instance._set(**{name: value})
        return instance


class MLWritable:
    """Mixin: DefaultParamsWritable equivalent (RapidsPCA.scala:53,182)."""

    def write(self) -> MLWriter:
        return MLWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)


class MLReadable:
    """Mixin: DefaultParamsReadable equivalent (RapidsPCA.scala:90,205)."""

    @classmethod
    def read(cls) -> MLReader:
        return MLReader(cls)

    @classmethod
    def load(cls, path: str):
        return cls.read().load(path)
