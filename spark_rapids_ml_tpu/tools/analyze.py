"""srml-check: AST-based invariant analyzer for the package's contracts.

The system's hardest guarantees — bitwise-equal reduce folds, single-filed
device dispatch through ``_DEVICE_LOCK``, donated-buffer streaming state,
the additive wire contract — were enforced by convention plus grep-shaped
lints (tests/test_lint.py), and each regressed at least once before a
human caught it in review. This module is the mechanical reviewer: it
parses the whole package with ``ast``, resolves a lightweight per-function
context (enclosing ``with`` locks, bound jit handles, call targets), and
runs a registry of rules the regex gates cannot express (a string built by
concatenation or f-string dodges a regex; it cannot dodge the AST).

Rule catalog (docs/static_analysis.md has the full rationale):

Lock discipline (the PR 13 "compile outside the lock" hardening class):
  ``device-lock``          device-dispatching calls in serve/daemon.py /
                           serve/scheduler.py must be lexically under
                           ``with _DEVICE_LOCK``.
  ``compile-outside-lock`` compile-path calls (``lower``/``compile``/
                           ``aot_prime``/``cost_analysis``) must NOT hold
                           the device lock — compiles are host work and
                           stall serving traffic.
  ``lock-order``           ``_DEVICE_LOCK`` is innermost by contract:
                           acquiring any other lock under it, or inverting
                           an ordering observed elsewhere, is a deadlock
                           hazard.

Donation (the donated streaming-state contract, ops/gram.py):
  ``use-after-donate``     a name passed at a ``donate_argnums`` position
                           of a ledgered jit is device-donated; reading it
                           again before reassignment is a use-after-free.

Determinism (the PR 7 unsorted-fold class):
  ``unsorted-iter``        iterating an un-``sorted()`` dict/set in the
                           bitwise-contract modules (ops/, models/,
                           parallel/, daemon fold/merge paths).
  ``wallclock-entropy``    ``time.time`` / ``random.*`` / unseeded
                           ``np.random.*`` in the bitwise-contract modules.

Wire contract (AST upgrade of the regex clamp gate):
  ``wire-op-clamp``        every op string the daemon dispatches must be in
                           ``_KNOWN_OPS`` and docs/protocol.md.
  ``ack-contract``         ack-dict fields may only be added, never removed,
                           versus the checked-in snapshot
                           (tools/analyze_contract.json).

Ported regex gates (the engine's first three rules; test_lint.py test
names are preserved as thin invokers):
  ``bare-print``           no ``print(`` in library code (tools/ and
                           ``__main__`` tails exempt).
  ``bare-collective``      no ``lax.psum``-family call outside parallel/.
  ``socket-timeout``       every ``socket.create_connection`` passes an
                           explicit timeout.

Suppression: an inline ``# srml: disable=<rule>[,<rule>...]`` pragma on
the finding's line suppresses it (add a justification comment); accepted
legacy findings live in tools/analyze_baseline.json keyed by
(rule, file, enclosing symbol, count) so they survive line drift. The
tier-1 gate is therefore "zero NEW findings"; baseline entries that no
longer match anything are reported as stale warnings so the baseline only
ever shrinks.

CLI::

    python -m spark_rapids_ml_tpu.tools.analyze            # human output
    python -m spark_rapids_ml_tpu.tools.analyze --json     # machine output
    python -m spark_rapids_ml_tpu.tools.analyze --rule device-lock
    python -m spark_rapids_ml_tpu.tools.analyze --write-baseline
    python -m spark_rapids_ml_tpu.tools.analyze --write-contract

Exit status: 0 = zero unsuppressed findings, 1 = findings, 2 = usage.
This module imports only the standard library (no jax, no package
imports), so it runs in milliseconds anywhere, CI included.
"""

from __future__ import annotations

import argparse
import ast
import json
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Set, Tuple

PKG_ROOT = Path(__file__).resolve().parent.parent
REPO_ROOT = PKG_ROOT.parent
BASELINE_PATH = Path(__file__).resolve().parent / "analyze_baseline.json"
CONTRACT_PATH = Path(__file__).resolve().parent / "analyze_contract.json"

#: Modules whose device dispatch must single-file through _DEVICE_LOCK.
DEVICE_MODULES = ("serve/daemon.py", "serve/scheduler.py")
#: Directories under the bitwise-determinism contract (identical inputs
#: must fold to identical bits on every host/process).
BITWISE_DIRS = ("ops", "models", "parallel")
#: Daemon/scheduler function-name fragments that put a function on the
#: fold/merge path (the daemon's slice of the bitwise contract).
FOLD_NAME_FRAGMENTS = ("merge", "fold", "reduce", "finalize", "commit", "step")

_PRAGMA_RE = re.compile(r"#\s*srml:\s*disable=([a-z0-9_,\- ]+)")


# ---------------------------------------------------------------------------
# findings, pragmas, baseline
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One rule violation: id, location, enclosing symbol, one-line why."""

    rule: str
    file: str
    line: int
    symbol: str
    message: str

    def format(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message} (in {self.symbol})"

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
        }


def format_findings(findings: Sequence[Finding]) -> str:
    return "\n".join(f.format() for f in findings)


class Baseline:
    """Accepted legacy findings, keyed (rule, file, symbol) with a count.

    Keying by enclosing symbol instead of line number survives unrelated
    edits above the finding; the count bounds how many findings of one
    rule a symbol may carry, so NEW findings in an already-baselined
    function still fail. ``stale()`` reports entries whose code is gone —
    the baseline is a ratchet and must only ever shrink.
    """

    def __init__(self, entries: Optional[Sequence[Dict[str, Any]]] = None):
        self.entries: Dict[Tuple[str, str, str], int] = {}
        for e in entries or []:
            key = (str(e["rule"]), str(e["file"]), str(e["symbol"]))
            self.entries[key] = self.entries.get(key, 0) + int(e.get("count", 1))
        self._matched: Dict[Tuple[str, str, str], int] = {}

    @classmethod
    def load(cls, path: Path = BASELINE_PATH) -> "Baseline":
        if not path.exists():
            return cls()
        data = json.loads(path.read_text())
        return cls(data.get("entries", []))

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        b = cls()
        for f in findings:
            key = (f.rule, f.file, f.symbol)
            b.entries[key] = b.entries.get(key, 0) + 1
        return b

    def as_json(self) -> str:
        entries = [
            {"rule": r, "file": fp, "symbol": s, "count": c}
            for (r, fp, s), c in sorted(self.entries.items())
        ]
        return json.dumps({"version": 1, "entries": entries}, indent=2) + "\n"

    def suppresses(self, f: Finding) -> bool:
        key = (f.rule, f.file, f.symbol)
        if self._matched.get(key, 0) < self.entries.get(key, 0):
            self._matched[key] = self._matched.get(key, 0) + 1
            return True
        return False

    def stale(self) -> List[str]:
        """Entries (or counts) that matched nothing in the last run."""
        out = []
        for key, cap in sorted(self.entries.items()):
            used = self._matched.get(key, 0)
            if used < cap:
                rule, fp, sym = key
                out.append(
                    f"stale baseline entry: {rule} in {fp} ({sym}) — "
                    f"{cap - used} of {cap} accepted finding(s) no longer "
                    "exist; shrink tools/analyze_baseline.json"
                )
        return out


# ---------------------------------------------------------------------------
# module model
# ---------------------------------------------------------------------------


class Module:
    """One parsed source file plus the lazy per-line pragma map."""

    def __init__(self, relpath: str, source: str, display_path: Optional[str] = None):
        self.relpath = relpath.replace("\\", "/")
        self.source = source
        self.display_path = display_path or self.relpath
        self.tree = ast.parse(source, filename=self.relpath)
        self.lines = source.split("\n")
        self._pragmas: Optional[Dict[int, Set[str]]] = None
        # Parent links let rules walk ancestors (loop/guard detection).
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._srml_parent = parent  # type: ignore[attr-defined]

    @property
    def pragmas(self) -> Dict[int, Set[str]]:
        if self._pragmas is None:
            self._pragmas = {}
            for i, line in enumerate(self.lines, start=1):
                m = _PRAGMA_RE.search(line)
                if m:
                    rules = {p.strip() for p in m.group(1).split(",") if p.strip()}
                    self._pragmas[i] = rules
        return self._pragmas

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.pragmas.get(line)
        return rules is not None and (rule in rules or "all" in rules)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = getattr(node, "_srml_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_srml_parent", None)

    def enclosing_symbol(self, node: ast.AST) -> str:
        parts = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(anc.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.insert(0, node.name)
        return ".".join(reversed(parts)) or "<module>"


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------


def dotted_name(expr: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(expr: ast.AST) -> Optional[str]:
    """The last identifier of a call target: ``x`` for ``a.b.x`` or ``x``."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def const_str(expr: ast.AST) -> Optional[str]:
    """Constant-fold an expression to a string where statically possible —
    plain constants, ``"a" + "b"`` concatenation, and constant-only
    f-strings — so wire-op strings cannot dodge the clamp by being built
    instead of written (the hole the old regex gate had)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left, right = const_str(expr.left), const_str(expr.right)
        if left is not None and right is not None:
            return left + right
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for v in expr.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                inner = const_str(v.value)
                if inner is None:
                    return None
                parts.append(inner)
            else:
                return None
        return "".join(parts)
    return None


_LOCKISH_RE = re.compile(r"(_lock$|_LOCK$|^lock$|^_cv$|_cond$)")


def lock_name(expr: ast.AST) -> Optional[str]:
    """Normalized lock identity of a ``with`` context expression, or None
    when it does not look like a lock. ``self._models_lock`` →
    ``_models_lock``; ``_DEVICE_LOCK`` → ``_DEVICE_LOCK``."""
    name = terminal_name(expr)
    if name is not None and _LOCKISH_RE.search(name):
        return name
    return None


def in_main_guard(mod: Module, node: ast.AST) -> bool:
    """True when the node sits under ``if __name__ == "__main__":``."""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.If):
            for sub in ast.walk(anc.test):
                if isinstance(sub, ast.Name) and sub.id == "__name__":
                    return True
    return False


def iter_functions(mod: Module) -> Iterator[ast.AST]:
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def held_locks(mod: Module, node: ast.AST) -> List[str]:
    """Locks lexically held at ``node``, outermost first (item order of a
    multi-item ``with A, B:`` preserved) — the resolved ``with``-stack
    WITHIN the node's own function. The walk stops at the first function
    boundary: a closure defined under ``with _DEVICE_LOCK`` runs later,
    when the lock is long released, so an enclosing function's ``with``
    must not read as held inside the closure."""
    withs: List[ast.With] = []
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            break
        if isinstance(anc, ast.With):
            withs.append(anc)
    stack: List[str] = []
    for w in reversed(withs):  # outermost with first, items left-to-right
        for item in w.items:
            ln = lock_name(item.context_expr)
            if ln is not None:
                stack.append(ln)
    return stack


def node_pos(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def node_end(node: ast.AST) -> Tuple[int, int]:
    return (
        getattr(node, "end_lineno", getattr(node, "lineno", 0)),
        getattr(node, "end_col_offset", getattr(node, "col_offset", 0)),
    )


# ---------------------------------------------------------------------------
# jit-handle registry (cross-module semantic context)
# ---------------------------------------------------------------------------


def _ledgered_jit_donate(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """donate_argnums of a ``ledgered_jit(...)`` / ``functools.partial(
    ledgered_jit, ...)`` expression, () when present without donation,
    None when the call is not a ledgered_jit registration at all."""
    fn = terminal_name(call.func)
    args = call.args
    if fn == "partial" and args and terminal_name(args[0]) == "ledgered_jit":
        pass
    elif fn == "ledgered_jit":
        pass
    else:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            positions: List[int] = []
            val = kw.value
            elts = val.elts if isinstance(val, (ast.Tuple, ast.List)) else [val]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    positions.append(e.value)
            return tuple(positions)
    return ()


def _pkg_module_relpath(dotted: str, known: Set[str]) -> Optional[str]:
    """``spark_rapids_ml_tpu.ops.gram`` (or ``ops.gram``) → the project
    relpath ``ops/gram.py`` when that module is in the analyzed set."""
    parts = dotted.split(".")
    for start in range(len(parts)):
        rel = "/".join(parts[start:]) + ".py"
        if rel in known:
            return rel
    return None


@dataclass
class JitRegistry:
    """Package-wide view of where jit handles come from.

    ``module_handles``: per-module map of MODULE-LEVEL names that ARE a
                   ledgered jit (name → donated arg positions, possibly
                   empty). Scoped per module: the decorated inner ``def
                   update`` every streaming factory carries must not make
                   every ``update`` in the package look like a dispatch.
    ``factories``: functions that RETURN a ledgered jit handle (name →
                   donated positions of the handle they return) — e.g.
                   ``gram.streaming_update(mesh)`` or kmeans'
                   ``_stream_step_fn``. Resolved to a fixpoint so a
                   factory that delegates to another factory (the
                   lru_cache split: ``_stream_softmax_stats_fn`` →
                   ``_stream_softmax_stats_cached``) is still a factory.
                   A call to a factory is host work; a call to what it
                   returned is a device dispatch.
    """

    module_handles: Dict[str, Dict[str, Tuple[int, ...]]] = field(
        default_factory=dict
    )
    factories: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    #: every handle name at any scope — only for resolving `return <name>`
    #: inside factory detection, never for call-site matching.
    _any_scope: Dict[str, Tuple[int, ...]] = field(default_factory=dict)

    @classmethod
    def build(cls, modules: Sequence[Module]) -> "JitRegistry":
        reg = cls()
        #: (factory-candidate def, its own return values), for the fixpoint.
        candidates: List[Tuple[Module, ast.AST, List[ast.AST]]] = []
        for mod in modules:
            mh = reg.module_handles.setdefault(mod.relpath, {})
            for node in ast.walk(mod.tree):
                # name = ledgered_jit("x", f, donate_argnums=...)
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    don = _ledgered_jit_donate(node.value)
                    if don is not None:
                        for t in node.targets:
                            tn = terminal_name(t)
                            if tn:
                                reg._any_scope[tn] = don
                                if _enclosing_function(mod, node) is None:
                                    mh[tn] = don
                # @functools.partial(ledgered_jit, "x", donate_argnums=...)
                # def update(...): ...   /   @ledgered_jit("x")
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Call):
                            don = _ledgered_jit_donate(dec)
                            if don is not None:
                                reg._any_scope[node.name] = don
                                if _enclosing_function(mod, node) is None:
                                    mh[node.name] = don
                    returns = [
                        ret.value
                        for ret in ast.walk(node)
                        if isinstance(ret, ast.Return)
                        and ret.value is not None
                        and _enclosing_function(mod, ret) is node
                    ]
                    if returns:
                        candidates.append((mod, node, returns))
        # Factory fixpoint: direct ledgered_jit returns, returns of a known
        # handle name, and returns of a call to an already-known factory.
        changed = True
        while changed:
            changed = False
            for mod, node, returns in candidates:
                if node.name in reg.factories:
                    continue
                for val in returns:
                    don: Optional[Tuple[int, ...]] = None
                    if isinstance(val, ast.Call):
                        don = _ledgered_jit_donate(val)
                        if don is None:
                            fn = terminal_name(val.func)
                            if fn in reg.factories:
                                don = reg.factories[fn]
                    else:
                        rn = terminal_name(val)
                        if rn is not None and rn in reg._any_scope:
                            don = reg._any_scope[rn]
                    if don is not None:
                        reg.factories[node.name] = don
                        changed = True
                        break
        return reg

    def bound_handles(
        self, mod: Module
    ) -> Dict[str, List[Tuple[Optional[ast.AST], Tuple[int, ...]]]]:
        """Dotted names in ``mod`` bound from a factory call or a handle:
        ``self.update = gram_ops.streaming_update(mesh)`` binds
        ``self.update`` as a dispatch handle donating position 0. Bare
        names carry their binding function as a visibility scope (a local
        ``update = _stream_step_fn(...)`` must not make a sibling
        function's unrelated ``update`` look like a dispatch); attribute
        bindings (``self.update``) cross methods and stay module-wide."""
        bound: Dict[str, List[Tuple[Optional[ast.AST], Tuple[int, ...]]]] = {}
        own = self.module_handles.get(mod.relpath, {})
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            don: Optional[Tuple[int, ...]] = None
            if isinstance(value, ast.Call):
                fn = terminal_name(value.func)
                if fn in self.factories:
                    don = self.factories[fn]
            else:
                vn = terminal_name(value)
                if vn in own:
                    don = own[vn]
            if don is None:
                continue
            for t in node.targets:
                dn = dotted_name(t)
                if dn:
                    scope = (
                        None if "." in dn else _enclosing_function(mod, node)
                    )
                    bound.setdefault(dn, []).append((scope, don))
        return bound

    def imported_handles(self, mod: Module, known_mods: Set[str]) -> Dict[str, Tuple[int, ...]]:
        """Module-level handles visible in ``mod`` through imports:
        ``from ...models.kmeans import apply_lloyd_update`` (direct name)
        and ``from ... import gram as gram_ops`` + ``gram_ops.<handle>``
        (the dotted spelling is resolved at the call site)."""
        out: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                src = _pkg_module_relpath(node.module, known_mods)
                if src is None:
                    continue
                src_handles = self.module_handles.get(src, {})
                for alias in node.names:
                    if alias.name in src_handles:
                        out[alias.asname or alias.name] = src_handles[alias.name]
        return out

    def module_aliases(self, mod: Module, known_mods: Set[str]) -> Dict[str, str]:
        """Import aliases that name whole analyzed modules:
        ``from spark_rapids_ml_tpu.ops import gram as gram_ops`` →
        ``{"gram_ops": "ops/gram.py"}``."""
        out: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    src = _pkg_module_relpath(
                        f"{node.module}.{alias.name}", known_mods
                    )
                    if src is not None:
                        out[alias.asname or alias.name] = src
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    src = _pkg_module_relpath(alias.name, known_mods)
                    if src is not None:
                        out[alias.asname or alias.name.split(".")[-1]] = src
        return out


def _enclosing_function(mod: Module, node: ast.AST) -> Optional[ast.AST]:
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

RULES: Dict[str, "Rule"] = {}


@dataclass
class Rule:
    id: str
    summary: str
    check: Callable[["Project"], List[Finding]]


def rule(rule_id: str, summary: str):
    def deco(fn: Callable[["Project"], List[Finding]]) -> Callable:
        RULES[rule_id] = Rule(rule_id, summary, fn)
        return fn

    return deco


class Project:
    """The analyzed file set plus its cross-module context.

    ``files`` maps package-relative posix paths (``serve/daemon.py``) to
    source text, so tests can assemble synthetic projects; ``from_package``
    loads the real tree. ``protocol_doc``/``contract`` feed the wire rules
    and are optional for fixtures. ``strict_floors`` arms the self-check
    floors (minimum dispatched-op counts etc.) that only make sense
    against the real package.
    """

    def __init__(
        self,
        files: Dict[str, str],
        protocol_doc: Optional[str] = None,
        contract: Optional[Dict[str, Any]] = None,
        strict_floors: bool = False,
        display_prefix: str = "",
    ):
        self.modules: List[Module] = []
        for rel in sorted(files):
            self.modules.append(
                Module(rel, files[rel], display_path=display_prefix + rel)
            )
        self.protocol_doc = protocol_doc
        self.contract = contract
        self.strict_floors = strict_floors
        self.registry = JitRegistry.build(self.modules)
        self._known_mods = {m.relpath for m in self.modules}
        self._jit_views: Dict[str, "ModuleJitView"] = {}
        #: report scope: when set (package-relative paths/prefixes), only
        #: findings in matching files are reported — analysis itself is
        #: always whole-program.
        self.report_filter: Optional[List[str]] = None
        #: non-fatal remarks (stale baseline entries land here too)
        self.notes: List[str] = []

    def jit_view(self, mod: Module) -> "ModuleJitView":
        view = self._jit_views.get(mod.relpath)
        if view is None:
            view = ModuleJitView(
                mod=mod,
                own=self.registry.module_handles.get(mod.relpath, {}),
                bound=self.registry.bound_handles(mod),
                imported=self.registry.imported_handles(mod, self._known_mods),
                aliases=self.registry.module_aliases(mod, self._known_mods),
                registry=self.registry,
            )
            self._jit_views[mod.relpath] = view
        return view

    @staticmethod
    def package_files(pkg_root: Path = PKG_ROOT) -> Dict[str, str]:
        """The real package's sources keyed by relpath — the raw material
        for from_package and for tests that seed a deliberate violation
        into a scratch copy of one module."""
        files: Dict[str, str] = {}
        for p in sorted(pkg_root.rglob("*.py")):
            rel = p.relative_to(pkg_root).as_posix()
            if "__pycache__" in rel:
                continue
            files[rel] = p.read_text()
        return files

    @classmethod
    def from_package(
        cls,
        pkg_root: Path = PKG_ROOT,
        contract_path: Path = CONTRACT_PATH,
        paths: Optional[Sequence[str]] = None,
    ) -> "Project":
        """The real tree. ``paths`` restricts which files findings are
        REPORTED for — the whole package is still parsed, because the
        rules are whole-program (the jit-factory registry in models//ops/
        is what keeps a serve/-only run from false-positive-flagging
        factory calls)."""
        files = cls.package_files(pkg_root)
        doc_path = pkg_root.parent / "docs" / "protocol.md"
        protocol_doc = doc_path.read_text() if doc_path.exists() else None
        contract = None
        if contract_path.exists():
            contract = json.loads(contract_path.read_text())
        project = cls(
            files,
            protocol_doc=protocol_doc,
            contract=contract,
            strict_floors=True,
            display_prefix=pkg_root.name + "/",
        )
        if paths:
            project.report_filter = list(paths)
        return project

    # -- scoping -----------------------------------------------------------

    def device_modules(self) -> List[Module]:
        return [m for m in self.modules if m.relpath in DEVICE_MODULES]

    def bitwise_scope(self, mod: Module, node: ast.AST) -> bool:
        """Whether ``node`` is under the bitwise-determinism contract:
        anywhere in ops//models//parallel/, or on a daemon/scheduler
        fold/merge path (function name carries a fold fragment)."""
        top = mod.relpath.split("/", 1)[0]
        if top in BITWISE_DIRS:
            return True
        if mod.relpath in DEVICE_MODULES:
            for anc in [node, *mod.ancestors(node)]:
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    name = anc.name.lower()
                    if any(f in name for f in FOLD_NAME_FRAGMENTS):
                        return True
        return False

    # -- running -----------------------------------------------------------

    def run_raw(self, rules: Optional[Sequence[str]] = None) -> List[Finding]:
        """All findings before pragma/baseline suppression."""
        selected = sorted(set(rules)) if rules else sorted(RULES)
        unknown = [r for r in selected if r not in RULES]
        if unknown:
            raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
        # Notes are per-run state (rules append as they check): reset so
        # a Project reused across runs reports only this run's notes.
        self.notes = []
        out: List[Finding] = []
        for rid in selected:
            out.extend(RULES[rid].check(self))
        if self.report_filter is not None:
            out = [f for f in out if self.in_report_scope(f.file)]
        out.sort(key=lambda f: (f.file, f.line, f.rule))
        return out

    def in_report_scope(self, display_path: str) -> bool:
        if self.report_filter is None:
            return True
        rel = display_path
        for m in self.modules:
            if m.display_path == display_path:
                rel = m.relpath
                break
        return any(
            rel == q or rel.startswith(q.rstrip("/") + "/")
            for q in self.report_filter
        )

    def run(
        self,
        rules: Optional[Sequence[str]] = None,
        baseline: Optional[Baseline] = None,
    ) -> List[Finding]:
        """Findings after inline pragmas and the baseline; stale-baseline
        warnings land in ``self.notes``."""
        raw = self.run_raw(rules)
        if baseline is not None:
            # A Baseline is reusable across runs: matched counts are
            # per-run state, reset here so a second run suppresses again.
            baseline._matched = {}
        by_display = {m.display_path: m for m in self.modules}
        kept: List[Finding] = []
        for f in raw:
            mod = by_display.get(f.file)
            if mod is not None and mod.suppressed(f.rule, f.line):
                continue
            if baseline is not None and baseline.suppresses(f):
                continue
            kept.append(f)
        if baseline is not None:
            self.notes.extend(baseline.stale())
        return kept

    def finding(
        self, mod: Module, node: ast.AST, rule_id: str, message: str
    ) -> Finding:
        return Finding(
            rule=rule_id,
            file=mod.display_path,
            line=getattr(node, "lineno", 1),
            symbol=mod.enclosing_symbol(node),
            message=message,
        )


# ---------------------------------------------------------------------------
# rule family 1: lock discipline
# ---------------------------------------------------------------------------

#: Call targets that always touch the device (dispatch or transfer).
_DEVICE_CALL_NAMES = frozenset(
    ("block_until_ready", "device_get", "device_put")
)
#: Compile-path call targets: host work that must not hold _DEVICE_LOCK.
_COMPILE_CALL_NAMES = frozenset(
    ("lower", "compile", "aot_prime", "cost_analysis")
)


@dataclass
class ModuleJitView:
    """Per-module resolution context for jit-handle call sites."""

    mod: Module
    own: Dict[str, Tuple[int, ...]]
    bound: Dict[str, List[Tuple[Optional[ast.AST], Tuple[int, ...]]]]
    imported: Dict[str, Tuple[int, ...]]
    aliases: Dict[str, str]
    registry: JitRegistry

    def resolve_call(self, call: ast.Call) -> Optional[Tuple[Tuple[int, ...], str]]:
        """(donated positions, why) when this call dispatches a ledgered
        jit handle, else None."""
        dn = dotted_name(call.func)
        if dn is not None and dn in self.bound:
            enclosing: List[ast.AST] = []
            fn = _enclosing_function(self.mod, call)
            while fn is not None:
                enclosing.append(fn)
                fn = _enclosing_function(self.mod, fn)
            for scope, don in self.bound[dn]:
                if scope is None or scope in enclosing:
                    return don, f"{dn} is bound from a jit factory"
        name = terminal_name(call.func)
        if name is None:
            return None
        if isinstance(call.func, ast.Name):
            if name in self.own:
                return self.own[name], f"{name} is a ledgered-jit entry"
            if name in self.imported:
                return self.imported[name], f"{name} is an imported ledgered-jit entry"
        elif isinstance(call.func, ast.Attribute):
            base = terminal_name(call.func.value)
            src = self.aliases.get(base or "")
            if src is not None:
                handles = self.registry.module_handles.get(src, {})
                if name in handles:
                    return handles[name], (
                        f"{base}.{name} is a ledgered-jit entry of {src}"
                    )
        return None


def _in_locked_helper(mod: Module, node: ast.AST) -> bool:
    """Whether the node sits in a ``*_locked``-suffixed function — the
    package convention for "the caller already holds the lock" (e.g.
    ``_Job._finalize_locked`` runs under finalize()'s _DEVICE_LOCK)."""
    fn = _enclosing_function(mod, node)
    while fn is not None:
        if fn.name.endswith("_locked"):
            return True
        fn = _enclosing_function(mod, fn)
    return False


def _is_dispatch_call(
    project: Project, mod: Module, call: ast.Call, view: ModuleJitView
) -> Optional[str]:
    """Why this call is a device dispatch, or None. The semantic model:
    ledgered-jit handles (direct, imported, or factory-bound), ``*_fn``
    jit handles, and the jax device/transfer entry points."""
    name = terminal_name(call.func)
    if name is None:
        return None
    if name in _DEVICE_CALL_NAMES:
        return f"jax.{name} touches the device"
    resolved = view.resolve_call(call)
    if resolved is not None:
        return resolved[1] + " (dispatches a device program)"
    if (
        name.endswith("_fn")
        and name not in project.registry.factories
        and not name.startswith(("init_", "plan_", "make_", "build_"))
    ):
        return f"{name} looks like a jit handle (*_fn convention)"
    return None


@rule(
    "device-lock",
    "device-dispatching calls in serve/daemon.py and serve/scheduler.py "
    "must run lexically under `with _DEVICE_LOCK` (and `*_locked` helpers "
    "must be called with a lock held)",
)
def _check_device_lock(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.device_modules():
        view = project.jit_view(mod)
        # *_locked helpers whose bodies DISPATCH: their call sites need
        # _DEVICE_LOCK specifically, not just some lock — a model lock
        # alone must not smuggle a device dispatch past the gate.
        dispatching_helpers: Set[str] = set()
        for fn_node in iter_functions(mod):
            if not fn_node.name.endswith("_locked"):
                continue
            for sub in ast.walk(fn_node):
                if isinstance(sub, ast.Call) and _is_dispatch_call(
                    project, mod, sub, view
                ):
                    dispatching_helpers.add(fn_node.name)
                    break
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            # The *_locked convention, checked from the caller's side: a
            # helper that documents "caller holds the lock" in its name
            # must see the lock lexically held at its call site — the
            # DEVICE lock when the helper dispatches, any lock otherwise
            # — unless the caller is itself a *_locked helper (legal
            # delegation: ITS caller holds the lock).
            if name is not None and name.endswith("_locked"):
                if _in_locked_helper(mod, node):
                    continue
                held = held_locks(mod, node)
                if name in dispatching_helpers and "_DEVICE_LOCK" not in held:
                    out.append(
                        project.finding(
                            mod,
                            node,
                            "device-lock",
                            f"call to {name}() without _DEVICE_LOCK held — "
                            "the helper dispatches to the device, and its "
                            "_locked suffix makes THIS call site "
                            "responsible for the lock",
                        )
                    )
                elif not held:
                    out.append(
                        project.finding(
                            mod,
                            node,
                            "device-lock",
                            f"call to {name}() with no lock held — the "
                            "_locked suffix documents a caller-holds-the-"
                            "lock contract",
                        )
                    )
                continue
            why = _is_dispatch_call(project, mod, node, view)
            if why is None:
                continue
            if "_DEVICE_LOCK" in held_locks(mod, node):
                continue
            if _in_locked_helper(mod, node):
                continue  # caller holds the lock (checked at its call site)
            out.append(
                project.finding(
                    mod,
                    node,
                    "device-lock",
                    f"device dispatch outside _DEVICE_LOCK: {why}; concurrent "
                    "sharded dispatches can deadlock the backend "
                    "(daemon threading contract)",
                )
            )
    return out


@rule(
    "compile-outside-lock",
    "compile-path calls (lower/compile/aot_prime/cost_analysis) must NOT "
    "hold _DEVICE_LOCK — compiles are host work and would stall serving",
)
def _check_compile_outside_lock(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.device_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name not in _COMPILE_CALL_NAMES:
                continue
            if "_DEVICE_LOCK" not in held_locks(mod, node):
                continue
            out.append(
                project.finding(
                    mod,
                    node,
                    "compile-outside-lock",
                    f"compile-path call .{name}() under _DEVICE_LOCK: compiles "
                    "are pure host work — holding the device lock through one "
                    "stalls every live dispatch for seconds (PR 13 hardening)",
                )
            )
    return out


@rule(
    "lock-order",
    "_DEVICE_LOCK is innermost by contract; acquiring another lock under "
    "it — or inverting a lock ordering observed elsewhere — risks deadlock",
)
def _check_lock_order(project: Project) -> List[Finding]:
    out: List[Finding] = []
    # (outer, inner) → first observing (module, node); lock identities are
    # scoped per module so unrelated `self.lock`s never alias.
    pairs: Dict[Tuple[str, str], Tuple[Module, ast.AST]] = {}
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            inner_names = [
                lock_name(item.context_expr)
                for item in node.items
                if lock_name(item.context_expr) is not None
            ]
            if not inner_names:
                continue
            enclosing = held_locks(mod, node)
            for i, inner in enumerate(inner_names):
                # `with A, B:` acquires B while holding A — earlier items
                # of the same statement are part of the held stack.
                outer_stack = enclosing + inner_names[:i]
                for outer in outer_stack:
                    if outer == inner:
                        continue
                    if outer == "_DEVICE_LOCK":
                        out.append(
                            project.finding(
                                mod,
                                node,
                                "lock-order",
                                f"acquires {inner} while holding _DEVICE_LOCK; "
                                "_DEVICE_LOCK is the INNERMOST lock by contract "
                                "(after any job/model lock, never before one)",
                            )
                        )
                        continue
                    key = (f"{mod.relpath}:{outer}", f"{mod.relpath}:{inner}")
                    pairs.setdefault(key, (mod, node))
    for (outer, inner), (mod, node) in sorted(pairs.items()):
        if (inner, outer) in pairs:
            out.append(
                project.finding(
                    mod,
                    node,
                    "lock-order",
                    f"lock-order inversion: {outer.split(':')[1]} → "
                    f"{inner.split(':')[1]} here, but the opposite order is "
                    "also taken in this file — an interleaving of the two "
                    "call paths deadlocks",
                )
            )
    return out


# ---------------------------------------------------------------------------
# rule family 2: use-after-donate
# ---------------------------------------------------------------------------


def _donated_arg_names(call: ast.Call, positions: Tuple[int, ...]) -> List[str]:
    names = []
    for p in positions:
        if p < len(call.args):
            dn = dotted_name(call.args[p])
            if dn is not None:
                names.append(dn)
    return names


def _accesses(fn_node: ast.AST, dotted: str) -> List[Tuple[Tuple[int, int], str]]:
    """All ordered (position, "load"|"store") accesses to ``dotted`` in
    the function — plain names and ``self.x``-style attributes."""
    acc: List[Tuple[Tuple[int, int], str]] = []
    for node in ast.walk(fn_node):
        dn = None
        ctx = None
        if isinstance(node, ast.Name):
            dn, ctx = node.id, node.ctx
        elif isinstance(node, ast.Attribute):
            dn, ctx = dotted_name(node), node.ctx
        if dn != dotted or ctx is None:
            continue
        kind = "store" if isinstance(ctx, (ast.Store, ast.Del)) else "load"
        acc.append((node_pos(node), kind))
    acc.sort()
    return acc


def _enclosing_stmt(mod: Module, node: ast.AST) -> ast.stmt:
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.stmt):
            return anc
    return node  # pragma: no cover - a Call always sits in a statement


def _accesses_after_call(
    mod: Module, fn_node: ast.AST, call: ast.Call, dotted: str
) -> List[Tuple[Tuple[int, int], str]]:
    """Accesses to ``dotted`` that can execute AFTER the donating call,
    in execution order: the tail of the call's own statement, then the
    following-sibling statements of each enclosing block up to the
    function. Mutually exclusive branches (the ``else`` arm of the
    ``if`` the call sits in) are NOT after the call — a read there can
    never see the donated buffer dead."""
    end = node_end(call)
    stmt = _enclosing_stmt(mod, call)
    acc = [a for a in _accesses(stmt, dotted) if a[0] > end]

    def scan(stmts) -> None:
        for later in stmts:
            if isinstance(later, ast.stmt):
                acc.extend(_accesses(later, dotted))

    node: ast.AST = stmt
    while node is not fn_node:
        parent = getattr(node, "_srml_parent", None)
        if parent is None:
            break
        for fieldname, value in ast.iter_fields(parent):
            if isinstance(value, list) and node in value:
                scan(value[value.index(node) + 1:])
                # Try semantics: handlers/else/finally execute after the
                # try body; finally executes after handlers and else too.
                if isinstance(parent, ast.Try):
                    if fieldname == "body":
                        for h in parent.handlers:
                            scan(h.body)
                        scan(parent.orelse)
                        scan(parent.finalbody)
                    elif fieldname in ("orelse",):
                        scan(parent.finalbody)
                elif isinstance(parent, (ast.For, ast.While, ast.AsyncFor)):
                    if fieldname == "body":
                        scan(parent.orelse)
        if isinstance(parent, ast.ExceptHandler):
            grand = getattr(parent, "_srml_parent", None)
            if isinstance(grand, ast.Try):
                scan(grand.finalbody)
        if parent is fn_node:
            break
        node = parent
    acc.sort()
    return acc


def _assign_target_names(target: ast.AST) -> Iterator[Optional[str]]:
    """Dotted names bound by one assignment target, unpacking tuples/
    lists/starred elements (``state, n = ...``)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assign_target_names(elt)
    elif isinstance(target, ast.Starred):
        yield from _assign_target_names(target.value)
    else:
        yield dotted_name(target)


def _healed_by_own_statement(mod: Module, call: ast.Call, donated: str) -> bool:
    """``state = update(state, ...)`` — or the tuple-unpack shape
    ``state, n = update(state, ...)`` — heals the donation in the very
    statement that made it: the canonical streaming-fold shapes."""
    stmt = _enclosing_stmt(mod, call)
    if isinstance(stmt, ast.Assign):
        return any(
            name == donated
            for t in stmt.targets
            for name in _assign_target_names(t)
        )
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return dotted_name(stmt.target) == donated
    return False


@rule(
    "use-after-donate",
    "a name passed at a donate_argnums position of a ledgered jit is "
    "device-donated; reading it again before reassignment is a "
    "use-after-free of the donated buffer",
)
def _check_use_after_donate(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        view = project.jit_view(mod)
        for fn_node in iter_functions(mod):
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Call):
                    continue
                # One visit per call: nested defs are walked separately.
                if _enclosing_function(mod, node) is not fn_node:
                    continue
                resolved = view.resolve_call(node)
                if resolved is None or not resolved[0]:
                    continue
                positions = resolved[0]
                name = terminal_name(node.func)
                for donated in _donated_arg_names(node, positions):
                    if _healed_by_own_statement(mod, node, donated):
                        continue
                    later = _accesses_after_call(mod, fn_node, node, donated)
                    if later and later[0][1] == "load":
                        out.append(
                            project.finding(
                                mod,
                                node,
                                "use-after-donate",
                                f"{donated} is donated to {name}() "
                                f"(donate_argnums) but read again at line "
                                f"{later[0][0][0]} before reassignment — the "
                                "buffer no longer exists after the dispatch",
                            )
                        )
                        continue
                    # Loop-carried reuse: a donating call inside a loop
                    # whose body never rebinds the donated name re-reads
                    # the dead buffer on the next iteration.
                    loop = None
                    for anc in mod.ancestors(node):
                        if isinstance(anc, (ast.For, ast.While)):
                            loop = anc
                            break
                        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            break
                    if loop is not None:
                        stores = [
                            pos
                            for pos, kind in _accesses(loop, donated)
                            if kind == "store"
                        ]
                        if not stores:
                            out.append(
                                project.finding(
                                    mod,
                                    node,
                                    "use-after-donate",
                                    f"{donated} is donated to {name}() inside "
                                    "a loop that never rebinds it — the next "
                                    "iteration reads the donated buffer",
                                )
                            )
    return out


# ---------------------------------------------------------------------------
# rule family 3: determinism
# ---------------------------------------------------------------------------

_DICT_ITER_METHODS = frozenset(("items", "keys", "values"))


def _is_local_literal_dict(mod: Module, loop_node: ast.AST, name: str) -> bool:
    """Whether ``name`` is assigned a dict literal in the same function
    before the loop — its iteration order is then fixed by construction
    (identical on every process), not by runtime insertion history."""
    fn = _enclosing_function(mod, loop_node)
    if fn is None:
        return False
    loop_line = getattr(loop_node, "lineno", 0)
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and getattr(node, "lineno", 0) <= loop_line
            and isinstance(node.value, ast.Dict)
            and any(
                isinstance(t, ast.Name) and t.id == name for t in node.targets
            )
        ):
            return True
    return False


def _is_keyed_rebuild(node: ast.AST, gen: "ast.comprehension") -> bool:
    """``{k: f(v) for k, v in d.items()}`` — a key-addressed dict→dict
    rebuild, not a fold: the result is consumed by key, and any later
    ORDERED iteration of it gets its own finding at that site."""
    if not isinstance(node, ast.DictComp):
        return False
    tgt = gen.target
    if isinstance(tgt, ast.Tuple) and tgt.elts and isinstance(tgt.elts[0], ast.Name):
        return (
            isinstance(node.key, ast.Name) and node.key.id == tgt.elts[0].id
        )
    return False


@rule(
    "unsorted-iter",
    "iterating an un-sorted() dict/set in the bitwise-contract modules "
    "(ops/, models/, parallel/, daemon fold/merge paths) makes fold order "
    "process-dependent — the PR 7 unsorted-fold class",
)
def _check_unsorted_iter(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        iters: List[Tuple[ast.AST, ast.AST, Optional[ast.comprehension]]] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append((node, node.iter, None))
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                for gen in node.generators:
                    iters.append((node, gen.iter, gen))
        for node, it, gen in iters:
            if not project.bitwise_scope(mod, node):
                continue
            what = None
            if isinstance(it, ast.Call):
                fn = it.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _DICT_ITER_METHODS
                    and not it.args
                ):
                    what = f".{fn.attr}()"
                    base = fn.value
                    if isinstance(base, ast.Name) and _is_local_literal_dict(
                        mod, node, base.id
                    ):
                        continue  # literal-ordered by construction
                elif isinstance(fn, ast.Name) and fn.id == "set":
                    what = "set(...)"
            elif isinstance(it, ast.Set):
                what = "a set literal"
            if what is None:
                continue
            if gen is not None and _is_keyed_rebuild(node, gen):
                continue
            out.append(
                project.finding(
                    mod,
                    node,
                    "unsorted-iter",
                    f"iterates {what} without sorted() on a bitwise-contract "
                    "path — insertion/hash order varies across processes, so "
                    "the fold is not reproducible; wrap the iterable in "
                    "sorted()",
                )
            )
    return out


_SEEDED_RNG_CTORS = frozenset(
    ("default_rng", "Generator", "RandomState", "SeedSequence", "PRNGKey", "key")
)


@rule(
    "wallclock-entropy",
    "time.time / random.* / unseeded np.random.* in the bitwise-contract "
    "modules injects wall-clock or global-RNG entropy into paths that must "
    "be bitwise-reproducible",
)
def _check_wallclock_entropy(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            if not project.bitwise_scope(mod, node):
                continue
            parts = dn.split(".")
            bad = None
            if dn == "time.time":
                bad = "time.time() is wall-clock entropy"
            elif parts[0] == "random" and len(parts) > 1:
                bad = f"{dn}() draws from the global stdlib RNG"
            elif (
                len(parts) >= 3
                and parts[-2] == "random"
                and parts[0] in ("np", "numpy")
                and parts[-1] not in _SEEDED_RNG_CTORS
            ):
                bad = f"{dn}() draws from the global numpy RNG"
            if bad is None:
                continue
            out.append(
                project.finding(
                    mod,
                    node,
                    "wallclock-entropy",
                    f"{bad} on a bitwise-contract path; thread a seeded "
                    "np.random.default_rng(seed) (or jax.random key) through "
                    "instead",
                )
            )
    return out


# ---------------------------------------------------------------------------
# rule family 4: wire contract
# ---------------------------------------------------------------------------


def collect_dispatched_ops(mod: Module) -> Dict[str, int]:
    """op strings the daemon dispatches on: ``op == "x"`` comparisons and
    ``op in ("x", "y")`` membership tests against a name ending in "op",
    with constant folding so concatenation/f-strings can't dodge."""
    ops: Dict[str, int] = {}

    def is_op_name(e: ast.AST) -> bool:
        tn = terminal_name(e)
        return tn is not None and (tn == "op" or tn.endswith("_op"))

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Compare):
            continue
        operands = [node.left, *node.comparators]
        if not any(is_op_name(o) for o in operands):
            continue
        for o, cmp_op in zip(operands[1:], node.ops):
            if isinstance(cmp_op, (ast.Eq, ast.NotEq)):
                s = const_str(o)
                if s is None and is_op_name(o):
                    s = const_str(node.left)
                if s is not None:
                    ops.setdefault(s, node.lineno)
            elif isinstance(cmp_op, (ast.In, ast.NotIn)) and isinstance(
                o, (ast.Tuple, ast.List, ast.Set)
            ):
                for elt in o.elts:
                    s = const_str(elt)
                    if s is not None:
                        ops.setdefault(s, node.lineno)
    return ops


def collect_known_ops(mod: Module) -> Optional[Set[str]]:
    """The ``_KNOWN_OPS = frozenset((...))`` clamp literal, AST-parsed."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(terminal_name(t) == "_KNOWN_OPS" for t in node.targets):
            continue
        known: Set[str] = set()
        for sub in ast.walk(node.value):
            s = const_str(sub)
            if s is not None:
                known.add(s)
        return known
    return None


@rule(
    "wire-op-clamp",
    "every op string the daemon dispatches must appear in _KNOWN_OPS (the "
    "metrics-label clamp) and docs/protocol.md (the frozen wire contract)",
)
def _check_wire_op_clamp(project: Project) -> List[Finding]:
    out: List[Finding] = []
    daemons = [m for m in project.modules if m.relpath == "serve/daemon.py"]
    for mod in daemons:
        dispatched = collect_dispatched_ops(mod)
        known = collect_known_ops(mod)
        if project.strict_floors and len(dispatched) < 15:
            out.append(
                Finding(
                    "wire-op-clamp",
                    mod.display_path,
                    1,
                    "<module>",
                    f"only {len(dispatched)} dispatched ops found — the "
                    "dispatch shape or the op collector regressed",
                )
            )
        if known is None:
            out.append(
                Finding(
                    "wire-op-clamp",
                    mod.display_path,
                    1,
                    "<module>",
                    "_KNOWN_OPS frozenset literal not found in serve/daemon.py",
                )
            )
            continue
        for op, line in sorted(dispatched.items()):
            if op not in known:
                out.append(
                    Finding(
                        "wire-op-clamp",
                        mod.display_path,
                        line,
                        "<module>",
                        f'op "{op}" is dispatched but missing from the '
                        "_KNOWN_OPS metrics-label clamp (its telemetry would "
                        'record under op="unknown")',
                    )
                )
            if project.protocol_doc is not None and not re.search(
                rf"\b{re.escape(op)}\b", project.protocol_doc
            ):
                out.append(
                    Finding(
                        "wire-op-clamp",
                        mod.display_path,
                        line,
                        "<module>",
                        f'op "{op}" is dispatched but absent from '
                        "docs/protocol.md (the frozen wire contract)",
                    )
                )
    return out


def collect_ack_fields(mod: Module) -> Set[str]:
    """Constant ack-dict field names the daemon answers with: keys of the
    dict passed to ``send_json`` (arg 1) / ``_send_arrays_counted``
    (arg 3) — inline literals AND acks built in a local variable first
    (its dict-literal assignment and ``payload["k"] = ...`` grows in the
    same function are resolved) — plus ``**helper()`` expansions resolved
    one level into same-module helper returns. Subscript stores on
    UNRELATED dicts in the same function are deliberately not counted:
    over-collection would mask a removed ack field behind any
    identically-named key (the gate must err toward reporting)."""
    # def name → constant keys of returned dict literals (for ** resolution)
    returns: Dict[str, Set[str]] = {}
    for fn_node in iter_functions(mod):
        keys: Set[str] = set()
        for ret in ast.walk(fn_node):
            if isinstance(ret, ast.Return) and isinstance(ret.value, ast.Dict):
                for k in ret.value.keys:
                    s = const_str(k) if k is not None else None
                    if s is not None:
                        keys.add(s)
        if keys:
            returns.setdefault(fn_node.name, set()).update(keys)

    fields: Set[str] = set()

    def scrape_dict(d: ast.Dict) -> None:
        for k, v in zip(d.keys, d.values):
            if k is None:  # ** expansion
                if isinstance(v, ast.Call):
                    helper = terminal_name(v.func)
                    fields.update(returns.get(helper, set()))
                continue
            s = const_str(k)
            if s is not None:
                fields.add(s)

    def scrape_ack_arg(arg: ast.AST, sender: Optional[ast.AST]) -> None:
        if isinstance(arg, ast.Dict):
            scrape_dict(arg)
            return
        if not isinstance(arg, ast.Name) or sender is None:
            return
        # Ack built in a local first: scrape its dict-literal assignment
        # and every constant subscript-store on THAT name.
        for node in ast.walk(sender):
            if isinstance(node, ast.Assign):
                if (
                    any(
                        isinstance(t, ast.Name) and t.id == arg.id
                        for t in node.targets
                    )
                    and isinstance(node.value, ast.Dict)
                ):
                    scrape_dict(node.value)
                elif (
                    len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == arg.id
                ):
                    s = const_str(node.targets[0].slice)
                    if s is not None:
                        fields.add(s)

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = terminal_name(node.func)
        if name == "send_json" and len(node.args) >= 2:
            scrape_ack_arg(node.args[1], _enclosing_function(mod, node))
        elif name == "_send_arrays_counted" and len(node.args) >= 4:
            scrape_ack_arg(node.args[3], _enclosing_function(mod, node))
    return fields


@rule(
    "ack-contract",
    "ack-dict fields are an additive wire contract: a field in the "
    "checked-in snapshot (tools/analyze_contract.json) may never disappear "
    "from the daemon's answers",
)
def _check_ack_contract(project: Project) -> List[Finding]:
    out: List[Finding] = []
    if project.contract is None:
        return out
    want = set(project.contract.get("ack_fields", []))
    daemons = [m for m in project.modules if m.relpath == "serve/daemon.py"]
    if not daemons:
        return out
    have: Set[str] = set()
    for mod in daemons:
        have |= collect_ack_fields(mod)
    for fieldname in sorted(want - have):
        out.append(
            Finding(
                "ack-contract",
                daemons[0].display_path,
                1,
                "<module>",
                f'ack field "{fieldname}" is in the wire-contract snapshot '
                "but no longer answered by the daemon — ack fields may only "
                "be ADDED (clients key on them); restore it or version the "
                "protocol",
            )
        )
    new = sorted(have - want)
    if new:
        project.notes.append(
            "new ack field(s) not yet in tools/analyze_contract.json "
            f"(additive, allowed): {', '.join(new)} — run "
            "`python -m spark_rapids_ml_tpu.tools.analyze --write-contract`"
        )
    return out


# ---------------------------------------------------------------------------
# ported regex gates (the engine's first rules)
# ---------------------------------------------------------------------------


@rule(
    "bare-print",
    "library code logs through the package logger, never print() — stdout "
    "belongs to the host application (and Spark's worker protocol); "
    "tools/ and `if __name__ == '__main__'` tails are exempt",
)
def _check_bare_print(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        if mod.relpath.split("/", 1)[0] == "tools":
            continue
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                if in_main_guard(mod, node):
                    continue
                out.append(
                    project.finding(
                        mod,
                        node,
                        "bare-print",
                        "bare print() in library code — use the package "
                        "logger (utils/logging.py) or record a metric",
                    )
                )
    return out


_COLLECTIVES = frozenset(
    ("psum", "pmean", "all_gather", "ppermute", "psum_scatter", "all_to_all")
)


@rule(
    "bare-collective",
    "device collectives go through parallel/mapreduce.py — a bare "
    "lax.psum/all_gather outside parallel/ bypasses the collective-trace "
    "booking that audits ICI/DCN movement (docs/mesh.md)",
)
def _check_bare_collective(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        if mod.relpath.split("/", 1)[0] == "parallel":
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _COLLECTIVES
                and terminal_name(fn.value) == "lax"
            ):
                out.append(
                    project.finding(
                        mod,
                        node,
                        "bare-collective",
                        f"bare collective lax.{fn.attr}() outside parallel/ "
                        "— route it through parallel.mapreduce so the "
                        "collective-trace accounting sees it",
                    )
                )
    return out


@rule(
    "socket-timeout",
    "socket.create_connection without an explicit timeout inherits the "
    "global default (None = block forever); one unreachable daemon would "
    "hang its caller instead of failing into the retry/healing path",
)
def _check_socket_timeout(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) != "socket.create_connection" and not (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "create_connection"
                and terminal_name(node.func.value) == "socket"
            ):
                continue
            has_timeout = len(node.args) >= 2 or any(
                kw.arg == "timeout" or kw.arg is None for kw in node.keywords
            )
            if not has_timeout:
                out.append(
                    project.finding(
                        mod,
                        node,
                        "socket-timeout",
                        "socket.create_connection without an explicit "
                        "timeout= — the default (None) blocks forever on an "
                        "unreachable peer",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def rewrite_baseline(
    project: Project,
    old: Optional[Baseline],
    new_findings: Sequence[Finding],
    selected_rules: Optional[Sequence[str]] = None,
) -> Baseline:
    """The --write-baseline merge: this run's new findings become
    accepted, still-live accepted entries keep their MATCHED counts
    (stale ones fall off — the ratchet), and entries a restricted run
    never evaluated (``--rule`` not selecting them, or a path filter
    excluding their file) are preserved verbatim — a partial run must
    not silently un-accept what it did not look at."""
    merged = Baseline.from_findings(new_findings)
    if old is None:
        return merged
    selected = set(selected_rules) if selected_rules else None
    known_files = {m.display_path for m in project.modules}
    for key, cap in old.entries.items():
        rule_id, file_, _sym = key
        if (
            (selected is not None and rule_id not in selected)
            or file_ not in known_files
            or not project.in_report_scope(file_)
        ):
            merged.entries[key] = merged.entries.get(key, 0) + cap
        else:
            used = old._matched.get(key, 0)
            if used:
                merged.entries[key] = merged.entries.get(key, 0) + used
    return merged


def write_contract(project: Project, path: Path = CONTRACT_PATH) -> Dict[str, Any]:
    fields: Set[str] = set()
    for mod in project.modules:
        if mod.relpath == "serve/daemon.py":
            fields |= collect_ack_fields(mod)
    contract = {"version": 1, "ack_fields": sorted(fields)}
    path.write_text(json.dumps(contract, indent=2) + "\n")
    return contract


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m spark_rapids_ml_tpu.tools.analyze",
        description="srml-check: AST invariant analyzer for the "
        "lock/donation/determinism/wire contracts",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="package-relative paths to restrict REPORTING to (e.g. "
        "'serve' or 'ops/gram.py'); the whole package is always parsed "
        "for cross-module context. Default: report everything",
    )
    parser.add_argument("--json", action="store_true", help="machine output")
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE",
        help="run only this rule (repeatable)",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=BASELINE_PATH,
        help="baseline JSON path (default: tools/analyze_baseline.json)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept the current unsuppressed findings into the baseline",
    )
    parser.add_argument(
        "--write-contract",
        action="store_true",
        help="refresh the ack-field wire-contract snapshot",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid:22s} {RULES[rid].summary}")
        return 0

    try:
        project = Project.from_package(paths=args.paths or None)
    except SyntaxError as e:
        print(f"srml-check: cannot parse {e.filename}:{e.lineno}: {e.msg}", file=sys.stderr)
        return 2

    if args.write_contract:
        contract = write_contract(project)
        print(
            f"wrote {CONTRACT_PATH} ({len(contract['ack_fields'])} ack fields)"
        )
        project.contract = contract

    baseline = None if args.no_baseline else Baseline.load(args.baseline)
    try:
        findings = project.run(rules=args.rules, baseline=baseline)
    except KeyError as e:
        print(f"srml-check: {e.args[0]}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # run() already consumed the old baseline, so `findings` are
        # exactly the NEW ones; rewrite_baseline keeps still-live accepted
        # entries (and preserves what a --rule/path-restricted run never
        # evaluated), dropping only the stale.
        merged = rewrite_baseline(project, baseline, findings, args.rules)
        args.baseline.write_text(merged.as_json())
        print(f"wrote {args.baseline} ({sum(merged.entries.values())} accepted findings)")
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.as_dict() for f in findings],
                    "notes": project.notes,
                    "rules": sorted(args.rules or RULES),
                    "ok": not findings,
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.format())
        for note in project.notes:
            print(f"note: {note}", file=sys.stderr)
        if not findings:
            n = len(args.rules) if args.rules else len(RULES)
            print(
                f"srml-check: OK — {len(project.modules)} files, {n} rules, "
                "zero unsuppressed findings"
            )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
