"""PCA.transform p50 latency — the second BASELINE.json headline metric.

The reference's transform re-uploads the PC matrix host→device on every
batch (rapidsml_jni.cu:85 — flagged in SURVEY.md §3.2 as the optimization
target); here the PC matrix is device-resident across batches and the
per-batch work is one (batch, d) × (d, k) MXU GEMM.

Baseline: an A100 cuML batch transform at 65536×2048 × 2048×32 is ~8.6
GFLOP ≈ 0.08 ms of GEMM plus per-batch PC upload (~0.25 ms for 0.5 MB
over PCIe effective ~2 GB/s with launch overhead) ≈ 0.35 ms. vs_baseline =
baseline_p50 / our_p50 (higher is better, >1 beats the A100 path).

Measurement notes (so the number stays comparable across rounds): the
measured path is this framework's quantize-on-ingest design — bf16 inputs,
f32 accumulation — against the reference's f32 path; the dtype is in the
metric name. The p50 is the per-batch *device* latency via slope_dt, which
subtracts the dev tunnel's fixed ~90 ms host round-trip (a harness
artifact, not TPU serving cost); the A100 baseline's per-batch PC upload is
kept in the baseline because eliminating it (device-resident PC) is a real
architectural difference, not a harness one.
"""

import os
import sys

if __package__ in (None, ""):  # direct script run: python benchmarks/bench_*.py
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

BASELINE_P50_MS = 0.35

D = int(os.environ.get("SRML_BENCH_D", 2048))
K = int(os.environ.get("SRML_BENCH_K", 32))
BATCH = int(os.environ.get("SRML_BENCH_BATCH_ROWS", 65536))
CALLS = int(os.environ.get("SRML_BENCH_CALLS", 200))


def main() -> None:
    from benchmarks import setup_platform

    setup_platform()
    import jax
    import jax.numpy as jnp

    from benchmarks import emit

    rng = np.random.default_rng(0)
    # Ingest-cast to bfloat16 (the framework's quantize-on-ingest design):
    # the batch GEMM is HBM-bound at these shapes, so halving the bytes
    # halves the latency; accumulation stays float32.
    pc = jnp.asarray(rng.normal(size=(D, K)), dtype=jnp.bfloat16)
    x = jnp.asarray(rng.normal(size=(BATCH, D)), dtype=jnp.bfloat16)

    @jax.jit
    def transform(pc, x):
        return jax.lax.dot_general(
            x, pc, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    # Per-batch device latency via the two-point slope: chained batches in
    # one sync window, so the tunnel's fixed ~90 ms host round-trip (a dev
    # harness artifact, not TPU serving latency) cancels out of the p50.
    from benchmarks import slope_dt, sync

    def run(n):
        out = None
        for _ in range(n):
            out = transform(pc, x)
        sync(out)
        return out

    run(CALLS)  # warm / compile both sizes once, outside the sample loop
    run(2 * CALLS)
    lat = [slope_dt(run, CALLS, 2 * CALLS, warm=False) * 1e3 for _ in range(9)]
    p50 = float(np.percentile(lat, 50))
    emit(
        f"pca_transform_p50_ms_batch{BATCH}_d{D}_k{K}_bf16",
        p50,
        "ms",
        BASELINE_P50_MS / p50,
    )


if __name__ == "__main__":
    main()
