"""Pipeline / tuning / evaluation / StandardScaler — Spark ML API parity."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    BinaryClassificationEvaluator,
    CrossValidator,
    LinearRegression,
    LogisticRegression,
    MulticlassClassificationEvaluator,
    ParamGridBuilder,
    PCA,
    Pipeline,
    PipelineModel,
    RegressionEvaluator,
    StandardScaler,
    StandardScalerModel,
    TrainValidationSplit,
)


@pytest.fixture
def reg_data(rng):
    n, d = 400, 8
    x = rng.normal(size=(n, d))
    w = rng.normal(size=(d,))
    y = x @ w + 0.25 + 0.01 * rng.normal(size=(n,))
    return {"features": x.astype(np.float32), "label": y}


# --------------------------- StandardScaler --------------------------------


def test_scaler_matches_numpy(rng, mesh8):
    x = rng.normal(size=(300, 6)) * 5 + 3
    ds = {"features": x.astype(np.float32)}
    model = StandardScaler(mesh=mesh8).setWithMean(True).setWithStd(True).fit(ds)
    out = model.transform(ds)["scaled_features"]
    ref = (x - x.mean(0)) / x.std(0, ddof=1)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
    # Spark defaults: withMean=False, withStd=True
    m2 = StandardScaler(mesh=mesh8).fit(ds)
    out2 = m2.transform(ds)["scaled_features"]
    np.testing.assert_allclose(out2, x / x.std(0, ddof=1), rtol=1e-4, atol=1e-4)


def test_scaler_zero_variance_feature(rng, mesh8):
    x = rng.normal(size=(50, 3)).astype(np.float32)
    x[:, 1] = 7.0  # constant feature
    model = StandardScaler(mesh=mesh8).setWithMean(True).fit({"features": x})
    out = model.transform({"features": x})["scaled_features"]
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out[:, 1], 0.0, atol=1e-6)  # MLlib: scales by 0


def test_scaler_persistence(rng, mesh8, tmp_path):
    x = rng.normal(size=(60, 4)).astype(np.float32)
    model = StandardScaler(mesh=mesh8).setWithMean(True).fit({"features": x})
    path = str(tmp_path / "scaler")
    model.save(path)
    loaded = StandardScalerModel.load(path)
    np.testing.assert_allclose(loaded.mean, model.mean)
    np.testing.assert_allclose(loaded.std, model.std)
    assert loaded.getWithMean() is True


# ------------------------------ Pipeline -----------------------------------


def test_pipeline_scaler_then_pca(rng, mesh8):
    x = (rng.normal(size=(200, 10)) * rng.uniform(1, 9, size=10)).astype(np.float32)
    ds = {"features": x}
    pipe = Pipeline(stages=[
        StandardScaler(mesh=mesh8).setWithMean(True).setOutputCol("scaled"),
        PCA(mesh=mesh8).setInputCol("scaled").setK(3).setOutputCol("pca"),
    ])
    pm = pipe.fit(ds)
    out = pm.transform(ds)
    assert out["pca"].shape == (200, 3)
    # Same result as manual staging.
    scaled = pm.stages[0].transform(ds)
    manual = pm.stages[1].transform(scaled)["pca"]
    np.testing.assert_allclose(out["pca"], manual, atol=1e-6)


def test_pipeline_rejects_non_stage():
    with pytest.raises(TypeError, match="neither"):
        Pipeline(stages=[object()]).fit({"features": np.zeros((4, 2), np.float32)})


def test_pipeline_persistence(rng, mesh8, tmp_path):
    x = rng.normal(size=(100, 6)).astype(np.float32)
    ds = {"features": x}
    pipe = Pipeline(stages=[
        StandardScaler(mesh=mesh8).setWithMean(True).setOutputCol("scaled"),
        PCA(mesh=mesh8).setInputCol("scaled").setK(2).setOutputCol("pca"),
    ])
    pm = pipe.fit(ds)
    path = str(tmp_path / "pm")
    pm.save(path)
    loaded = PipelineModel.load(path)
    assert [type(s).__name__ for s in loaded.stages] == [
        "StandardScalerModel", "PCAModel",
    ]
    np.testing.assert_allclose(
        loaded.transform(ds)["pca"], pm.transform(ds)["pca"], atol=1e-6
    )


# --------------------------- ParamGridBuilder ------------------------------


def test_param_grid_builder():
    lr = LinearRegression()
    grid = (
        ParamGridBuilder()
        .baseOn((lr.getParam("fitIntercept"), True))
        .addGrid(lr.getParam("regParam"), [0.0, 0.1, 1.0])
        .addGrid(lr.getParam("maxIter"), [5, 10])
        .build()
    )
    assert len(grid) == 6
    for m in grid:
        assert m[lr.getParam("fitIntercept")] is True
    reg_values = {m[lr.getParam("regParam")] for m in grid}
    assert reg_values == {0.0, 0.1, 1.0}


# ------------------------------ Evaluators ---------------------------------


def test_regression_evaluator():
    ds = {"label": np.array([1.0, 2.0, 3.0]), "prediction": np.array([1.5, 2.0, 2.5])}
    ev = RegressionEvaluator()
    assert ev.evaluate(ds) == pytest.approx(np.sqrt(np.mean([0.25, 0.0, 0.25])))
    assert not ev.isLargerBetter()
    assert ev.setMetricName("mae").evaluate(ds) == pytest.approx(1.0 / 3)
    ev2 = RegressionEvaluator().setMetricName("r2")
    assert ev2.isLargerBetter()
    perfect = {"label": ds["label"], "prediction": ds["label"]}
    assert ev2.evaluate(perfect) == pytest.approx(1.0)


def test_binary_evaluator_auc():
    # Perfect separation -> AUC 1; anti-separation -> 0; random-ish in between.
    y = np.array([0, 0, 1, 1], float)
    ev = BinaryClassificationEvaluator()
    assert ev.evaluate({"label": y, "prediction": np.array([0.1, 0.2, 0.8, 0.9])}) == 1.0
    assert ev.evaluate({"label": y, "prediction": np.array([0.9, 0.8, 0.2, 0.1])}) == 0.0
    # Ties take midranks: all-equal scores -> 0.5.
    assert ev.evaluate({"label": y, "prediction": np.full(4, 0.5)}) == pytest.approx(0.5)


def test_binary_evaluator_uses_raw_prediction(rng, mesh8):
    # LogReg transform now emits rawPrediction/probability; the evaluator's
    # default reads the rawPrediction vector (positive-class margin), giving
    # a real threshold-sweep AUC rather than the hard-label one.
    n, d = 400, 5
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d,))
    y = (x @ w + 0.5 * rng.normal(size=n) > 0).astype(np.float64)
    ds = {"features": x, "label": y}
    model = LogisticRegression(mesh=mesh8).setMaxIter(25).fit(ds)
    out = model.transform(ds)
    assert out["rawPrediction"].shape == (n, 2)
    assert out["probability"].shape == (n, 2)
    np.testing.assert_allclose(out["probability"].sum(axis=1), 1.0, atol=1e-12)
    # rawPrediction[:, 1] is the log-odds; softmax of raw == probability.
    np.testing.assert_allclose(
        1 / (1 + np.exp(-out["rawPrediction"][:, 1])),
        out["probability"][:, 1],
        atol=1e-12,
    )
    auc_raw = BinaryClassificationEvaluator().evaluate(out)
    hard_only = {"label": y, "prediction": out["prediction"].astype(np.float64)}
    auc_hard = BinaryClassificationEvaluator().evaluate(hard_only)
    assert auc_raw > 0.8
    # The score-based AUC is at least as informative as the one-threshold AUC
    # and generally differs from it (it sweeps thresholds).
    assert auc_raw >= auc_hard - 1e-9


def test_multiclass_evaluator():
    ds = {"label": np.array([0, 1, 2, 1.0]), "prediction": np.array([0, 1, 1, 1.0])}
    ev = MulticlassClassificationEvaluator()
    assert ev.evaluate(ds) == pytest.approx(0.75)
    f1 = ev.setMetricName("f1").evaluate(ds)
    assert 0.0 < f1 < 1.0


# ---------------------------- CrossValidator -------------------------------


def test_cross_validator_picks_better_reg(reg_data, mesh8):
    lr = LinearRegression(mesh=mesh8)
    grid = (
        ParamGridBuilder()
        .addGrid(lr.getParam("regParam"), [0.0, 100.0])  # 100.0 badly underfits
        .build()
    )
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(), numFolds=3, seed=7,
    )
    cvm = cv.fit(reg_data)
    assert len(cvm.avgMetrics) == 2
    assert cvm.avgMetrics[0] < cvm.avgMetrics[1]  # rmse: lower is better
    assert cvm.bestModel.getOrDefault(cvm.bestModel.getParam("regParam")) == 0.0
    out = cvm.transform(reg_data)
    assert "prediction" in out


def test_copy_extra_keys_by_parent_uid():
    """Param-keyed extras apply by (parent uid, name), like Spark ParamMaps.

    Regression: a grid keyed on one estimator's maxIter must not set the
    same-named param on an unrelated estimator, and 'k' must not collide
    between PCA and KMeans when both sit in one Pipeline.
    """
    from spark_rapids_ml_tpu import KMeans

    lr = LinearRegression().setMaxIter(7)
    km = KMeans().setMaxIter(11)
    # Extra keyed on lr.maxIter: applies to lr copies only.
    lr2 = lr.copy({lr.getParam("maxIter"): 99})
    km2 = km.copy({lr.getParam("maxIter"): 99})
    assert lr2.getMaxIter() == 99
    assert km2.getMaxIter() == 11
    # Same-class different-instance is also skipped (Spark strictness).
    other = LinearRegression()
    lr3 = lr.copy({other.getParam("maxIter"): 55})
    assert lr3.getMaxIter() == 7
    # Through a Pipeline: extras reach exactly the stage they were keyed on.
    pca, km4 = PCA().setK(3), KMeans().setK(8)
    pipe = Pipeline(stages=[pca, km4])
    tuned = pipe.copy({km4.getParam("k"): 5})
    assert tuned.getStages()[0].getK() == 3
    assert tuned.getStages()[1].getK() == 5


def test_cross_validator_validation():
    lr = LinearRegression()
    cv = CrossValidator(estimator=lr, evaluator=RegressionEvaluator(), numFolds=1)
    with pytest.raises(ValueError, match="numFolds"):
        cv.fit({"features": np.zeros((10, 2), np.float32), "label": np.zeros(10)})
    with pytest.raises(ValueError, match="estimator and evaluator"):
        CrossValidator(estimator=lr).fit({"features": np.zeros((10, 2), np.float32)})


def test_tuned_model_persistence(reg_data, mesh8, tmp_path):
    from spark_rapids_ml_tpu import CrossValidatorModel, TrainValidationSplitModel

    lr = LinearRegression()
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [0.0, 10.0]).build()
    cv = CrossValidator(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(), numFolds=2, seed=3,
    )
    model = cv.fit(reg_data)
    path = str(tmp_path / "cvm")
    model.save(path)
    loaded = CrossValidatorModel.load(path)
    assert loaded.uid == model.uid
    assert loaded.avgMetrics == pytest.approx(model.avgMetrics)
    np.testing.assert_allclose(
        loaded.bestModel.coefficients, model.bestModel.coefficients
    )
    out = loaded.transform(reg_data)
    np.testing.assert_allclose(
        out["prediction"], model.transform(reg_data)["prediction"]
    )

    tvs = TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=RegressionEvaluator(), trainRatio=0.7, seed=3,
    )
    tmodel = tvs.fit(reg_data)
    tpath = str(tmp_path / "tvsm")
    tmodel.save(tpath)
    tloaded = TrainValidationSplitModel.load(tpath)
    assert tloaded.uid == tmodel.uid
    assert tloaded.validationMetrics == pytest.approx(tmodel.validationMetrics)
    np.testing.assert_allclose(
        tloaded.bestModel.coefficients, tmodel.bestModel.coefficients
    )


def test_train_validation_split_logreg(rng, mesh8):
    n, d = 600, 6
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d,))
    y = (x @ w + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    ds = {"features": x, "label": y}
    lr = LogisticRegression(mesh=mesh8).setMaxIter(25)
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"), [1e-4, 50.0]).build()
    tvs = TrainValidationSplit(
        estimator=lr, estimatorParamMaps=grid,
        evaluator=MulticlassClassificationEvaluator(), trainRatio=0.75, seed=1,
    )
    model = tvs.fit(ds)
    assert len(model.validationMetrics) == 2
    # The tiny-reg fit must beat the crushed one on accuracy.
    assert model.validationMetrics[0] > model.validationMetrics[1]
    acc = np.mean(model.transform(ds)["prediction"] == y)
    assert acc > 0.9
