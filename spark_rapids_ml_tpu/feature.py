"""Drop-in feature-transformer namespace.

The reference's public entry point is ``com.nvidia.spark.ml.feature.PCA``
(reference PCA.scala:27-37) — a thin alias namespace so user code changes
only the import. This module is the same shim for Python:

    from spark_rapids_ml_tpu.feature import PCA
"""

from spark_rapids_ml_tpu.models.pca import PCA, PCAModel
from spark_rapids_ml_tpu.models.scaler import StandardScaler, StandardScalerModel

__all__ = ["PCA", "PCAModel", "StandardScaler", "StandardScalerModel"]
