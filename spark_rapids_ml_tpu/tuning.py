"""Model selection — pyspark.ml.tuning equivalents.

``ParamGridBuilder`` / ``CrossValidator`` / ``TrainValidationSplit`` with
Spark's semantics: the grid is a list of param maps; each candidate is
evaluated with the caller's Evaluator; the best configuration is re-fit on
the FULL dataset. Fold assignment is a seeded permutation of row indices
(``df.randomSplit`` analogue) over the host dataset abstraction
(core.dataset.take_rows), so any container kind works.

TPU note: candidates are fitted sequentially — each fit already owns the
whole device mesh (the parallelism axis Spark's ``parallelism`` param
exploits is occupied by data parallelism here), and jit caching makes
same-shape refits cheap.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import os

from spark_rapids_ml_tpu.core.dataset import num_rows, take_rows
from spark_rapids_ml_tpu.core.params import (
    Estimator,
    HasSeed,
    Model,
    Param,
    ParamDecl,
    TypeConverters,
)
from spark_rapids_ml_tpu.core.persistence import (
    DefaultParamsReader,
    DefaultParamsWriter,
    MLReadable,
    MLWritable,
)
from spark_rapids_ml_tpu.evaluation import Evaluator


class _TunedModelPersistence(MLWritable, MLReadable):
    """Nested save/load for tuned models, mirroring the Pipeline layout
    (pipeline.py::_StagesMixin): metrics ride the metadata JSON; the best
    model is persisted via its own writer under ``bestModel/``. Spark's
    CrossValidatorModel/TrainValidationSplitModel are MLWritable the same
    way (metadata + nested bestModel path)."""

    _metrics_attr = "avgMetrics"  # subclass overrides

    def save(self, path: str) -> None:
        # Validate BEFORE touching the filesystem: a failed save must not
        # leave a partial directory that blocks every retry.
        if self.bestModel is None:
            raise ValueError("cannot save a tuned model with no bestModel")
        if not isinstance(self.bestModel, MLWritable):
            raise TypeError(f"bestModel {self.bestModel.uid} is not MLWritable")
        if os.path.exists(path):
            raise FileExistsError(f"path {path} already exists")
        os.makedirs(path)
        try:
            DefaultParamsWriter.save_metadata(
                self, path,
                extra={self._metrics_attr: list(getattr(self, self._metrics_attr))},
            )
            self.bestModel.save(os.path.join(path, "bestModel"))
        except BaseException:
            # A nested-writer failure (e.g. a non-MLWritable Pipeline
            # stage) must not leave a partial directory that blocks every
            # retry with FileExistsError.
            import shutil

            shutil.rmtree(path, ignore_errors=True)
            raise

    @classmethod
    def load(cls, path: str):
        meta = DefaultParamsReader.load_metadata(path)
        best = DefaultParamsReader.load_instance(os.path.join(path, "bestModel"))
        obj = cls(bestModel=best)
        obj.uid = meta["uid"]
        setattr(obj, cls._metrics_attr, list(meta.get(cls._metrics_attr, [])))
        for name, value in meta.get("defaultParamMap", {}).items():
            if obj.hasParam(name):
                obj.setDefault(**{name: value})
        for name, value in meta.get("paramMap", {}).items():
            if obj.hasParam(name):
                obj._set(**{name: value})
        return obj


class ParamGridBuilder:
    """Cartesian grid of param maps (pyspark.ml.tuning.ParamGridBuilder)."""

    def __init__(self):
        self._grid: Dict[Param, Sequence] = {}
        self._base: Dict[Param, object] = {}

    def baseOn(self, *args) -> "ParamGridBuilder":
        if len(args) == 1 and isinstance(args[0], dict):
            self._base.update(args[0])
        else:
            for param, value in args:
                self._base[param] = value
        return self

    def addGrid(self, param: Param, values: Sequence) -> "ParamGridBuilder":
        if not isinstance(param, Param):
            raise TypeError(f"addGrid expects a Param, got {type(param).__name__}")
        self._grid[param] = list(values)
        return self

    def build(self) -> List[Dict[Param, object]]:
        maps = [dict(self._base)]
        for param, values in self._grid.items():
            maps = [{**m, param: v} for m in maps for v in values]
        return maps


class _ValidatorParams(HasSeed):
    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 uid=None):
        super().__init__(uid=uid)
        self._est = estimator
        self._maps = list(estimatorParamMaps or [{}])
        self._eval = evaluator

    def setEstimator(self, est: Estimator):
        self._est = est
        return self

    def setEstimatorParamMaps(self, maps):
        self._maps = list(maps)
        return self

    def setEvaluator(self, ev: Evaluator):
        self._eval = ev
        return self

    def getEstimator(self) -> Estimator:
        return self._est

    def getEstimatorParamMaps(self):
        return list(self._maps)

    def getEvaluator(self) -> Evaluator:
        return self._eval

    def _copy_extra_state(self, source):
        self._est = getattr(source, "_est", None)
        self._maps = list(getattr(source, "_maps", [{}]))
        self._eval = getattr(source, "_eval", None)

    def _check(self):
        if self._est is None or self._eval is None:
            raise ValueError("estimator and evaluator must both be set")

    def _fit_and_eval(self, train, val) -> List[float]:
        metrics = []
        for pmap in self._maps:
            model = self._est.fit(train, params=pmap or None)
            metrics.append(float(self._eval.evaluate(model.transform(val))))
        return metrics

    def _best_index(self, avg: np.ndarray) -> int:
        return int(np.argmax(avg) if self._eval.isLargerBetter() else np.argmin(avg))


class CrossValidator(Estimator, _ValidatorParams):
    """k-fold CV over the param grid; best map re-fit on the full data."""

    _uid_prefix = "CrossValidator"
    numFolds = ParamDecl(
        "numFolds", "number of folds (>= 2)", TypeConverters.toInt,
    )

    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 numFolds: int = 3, seed: int = 0, uid=None):
        super().__init__(estimator, estimatorParamMaps, evaluator, uid=uid)
        self.setDefault(numFolds=3, seed=0)
        self._set(numFolds=numFolds, seed=seed)

    def setNumFolds(self, value: int) -> "CrossValidator":
        return self._set(numFolds=value)

    def getNumFolds(self) -> int:
        return self.getOrDefault(self.numFolds)

    def _fit(self, dataset) -> "CrossValidatorModel":
        self._check()
        k = self.getNumFolds()
        if k < 2:
            raise ValueError(f"numFolds = {k} must be >= 2")
        n = num_rows(dataset)
        if n < k:
            raise ValueError(f"dataset has {n} rows < numFolds = {k}")
        rng = np.random.default_rng(self.getSeed())
        perm = rng.permutation(n)
        metrics = np.zeros((k, len(self._maps)))
        for fold in range(k):
            val_idx = np.sort(perm[fold::k])
            train_idx = np.sort(np.concatenate(
                [perm[f::k] for f in range(k) if f != fold]
            ))
            metrics[fold] = self._fit_and_eval(
                take_rows(dataset, train_idx), take_rows(dataset, val_idx)
            )
        avg = metrics.mean(axis=0)
        best = self._best_index(avg)
        best_model = self._est.fit(dataset, params=self._maps[best] or None)
        out = CrossValidatorModel(
            bestModel=best_model, avgMetrics=avg.tolist(),
        )
        out.uid = self.uid
        out._eval = self._eval
        return out


class CrossValidatorModel(Model, _TunedModelPersistence):
    _uid_prefix = "CrossValidatorModel"
    _metrics_attr = "avgMetrics"

    def __init__(self, bestModel=None, avgMetrics=None, uid=None):
        super().__init__(uid=uid)
        self.bestModel = bestModel
        self.avgMetrics = list(avgMetrics or [])
        self._eval = None

    def _copy_extra_state(self, source):
        self.bestModel = source.bestModel
        self.avgMetrics = list(source.avgMetrics)
        self._eval = getattr(source, "_eval", None)

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)


class TrainValidationSplit(Estimator, _ValidatorParams):
    """Single random train/validation split over the param grid."""

    _uid_prefix = "TrainValidationSplit"
    trainRatio = ParamDecl(
        "trainRatio", "fraction of rows used for training (0, 1)",
        TypeConverters.toFloat,
    )

    def __init__(self, estimator=None, estimatorParamMaps=None, evaluator=None,
                 trainRatio: float = 0.75, seed: int = 0, uid=None):
        super().__init__(estimator, estimatorParamMaps, evaluator, uid=uid)
        self.setDefault(trainRatio=0.75, seed=0)
        self._set(trainRatio=trainRatio, seed=seed)

    def setTrainRatio(self, value: float) -> "TrainValidationSplit":
        return self._set(trainRatio=value)

    def getTrainRatio(self) -> float:
        return self.getOrDefault(self.trainRatio)

    def _fit(self, dataset) -> "TrainValidationSplitModel":
        self._check()
        ratio = self.getTrainRatio()
        if not 0.0 < ratio < 1.0:
            raise ValueError(f"trainRatio = {ratio} must be in (0, 1)")
        n = num_rows(dataset)
        n_train = int(round(n * ratio))
        if n_train == 0 or n_train == n:
            raise ValueError(f"trainRatio = {ratio} leaves an empty split (n = {n})")
        rng = np.random.default_rng(self.getSeed())
        perm = rng.permutation(n)
        train_idx = np.sort(perm[:n_train])
        val_idx = np.sort(perm[n_train:])
        metrics = np.asarray(self._fit_and_eval(
            take_rows(dataset, train_idx), take_rows(dataset, val_idx)
        ))
        best = self._best_index(metrics)
        best_model = self._est.fit(dataset, params=self._maps[best] or None)
        out = TrainValidationSplitModel(
            bestModel=best_model, validationMetrics=metrics.tolist(),
        )
        out.uid = self.uid
        return out


class TrainValidationSplitModel(Model, _TunedModelPersistence):
    _uid_prefix = "TrainValidationSplitModel"
    _metrics_attr = "validationMetrics"

    def __init__(self, bestModel=None, validationMetrics=None, uid=None):
        super().__init__(uid=uid)
        self.bestModel = bestModel
        self.validationMetrics = list(validationMetrics or [])

    def _copy_extra_state(self, source):
        self.bestModel = source.bestModel
        self.validationMetrics = list(source.validationMetrics)

    def _transform(self, dataset):
        return self.bestModel.transform(dataset)
