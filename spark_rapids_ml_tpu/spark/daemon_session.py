"""Daemon resolution for Spark-driven fits.

Who runs the data-plane daemon depends on the deployment:

* **Cluster**: each TPU host runs one ``DataPlaneDaemon`` (one process owns
  the host's chips, like the reference's one-GPU-per-executor resource
  model, README.md:110-113). The driver learns the primary address from
  ``spark.srml.daemon.address`` / ``$SRML_DAEMON_ADDRESS`` and ships it to
  tasks; an executor colocated with a *different* TPU host overrides the
  target with its OWN host's daemon via the executor-local
  ``$SRML_DAEMON_ADDRESS`` (the executor→local-host routing rule — row
  data flows executor → nearest TPU host). At finalize the driver pulls
  each peer daemon's O(d²) partials (``export_state``) and folds them
  into the primary (``merge_state``) — the cross-daemon reduce that
  makes the Spark-fed fit span hosts (the any-number-of-executors
  ``RDD.reduce`` property, RapidsRowMatrix.scala:139); iterative fits
  sync the Lloyd/Newton iterate back out with ``get_iterate``/
  ``set_iterate`` at every pass boundary (spark/estimator.py). KMeans
  needs the full daemon set up front (centers must be seeded before the
  first scan): list it in ``spark.srml.daemon.addresses`` /
  ``$SRML_DAEMON_ADDRESSES`` (comma-separated; other algorithms discover
  peers from the task acks and need no list). Every daemon address must
  be reachable from BOTH its executors and the driver.
* **Local / tests**: nothing configured — the driver starts one in-process
  daemon, shared across fits (jit caches stay warm), torn down at exit.

An optional shared-secret token (``spark.srml.daemon.token`` /
``$SRML_DAEMON_TOKEN``) is checked by the daemon on every op.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional, Tuple

_lock = threading.Lock()
_owned_daemon = None  # in-process daemon for local mode


def _spark_conf_get(spark, key: str) -> Optional[str]:
    try:
        return spark.conf.get(key)
    except Exception:
        return None


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, sep, port = addr.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(
            f"daemon address {addr!r} must be 'host:port' (e.g. "
            "'tpu-host-0:9747')"
        )
    return host or "127.0.0.1", int(port)


def resolve(spark=None) -> Tuple[str, int, Optional[str]]:
    """Return (host, port, token) of the daemon this driver should use,
    starting an in-process one if nothing is configured."""
    addr = os.environ.get("SRML_DAEMON_ADDRESS")
    if not addr and spark is not None:
        addr = _spark_conf_get(spark, "spark.srml.daemon.address")
    token = os.environ.get("SRML_DAEMON_TOKEN")
    if token is None and spark is not None:
        token = _spark_conf_get(spark, "spark.srml.daemon.token")
    if addr:
        return (*_parse_addr(addr), token)
    return (*_local_daemon().address, token)


def client_kwargs(spark=None) -> dict:
    """Resilience tuning for every data-plane client a Spark fit or
    transform creates — how the Spark layer honors the daemon's
    backpressure/healing contract (docs/protocol.md "Client retry
    obligations"). Sources, env first then Spark conf:

    * ``$SRML_DAEMON_TIMEOUT_S`` / ``spark.srml.daemon.timeout_s`` —
      per-socket-syscall timeout (default 120 s).
    * ``$SRML_DAEMON_OP_DEADLINE_S`` / ``spark.srml.daemon.op_deadline_s``
      — per-op healing deadline: total time one op may spend across
      reconnects, replays, and honored `busy` retry_after_s waits before
      the failure surfaces to Spark's own task retry.
    * ``$SRML_DAEMON_OP_ATTEMPTS`` / ``spark.srml.daemon.op_attempts`` —
      reconnect attempts per op.

    Unset keys are omitted so the client's defaults rule. Executors call
    this with ``spark=None`` (env only — the executor's env, like the
    ``$SRML_DAEMON_ADDRESS`` routing rule)."""

    def _get(env_name: str, conf_key: str) -> Optional[str]:
        v = os.environ.get(env_name)
        if v is None and spark is not None:
            v = _spark_conf_get(spark, conf_key)
        return v

    out: dict = {}
    t = _get("SRML_DAEMON_TIMEOUT_S", "spark.srml.daemon.timeout_s")
    if t:
        out["timeout"] = float(t)
    d = _get("SRML_DAEMON_OP_DEADLINE_S", "spark.srml.daemon.op_deadline_s")
    if d:
        out["op_deadline_s"] = float(d)
    a = _get("SRML_DAEMON_OP_ATTEMPTS", "spark.srml.daemon.op_attempts")
    if a:
        out["max_op_attempts"] = int(a)
    return out


def recovery_attempts(spark=None) -> int:
    """Fit-level pass-replay budget (spark/estimator.py "Crash recovery"):
    how many times one pass-boundary unit may be replayed after a daemon
    incarnation change before the failure surfaces. 0 (the default) =
    recovery off — a daemon restart mid-fit fails loudly. Sources, env
    first then Spark conf then config: ``$SRML_FIT_RECOVERY_ATTEMPTS`` /
    ``spark.srml.fit.recovery_attempts`` /
    ``config "fit_recovery_attempts"``."""
    sources = [("$SRML_FIT_RECOVERY_ATTEMPTS",
                os.environ.get("SRML_FIT_RECOVERY_ATTEMPTS"))]
    if spark is not None:
        sources.append((
            "spark.srml.fit.recovery_attempts",
            _spark_conf_get(spark, "spark.srml.fit.recovery_attempts"),
        ))
    for src, v in sources:
        if v is None:
            continue
        try:
            return max(int(v), 0)
        except (TypeError, ValueError):
            # A typo'd value must not SILENTLY disable the crash
            # recovery the operator explicitly configured: warn and
            # fall through to the next source.
            from spark_rapids_ml_tpu.utils.logging import get_logger

            get_logger("spark.daemon_session").warning(
                "ignoring invalid fit recovery attempts %r from %s "
                "(want a non-negative integer)", v, src,
            )
    from spark_rapids_ml_tpu import config

    try:
        return max(int(config.get("fit_recovery_attempts")), 0)
    except (TypeError, ValueError):
        return 0


def _env_conf_config(spark, env_name: str, conf_key: str, config_key: str,
                     cast, floor=None):
    """Shared resolution ladder for fit-policy knobs (the
    ``recovery_attempts`` pattern): env, then Spark conf, then the
    process config default. A typo'd value warns and falls through —
    it must never SILENTLY disable a policy the operator configured."""
    sources = [(f"${env_name}", os.environ.get(env_name))]
    if spark is not None:
        sources.append((conf_key, _spark_conf_get(spark, conf_key)))
    for src, v in sources:
        if v is None:
            continue
        try:
            v = cast(v)
            return v if floor is None else max(v, floor)
        except (TypeError, ValueError):
            from spark_rapids_ml_tpu.utils.logging import get_logger

            get_logger("spark.daemon_session").warning(
                "ignoring invalid %s value %r from %s", config_key, v, src,
            )
    from spark_rapids_ml_tpu import config

    try:
        v = cast(config.get(config_key))
        return v if floor is None else max(v, floor)
    except (TypeError, ValueError):
        return floor if floor is not None else cast(0)


def daemon_loss_tolerance(spark=None) -> int:
    """Elastic-fit death budget (spark/estimator.py; docs/protocol.md
    "Permanent daemon loss"): how many peer daemons one fit may declare
    permanently dead and amputate. 0 (the default) = elastic degrade
    off — a lost daemon fails the fit loudly, and no classification
    probe ever runs. Sources, env first then Spark conf then config:
    ``$SRML_FIT_DAEMON_LOSS_TOLERANCE`` /
    ``spark.srml.fit.daemon_loss_tolerance`` /
    ``config "fit_daemon_loss_tolerance"``."""
    return _env_conf_config(
        spark, "SRML_FIT_DAEMON_LOSS_TOLERANCE",
        "spark.srml.fit.daemon_loss_tolerance",
        "fit_daemon_loss_tolerance", int, floor=0,
    )


def daemon_death_timeout_s(spark=None) -> float:
    """The death deadline: the TOTAL reconnect/healing budget a peer
    implicated in a failed pass gets on its liveness probe before it
    escalates from *retrying* to *declared dead*. Sources:
    ``$SRML_FIT_DAEMON_DEATH_TIMEOUT_S`` /
    ``spark.srml.fit.daemon_death_timeout_s`` /
    ``config "fit_daemon_death_timeout_s"``."""
    return _env_conf_config(
        spark, "SRML_FIT_DAEMON_DEATH_TIMEOUT_S",
        "spark.srml.fit.daemon_death_timeout_s",
        "fit_daemon_death_timeout_s", float, floor=0.1,
    )


def daemon_join_policy(spark=None) -> str:
    """Elastic-fit GROW policy (spark/estimator.py; docs/protocol.md
    "Mid-fit daemon join"): whether a daemon that appears mid-fit may be
    admitted into a running fit. ``off`` (the default) keeps the
    unlisted-peer loud rejection byte-for-byte and runs no discovery
    probe; ``boundary`` admits new daemons at the next pass boundary
    only, seeded from the recovery ledger. An unrecognized value warns
    and reads as ``off`` — a typo must not silently open the admission
    door. Sources: ``$SRML_FIT_DAEMON_JOIN_POLICY`` /
    ``spark.srml.fit.daemon_join_policy`` /
    ``config "fit_daemon_join_policy"``."""

    def _policy(v) -> str:
        v = str(v).strip().lower()
        if v not in ("off", "boundary"):
            raise ValueError(v)
        return v

    try:
        return _env_conf_config(
            spark, "SRML_FIT_DAEMON_JOIN_POLICY",
            "spark.srml.fit.daemon_join_policy",
            "fit_daemon_join_policy", _policy, floor=None,
        )
    except (TypeError, ValueError):
        # Every source (including the config default's last-resort
        # cast) was invalid — admission stays closed.
        return "off"


def daemon_join_limit(spark=None) -> int:
    """The join budget: how many daemons one fit may admit mid-fit
    before a further newcomer fails the fit loudly (the
    ``daemon_loss_tolerance`` contract, mirrored for growth). Sources:
    ``$SRML_FIT_DAEMON_JOIN_LIMIT`` /
    ``spark.srml.fit.daemon_join_limit`` /
    ``config "fit_daemon_join_limit"``."""
    return _env_conf_config(
        spark, "SRML_FIT_DAEMON_JOIN_LIMIT",
        "spark.srml.fit.daemon_join_limit",
        "fit_daemon_join_limit", int, floor=0,
    )


def resolve_all(spark=None) -> list:
    """The full daemon set for fits that must know every peer BEFORE the
    first scan (kmeans: centers are seeded on all daemons up front).
    Parsed from ``$SRML_DAEMON_ADDRESSES`` / ``spark.srml.daemon.addresses``
    (comma-separated host:port). Empty when unconfigured — single-pass
    algorithms then discover peers from task acks instead."""
    addrs = os.environ.get("SRML_DAEMON_ADDRESSES")
    if not addrs and spark is not None:
        addrs = _spark_conf_get(spark, "spark.srml.daemon.addresses")
    if not addrs:
        return []
    return [_parse_addr(a.strip()) for a in addrs.split(",") if a.strip()]


def fleet_seeds(spark=None) -> list:
    """Seed addresses for the gossiped-fleet bootstrap
    (``router.bootstrap_table`` — ONE reachable seed is enough; the
    seed's FleetView names the rest). The config/env/Spark-conf ladder:
    ``$SRML_FLEET_SEED_ADDRESSES`` / ``spark.srml.fleet.seed_addresses``
    / ``config "fleet_seed_addresses"`` (comma-separated host:port).
    Empty when unconfigured."""
    from spark_rapids_ml_tpu import config

    addrs = os.environ.get("SRML_FLEET_SEED_ADDRESSES")
    if not addrs and spark is not None:
        addrs = _spark_conf_get(spark, "spark.srml.fleet.seed_addresses")
    if not addrs:
        addrs = config.get("fleet_seed_addresses")
    if not addrs:
        return []
    return [a.strip() for a in str(addrs).split(",") if a.strip()]


def _local_daemon():
    global _owned_daemon
    with _lock:
        if _owned_daemon is None:
            from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon

            _owned_daemon = DataPlaneDaemon(ttl=3600.0).start()
            atexit.register(shutdown)
        return _owned_daemon


def shutdown() -> None:
    """Stop the in-process daemon (idempotent)."""
    global _owned_daemon
    with _lock:
        d, _owned_daemon = _owned_daemon, None
    if d is not None:
        d.stop()


def task_context() -> Tuple[int, int]:
    """(partition_id, attempt) for the CURRENT task, executor-side.

    Uses pyspark's TaskContext when running inside a real executor;
    otherwise falls back to ``$SRML_PARTITION_ID`` / ``$SRML_ATTEMPT``
    (set by non-Spark task runners, e.g. the test harness)."""
    try:
        from pyspark import TaskContext

        ctx = TaskContext.get()
        if ctx is not None:
            return int(ctx.partitionId()), int(ctx.attemptNumber())
    except ImportError:
        pass
    return (
        int(os.environ.get("SRML_PARTITION_ID", "0")),
        int(os.environ.get("SRML_ATTEMPT", "0")),
    )


def executor_daemon_address(default_host: str, default_port: int) -> Tuple[str, int]:
    """Executor-side routing rule: a task feeds ITS host's daemon when the
    executor env names one, else the driver-resolved address."""
    addr = os.environ.get("SRML_DAEMON_ADDRESS")
    if addr:
        return _parse_addr(addr)
    return default_host, default_port
