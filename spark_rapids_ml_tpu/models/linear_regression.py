"""LinearRegression — placeholder, implemented in the breadth pass."""

from spark_rapids_ml_tpu.core.params import Estimator, Model


class LinearRegression(Estimator):
    _uid_prefix = "LinearRegression"


class LinearRegressionModel(Model):
    _uid_prefix = "LinearRegressionModel"
