"""Pairwise squared-Euclidean distances via the Gram trick.

Not present in the reference (PCA-only), but required by the north-star
algorithm set (BASELINE.json: KMeans pairwise-dist kernel, approx-KNN
distance kernel). ‖x−y‖² = ‖x‖² + ‖y‖² − 2⟨x,y⟩ turns the O(m·k·d) distance
computation into one MXU GEMM plus rank-1 updates — the TPU-idiomatic form.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sq_euclidean(
    x: jax.Array,
    y: jax.Array,
    compute_dtype=None,
    accum_dtype=jnp.float32,
) -> jax.Array:
    """(m, d) × (k, d) → (m, k) squared distances, clipped at 0."""
    from spark_rapids_ml_tpu.ops.gram import mm_precision

    xc = x.astype(compute_dtype) if compute_dtype is not None else x
    yc = y.astype(compute_dtype) if compute_dtype is not None else y
    with mm_precision(xc.dtype):
        xy = jax.lax.dot_general(
            xc, yc, (((1,), (1,)), ((), ())), preferred_element_type=accum_dtype
        )
    x2 = jnp.sum(jnp.square(x.astype(accum_dtype)), axis=1)
    y2 = jnp.sum(jnp.square(y.astype(accum_dtype)), axis=1)
    d = x2[:, None] + y2[None, :] - 2.0 * xy
    return jnp.maximum(d, 0.0)


def fused_topk_fits(q: int, m: int, d: int, k: int, accum_dtype=jnp.float32) -> bool:
    """Shape/dtype/VMEM feasibility of the fused streaming distance+top-k
    kernel (:func:`~spark_rapids_ml_tpu.ops.pallas_kernels.dist_topk_pallas`)
    — the SHAPE half of the gate; callers AND it with the backend/config
    half (``ops.gram._pallas_backend_ok``, or force it on for interpret-mode
    goldens). f64 accumulation stays on the XLA two-step: the kernel
    computes and emits f32 scores.

    Deliberately NO feature-width alignment gate: d rides whole blocks
    (never tiled across the grid), and Mosaic masks a non-128 lane tail —
    the same shipped contract as the arbitrary-d IVF scan/probe kernels
    (``ivf_scan_select_pallas``/``probe_select_pallas``); the gram gate's
    d % 128 is about its resident (d, d) accumulator tiling, which this
    kernel does not have."""
    from spark_rapids_ml_tpu.ops import pallas_kernels as pk

    if jnp.dtype(accum_dtype) != jnp.float32:
        return False
    if not 0 < k <= min(pk.DIST_TOPK_MAX_K, m):
        return False
    bm = min(pk.DIST_TOPK_BLOCK_M, -(-m // 8) * 8)
    qb = min(pk.DIST_TOPK_BLOCK_Q, -(-q // 8) * 8)
    # Per grid step: the (bm, d) db block + (d, qb) query panel (each
    # double-buffered by the pipeline, ≤ f32), the f32 score tile, and the
    # (k_pad + bm, qb) merge planes (f32 distances + i32 ids).
    return (
        2 * (bm * d + d * qb) * 4
        + bm * qb * 4
        + (bm + 2 * (-(-k // 8) * 8)) * qb * 8
    ) <= 64 * 2**20
