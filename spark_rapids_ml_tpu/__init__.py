"""spark_rapids_ml_tpu — TPU-native distributed ML acceleration framework.

A brand-new framework providing the capabilities of NVIDIA's
spark-rapids-ml (Scala/JNI era — drop-in Spark ML estimators accelerated by a
native math core; reference: /root/reference) re-designed TPU-first:

* The cuBLAS/cuSOLVER/RAFT JNI library (reference ``native/src/rapidsml_jni.cu``)
  becomes XLA-compiled JAX kernels (``ops/``) with Pallas where fusion matters.
* The per-partition Gram matrix + JVM ``RDD.reduce`` combine (reference
  ``RapidsRowMatrix.scala:122-139``) becomes ``shard_map`` + ``jax.lax.psum``
  over ICI/DCN (``parallel/``).
* The cuDF LIST-column data plane (reference ``ColumnarRdd``) becomes an
  Arrow columnar bridge with an optional native C++ fast path (``bridge/``).
* The Spark ML Estimator/Model/Params contract (reference
  ``RapidsPCA.scala``) is reproduced in ``core/params.py`` so estimators are
  drop-in shaped: ``PCA().setInputCol(...).setK(3).fit(df)``.

Model families (per BASELINE.json north-star configs): PCA, KMeans,
LinearRegression, LogisticRegression, (approx-)KNN.
"""

__version__ = "0.1.0"

from spark_rapids_ml_tpu import config as config

# Persistent XLA compilation cache (ROADMAP 2b): wire the config key to
# jax at package init, before any model import can compile a program —
# identical programs from an earlier process (a restarted daemon, the
# next bench round) become disk hits, counted by
# srml_xla_persistent_cache_hits_total (utils/xprof.py).
_compile_cache_dir = config.get("compile_cache_dir")
if _compile_cache_dir:
    import jax as _jax

    _jax.config.update("jax_compilation_cache_dir", str(_compile_cache_dir))
del _compile_cache_dir

# Re-export the user-facing estimator namespace, mirroring the reference's
# thin `com.nvidia.spark.ml.feature.PCA` shim (reference PCA.scala:27-37).
from spark_rapids_ml_tpu.models.pca import PCA, PCAModel
from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel
from spark_rapids_ml_tpu.models.linear_regression import (
    LinearRegression,
    LinearRegressionModel,
)
from spark_rapids_ml_tpu.models.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from spark_rapids_ml_tpu.models.knn import (
    NearestNeighbors,
    NearestNeighborsModel,
    ApproximateNearestNeighbors,
    ApproximateNearestNeighborsModel,
)
from spark_rapids_ml_tpu.models.random_forest import (
    RandomForestClassifier,
    RandomForestClassificationModel,
    RandomForestRegressor,
    RandomForestRegressionModel,
)
from spark_rapids_ml_tpu.models.scaler import StandardScaler, StandardScalerModel
from spark_rapids_ml_tpu.pipeline import Pipeline, PipelineModel
from spark_rapids_ml_tpu.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)
from spark_rapids_ml_tpu.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)

__all__ = [
    "PCA",
    "PCAModel",
    "KMeans",
    "KMeansModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "NearestNeighbors",
    "NearestNeighborsModel",
    "ApproximateNearestNeighbors",
    "ApproximateNearestNeighborsModel",
    "RandomForestClassifier",
    "RandomForestClassificationModel",
    "RandomForestRegressor",
    "RandomForestRegressionModel",
    "StandardScaler",
    "StandardScalerModel",
    "Pipeline",
    "PipelineModel",
    "ParamGridBuilder",
    "CrossValidator",
    "CrossValidatorModel",
    "TrainValidationSplit",
    "TrainValidationSplitModel",
    "RegressionEvaluator",
    "BinaryClassificationEvaluator",
    "MulticlassClassificationEvaluator",
    "config",
    "__version__",
]
