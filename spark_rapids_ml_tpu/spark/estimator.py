"""PySpark DataFrame adapters for the core estimators.

The reference's user contract: change one import, keep the Spark ML code
(`new com.nvidia.spark.ml.feature.PCA().setInputCol(...).fit(df)`,
reference PCA.scala:27-37, README.md:27-37 — with the features column as
ArrayType rather than Vector). These wrappers reproduce that contract for
PySpark: ``SparkPCA().setInputCol("features").setK(3).fit(spark_df)``.

**fit is distributed**, reproducing the reference's defining property —
per-partition work on executors with only O(d²) partials crossing the
wire (RapidsRowMatrix.scala:118-139). Each partition task streams its
Arrow batches to the TPU-host data-plane daemon (``serve/``) and commits;
the driver finalizes and receives only the model. The dataset is NEVER
collected to the driver. Iterative algorithms (KMeans/LogReg) run one
Spark job per pass with a daemon ``step`` at each boundary — the Lloyd /
Newton scan loop with Spark as the scan engine. Task retries and
speculative duplicates are safe: feeds stage per (partition, attempt) and
only ``commit`` folds them in (see serve/daemon.py).

``transform`` runs the model on Arrow batches via ``mapInArrow`` (one
batch per executor task — the analogue of the reference's columnar UDF,
RapidsPCA.scala:128-161), falling back to a collect-based path for old
PySpark.

pyspark is optional: import of this module never requires it; calling
``fit``/``transform`` with a Spark DataFrame does. KNN/ANN fits stream
rows to the daemon(s) like everything else; with multiple daemons the
index is built and served as PER-DAEMON SHARDS with fan-out/merge
queries (``_fit_knn``) — nothing ever collects to the driver.
"""

from __future__ import annotations

import uuid
from typing import Any, Optional

import numpy as np

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.spark import daemon_session
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import journal
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.logging import get_logger
from spark_rapids_ml_tpu.utils.profiling import trace_span

logger = get_logger("spark.estimator")

#: Crash-recovery telemetry (docs/observability.md). Recoveries are the
#: pass replays the fit performed after a daemon incarnation change or a
#: poisoned pass; drop errors are cleanup drops that failed — each one is
#: a daemon job leaked until the TTL reaper finds it.
_M_FIT_RECOVERIES = metrics_mod.counter(
    "srml_fit_recoveries_total",
    "Fit passes replayed after a daemon incarnation change or poisoned "
    "pass, by algo",
)
_M_DROP_ERRORS = metrics_mod.counter(
    "srml_client_drop_errors_total",
    "Cleanup drop() calls that failed (the daemon job leaks until its "
    "TTL), by stage",
)
_M_MESH_PATHS = metrics_mod.counter(
    "srml_fit_mesh_reduce_paths_total",
    "Multi-daemon pass reductions by path (collective = on-mesh "
    "reduce_mesh; hub = driver-mediated export/merge fallback)",
)
_M_DAEMON_LOSSES = metrics_mod.counter(
    "srml_fit_daemon_losses_total",
    "Peer daemons declared permanently dead and quarantined by an "
    "elastic fit (fit_daemon_loss_tolerance > 0; docs/protocol.md "
    "'Permanent daemon loss'), by algo",
)
_M_FIT_REROUTES = metrics_mod.counter(
    "srml_fit_reroutes_total",
    "Feed passes rerun on the shrunken topology after a daemon loss — "
    "the dead daemon's partitions reroute to survivors, by algo",
)
_M_FIT_JOINS = metrics_mod.counter(
    "srml_fit_joins_total",
    "Daemons admitted into a RUNNING fit at a pass boundary "
    "(fit_daemon_join_policy=boundary; docs/protocol.md 'Mid-fit "
    "daemon join'), by algo",
)
_M_FIT_REBALANCED = metrics_mod.counter(
    "srml_fit_rebalanced_rows_total",
    "Rows the task layer rebalanced onto mid-fit joiners on their "
    "first acked pass after admission, by algo",
)


def _drop_quietly(client, job: str, stage: str) -> None:
    """Cleanup drop that cannot mask the fit's outcome — but is COUNTED
    and logged: a silently swallowed failure here leaks a daemon job
    (d×d device buffers, or a dataset-sized knn stage) invisibly until
    the TTL reaper hides the evidence."""
    try:
        client.drop(job)
    except Exception as e:
        _M_DROP_ERRORS.inc(stage=stage)
        logger.debug(
            "cleanup drop of job %r failed (%s); the daemon holds it "
            "until its TTL: %s", job, stage, e,
        )


def _pyspark():
    try:
        import pyspark  # noqa: F401
        from pyspark.sql import DataFrame

        return DataFrame
    except ImportError:
        return None


# Extra DataFrame types treated as Spark-shaped (duck-typed stand-ins that
# implement the same surface — the test harness's SimDataFrame registers
# here so the REAL wrapper code paths run without a pyspark install).
_EXTRA_DF_TYPES: tuple = ()


def register_dataframe_type(cls) -> None:
    global _EXTRA_DF_TYPES
    _EXTRA_DF_TYPES = tuple(set(_EXTRA_DF_TYPES) | {cls})


def _is_spark_df(dataset: Any) -> bool:
    if _EXTRA_DF_TYPES and isinstance(dataset, _EXTRA_DF_TYPES):
        return True
    df_cls = _pyspark()
    return df_cls is not None and isinstance(dataset, df_cls)


def _check_not_orphan_spark_df(dataset: Any) -> None:
    """Raise the promised clear error for Spark-shaped datasets when
    pyspark is missing (instead of an opaque core-estimator failure)."""
    if _pyspark() is None and (
        hasattr(dataset, "sparkSession")
        or type(dataset).__module__.split(".")[0] == "pyspark"
    ):
        raise ImportError(
            "pyspark is not installed; Spark* estimators need it for "
            "DataFrame inputs. Use the core estimators "
            "(spark_rapids_ml_tpu.PCA etc.) with arrow/pandas/numpy data."
        )


def _df_to_arrow(df, columns):
    """Spark DataFrame -> pyarrow.Table restricted to ``columns``."""
    import pyarrow as pa

    selected = df.select(*columns)
    # Spark 4 / recent 3.x: native Arrow collect.
    if hasattr(selected, "toArrow"):
        return selected.toArrow()
    pdf = selected.toPandas()
    return pa.Table.from_pandas(pdf, preserve_index=False)


# Executor-side cache: daemon instance id per (fit job, host, port).
# Scoping by JOB makes the cache safe under Spark python-worker reuse:
# a daemon restarted BETWEEN fits gets a fresh ping on the next fit
# (a stale id would make the driver treat the same daemon as a peer and
# fail spuriously), while within one fit — where a restart loses the
# job state and fails the fit anyway — passes and tasks share one ping.
_DAEMON_ID_CACHE: dict = {}


def _evict_daemon_id_cache(job: str, addr: Optional[str] = None,
                           prefix: bool = False) -> None:
    """Drop this fit's id-cache routes from THIS PROCESS's cache (all of
    them on fit exit; only a quarantined daemon's on amputation). The
    entries are job-scoped, so without the fit-exit sweep a long-lived
    driver-process deployment (tasks running in the driver's
    interpreter) leaks one per (fit, daemon) and a RECYCLED job name
    could inherit a stale daemon id from the fit that used the name
    before. Each process owns its own copy: the eviction that matters on
    real executors (reused Spark python workers) rides the replayed
    task itself — ``_FeedTask.evict_routes``. ``prefix`` sweeps every
    job under a uid prefix (the KNN fit shell, which exits outside the
    scope that minted the exact job name)."""
    if addr is not None:
        try:
            host, port = daemon_session._parse_addr(addr)
        except ValueError:
            return
        _DAEMON_ID_CACHE.pop((job, host, port), None)
        return
    match = (
        (lambda k: str(k[0]).startswith(job)) if prefix
        else (lambda k: k[0] == job)
    )
    for key in [k for k in _DAEMON_ID_CACHE if match(k)]:
        _DAEMON_ID_CACHE.pop(key, None)


class _FeedTask:
    """The executor-side partition feeder (a plain-pickle-able callable —
    shipped to tasks by Spark's closure serializer; imports happen on the
    executor).

    One task = one partition = one daemon connection: stream every Arrow
    batch to the stage keyed (partition, attempt), then commit. Retries
    restart the stage; duplicates of committed partitions are discarded
    daemon-side — Spark's at-least-once task execution becomes
    exactly-once accumulation (see serve/daemon.py)."""

    def __init__(self, host, port, token, job, algo, input_col, label_col,
                 params, pass_id, evict_routes=()):
        self.host, self.port, self.token = host, port, token
        self.job, self.algo = job, algo
        self.input_col, self.label_col = input_col, label_col
        self.params, self.pass_id = params, pass_id
        # Quarantined-daemon addresses (elastic degrade): evicted from
        # the EXECUTOR-side id cache at task start — the cache lives in
        # reused Spark python workers, where the driver's own eviction
        # cannot reach; a replacement daemon at the dead address must be
        # re-pinged, not answered from the ghost's cached id.
        self.evict_routes = tuple(evict_routes)
        # Distributed tracing: the driver's journal frame at task
        # construction rides the closure to the executor, whose client
        # stamps it on every wire op — the daemon's spans then parent
        # into THIS fit's run even though the executor process never
        # opened it (docs/protocol.md "trace_ctx").
        self.trace_ctx = journal.trace_ctx()

    def __call__(self, batches):
        import pyarrow as pa

        from spark_rapids_ml_tpu.serve.client import DataPlaneClient
        from spark_rapids_ml_tpu.spark import daemon_session as ds

        pid, attempt = ds.task_context()
        h, p = ds.executor_daemon_address(self.host, self.port)
        for bad in self.evict_routes:
            # Executor-side quarantine eviction (see __init__): runs in
            # the worker process that actually OWNS the cache.
            try:
                bh, bp = ds._parse_addr(bad)
            except ValueError:
                continue
            _DAEMON_ID_CACHE.pop((self.job, bh, bp), None)
        rows = 0
        # client_kwargs(): executor-env resilience tuning — per-op healing
        # deadline, socket timeout — so a daemon hiccup or busy-shed is
        # absorbed by the client before it ever costs a Spark task retry.
        with DataPlaneClient(h, p, token=self.token,
                             trace_ctx=self.trace_ctx,
                             **ds.client_kwargs()) as c:
            # The daemon's self-reported identity: the driver keys its
            # merge/reconcile on this, never on the address spelling (an
            # alias of the primary must not look like a peer).
            daemon_id = _DAEMON_ID_CACHE.get((self.job, h, p))
            if daemon_id is None:
                daemon_id = c.server_id() or f"{h}:{p}"
                if len(_DAEMON_ID_CACHE) > 256:  # bound worker-reuse growth
                    _DAEMON_ID_CACHE.clear()
                _DAEMON_ID_CACHE[(self.job, h, p)] = daemon_id
            for batch in batches:
                if batch.num_rows == 0:
                    continue
                c.feed(
                    self.job,
                    pa.Table.from_batches([batch]),
                    algo=self.algo,
                    input_col=self.input_col,
                    label_col=self.label_col,
                    params=self.params,
                    partition=pid,
                    attempt=attempt,
                    pass_id=self.pass_id,
                )
                rows += batch.num_rows
            if rows > 0:
                c.commit(
                    self.job, partition=pid, attempt=attempt, pass_id=self.pass_id
                )
            if c.last_server_id and c.last_server_id != daemon_id:
                # The daemon ANSWERED with a different identity than the
                # cached ping: it restarted (volatile, new instance id)
                # under this reused worker. The ack must name who really
                # holds the rows, and later tasks must not keep
                # reporting the ghost id.
                daemon_id = c.last_server_id
                _DAEMON_ID_CACHE[(self.job, h, p)] = daemon_id
        # The ack names the daemon this task actually fed (id + a
        # reachable address): the driver merges partials from exactly
        # this set and reconciles the row counts — no daemon's rows can
        # be silently dropped. `boots` carries every daemon INCARNATION
        # the task's acks came from: two boots in one pass means the
        # daemon restarted under the scan and rows acked to the dead
        # incarnation are gone — the driver's fence (docs/protocol.md
        # "Crash recovery").
        yield pa.RecordBatch.from_pydict(
            {
                "partition": pa.array([pid], pa.int32()),
                "rows": pa.array([rows], pa.int64()),
                "daemon": pa.array([f"{h}:{p}"], pa.string()),
                "daemon_id": pa.array([daemon_id], pa.string()),
                "boots": pa.array(
                    [",".join(sorted(c.seen_boot_ids))], pa.string()
                ),
            }
        )


class _LabelMaxTask:
    """O(1)-result label scan: each task reports its partitions' max
    label. One tiny Spark job, like the reference's numCols probe
    (RapidsPCA.scala:73-74) — how the driver learns n_classes without
    collecting labels."""

    def __init__(self, label_col):
        self._label = label_col

    def __call__(self, batches):
        import numpy as np
        import pyarrow as pa

        mx = -1.0
        for batch in batches:
            if batch.num_rows:
                arr = np.asarray(
                    pa.Table.from_batches([batch])
                    .column(self._label)
                    .to_numpy(zero_copy_only=False)
                )
                if arr.size:
                    mx = max(mx, float(np.max(arr)))
        yield pa.RecordBatch.from_pydict({"maxlabel": pa.array([mx], pa.float64())})


def _probe_num_classes(df, label_col) -> int:
    acks = df.select(label_col).mapInArrow(
        _LabelMaxTask(label_col), "maxlabel double"
    ).collect()
    mx = max((float(r["maxlabel"]) for r in acks), default=-1.0)
    return max(int(mx) + 1, 2)


def _ack_rows(acks):
    """(total rows, rows by daemon id, id → reachable address, partition →
    winning daemon id, daemon id → boot incarnations observed) from one
    feed pass's task acks. Daemons are keyed by their self-reported
    instance id — address spellings alias."""
    per: dict = {}
    addr_of: dict = {}
    owner: dict = {}
    boots: dict = {}
    for r in acks:
        did = r["daemon_id"]
        per[did] = per.get(did, 0) + int(r["rows"])
        addr_of.setdefault(did, r["daemon"])
        if int(r["rows"]) > 0:
            owner[int(r["partition"])] = did
        bs = boots.setdefault(did, set())
        for b in str(r["boots"] or "").split(","):
            if b:
                bs.add(b)
    return sum(per.values()), per, addr_of, owner, boots


def _incarnation_change(addr: str, boots) -> RuntimeError:
    """The fence: a pass whose acks span two incarnations of one daemon
    fed SOME rows to a state that died with the old incarnation — the
    acked row count is poisoned and must not be trusted (or silently
    reconciled). With recovery enabled the estimator replays the pass
    from the last boundary; otherwise this failure IS the answer."""
    return RuntimeError(
        f"daemon {addr} restarted mid-pass (incarnations "
        f"{sorted(boots)}): rows acked to the dead incarnation are gone "
        "from the accumulator while the tasks still count them. Enable "
        "fit recovery (SRML_FIT_RECOVERY_ATTEMPTS / "
        "spark.srml.fit.recovery_attempts) to replay the pass from the "
        "last boundary, or refit."
    )


def _split_brain(context: str, expected: int, got: int, detail: str) -> RuntimeError:
    """The loud failure the multi-daemon plane promises: committed rows
    and task-acked rows MUST reconcile — a mismatch means the model would
    silently miss (or double-count) data, and the fit must fail instead
    of returning it."""
    if got > expected:
        hint = (
            "the daemon holds MORE rows than this fit's winning task acks "
            "— a task likely committed here, lost its ack, and was re-run "
            "against a different daemon (cross-daemon retry), or rows were "
            "fed outside this fit. Keep executor→daemon routing sticky "
            "across retries (host-local daemons + Spark locality)."
        )
    else:
        hint = (
            "the daemon holds FEWER rows than tasks acked — its job was "
            "TTL-evicted or recreated mid-fit. Raise the daemon ttl "
            "relative to fit duration."
        )
    return RuntimeError(
        f"daemon row-count mismatch at {context}: tasks acked {expected} "
        f"rows ({detail}) but the daemon plane accounts {got}; {hint} "
        "Refit after fixing the cause."
    )


def _reduce_on_mesh(
    client, job, primary_id, per_daemon, addr_of, owner, boots,
    wire_algo, feed_params, drop_peer, cache,
):
    """Collective-first pass reduction (docs/mesh.md): when the primary
    and every row-holding peer are co-resident members of one mesh (one
    JAX runtime — multichip single-host daemons, or a multi-host
    jax.distributed plane), ONE ``reduce_mesh`` op folds all peer
    partials on the device plane and the O(d²) arrays never cross the
    wire. Returns True when the pass is reduced (or there was nothing to
    reduce); False hands the pass to the export/merge hub
    (:func:`_merge_peer_daemons`) — the degraded mode for daemons on
    separate runtimes or predating the op.

    The split-brain row accounting does not weaken on this path: the
    driver ships its task-ack view (rows + owned partitions per peer)
    and the daemon re-validates it against every peer's live
    ``(boot_id, pass_rows)`` in a pre-reduce gather, refusing the whole
    fold on any mismatch or on a membership-epoch change. A co-resident
    peer that REBOOTED since the scan acked raises the incarnation
    fence here — recovery (when enabled) replays the pass."""
    peer_rows = {
        d: n for d, n in per_daemon.items() if d != primary_id and n > 0
    }
    if not peer_rows:
        return True  # single-daemon pass: nothing to reduce on any path
    if "hub_only" not in cache:
        cache["hub_only"] = not bool(config.get("mesh_collectives"))
    if cache["hub_only"]:
        _M_MESH_PATHS.inc(path="hub")
        return False
    # Two attempts: the daemon's epoch fence is process-global, so an
    # UNRELATED daemon joining/leaving between our mesh_info and the
    # reduce refuses it spuriously — one re-read revalidates every
    # actual participant against the fresh epoch. A second mismatch
    # (sustained churn) surfaces; recovery treats it like any daemon
    # failure.
    for attempt in range(2):
        try:
            info = client.mesh_info()
        except Exception as e:
            logger.debug(
                "mesh_info unavailable on the primary (%s); this fit uses "
                "the driver-hub merge", e,
            )
            cache["hub_only"] = True
            _M_MESH_PATHS.inc(path="hub")
            return False
        members = {
            str(m["id"]): str(m["boot_id"]) for m in info.get("members", [])
        }
        if primary_id not in members:
            _M_MESH_PATHS.inc(path="hub")
            return False
        for did in sorted(peer_rows):
            if did not in members:
                # A genuinely remote daemon (its runtime is not this
                # mesh): the hub is the correct path, not a failure.
                _M_MESH_PATHS.inc(path="hub")
                return False
            ack_boot = next(iter(boots.get(did) or []), None)
            if ack_boot is not None and members[did] != ack_boot:
                raise _incarnation_change(
                    addr_of.get(did, did), {ack_boot, members[did]}
                )
        peers = {
            did: {
                "boot_id": members[did],
                "rows": int(n),
                "partitions": sorted(
                    int(p) for p, d in owner.items() if d == did
                ),
            }
            for did, n in peer_rows.items()
        }
        try:
            client.reduce_mesh(
                job, epoch=int(info["epoch"]), peers=peers, algo=wire_algo,
                params=feed_params, drop_peers=drop_peer,
            )
        except RuntimeError as e:
            if attempt == 0 and "membership changed" in str(e):
                continue
            raise
        _M_MESH_PATHS.inc(path="collective")
        return True


def _merge_peer_daemons(
    client, job, primary_id, per_daemon, addr_of, owner, get_peer,
    wire_algo, feed_params, drop_peer,
):
    """Pull every peer daemon's committed partials into the primary — the
    cross-daemon reduce (the any-number-of-executors ``RDD.reduce``,
    reference RapidsRowMatrix.scala:139, with daemons as leaves). Each
    peer's export is reconciled against what its tasks acked BEFORE it is
    folded — per partition, so a cross-daemon retry orphan or a lost
    partition is named precisely — and a short/overfull peer fails the
    fit instead of corrupting it."""
    for did, fed in sorted(per_daemon.items()):
        if did == primary_id or fed == 0:
            continue
        addr = addr_of[did]
        peer = get_peer(did, addr)
        arrays, meta = peer.export_state(job)
        if drop_peer:
            peer.drop(job)
        committed = {int(p): int(n) for p, n in (meta.get("committed") or {}).items()}
        owned = {p for p, d in owner.items() if d == did}
        orphans = sorted(p for p in committed if p not in owned)
        lost = sorted(p for p in owned if p not in committed)
        if int(meta["pass_rows"]) != fed or orphans or lost:
            parts = []
            if orphans:
                parts.append(
                    f"partitions {orphans} committed here but acked on "
                    "another daemon (cross-daemon retry orphans)"
                )
            if lost:
                parts.append(f"partitions {lost} acked here but not committed")
            raise _split_brain(
                f"peer daemon {addr} export", fed, int(meta["pass_rows"]),
                "; ".join(parts) or f"{addr}={fed}",
            )
        client.merge_state(
            job, arrays, rows=int(meta["pass_rows"]), algo=wire_algo,
            n_cols=int(meta["n_cols"]), params=feed_params,
        )


class _SparkAdapter:
    """Wraps a core estimator class with Spark DataFrame in/out.

    Non-Spark datasets pass straight through to the core estimator, so the
    Spark wrapper is a superset of the core API.
    """

    _core_cls = None  # override
    _model_attr = "model"
    # Daemon wire protocol this estimator's fit speaks; None → Arrow
    # collect (KNN: the fitted model IS the dataset; scaler: trivial).
    _daemon_algo: Optional[str] = None

    def __init__(self, **kwargs):
        self._core = type(self)._core_cls(**kwargs)

    def __getattr__(self, name):
        # Fluent setters return self (the wrapper), others pass through.
        attr = getattr(self._core, name)
        if callable(attr) and name.startswith("set"):
            def fluent(*a, **kw):
                attr(*a, **kw)
                return self

            return fluent
        return attr

    def fit(self, dataset):
        if _is_spark_df(dataset):
            if self._daemon_algo == "knn":
                return self._fit_knn(dataset)
            if self._daemon_algo is None:
                # Never collect a DataFrame to the driver to fit — every
                # shipped estimator speaks a daemon protocol; a custom
                # wrapper without one must opt into the core API.
                raise NotImplementedError(
                    f"{type(self).__name__} has no daemon fit protocol; "
                    "use the core estimator with in-memory data"
                )
            core_model = self._fit_distributed(dataset)
        else:
            _check_not_orphan_spark_df(dataset)
            core_model = self._core.fit(dataset)
        return _SparkModelAdapter(core_model)

    def _fit_knn(self, df):
        """Journal-wrapped shell — see :meth:`_fit_knn_inner`."""
        with journal.run(
            "fit", estimator=type(self).__name__, algo="knn",
            uid=self._core.uid,
        ):
            try:
                return self._fit_knn_inner(df)
            finally:
                # This fit's job is f"{uid}-{hex}" — sweep by prefix
                # (the exact name is minted inside the inner scope).
                _evict_daemon_id_cache(f"{self._core.uid}-", prefix=True)

    def _fit_knn_inner(self, df):
        """Daemon-fed KNN/ANN fit: executors stream partitions to a knn
        accumulation job; finalize BUILDS the index on the daemon's
        devices and registers it for kneighbors serving. The dataset (and
        the index, which is the same size) never reaches the driver —
        BASELINE config #5 (10M×768 ≈ 31 GB) would OOM it.

        Multi-daemon feeds build a SHARDED index (the pod-scale ANN path,
        BASELINE config #5 on v5e-64): each daemon builds and serves the
        shard holding ITS committed partitions, ids translated to global
        partition-major positions daemon-side, and ``kneighbors`` fans the
        query batch to every shard and merges top-k (models/knn.merge_topk
        — the daemon-level twin of the device merges). IVF shards bucket
        against ONE shared quantizer: the first daemon's build trains it
        and the driver forwards the (nlist, d) centroids — O(nlist·d) on
        the wire, never the data — so the union of per-shard probes equals
        the single-index candidate set."""
        core = self._core
        spark = getattr(df, "sparkSession", None)
        host, port, token = daemon_session.resolve(spark)
        ckw = daemon_session.client_kwargs(spark)
        job = f"{core.uid}-{uuid.uuid4().hex[:8]}"
        input_col = core.getOrDefault("featuresCol")
        sel = df.select(input_col)
        ivf = core.hasParam("nlist")
        metric = (
            core.getOrDefault("metric") if core.hasParam("metric")
            else "euclidean"
        )
        if ivf and metric == "inner_product":
            raise ValueError(
                "metric='inner_product' is supported by the exact "
                "NearestNeighbors only"
            )

        from spark_rapids_ml_tpu.serve.client import DataPlaneClient

        fn = _FeedTask(
            host, port, token, job, "knn", input_col, "label", {}, None
        )
        with trace_span("feed pass"):
            acks = sel.mapInArrow(
                fn,
                "partition int, rows long, daemon string, daemon_id string, "
                "boots string",
            ).collect()
        total, per_daemon, addr_of, _, _ = _ack_rows(acks)
        if total == 0:
            raise ValueError("cannot fit on an empty DataFrame")
        with DataPlaneClient(host, port, token=token, **ckw) as pc0:
            primary_id = pc0.server_id() or f"{host}:{port}"
        fed = {d: n for d, n in per_daemon.items() if n > 0}

        def _cleanup(drop_jobs=True, drop_models=()):
            # Free dataset-sized state BEFORE failing: a knn job/shard
            # holds the raw rows, and leaking them until TTL on every
            # daemon could OOM the corrected refit.
            for did in fed:
                try:
                    ah, ap = daemon_session._parse_addr(addr_of[did])
                    with DataPlaneClient(ah, ap, token=token, **ckw) as dc:
                        if drop_jobs:
                            _drop_quietly(dc, job, "knn_cleanup")
                        for m in drop_models:
                            dc.drop_model(m)
                except Exception as e:
                    _M_DROP_ERRORS.inc(stage="knn_cleanup")
                    logger.debug(
                        "knn cleanup on %s failed: %s", addr_of[did], e
                    )

        multi = len(fed) > 1
        if multi and any(":" in d for d in list(fed) + [primary_id]):
            _cleanup()
            raise RuntimeError(
                "knn fit fed multiple daemons but at least one does not "
                "self-report an instance id — it predates the sharded "
                "index serve. Upgrade every daemon, or route all "
                "executors to one daemon."
            )
        # Global ids are partition-major positions of the fitted DataFrame
        # (the single-daemon convention); each daemon's shard translates
        # its local positions through this base map.
        part_rows: dict = {}
        for r in acks:
            if int(r["rows"]) > 0:
                part_rows[int(r["partition"])] = int(r["rows"])
        id_base, cum = {}, 0
        for pid in sorted(part_rows):
            id_base[pid] = cum
            cum += part_rows[pid]
        name = f"knnidx-{job}"
        # Primary first (deterministic quantizer owner), then peers by id.
        daemon_ids = sorted(fed, key=lambda d: (d != primary_id, d))
        # The concurrent shard builds/samples below run on POOL threads,
        # whose journal stack is empty — capture the driver's fit frame
        # here so their clients still stamp it (trace_ctx ctor arg) and
        # the daemons' heaviest spans (index builds, sampling) parent
        # into the fit tree instead of orphaning.
        fit_ctx = journal.trace_ctx()

        def _finalize_shard(did, centroids=None, first=False,
                            train_rows_sample=None):
            ah, ap = daemon_session._parse_addr(addr_of[did])
            with DataPlaneClient(ah, ap, token=token, trace_ctx=fit_ctx,
                                 **ckw) as client:
                if ivf:
                    info = client.finalize_knn(
                        job, register_as=name, mode="ivf",
                        nlist=core.getNlist(), nprobe=core.getNprobe(),
                        seed=core.getSeed(), metric=metric,
                        row_id_base=id_base if multi else None,
                        centroids=centroids,
                        return_centroids=multi and first,
                        train_rows_sample=train_rows_sample,
                    )
                else:
                    info = client.finalize_knn(
                        job, register_as=name, mode="exact", metric=metric,
                        row_id_base=id_base if multi else None,
                    )
            n_shard = int(info["n_rows"][0])
            if n_shard != fed[did]:
                raise _split_brain(
                    f"knn shard build on {addr_of[did]}", fed[did], n_shard,
                    ", ".join(f"{addr_of[d]}={n}"
                              for d, n in sorted(fed.items())),
                )
            return info, (addr_of[did], n_shard)

        shards = []
        try:
            from concurrent.futures import ThreadPoolExecutor

            with trace_span("knn build"):
                if ivf and multi:
                    # The quantizer owner must not train on its OWN shard
                    # alone: locality-sticky routing makes that shard a
                    # non-random slice, skewing the shared centroids away
                    # from the peers' regions (ADVICE r5(b)). Sample every
                    # daemon in proportion to its committed rows and hand
                    # the union to the owning build — O(sample·d) on the
                    # wire, never the dataset.
                    with trace_span("quantizer sample"):
                        want = min(
                            total, max(64 * core.getNlist(), 4096), 65536
                        )

                        def _sample_shard(i, did):
                            # Ceil split: the union never rounds below
                            # ``want`` (the build's >= nlist floor).
                            n_d = (want * fed[did] + total - 1) // total
                            ah, ap = daemon_session._parse_addr(addr_of[did])
                            with DataPlaneClient(
                                ah, ap, token=token, trace_ctx=fit_ctx,
                                **ckw
                            ) as dc:
                                return dc.sample_rows(
                                    job, n_d, seed=core.getSeed() + i
                                )

                        # Independent per-daemon reads: pay the max RTT,
                        # not the sum (same pattern as the peer builds
                        # below). Ordered futures keep the union — and
                        # therefore the trained quantizer — deterministic.
                        with ThreadPoolExecutor(
                            max_workers=min(len(daemon_ids), 16)
                        ) as ex:
                            futs = [
                                ex.submit(_sample_shard, i, did)
                                for i, did in enumerate(daemon_ids)
                            ]
                            train_sample = np.concatenate(
                                [f.result() for f in futs], axis=0
                            )
                    # The first build is the quantizer owner — it must run
                    # before the peers; the peers' dataset-sized builds are
                    # then independent and run CONCURRENTLY (fit wall-clock =
                    # first + max of the rest, not the sum over daemons).
                    first_info, first_shard = _finalize_shard(
                        daemon_ids[0], first=True,
                        train_rows_sample=train_sample,
                    )
                    shards.append(first_shard)
                    cent = first_info["centroids"]
                    rest = daemon_ids[1:]
                    with ThreadPoolExecutor(max_workers=min(len(rest), 16)) as ex:
                        futs = [ex.submit(_finalize_shard, did, cent)
                                for did in rest]
                        shards.extend(f.result()[1] for f in futs)
                else:
                    # Exact mode (or one daemon): no cross-shard dependency —
                    # every build runs concurrently.
                    with ThreadPoolExecutor(
                        max_workers=min(len(daemon_ids), 16)
                    ) as ex:
                        futs = [ex.submit(_finalize_shard, did)
                                for did in daemon_ids]
                        shards.extend(f.result()[1] for f in futs)
        except Exception:
            _cleanup(drop_models=[name])
            raise
        if sum(n for _, n in shards) != total:
            _cleanup(drop_jobs=False, drop_models=[name])
            raise _split_brain(
                "knn index build", total, sum(n for _, n in shards),
                ", ".join(f"{a}={n}" for a, n in shards),
            )
        if multi:
            home_h, home_p = host, port
        else:
            # The index may have been built on an executor-override daemon
            # (not the driver-resolved one): the handle must query and
            # release where the index actually LIVES.
            home_h, home_p = daemon_session._parse_addr(shards[0][0])
        return _DaemonKNNModel(
            core, home_h, home_p, token, name,
            n_rows=total, input_col=input_col,
            shards=shards if multi else None, client_kw=ckw,
        )

    # -- distributed fit ---------------------------------------------------

    def _fit_distributed(self, df):
        """Journal-wrapped shell — the run journal (env
        ``SRML_RUN_JOURNAL``) gets one run per fit, with every feed
        pass / step / merge / finalize phase nested under it; see
        :meth:`_fit_distributed_inner` for the actual protocol."""
        with journal.run(
            "fit", estimator=type(self).__name__, algo=self._daemon_algo,
            uid=self._core.uid,
        ):
            return self._fit_distributed_inner(df)

    def _fit_distributed_inner(self, df):
        """Executor-fed fit: partition batches flow task→daemon, the
        driver sees only O(d²) finalize output — the reference's
        partition-Gram + small-partials property (RapidsRowMatrix.scala:
        118-139) with the daemon replacing the JVM tree-reduce."""
        core = self._core
        algo = self._daemon_algo
        # A scaler fit is a strict subset of the pca job's statistics —
        # it feeds the pca protocol and finalizes raw moments. Both
        # forest estimators speak the ONE "rf" job protocol (the params'
        # n_classes picks Gini vs variance daemon-side).
        wire_algo = (
            "pca" if algo == "scaler"
            else "rf" if algo in ("rf_classifier", "rf_regressor")
            else algo
        )
        spark = getattr(df, "sparkSession", None)
        host, port, token = daemon_session.resolve(spark)
        # Resilience tuning for every client this fit opens (driver AND,
        # via each task's own env read, executors): op deadlines bound the
        # healing, busy hints are honored with jittered waits.
        ckw = daemon_session.client_kwargs(spark)
        # Crash recovery: how many times one pass-boundary unit (scan +
        # step / finalize) may be REPLAYED after a daemon incarnation
        # change before the failure surfaces. 0 = off — and genuinely
        # zero-overhead: no ledger pulls, no extra wire ops.
        rec_attempts = daemon_session.recovery_attempts(spark)
        # Elastic degrade (docs/protocol.md "Permanent daemon loss"): how
        # many PEER daemons this fit may declare permanently dead and
        # amputate, and the reconnect/deadline budget a peer gets before
        # it escalates from *retrying* to *declared dead*. 0 (default) =
        # off: a lost daemon is today's loud error and no classification
        # probe ever runs. The recovery LEDGER arms for either feature —
        # an amputation rewinds survivors through the same boundary
        # replay a reboot does.
        loss_tolerance = daemon_session.daemon_loss_tolerance(spark)
        death_timeout = daemon_session.daemon_death_timeout_s(spark)
        elastic = loss_tolerance > 0
        # Elastic grow (docs/protocol.md "Mid-fit daemon join"): the
        # inverse direction — whether a daemon that APPEARS mid-fit
        # (dynamic allocation, a spot host coming up) may be admitted
        # at the next pass boundary. "off" (default) keeps the
        # unlisted-peer loud rejection byte-for-byte and runs no
        # discovery probe; the ledger arms for it like for the death
        # policy, because admission IS a boundary replay: the joiner is
        # seeded with the ledger iterate and the failed pass reruns on
        # the grown topology.
        join_policy = daemon_session.daemon_join_policy(spark)
        join_limit = daemon_session.daemon_join_limit(spark)
        grow = join_policy == "boundary"
        ledger_on = bool(rec_attempts) or elastic or grow
        job = f"{core.uid}-{uuid.uuid4().hex[:8]}"
        input_col = core.getOrDefault(
            "inputCol" if core.hasParam("inputCol") else "featuresCol"
        )
        label_col = (
            core.getOrDefault("labelCol")
            if algo in ("linreg", "logreg", "rf_classifier", "rf_regressor")
            else None
        )
        cols = [input_col] + ([label_col] if label_col else [])
        sel = df.select(*cols)
        multi_pass = algo in (
            "kmeans", "logreg", "rf_classifier", "rf_regressor",
        )
        if multi_pass:
            sel = sel.persist()

        from spark_rapids_ml_tpu.serve.client import DataPlaneClient

        feed_params = {}
        # Peer daemons (executor-local routing): keyed by self-reported
        # instance id (address spellings alias); discovered from task
        # acks pass by pass, seeded up front for kmeans (resolve_all).
        peers: dict = {}
        total_fed = 0
        fed_by_daemon: dict = {}
        client = DataPlaneClient(host, port, token=token, **ckw)
        primary_id = client.server_id() or f"{host}:{port}"
        addr_by_id = {primary_id: f"{host}:{port}"}
        # One long-lived client per peer daemon for the whole fit (the
        # primary already has one): merges and iterate syncs happen every
        # pass, and per-op TCP connect churn would dominate small passes.
        peer_clients: dict = {}
        # Per-fit collective-path memory (_reduce_on_mesh): remembers a
        # "this plane has no mesh ops" verdict so a fit probes once, not
        # every pass.
        mesh_cache: dict = {}
        # Amputated daemons (id → last known address): a quarantined
        # daemon is out of the fit for good — its routes are evicted, it
        # is never synced or merged again, and a replayed pass that still
        # acks rows from it fails loudly (it is alive with unrewound
        # state; the routing must stop feeding it).
        quarantined: dict = {}
        # Mid-fit joiners (id → address), and the subset whose first
        # post-admission acked pass has not landed yet — the rebalanced-
        # rows metric counts exactly that first pass (the rows the task
        # layer actually moved onto the newcomer).
        joined: dict = {}
        awaiting_rebalance: set = set()

        def peer_client(did, addr=None):
            c = peer_clients.get(did)
            if c is None:
                h2, p2 = (
                    daemon_session._parse_addr(addr)
                    if addr is not None else peers[did]
                )
                c = DataPlaneClient(h2, p2, token=token, **ckw)
                peer_clients[did] = c
            return c

        def seed_peer_daemons(seed_fn):
            """Register + pre-seed every CONFIGURED peer daemon
            (spark.srml.daemon.addresses) before pass 0 — the one
            implementation of the alias-proof discovery both seeded
            protocols (kmeans centers, forest iterate) share: peers key
            by self-reported instance id (address spellings alias), a
            client that never registers closes here (including on an
            unreachable/unauthorized peer), registered ones are closed
            by the fit's outer finally."""
            for ph, pp in daemon_session.resolve_all(spark):
                pc = DataPlaneClient(ph, pp, token=token, **ckw)
                registered = False
                try:
                    pid_ = pc.server_id() or f"{ph}:{pp}"
                    if pid_ == primary_id or pid_ in peers:
                        continue  # an alias of a daemon already seeded
                    peers[pid_] = (ph, pp)
                    peer_clients[pid_] = pc
                    registered = True
                    seed_fn(pc)
                finally:
                    if not registered:
                        pc.close()

        # Driver-held recovery ledger: the last-known-good iterate and
        # the pass it opens, snapshotted from the same get_iterate pull
        # the peer sync already makes at every boundary. On a daemon
        # incarnation change the pass is replayed from HERE — the daemon
        # is re-seeded (set_iterate recreates the job if the restart lost
        # it entirely), so recovery works even without daemon-side
        # durable state.
        ledger: dict = {"arrays": None, "iteration": None}

        try:
            if algo == "logreg":
                # Spark ML infers numClasses from the labels; here one
                # O(1)-result probe job (per-partition max) picks the
                # binary-Newton vs multinomial-MM daemon protocol.
                n_classes = _probe_num_classes(sel, label_col)
                feed_params = {"n_classes": n_classes}
            if algo == "kmeans":
                k = core.getK()
                feed_params = {
                    "k": k,
                    "seed": core.getSeed(),
                    "init": core.getInitMode(),
                }
                # Deterministic driver-side seeding: a small prefix sample
                # (≥ k rows) — ONE tiny Spark job, like the reference's
                # numCols probe (RapidsPCA.scala:73-74). The SAME batch +
                # rng seed goes to every configured daemon
                # (spark.srml.daemon.addresses), so all hosts open pass 0
                # with bitwise-identical centers; a peer daemon NOT listed
                # there fails its tasks loudly (centers unseeded).
                seed_n = max(k, min(4096, 32 * k))
                seed_tbl = _df_to_arrow(sel.limit(seed_n), [input_col])
                client.seed_kmeans(
                    job, seed_tbl, k=k, input_col=input_col, params=feed_params
                )
                seed_peer_daemons(
                    lambda pc: pc.seed_kmeans(
                        job, seed_tbl, k=k, input_col=input_col,
                        params=feed_params,
                    )
                )
                if ledger_on:
                    # Ledger seed: pass 0 opens with the seeded centers —
                    # a pass-0 replay re-installs exactly these.
                    ledger["arrays"], ledger["iteration"] = (
                        client.get_iterate(job)
                    )
            if algo in ("rf_classifier", "rf_regressor"):
                from spark_rapids_ml_tpu.bridge.arrow import (
                    table_column_to_matrix,
                )
                from spark_rapids_ml_tpu.models import (
                    random_forest as rf_mod,
                )
                from spark_rapids_ml_tpu.ops.histogram import (
                    quantile_bin_edges,
                )

                # numClasses from an O(1)-result label probe (the logreg
                # pattern); 0 = regression (variance splits).
                n_classes = (
                    _probe_num_classes(sel, label_col)
                    if algo == "rf_classifier" else 0
                )
                feed_params = {
                    "num_trees": core.getNumTrees(),
                    "max_depth": core.getMaxDepth(),
                    "max_bins": core.getMaxBins(),
                    "n_classes": n_classes,
                    "subset": core.getFeatureSubsetStrategy(),
                    "seed": core.getSeed(),
                    "bootstrap": core.getBootstrap(),
                    "min_instances": core.getMinInstancesPerNode(),
                }
                # Deterministic driver-side binning seed: a bounded
                # prefix sample (ONE tiny Spark job — the kmeans-seed /
                # numCols-probe pattern, RapidsPCA.scala:73-74) trains
                # the quantile sketch, and set_iterate installs the
                # SAME (edges + empty node tables) iterate on every
                # configured daemon before pass 0 — all hosts bin
                # bitwise-identically; an unlisted peer daemon fails
                # its tasks loudly (iterate unseeded), exactly the
                # kmeans contract.
                sample_n = int(config.get("forest_seed_sample_rows"))
                seed_tbl = _df_to_arrow(sel.limit(sample_n), [input_col])
                sample = table_column_to_matrix(seed_tbl, input_col, None)
                if sample.shape[0] == 0:
                    raise ValueError("cannot fit on an empty DataFrame")
                rf_n_cols = int(sample.shape[1])
                rf_spec = rf_mod.forest_spec_from_params(
                    feed_params, rf_n_cols
                )
                init_arrays = rf_mod.init_forest_arrays(
                    rf_spec, quantile_bin_edges(sample, rf_spec.max_bins)
                )
                client.set_iterate(
                    job, init_arrays, 0, algo=wire_algo,
                    n_cols=rf_n_cols, params=feed_params,
                )
                seed_peer_daemons(
                    lambda pc: pc.set_iterate(
                        job, init_arrays, 0, algo=wire_algo,
                        n_cols=rf_n_cols, params=feed_params,
                    )
                )
                if ledger_on:
                    # Ledger seed: a pass-0 replay re-installs exactly
                    # the seeded (edges + empty tables) iterate.
                    ledger["arrays"], ledger["iteration"] = (
                        client.get_iterate(job)
                    )

            def run_pass(pass_id, merge=True, drop_peer=False):
                """One executor scan; folds peer-daemon partials into the
                primary and reconciles row counts. Returns the pass total."""
                nonlocal total_fed
                fn = _FeedTask(
                    host, port, token, job, wire_algo, input_col,
                    label_col or "label", feed_params, pass_id,
                    # Ship the amputation set to the executors: THEIR
                    # cache copies hold the dead daemon's id (reused
                    # python workers), not the driver's.
                    evict_routes=sorted(
                        addr for addr in quarantined.values() if addr
                    ),
                )
                with trace_span("feed pass"):
                    acks = sel.mapInArrow(
                        fn,
                        "partition int, rows long, daemon string, "
                        "daemon_id string, boots string",
                    ).collect()
                n, per, addr_of, owner, boots = _ack_rows(acks)
                for did, cnt in per.items():
                    if cnt > 0 and did in quarantined:
                        # The amputation's safety valve: a daemon that
                        # was declared dead but ANSWERS the replayed
                        # scan is alive with unrewound state — folding
                        # its rows would corrupt the model the rewind
                        # just repaired.
                        raise RuntimeError(
                            f"daemon {addr_of[did]} ({did}) was declared "
                            f"dead and quarantined, yet acked {cnt} rows "
                            "of the replayed pass: it is alive and holds "
                            "un-rewound state. Stop routing executors to "
                            "it (it left this fit for good), or refit."
                        )
                for did, cnt in per.items():
                    fed_by_daemon[did] = fed_by_daemon.get(did, 0) + cnt
                    addr_by_id.setdefault(did, addr_of[did])
                    # Only a daemon that actually holds rows becomes a
                    # peer: an all-empty-partitions executor acks rows=0
                    # without ever creating the job there — set_iterate
                    # against it would fail an otherwise-consistent fit.
                    if cnt > 0 and did != primary_id and did not in peers:
                        # An unknown id AT THE PRIMARY ADDRESS — or one
                        # the live primary now answers with (the
                        # alias-proof identity check; address spellings
                        # alias) — is not a peer: it is the primary
                        # having restarted WITHOUT durable state (a
                        # state_dir daemon keeps its instance id).
                        # Registering it would export the primary's
                        # state and merge it into itself. Fence it like
                        # any incarnation change; recover() re-resolves
                        # the identity. The ping runs once per newly
                        # seen id per fit — not per pass.
                        if addr_of[did] == f"{host}:{port}" or did == (
                            client.server_id() or primary_id
                        ):
                            raise _incarnation_change(
                                addr_of[did], {primary_id, did}
                            )
                        # Instance ids are opaque hex; a ":" means the
                        # address-string FALLBACK for a daemon that does
                        # not report an id — such a daemon predates the
                        # multi-host ops entirely, and an aliased
                        # spelling of the primary would masquerade as a
                        # peer. Refuse clearly instead of failing later
                        # with an opaque unknown-op error (or worse,
                        # merging the primary into itself).
                        if ":" in did or ":" in primary_id:
                            raise RuntimeError(
                                f"task acks name a second daemon "
                                f"({addr_of[did]} vs primary "
                                f"{addr_by_id[primary_id]}) but at least "
                                "one daemon does not report an instance "
                                "id — it predates the multi-host data "
                                "plane. Upgrade every daemon, or unify "
                                "the daemon address spelling and use one "
                                "daemon."
                            )
                        peers[did] = daemon_session._parse_addr(addr_of[did])
                # The grow metric's ground truth: the first pass a
                # joiner actually acks rows for IS the rebalance — the
                # task layer moved those rows onto the newcomer.
                for did in sorted(awaiting_rebalance):
                    if per.get(did, 0) > 0:
                        _M_FIT_REBALANCED.inc(per[did], algo=str(algo))
                        awaiting_rebalance.discard(did)
                # Incarnation fence AFTER peer registration (recover()
                # must know every daemon this pass touched, so it can
                # rewind/drop them all) but BEFORE any merge: partials
                # from a daemon that restarted under the scan are partial
                # in an unknowable way — folding them would poison the
                # primary.
                for did, bs in boots.items():
                    if len(bs) > 1:
                        raise _incarnation_change(addr_of.get(did, did), bs)
                if merge:
                    with trace_span("merge peers"):
                        # Collective first (docs/mesh.md): co-resident
                        # daemons reduce on the device plane; the
                        # export/merge hub is the fallback for peers on
                        # a different runtime (or predating the op).
                        if not _reduce_on_mesh(
                            client, job, primary_id, per, addr_of, owner,
                            boots, wire_algo, feed_params, drop_peer,
                            mesh_cache,
                        ):
                            _merge_peer_daemons(
                                client, job, primary_id, per, addr_of,
                                owner, peer_client, wire_algo, feed_params,
                                drop_peer=drop_peer,
                            )
                total_fed += n
                return n

            def _fed_detail():
                return ", ".join(
                    f"{addr_by_id.get(d, d)}={c}"
                    for d, c in sorted(fed_by_daemon.items())
                ) or "no acks"

            def finalize_guarded(params, pass_rows_expected=None):
                """Primary finalize + the split-brain row guard: the
                daemon-accounted total must equal what tasks acked.
                Replay-safe split: finalize with drop=False, validate,
                THEN drop — a guard failure leaves the job intact for a
                recovery replay. ``pass_rows_expected`` additionally pins
                the CURRENT pass's rows (the kmeans cost reads the
                current pass's state; a job resurrected at an empty
                boundary would silently answer cost 0)."""
                with trace_span("finalize"):
                    arrays, fin_rows, meta = client.finalize(
                        job, params, drop=False, with_meta=True
                    )
                if fin_rows != total_fed:
                    raise _split_brain(
                        "finalize", total_fed, fin_rows, _fed_detail()
                    )
                if (
                    pass_rows_expected is not None
                    and meta.get("pass_rows") is not None
                    and int(meta["pass_rows"]) != int(pass_rows_expected)
                ):
                    raise _split_brain(
                        "finalize (current pass)", int(pass_rows_expected),
                        int(meta["pass_rows"]), _fed_detail(),
                    )
                # Best-effort: the validated arrays are already in hand —
                # a cleanup failure here must not fail (or re-scan) the
                # fit. The outer finally retries the drop anyway.
                _drop_quietly(client, job, "finalize")
                return arrays, fin_rows

            def sync_and_record(push_peers=True):
                """Pass boundary: distribute the primary's post-step
                iterate to every peer AND snapshot it into the recovery
                ledger (one get_iterate serves both).
                ``push_peers=False`` records the ledger only — the
                converged-logreg boundary, where nothing will read a
                peer's iterate but a finalize replay still rewinds to
                exactly this iterate."""
                if not (peers and push_peers) and not ledger_on:
                    return
                arrays, iteration = client.get_iterate(job)
                if push_peers:
                    for did in sorted(peers):
                        peer_client(did).set_iterate(job, arrays, iteration)
                if ledger_on:
                    # The ledger advances ONLY once every daemon holds
                    # the new boundary: a half-pushed boundary (a peer
                    # died mid-sync) must replay from the OLD one — an
                    # early-advanced ledger would pin the daemons at
                    # iteration N+1 while the replay re-feeds pass N,
                    # turning every replay into a stale-pass rejection.
                    ledger["arrays"], ledger["iteration"] = arrays, iteration

            def _probe_alive(addr_tuple) -> bool:
                """Liveness verdict under the death policy: the probing
                client's op deadline IS ``fit_daemon_death_timeout_s``,
                so the daemon gets the WHOLE reconnect/backoff budget to
                answer one ping — a slow or busy daemon that makes it in
                time is never amputated on a hunch."""
                probe_kw = dict(ckw)
                probe_kw["op_deadline_s"] = death_timeout
                probe_kw["max_op_attempts"] = max(
                    int(probe_kw.get("max_op_attempts", 5)), 8
                )
                try:
                    with DataPlaneClient(*addr_tuple, token=token,
                                         **probe_kw) as pc:
                        pc.ping()
                    return True
                except Exception:
                    return False

            def try_admit(err) -> bool:
                """The grow policy's admission step (docs/protocol.md
                "Mid-fit daemon join"), run only after a pass unit
                already failed — a new daemon's unseeded-job rejection
                of its first feeds IS the detection signal, and the
                happy path stays zero-overhead (one env/conf re-read,
                no wire ops unless a genuinely new address appears).
                Re-reads the configured daemon set (Spark dynamic
                allocation re-points ``spark.srml.daemon.addresses``),
                identifies addresses that resolve to an instance id
                this fit does not know, and admits each at the CURRENT
                pass boundary: ``set_iterate`` seeds it with the ledger
                iterate (the same algo/n_cols/params creation fields a
                quarantine replay uses — the job is created from
                nothing on the joiner), membership registration bumps
                the mesh epoch daemon-side so the next collective
                reduce re-fences, and the caller's ``recover`` rewinds
                every daemon to the same boundary before the replay
                rebalances partitions onto the newcomer. True = at
                least one daemon admitted (replay on the grown
                topology); False = nothing new appeared — the loss
                policy or the transient replay budget rules."""
                if not grow or ledger["arrays"] is None:
                    # No boundary iterate to seed a joiner from (a
                    # single-pass algo, whose ack path already admits
                    # unknown peers natively, or a pre-seed failure).
                    return False
                known = {f"{host}:{port}"}
                known.update(f"{h2}:{p2}" for h2, p2 in peers.values())
                known.update(a for a in quarantined.values() if a)
                known.update(a for a in joined.values() if a)
                candidates = [
                    (ph, pp) for ph, pp in daemon_session.resolve_all(spark)
                    if f"{ph}:{pp}" not in known
                ]
                admitted = []
                for ph, pp in candidates:
                    addr = f"{ph}:{pp}"
                    pc = DataPlaneClient(ph, pp, token=token, **ckw)
                    registered = False
                    try:
                        try:
                            did = pc.server_id()
                        except Exception:
                            continue  # configured but not up yet
                        # Alias fences, in the run_pass order: an
                        # unknown ADDRESS may still be a spelling of a
                        # daemon this fit already knows.
                        if not did or did == primary_id or did in peers:
                            continue
                        if did in quarantined:
                            # A dead daemon's address re-answering with
                            # the same id is the quarantine safety
                            # valve's territory, not a joiner.
                            continue
                        if len(joined) + 1 > join_limit:
                            raise RuntimeError(
                                f"daemon {addr} ({did}) appeared mid-fit "
                                f"but this fit's join budget is spent "
                                f"(fit_daemon_join_limit={join_limit}, "
                                f"{len(joined)} already admitted). Raise "
                                "the limit, or stop routing executors "
                                "to it until the next fit."
                            ) from err
                        # The admission handshake: seed the joiner with
                        # the boundary iterate. A joiner that vanishes
                        # UNDER the handshake must not half-join — the
                        # set_iterate failure surfaces here, nothing
                        # was registered, and the original error's
                        # replay path resumes without it.
                        faults.checkpoint("daemon.join")
                        arrays = ledger["arrays"]
                        n_cols = int(
                            arrays["centers"].shape[1]
                            if "centers" in arrays
                            else arrays["bin_edges"].shape[0]
                            if "bin_edges" in arrays
                            else arrays["w"].shape[0]
                        )
                        pc.set_iterate(
                            job, arrays, int(ledger["iteration"]),
                            algo=wire_algo, n_cols=n_cols,
                            params=feed_params,
                        )
                        peers[did] = (ph, pp)
                        addr_by_id[did] = addr
                        peer_clients[did] = pc
                        registered = True
                        joined[did] = addr
                        awaiting_rebalance.add(did)
                        admitted.append(did)
                        _M_FIT_JOINS.inc(algo=str(algo))
                        journal.mark(
                            "fit daemon join", algo=algo, job=job,
                            daemon=did, addr=addr,
                            iteration=int(ledger["iteration"]),
                        )
                        logger.warning(
                            "fit elastic grow (%s): daemon %s (%s) "
                            "admitted at the pass-%d boundary — seeded "
                            "with the ledger iterate; replaying the "
                            "failed pass on the %d-daemon topology",
                            algo, addr, did, int(ledger["iteration"]),
                            len(peers) + 1,
                        )
                    finally:
                        if not registered:
                            pc.close()
                return bool(admitted)

            def try_quarantine(err) -> bool:
                """The death policy's classification step, run only after
                a pass unit already failed (zero wire ops on the happy
                path): probe every peer within the death deadline,
                corroborate with mesh membership when co-resident, and
                amputate the corroborated-dead peers if the loss budget
                allows. True = at least one daemon quarantined (the pass
                replays on the shrunken topology); False = nothing
                classified as dead — the transient replay budget (or the
                original error) rules."""
                if not peers:
                    return False
                # Mesh corroboration (docs/mesh.md): on the collective
                # path the membership registry is a second witness — a
                # peer the device plane still lists as a live member is
                # NOT dead, however its TCP probe fared.
                live_members = None
                if not mesh_cache.get("hub_only"):
                    try:
                        info = client.mesh_info()
                        live_members = {
                            str(m["id"]) for m in info.get("members", [])
                        }
                    except Exception:
                        live_members = None
                # Probes run CONCURRENTLY (independent reads): a pod-
                # scale fit partitioned away from several peers must
                # classify in ~one death deadline, not n_peers of them.
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(
                    max_workers=min(len(peers) + 1, 16)
                ) as ex:
                    primary_fut = ex.submit(_probe_alive, (host, port))
                    peer_futs = {
                        did: ex.submit(_probe_alive, peers[did])
                        for did in sorted(peers)
                    }
                    primary_ok = primary_fut.result()
                    alive = {d_: f.result() for d_, f in peer_futs.items()}
                # The primary is the reduce target and the rewind anchor:
                # its loss is not survivable by amputation — name that
                # clearly instead of burning the tolerance on peers.
                if not primary_ok:
                    raise RuntimeError(
                        f"primary daemon {host}:{port} is unreachable "
                        f"(no answer within the {death_timeout:.1f}s "
                        "death deadline): elastic degrade can only "
                        "amputate PEER daemons — the primary holds the "
                        "folded state. Restart it (crash recovery "
                        "resurrects durable jobs) or refit."
                    ) from err
                dead = []
                for did in sorted(peers):
                    if alive[did]:
                        continue
                    if live_members is not None and did in live_members:
                        logger.warning(
                            "peer daemon %s failed its liveness probe "
                            "but is still a live mesh member — treating "
                            "the failure as transient, not a death",
                            addr_by_id.get(did, did),
                        )
                        continue
                    dead.append(did)
                if not dead:
                    return False
                if len(quarantined) + len(dead) > loss_tolerance:
                    raise RuntimeError(
                        f"daemon(s) "
                        f"{[addr_by_id.get(d, d) for d in dead]} gave no "
                        f"answer within the {death_timeout:.1f}s death "
                        f"deadline, but this fit's loss budget is spent "
                        f"(fit_daemon_loss_tolerance={loss_tolerance}, "
                        f"{len(quarantined)} already quarantined). Raise "
                        "the tolerance, or refit on the surviving "
                        "daemons."
                    ) from err
                for did in dead:
                    addr = addr_by_id.get(did)
                    quarantined[did] = addr
                    peers.pop(did, None)
                    pc = peer_clients.pop(did, None)
                    if pc is not None:
                        pc.close()
                    if addr is not None:
                        # The replayed tasks must re-ping whatever now
                        # answers at the dead daemon's address — a cached
                        # id would resurrect the ghost.
                        _evict_daemon_id_cache(job, addr)
                    _M_DAEMON_LOSSES.inc(algo=str(algo))
                    journal.mark(
                        "fit daemon loss", algo=algo, job=job,
                        daemon=did, addr=addr,
                    )
                    logger.warning(
                        "fit elastic degrade (%s): peer daemon %s (%s) "
                        "declared dead — no answer within the %.1fs "
                        "death deadline; quarantining it and replaying "
                        "from the last pass boundary with its "
                        "partitions rerouted to the %d survivor(s)",
                        algo, addr, did, death_timeout, len(peers) + 1,
                    )
                return True

            def recover(err):
                """Rewind the fit to the last pass boundary: re-seed the
                iterate from the driver ledger on EVERY daemon
                (set_iterate discards the poisoned pass-local state and
                recreates lost jobs), then resynchronize the row
                accounting from the daemon's authoritative total. With no
                ledger yet (pass 0 of a fresh fit, or a single-pass
                algo) the unit is re-runnable from nothing: drop the
                jobs and replay the whole scan."""
                nonlocal total_fed, primary_id
                _M_FIT_RECOVERIES.inc(algo=str(algo))
                logger.warning(
                    "fit recovery (%s): replaying from the last pass "
                    "boundary after: %s", algo, err,
                )
                journal.mark(
                    "fit recovery", algo=algo, job=job, error=str(err)[:300]
                )
                with trace_span("recovery"):
                    # Re-resolve the primary's identity: a volatile
                    # (no-state_dir) restart minted a new instance id,
                    # and the replay's acks must match it — otherwise
                    # the restarted primary would register as its own
                    # peer and be merged into itself.
                    new_id = client.server_id() or primary_id
                    if new_id != primary_id:
                        addr_by_id[new_id] = f"{host}:{port}"
                        peers.pop(new_id, None)
                        primary_id = new_id
                    arrays = ledger["arrays"]
                    if arrays is not None:
                        # Registration-table shape dispatch: which array
                        # carries the feature width per iterate layout
                        # (kmeans centers / forest bin edges / logreg w).
                        n_cols = int(
                            arrays["centers"].shape[1]
                            if "centers" in arrays
                            else arrays["bin_edges"].shape[0]
                            if "bin_edges" in arrays
                            else arrays["w"].shape[0]
                        )
                        iteration = int(ledger["iteration"])
                        client.set_iterate(
                            job, arrays, iteration, algo=wire_algo,
                            n_cols=n_cols, params=feed_params,
                        )
                        for did in sorted(peers):
                            peer_client(did).set_iterate(
                                job, arrays, iteration, algo=wire_algo,
                                n_cols=n_cols, params=feed_params,
                            )
                        total_fed = int(client.status(job)["rows"])
                    else:
                        for c_ in [client] + [
                            peer_client(d) for d in sorted(peers)
                        ]:
                            _drop_quietly(c_, job, "recovery")
                        total_fed = 0
                    fed_by_daemon.clear()

            def with_recovery(body):
                """Run one pass-boundary-delimited unit (scan [+ step]
                [+ finalize]) under the bounded replay loop. Recovery
                off (the default) adds nothing: the first failure
                surfaces unchanged. Deterministic driver-side failures
                (validation/config/programming errors) are never
                replayed — a full-dataset re-scan cannot fix an empty
                DataFrame or a bad label column. Daemon/task failures
                (RuntimeError from acks, transport errors, job aborts)
                are the retryable class the replay exists for.

                Elastic degrade rides the same loop: a failure that
                classifies as a PERMANENT daemon death (try_quarantine)
                replays the pass on the shrunken topology without
                consuming the transient replay budget — each amputation
                consumes the loss tolerance instead, so both budgets
                stay bounded."""
                attempt = 0
                while True:
                    try:
                        return body()
                    except (ValueError, TypeError, KeyError,
                            AttributeError, AssertionError,
                            NotImplementedError):
                        raise  # deterministic — a replay cannot help
                    except Exception as e:
                        # Grow first: a failure caused by an unadmitted
                        # newcomer (its unseeded-job rejections failed
                        # the scan) is healed by ADMITTING it, and the
                        # admission consumes the join budget — not the
                        # transient replay budget, and never the loss
                        # tolerance (every incumbent is alive).
                        if grow and try_admit(e):
                            with trace_span("elastic grow"):
                                journal.mark(
                                    "fit elastic-grow", algo=algo,
                                    job=job, error=str(e)[:300],
                                )
                                recover(e)
                            continue
                        if elastic and try_quarantine(e):
                            with trace_span("elastic degrade"):
                                _M_FIT_REROUTES.inc(algo=str(algo))
                                journal.mark(
                                    "fit elastic-degrade", algo=algo,
                                    job=job, error=str(e)[:300],
                                )
                                recover(e)
                            continue
                        if attempt >= rec_attempts:
                            raise
                        attempt += 1
                        recover(e)

            if algo == "scaler":

                def scaler_shot():
                    n = run_pass(None, drop_peer=True)
                    if n == 0:
                        raise ValueError("cannot fit on an empty DataFrame")
                    return finalize_guarded(
                        {"raw_moments": True}, pass_rows_expected=n
                    )

                arrays, _ = with_recovery(scaler_shot)
                from spark_rapids_ml_tpu.models.scaler import StandardScalerModel

                cnt = float(arrays["count"][0])
                mean = np.asarray(arrays["colsum"], np.float64) / cnt
                var = (
                    np.asarray(arrays["gram_diag"], np.float64)
                    - cnt * mean * mean
                ) / max(cnt - 1.0, 1.0)
                model = StandardScalerModel(
                    mean=mean, std=np.sqrt(np.maximum(var, 0.0))
                )
            elif algo == "pca":

                def pca_shot():
                    n = run_pass(None, drop_peer=True)
                    if n == 0:
                        raise ValueError("cannot fit on an empty DataFrame")
                    return finalize_guarded(
                        {
                            "k": core.getK(),
                            "mean_center": core.getMeanCentering(),
                            "solver": core.getSolver(),
                        },
                        pass_rows_expected=n,
                    )

                arrays, _ = with_recovery(pca_shot)
                from spark_rapids_ml_tpu.models.pca import PCAModel

                model = PCAModel(
                    pc=arrays["pc"],
                    explained_variance=arrays["explained_variance"],
                    mean=arrays["mean"],
                )
            elif algo == "linreg":

                def linreg_shot():
                    n = run_pass(None, drop_peer=True)
                    if n == 0:
                        raise ValueError("cannot fit on an empty DataFrame")
                    return finalize_guarded(
                        {
                            "reg": core.getRegParam(),
                            "elastic_net": core.getElasticNetParam(),
                            "fit_intercept": core.getFitIntercept(),
                            "max_iter": core.getMaxIter(),
                            "tol": core.getTol(),
                        },
                        pass_rows_expected=n,
                    )

                arrays, rows = with_recovery(linreg_shot)
                from spark_rapids_ml_tpu.models.linear_regression import (
                    LinearRegressionModel,
                    LinearRegressionTrainingSummary,
                )

                model = LinearRegressionModel(
                    coefficients=arrays["coefficients"],
                    intercept=float(arrays["intercept"][0]),
                )
                model._summary = LinearRegressionTrainingSummary(
                    rmse=float(arrays["rmse"][0]),
                    r2=float(arrays["r2"][0]),
                    rss=float("nan"),
                    tss=float("nan"),
                    n_rows=rows,
                )
            elif algo == "kmeans":
                tol2 = core.getTol() ** 2
                info = {"cost": float("nan"), "iteration": 0}

                def kmeans_pass(pass_id):
                    n = run_pass(pass_id)
                    if n == 0:
                        raise ValueError("cannot fit on an empty DataFrame")
                    with trace_span("step"):
                        inf = client.step(job)
                    # The step's statistics must cover exactly the rows
                    # the scan acked: a job resurrected mid-pass (its
                    # pass-local state died with the old incarnation)
                    # answers short here instead of stepping on partial
                    # sums.
                    if int(inf["pass_rows"]) != n:
                        raise _split_brain(
                            f"step (pass {pass_id})", n,
                            int(inf["pass_rows"]), _fed_detail(),
                        )
                    # Every peer opens the new pass with the primary's
                    # post-step centers (set_iterate resets its pass
                    # stats) — the cross-host Lloyd lockstep — and the
                    # recovery ledger snapshots the same pull. Runs even
                    # on the converged pass: the final cost-only scan
                    # below feeds peers against the updated centers.
                    # INSIDE the recovery unit: a daemon dying in this
                    # window rewinds to the previous boundary and the
                    # whole scan+step+sync replays.
                    sync_and_record()
                    return inf

                for it in range(core.getMaxIter()):
                    info = with_recovery(lambda pid=it: kmeans_pass(pid))
                    if info["moved2"] <= tol2:
                        break

                # One final cost-only scan at the UPDATED centers (r2
                # advisor: step() evaluates cost against the pre-update
                # centers, so the last step's cost is one Lloyd iteration
                # stale). finalize reads the unstepped pass's inertia —
                # the exact fit_kmeans_stream trainingCost semantics.
                def kmeans_final():
                    n = run_pass(info["iteration"])
                    fin_arrays, _ = finalize_guarded(
                        {}, pass_rows_expected=n
                    )
                    return n, fin_arrays

                n_rows, arrays = with_recovery(kmeans_final)
                cost = float(arrays["cost"][0])
                from spark_rapids_ml_tpu.models.kmeans import (
                    KMeansModel,
                    KMeansSummary,
                )

                model = KMeansModel(centers=arrays["centers"])
                model._training_cost = cost
                model._n_iter = info["iteration"]
                model._summary = KMeansSummary(
                    trainingCost=cost,
                    numIter=info["iteration"],
                    k=core.getK(),
                    n_rows=n_rows,
                )
            elif algo in ("rf_classifier", "rf_regressor"):
                info = {"open_nodes": 1, "iteration": 0, "depth": 0}
                rows = 0

                def rf_pass(pass_id):
                    n = run_pass(pass_id)
                    if n == 0:
                        raise ValueError("cannot fit on an empty DataFrame")
                    with trace_span("step"):
                        inf = client.step(job)
                    # The step's histogram must cover exactly the rows
                    # the scan acked (the kmeans/logreg fence): a job
                    # resurrected mid-pass answers short here instead of
                    # splitting on partial histograms.
                    if int(inf["pass_rows"]) != n:
                        raise _split_brain(
                            f"step (pass {pass_id})", n,
                            int(inf["pass_rows"]), _fed_detail(),
                        )
                    # Boundary sync INSIDE the recovery unit: peers open
                    # the next depth with the primary's grown node
                    # tables, and the ledger snapshots the same pull —
                    # a daemon dying here rewinds to the previous
                    # boundary and the whole scan+step+sync replays.
                    sync_and_record()
                    return n, inf

                # One histogram pass per tree depth, until every
                # frontier closed (or maxDepth landed its last split).
                for it in range(core.getMaxDepth() + 1):
                    rows, info = with_recovery(lambda pid=it: rf_pass(pid))
                    if int(info["open_nodes"]) == 0:
                        break
                arrays, _ = with_recovery(lambda: finalize_guarded({}))
                from spark_rapids_ml_tpu.models.random_forest import (
                    RandomForestClassificationModel,
                    RandomForestRegressionModel,
                )

                arrays = dict(arrays)
                arrays.pop("n_iter", None)
                cls = (
                    RandomForestClassificationModel
                    if algo == "rf_classifier"
                    else RandomForestRegressionModel
                )
                model = cls(arrays=arrays)
            else:  # logreg
                info = {"loss": float("nan"), "iteration": 0}
                step_params = {
                    "reg": core.getRegParam(),
                    "fit_intercept": core.getFitIntercept(),
                }
                rows = 0

                def logreg_pass(pass_id):
                    n = run_pass(pass_id)
                    if n == 0:
                        raise ValueError("cannot fit on an empty DataFrame")
                    with trace_span("step"):
                        inf = client.step(job, params=step_params)
                    if int(inf["pass_rows"]) != n:
                        raise _split_brain(
                            f"step (pass {pass_id})", n,
                            int(inf["pass_rows"]), _fed_detail(),
                        )
                    # Boundary sync INSIDE the recovery unit (a daemon
                    # dying here rewinds to the previous boundary and the
                    # whole scan+step+sync replays). Converged: nothing
                    # reads a peer sync now, but the ledger still needs
                    # THIS iterate — a finalize replay rewinds to it.
                    # (Pass 0 needs no peer sync either way: every daemon
                    # starts at the zero iterate — a pass-0 replay just
                    # drops and recreates the job.)
                    sync_and_record(
                        push_peers=inf["delta"] > core.getTol()
                    )
                    return n, inf

                for it in range(core.getMaxIter()):
                    rows, info = with_recovery(
                        lambda pid=it: logreg_pass(pid)
                    )
                    if info["delta"] <= core.getTol():
                        break
                arrays, _ = with_recovery(lambda: finalize_guarded({}))
                from spark_rapids_ml_tpu.models.logistic_regression import (
                    LogisticRegressionModel,
                    LogisticTrainingSummary,
                )

                coef = arrays["coefficients"]
                model = LogisticRegressionModel(
                    coefficients=coef,
                    # Binary: scalar; multinomial ((C, d) coef): (C,) vector.
                    intercept=(
                        float(arrays["intercept"][0])
                        if coef.ndim == 1
                        else np.asarray(arrays["intercept"])
                    ),
                )
                model._summary = LogisticTrainingSummary(
                    loss=info["loss"], numIter=info["iteration"], n_rows=rows
                )
        finally:
            # The fit's id-cache routes die with the fit (success,
            # failure, or quarantine): the entries are job-scoped, so a
            # leaked one both grows forever on a long-lived driver and
            # could hand a RECYCLED job name a stale daemon id.
            _evict_daemon_id_cache(job)
            # no-op when finalize already dropped it; failures are
            # COUNTED (srml_client_drop_errors_total) — a swallowed drop
            # leaks the daemon job until the TTL reaper hides it.
            _drop_quietly(client, job, "primary")
            client.close()
            for did in list(peers):
                try:
                    _drop_quietly(peer_client(did), job, "peer")
                except Exception as e:  # peer_client() itself can fail
                    _M_DROP_ERRORS.inc(stage="peer")
                    logger.debug(
                        "cleanup drop on peer %s failed: %s", did, e
                    )
            for pc in peer_clients.values():
                pc.close()
            if multi_pass:
                sel.unpersist()
        model.uid = core.uid
        core._copy_params_to(model)
        return model


def _serve_spec(core_model):
    """(wire algo, [(role, output column name, kind)]) for models that
    declare the daemon serving contract (``_serve_algo``/``_serve_outputs``
    on the model class); None for models without one (KNN — no transform)."""
    algo = getattr(core_model, "_serve_algo", None)
    outs = getattr(core_model, "_serve_outputs", None)
    if not algo or not outs:
        return None
    return algo, [
        (role, core_model.getOrDefault(param), kind) for role, param, kind in outs
    ]


def _scalar_params(core_model):
    """Serving-behavior params of the model (``_serve_params`` on the
    model class, e.g. scaler withMean/withStd) — what a served daemon
    copy needs to transform identically. Cosmetic params (column names,
    k, ...) don't change the served output and are excluded so they don't
    fragment the daemon registry."""
    names = getattr(core_model, "_serve_params", ())
    return {n: core_model.getOrDefault(n) for n in names}


def _model_fingerprint(core_model) -> str:
    """Content hash of the fitted arrays + serving params: the daemon
    registry key. Two models with identical fits share a served copy;
    a refit under the same uid gets a fresh one."""
    import hashlib

    h = hashlib.md5()
    for k, v in sorted(core_model._model_data().items()):
        h.update(k.encode())
        if v is not None:
            h.update(np.ascontiguousarray(v).tobytes())
    for k, v in sorted(_scalar_params(core_model).items()):
        h.update(f"{k}={v!r}".encode())
    return h.hexdigest()[:12]


def _arrow_kind_type(kind):
    import pyarrow as pa

    return {
        "vec": pa.list_(pa.float64()),
        "ivec": pa.list_(pa.int64()),
        "int": pa.int32(),
        "double": pa.float64(),
    }[kind]


def _output_column(vals, kind, n_rows):
    """Build one canonical output column: the declared mapInArrow schema
    (vec → list<float64>, ivec → list<int64>, int → int32, double →
    float64) must hold regardless of the compute dtype the transform ran
    in."""
    import pyarrow as pa

    if n_rows == 0:
        return pa.array([], _arrow_kind_type(kind))
    if vals is None:
        raise RuntimeError(
            "daemon transform returned no array for a declared output role "
            "(client/daemon version skew?) — upgrade the daemon or set "
            "SRML_TRANSFORM_LOCAL=1 to score executor-side"
        )
    vals = np.asarray(vals)
    if kind in ("vec", "ivec"):
        from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column

        dt = np.float64 if kind == "vec" else np.int64
        col = matrix_to_list_column(vals.astype(dt))
        return col.cast(_arrow_kind_type(kind))
    if kind == "int":
        return pa.array(vals.astype(np.int32))
    return pa.array(vals.astype(np.float64))


def _derive_output_schema(dataset, outputs):
    """Output schema = input schema + declared output fields, computed
    WITHOUT running a Spark job (the round-2 review flagged the old
    limit(1) probe as one job per transform call). Duck-typed test
    harnesses have no StructType schema — they ignore the argument."""
    try:
        from pyspark.sql import types as T

        base = dataset.schema
    except (ImportError, AttributeError):
        return None
    out_names = {name for _, name, _ in outputs}
    fields = [f for f in base.fields if f.name not in out_names]
    spark_types = {
        "vec": lambda: T.ArrayType(T.DoubleType()),
        "ivec": lambda: T.ArrayType(T.LongType()),
        "int": T.IntegerType,
        "double": T.DoubleType,
    }
    for _, name, kind in outputs:
        fields.append(T.StructField(name, spark_types[kind](), True))
    return T.StructType(fields)


def _append_outputs(table, role_arrays, outputs):
    """Append/replace the model's output columns on one batch table."""
    for role, colname, kind in outputs:
        if colname in table.column_names:
            table = table.drop_columns([colname])
        table = table.append_column(
            colname, _output_column(role_arrays.get(role), kind, table.num_rows)
        )
    return table


class _TransformTask:
    """Executor-side (CPU) batch transform — the EXPLICIT fallback when no
    daemon should be used (SRML_TRANSFORM_LOCAL=1). Pickle-able: the
    model's fitted arrays ride the closure to each task, resident for the
    task's lifetime — no per-batch re-upload (fixes rapidsml_jni.cu:85),
    but the compute runs on the executor's host backend, not the TPU."""

    def __init__(self, core_model, input_col, outputs):
        self._core = core_model
        self._input_col = input_col
        self._outputs = outputs

    def __call__(self, batches):
        import pyarrow as pa

        from spark_rapids_ml_tpu.core.dataset import as_matrix

        for batch in batches:
            table = pa.Table.from_batches([batch])
            if table.num_rows == 0:
                yield from _append_outputs(table, {}, self._outputs).to_batches()
                continue
            x = as_matrix(table, self._input_col)
            outs = self._core.transform_matrix(x)
            yield from _append_outputs(table, outs, self._outputs).to_batches()


class _DaemonTransformTask:
    """Executor-side feeder for TPU-served transform: batches stream to
    the data-plane daemon's ``transform`` op and the projected columns
    come back — the reference's accelerator-resident columnar UDF
    (RapidsPCA.scala:128-161 → rapidsml_jni.cu:75-107), with the model
    registered once (ensure_model) and device-resident across batches.
    Only the features column crosses the wire; passthrough columns never
    leave the executor."""

    def __init__(self, core_model, host, port, token, input_col, algo, outputs):
        self._core = core_model  # fitted arrays ride the closure (jit caches strip)
        self.host, self.port, self.token = host, port, token
        self._input_col = input_col
        self._algo = algo
        self._outputs = outputs
        self._name = f"{core_model.uid}-{_model_fingerprint(core_model)}"
        self._params = _scalar_params(core_model)

    def __call__(self, batches):
        import pyarrow as pa

        from spark_rapids_ml_tpu.serve.client import DataPlaneClient
        from spark_rapids_ml_tpu.spark import daemon_session as ds

        h, p = ds.executor_daemon_address(self.host, self.port)
        with DataPlaneClient(h, p, token=self.token, **ds.client_kwargs()) as c:
            registered = c.model_exists(self._name)
            for batch in batches:
                table = pa.Table.from_batches([batch])
                if table.num_rows == 0:
                    yield from _append_outputs(table, {}, self._outputs).to_batches()
                    continue
                if not registered:
                    c.ensure_model(
                        self._name, self._algo, self._core._model_data(),
                        params=self._params,
                    )
                    registered = True
                try:
                    outs = c.transform(
                        self._name,
                        table.select([self._input_col]),
                        input_col=self._input_col,
                    )
                except RuntimeError as e:
                    if "no such model" not in str(e):
                        raise
                    # Registrations are stateless and TTL-evictable; the
                    # documented recovery (docs/protocol.md) is to
                    # re-register and retry — the task has everything.
                    c.ensure_model(
                        self._name, self._algo, self._core._model_data(),
                        params=self._params,
                    )
                    outs = c.transform(
                        self._name,
                        table.select([self._input_col]),
                        input_col=self._input_col,
                    )
                yield from _append_outputs(table, outs, self._outputs).to_batches()


_KNN_OUTPUTS = (
    ("distances", "knn_distances", "vec"),
    ("indices", "knn_indices", "ivec"),
)


def _fanout_kneighbors(ex, shard_clients, name, queries, k, input_col,
                       descending):
    """Query every shard daemon concurrently and merge top-k — the ONE
    implementation both the executor task and the driver handle use.
    ``ex``: a ThreadPoolExecutor (caller-owned, reusable across batches);
    ``shard_clients``: [((addr, shard_rows), client)] with one client per
    shard (no socket sharing across threads). Per-batch latency is the
    slowest shard, not the sum."""
    from spark_rapids_ml_tpu.models.knn import merge_topk

    def one(entry):
        (_addr, n_shard), c = entry
        return c.kneighbors(name, queries, k=min(k, n_shard),
                            input_col=input_col)

    results = list(ex.map(one, shard_clients))
    return merge_topk(
        [d for d, _ in results], [i for _, i in results], k,
        descending=descending,
    )


class _DaemonKNNTask:
    """Executor-side query feeder: each batch's query rows go to the
    daemon's ``kneighbors`` op; neighbor distance/index columns come
    back. The database-sized index stays daemon-resident.

    Sharded index (``shards``: [(addr, shard_rows)]): the batch fans out
    to EVERY shard daemon and the task merges the per-shard top-k
    host-side (models/knn.merge_topk) — O(q·k·shards) merged per batch,
    independent of database size."""

    def __init__(self, host, port, token, name, input_col, k,
                 shards=None, descending=False):
        self.host, self.port, self.token = host, port, token
        self._name = name
        self._input_col = input_col
        self._k = k
        self._shards = shards
        self._descending = descending

    def __call__(self, batches):
        import contextlib
        from concurrent.futures import ThreadPoolExecutor

        import pyarrow as pa

        from spark_rapids_ml_tpu.serve.client import DataPlaneClient
        from spark_rapids_ml_tpu.spark import daemon_session as ds

        with contextlib.ExitStack() as stack:
            ckw = ds.client_kwargs()
            if self._shards:
                clients = [
                    (s, stack.enter_context(DataPlaneClient(
                        *ds._parse_addr(s[0]), token=self.token, **ckw)))
                    for s in self._shards
                ]
                # One pool for the task's lifetime (threads reused across
                # batches, like the clients above).
                ex = stack.enter_context(
                    ThreadPoolExecutor(max_workers=min(len(clients), 16))
                )
            else:
                h, p = ds.executor_daemon_address(self.host, self.port)
                clients = [
                    ((f"{h}:{p}", None), stack.enter_context(
                        DataPlaneClient(h, p, token=self.token, **ckw)))
                ]
            for batch in batches:
                table = pa.Table.from_batches([batch])
                if table.num_rows == 0:
                    yield from _append_outputs(table, {}, _KNN_OUTPUTS).to_batches()
                    continue
                q = table.select([self._input_col])
                if self._shards:
                    dists, idx = _fanout_kneighbors(
                        ex, clients, self._name, q, self._k,
                        self._input_col, self._descending,
                    )
                else:
                    dists, idx = clients[0][1].kneighbors(
                        self._name, q, k=self._k, input_col=self._input_col
                    )
                out = {"distances": dists, "indices": idx}
                yield from _append_outputs(table, out, _KNN_OUTPUTS).to_batches()


class _DaemonKNNModel:
    """Fitted KNN/ANN handle whose index lives ON the TPU-host daemon.

    The reference never materializes the dataset on the driver
    (RapidsRowMatrix.scala:118-139); for KNN the fitted model IS the
    dataset, so driver-side persistence is structurally impossible at
    config-#5 scale (10M×768 ≈ 31 GB) — queries are served remotely
    instead. Use the core (non-Spark) API for an in-memory, persistable
    index."""

    def __init__(self, core, host, port, token, name, n_rows, input_col,
                 shards=None, client_kw=None):
        self._core = core  # the estimator: param surface (k, featuresCol…)
        self._host, self._port, self._token = host, port, token
        self._name = name
        self._n_rows = n_rows
        self._input_col = input_col
        # [(addr, shard_rows)] when the index spans daemons (each daemon
        # serves the shard of ITS committed partitions); None = one daemon.
        self._shards = shards
        # Fit-time resilience tuning (spark conf + env, resolved by
        # _fit_knn): the handle has no spark session at query time, so
        # driver-side kneighbors/release reuse what the fit resolved —
        # the same capture pattern as host/port/token.
        self._client_kw = dict(client_kw or {})

    def __getattr__(self, name):
        return getattr(self._core, name)

    @property
    def daemon_model_name(self) -> str:
        return self._name

    @property
    def numRows(self) -> int:
        return self._n_rows

    @property
    def shards(self):
        """[(daemon address, rows served there)] for a cross-daemon
        sharded index; None when one daemon serves the whole database."""
        return None if self._shards is None else list(self._shards)

    def _descending(self) -> bool:
        return (
            self._core.hasParam("metric")
            and self._core.getOrDefault("metric") == "inner_product"
        )

    def kneighbors(self, queries, k=None):
        """Driver-side convenience for ndarray queries: (distances (q, k),
        indices (q, k)); indices are global partition-major row positions
        of the fitted DataFrame. A sharded index fans the batch to every
        shard daemon and merges top-k (exact given exact shard answers —
        models/knn.merge_topk)."""
        from spark_rapids_ml_tpu.serve.client import DataPlaneClient

        if _is_spark_df(queries):
            raise TypeError(
                "pass a DataFrame to transform() for distributed queries; "
                "kneighbors takes an (q, d) ndarray"
            )
        k = self._core.getOrDefault("k") if k is None else k
        queries = np.asarray(queries)
        ckw = self._client_kw
        if self._shards is None:
            with DataPlaneClient(self._host, self._port,
                                 token=self._token, **ckw) as c:
                return c.kneighbors(
                    self._name, queries, k=k, input_col=self._input_col
                )
        import contextlib
        from concurrent.futures import ThreadPoolExecutor

        with contextlib.ExitStack() as stack:
            clients = [
                (s, stack.enter_context(DataPlaneClient(
                    *daemon_session._parse_addr(s[0]), token=self._token,
                    **ckw)))
                for s in self._shards
            ]
            ex = stack.enter_context(
                ThreadPoolExecutor(max_workers=min(len(clients), 16))
            )
            return _fanout_kneighbors(
                ex, clients, self._name, queries, k, self._input_col,
                self._descending(),
            )

    def transform(self, dataset):
        """Distributed query: appends knn_distances (list<double>) and
        knn_indices (list<long>) columns via mapInArrow tasks that hit
        the daemon — no index download, no driver collect."""
        if not _is_spark_df(dataset):
            dists, idx = self.kneighbors(
                __import__(
                    "spark_rapids_ml_tpu.core.dataset", fromlist=["as_matrix"]
                ).as_matrix(dataset, self._input_col)
            )
            from spark_rapids_ml_tpu.core.dataset import with_column

            out = with_column(dataset, "knn_distances", dists)
            return with_column(out, "knn_indices", idx)
        fn = _DaemonKNNTask(
            self._host, self._port, self._token, self._name,
            self._input_col, self._core.getOrDefault("k"),
            shards=self._shards, descending=self._descending(),
        )
        return dataset.mapInArrow(
            fn, _derive_output_schema(dataset, _KNN_OUTPUTS)
        )

    def release(self) -> bool:
        """Free the daemon-resident index now (it is dataset-sized and
        otherwise held until the daemon's extended KNN TTL; a sharded
        index frees every shard). The handle is unusable afterwards."""
        from spark_rapids_ml_tpu.serve.client import DataPlaneClient

        addrs = (
            [f"{self._host}:{self._port}"] if self._shards is None
            else [a for a, _ in self._shards]
        )
        any_dropped = False
        for addr in addrs:
            try:
                h, p = daemon_session._parse_addr(addr)
                with DataPlaneClient(h, p, token=self._token,
                                     **self._client_kw) as c:
                    any_dropped = c.drop_model(self._name) or any_dropped
            except OSError:
                continue  # daemon already gone — nothing to free there
        return any_dropped

    def write(self):
        raise NotImplementedError(
            "a daemon-resident KNN index is dataset-sized and cannot be "
            "persisted from the driver; fit the core "
            "(spark_rapids_ml_tpu.NearestNeighbors / "
            "ApproximateNearestNeighbors) estimator on in-memory data for "
            "a persistable model"
        )


class _SparkModelAdapter:
    """Wraps a fitted core Model with Spark DataFrame transform."""

    def __init__(self, core_model):
        self._core = core_model

    def __getattr__(self, name):
        return getattr(self._core, name)

    def _transform_input_col(self):
        core = self._core
        return core.getOrDefault(
            "inputCol" if core.hasParam("inputCol") else "featuresCol"
        )

    def _derive_output_schema(self, dataset, outputs):
        return _derive_output_schema(dataset, outputs)

    def transform(self, dataset):
        if not _is_spark_df(dataset):
            _check_not_orphan_spark_df(dataset)
            return self._core.transform(dataset)
        import os

        core = self._core
        spec = _serve_spec(core)

        if hasattr(dataset, "mapInArrow") and spec is not None:
            # Distributed, lazy: one Arrow batch per executor partition —
            # served from the TPU via the daemon unless the explicit
            # executor-CPU fallback is requested.
            algo, outputs = spec
            input_col = self._transform_input_col()
            local = os.environ.get("SRML_TRANSFORM_LOCAL", "").lower() in (
                "1", "true",
            )
            if local:
                fn = _TransformTask(core, input_col, outputs)
            else:
                spark = getattr(dataset, "sparkSession", None)
                host, port, token = daemon_session.resolve(spark)
                fn = _DaemonTransformTask(
                    core, host, port, token, input_col, algo, outputs
                )
            return dataset.mapInArrow(
                fn, self._derive_output_schema(dataset, outputs)
            )

        # No collect-based fallback: every Spark code path must keep the
        # dataset off the driver (the reference's defining property,
        # RapidsRowMatrix.scala:118-139). mapInArrow exists since
        # pyspark 3.3; models without a serving contract have no Spark
        # transform at all.
        raise NotImplementedError(
            "distributed transform needs DataFrame.mapInArrow (pyspark "
            ">= 3.3) and a model with a serving contract; for in-memory "
            "data use the core estimators (spark_rapids_ml_tpu.*) directly"
        )


def _make_wrapper(name, core_cls, doc, daemon_algo=None):
    cls = type(
        name,
        (_SparkAdapter,),
        {"_core_cls": core_cls, "__doc__": doc, "_daemon_algo": daemon_algo},
    )
    return cls


from spark_rapids_ml_tpu.models.kmeans import KMeans as _KMeans
from spark_rapids_ml_tpu.models.knn import (
    ApproximateNearestNeighbors as _ApproximateNearestNeighbors,
    NearestNeighbors as _NearestNeighbors,
)
from spark_rapids_ml_tpu.models.linear_regression import (
    LinearRegression as _LinearRegression,
)
from spark_rapids_ml_tpu.models.logistic_regression import (
    LogisticRegression as _LogisticRegression,
)
from spark_rapids_ml_tpu.models.pca import PCA as _PCA
from spark_rapids_ml_tpu.models.random_forest import (
    RandomForestClassifier as _RandomForestClassifier,
    RandomForestRegressor as _RandomForestRegressor,
)
from spark_rapids_ml_tpu.models.scaler import StandardScaler as _StandardScaler

SparkPCA = _make_wrapper(
    "SparkPCA", _PCA, "PCA over PySpark DataFrames (ArrayType features column).",
    daemon_algo="pca",
)
SparkKMeans = _make_wrapper(
    "SparkKMeans", _KMeans, "KMeans over PySpark DataFrames.",
    daemon_algo="kmeans",
)
SparkLinearRegression = _make_wrapper(
    "SparkLinearRegression", _LinearRegression,
    "LinearRegression over PySpark DataFrames.", daemon_algo="linreg",
)
SparkLogisticRegression = _make_wrapper(
    "SparkLogisticRegression", _LogisticRegression,
    "LogisticRegression over PySpark DataFrames.", daemon_algo="logreg",
)
SparkNearestNeighbors = _make_wrapper(
    "SparkNearestNeighbors", _NearestNeighbors,
    "Exact KNN over PySpark DataFrames — daemon-fed fit, daemon-served "
    "queries (the dataset never reaches the driver).",
    daemon_algo="knn",
)
SparkApproximateNearestNeighbors = _make_wrapper(
    "SparkApproximateNearestNeighbors",
    _ApproximateNearestNeighbors,
    "IVF-Flat approximate KNN over PySpark DataFrames — daemon-fed fit "
    "(device-side quantizer + bucketize), daemon-served queries.",
    daemon_algo="knn",
)
SparkStandardScaler = _make_wrapper(
    "SparkStandardScaler", _StandardScaler,
    "StandardScaler over PySpark DataFrames (ArrayType features column).",
    daemon_algo="scaler",
)
SparkRandomForestClassifier = _make_wrapper(
    "SparkRandomForestClassifier", _RandomForestClassifier,
    "RandomForest classification over PySpark DataFrames — histogram "
    "trees on binned features, one daemon pass per depth (the `rf` job "
    "protocol).",
    daemon_algo="rf_classifier",
)
SparkRandomForestRegressor = _make_wrapper(
    "SparkRandomForestRegressor", _RandomForestRegressor,
    "RandomForest regression over PySpark DataFrames — variance-split "
    "histogram trees on binned features (the `rf` job protocol).",
    daemon_algo="rf_regressor",
)
