"""Phase-named tracing spans — the NVTX-range idiom, TPU-native.

The reference wraps its two fit phases in NVTX ranges so they show up in
Nsight (``NvtxRange("compute cov", RED)`` / ``NvtxRange("cuSolver SVD",
BLUE)``, RapidsRowMatrix.scala:62,70, closed in ``finally``). The TPU
equivalent is ``jax.profiler.TraceAnnotation``, which names the span in
xprof/Perfetto traces. ``trace_span`` keeps the same phase-named-span
idiom and additionally feeds the two always-on observability sinks:

* the process-wide metrics registry — every span's wall-clock lands in
  the ``srml_phase_duration_seconds{phase=...}`` histogram (so bench
  records and the daemon's ``metrics`` op carry per-phase breakdowns);
* the run journal (``utils/journal.py``, env ``SRML_RUN_JOURNAL``) —
  one JSON line per phase with run/span/parent ids.

With tracing off, the journal unset, and metrics disabled, a span is a
Timer plus three cheap flag checks — safe on hot paths.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.utils import journal
from spark_rapids_ml_tpu.utils import metrics
from spark_rapids_ml_tpu.utils.logging import get_logger

_logger = get_logger(__name__)

#: Every trace_span records here: the per-phase latency breakdown all
#: other layers (bench.py, docs/observability.md) read.
PHASE_SECONDS = metrics.histogram(
    "srml_phase_duration_seconds",
    "Wall-clock duration of trace_span phases, by phase name",
)


class Timer:
    """Wall-clock timer with a monotonic clock; used by spans and benches."""

    def __init__(self) -> None:
        self.start = time.perf_counter()
        self.elapsed: Optional[float] = None

    def stop(self) -> float:
        self.elapsed = time.perf_counter() - self.start
        return self.elapsed


@contextlib.contextmanager
def trace_span(name: str, log: bool = False) -> Iterator[Timer]:
    """Context manager naming a phase in the JAX profiler timeline.

    Usage mirrors the reference's try/finally NvtxRange pattern::

        with trace_span("compute cov"):
            gram = compute_gram(...)
    """
    timer = Timer()
    tracing = config.get("tracing")
    if tracing:
        import jax.profiler

        cm: contextlib.AbstractContextManager = jax.profiler.TraceAnnotation(name)
    else:
        cm = contextlib.nullcontext()
    with cm, journal.span(name):
        try:
            yield timer
        finally:
            timer.stop()
            PHASE_SECONDS.observe(timer.elapsed, phase=name)
            if log or tracing:
                _logger.debug("phase %s: %.3fs", name, timer.elapsed)
