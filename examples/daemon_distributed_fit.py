"""Executor-fed distributed fit through the TPU-host data-plane daemon.

Emulates N Spark tasks (threads here; real tasks connect over the
network) streaming Arrow partitions with the EXACTLY-ONCE commit
protocol: feeds stage per (partition, attempt) and only ``commit`` folds
them in, so task retries and speculative duplicates cannot double-count
(the semantics the Spark wrappers rely on — spark/estimator.py drives
this protocol automatically for `SparkPCA().fit(df)` etc.). The driver
finalizes and receives only the model. Iterative algorithms use the same
wire protocol with one scan per iteration and a step() call at each pass
boundary.
"""

import os
import sys

if __package__ in (None, ""):  # runnable without installation
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import threading

import numpy as np

from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon

rng = np.random.default_rng(0)
data = (rng.normal(size=(200_000, 128)) * np.logspace(0, -1.5, 128)).astype(np.float32)
parts = np.array_split(data, 8)

with DataPlaneDaemon(ttl=600.0) as daemon:  # idle jobs evicted after 10 min
    host, port = daemon.address

    def task(pid, part):
        with DataPlaneClient(host, port) as c:
            for sub in np.array_split(part, 2):  # several batches per task
                c.feed("demo", sub, algo="pca", partition=pid)
            c.commit("demo", partition=pid)  # the only point rows count

    threads = [
        threading.Thread(target=task, args=(i, p)) for i, p in enumerate(parts)
    ]
    [t.start() for t in threads]
    [t.join() for t in threads]

    # A retried duplicate of partition 0 (Spark speculation): harmless —
    # its feeds stage separately and its commit is discarded as duplicate.
    with DataPlaneClient(host, port) as c:
        c.feed("demo", parts[0], algo="pca", partition=0, attempt=1)
        c.commit("demo", partition=0, attempt=1)

    with DataPlaneClient(host, port) as c:
        assert c.status("demo")["rows"] == data.shape[0]  # no double count
        result = c.finalize_pca("demo", k=8)
print("pc:", result["pc"].shape, "ev:", result["explained_variance"][:4])
