"""srml-check engine tests (spark_rapids_ml_tpu/tools/analyze.py).

Three layers, mirroring the analyzer's contract (docs/static_analysis.md):

1. Per-rule FIXTURES — for every rule, a positive snippet that must flag
   and a negative twin that must not. The fixtures are tiny synthetic
   projects (dict of relpath → source), so each rule's semantic model
   (lock stacks, jit-handle resolution, constant folding) is pinned
   independently of the real tree.
2. SUPPRESSION — inline ``# srml: disable=`` pragmas, the baseline
   round-trip (finding → baselined → code removed → stale-entry warning),
   and the seeded-violation gate: a deliberate device dispatch outside
   ``_DEVICE_LOCK`` spliced into a scratch copy of daemon.py must be
   caught.
3. The WHOLE-PACKAGE run — the tier-1 gate: zero unsuppressed findings
   over the real tree, plus the ``--json`` CLI contract.

No jax import anywhere in this file: the analyzer is stdlib-only and
must stay runnable before the environment can even build a device.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from spark_rapids_ml_tpu.tools import analyze
from spark_rapids_ml_tpu.tools.analyze import Baseline, Project

REPO = Path(__file__).resolve().parent.parent

#: Minimal ops module defining a donating streaming factory — gives the
#: fixtures a realistic jit registry (the daemon fixtures bind from it).
GRAM_FIXTURE = '''
import functools
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit

def streaming_update(mesh):
    @functools.partial(ledgered_jit, "gram.streaming_update", donate_argnums=(0,))
    def update(state, x, mask):
        return state
    return update
'''


def run_rules(files, *rules, **kw):
    project = Project(files=dict(files), **kw)
    return project, project.run_raw(rules=list(rules))


_PKG_PROJECT = []


def pkg_project() -> Project:
    """One parsed real-tree Project shared by the whole-package tests —
    runs are stateless (matched counts and notes reset per run), so the
    read+parse+registry cost is paid once per session."""
    if not _PKG_PROJECT:
        _PKG_PROJECT.append(Project.from_package())
    return _PKG_PROJECT[0]


def rule_ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# family 1: lock discipline
# ---------------------------------------------------------------------------


def _daemon(src: str) -> dict:
    return {"ops/gram.py": GRAM_FIXTURE, "serve/daemon.py": src}


def test_device_lock_flags_dispatch_outside_lock():
    _, found = run_rules(_daemon('''
import threading
from spark_rapids_ml_tpu.ops.gram import streaming_update
_DEVICE_LOCK = threading.Lock()

class Job:
    def __init__(self, mesh):
        self.update = streaming_update(mesh)
    def fold(self, state, xs, ms):
        state = self.update(state, xs, ms)
        return state
'''), "device-lock")
    assert rule_ids(found) == ["device-lock"]
    assert "self.update" in found[0].message


def test_device_lock_passes_dispatch_under_lock():
    _, found = run_rules(_daemon('''
import threading
from spark_rapids_ml_tpu.ops.gram import streaming_update
_DEVICE_LOCK = threading.Lock()

class Job:
    def __init__(self, mesh):
        self.update = streaming_update(mesh)
    def fold(self, state, xs, ms):
        with _DEVICE_LOCK:
            state = self.update(state, xs, ms)
        return state
'''), "device-lock")
    assert found == []


def test_device_lock_flags_block_until_ready_and_fn_handles():
    _, found = run_rules(_daemon('''
import jax

def wait(out):
    return jax.block_until_ready(out)

def serve(q, _exact_knn_fn):
    return _exact_knn_fn(q)
'''), "device-lock")
    assert rule_ids(found) == ["device-lock", "device-lock"]


def test_device_lock_locked_helper_convention():
    # Inside a *_locked helper the caller holds the lock — exempt; but a
    # CALL site of a *_locked helper carries the obligation: a helper
    # that DISPATCHES needs _DEVICE_LOCK there specifically (a model
    # lock alone must not smuggle a dispatch past the gate), and any
    # *_locked helper needs at least some lock.
    src = '''
import threading
import jax
_DEVICE_LOCK = threading.Lock()

class Job:
    lock = threading.Lock()
    def _finalize_locked(self):
        return jax.device_get(self.state)
    def _prune_locked(self):
        self.stale = None
    def finalize(self):
        with self.lock:
            with _DEVICE_LOCK:
                return self._finalize_locked()
    def model_lock_only(self):
        with self.lock:
            return self._finalize_locked()
    def broken(self):
        return self._finalize_locked()
    def prune(self):
        with self.lock:
            self._prune_locked()
'''
    _, found = run_rules(_daemon(src), "device-lock")
    assert [(f.symbol, "without _DEVICE_LOCK" in f.message) for f in found] == [
        ("Job.model_lock_only", True),
        ("Job.broken", True),
    ]


def test_device_lock_allows_locked_to_locked_delegation():
    # A *_locked helper delegating to another *_locked helper is the
    # convention working as designed: the OUTER caller holds the lock.
    _, found = run_rules(_daemon('''
class Job:
    def _cleanup_locked(self):
        pass
    def _finalize_locked(self):
        return self._cleanup_locked()
'''), "device-lock")
    assert found == []


def test_compile_outside_lock_twins():
    bad = _daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

def warm(jit_obj, args):
    with _DEVICE_LOCK:
        jit_obj.aot_prime(*args)
''')
    good = _daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

def warm(jit_obj, args):
    jit_obj.aot_prime(*args)
''')
    _, found = run_rules(bad, "compile-outside-lock")
    assert rule_ids(found) == ["compile-outside-lock"]
    _, found = run_rules(good, "compile-outside-lock")
    assert found == []


def test_lock_order_flags_acquisition_under_device_lock():
    _, found = run_rules(_daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

class D:
    _models_lock = threading.Lock()
    def bad(self):
        with _DEVICE_LOCK:
            with self._models_lock:
                pass
    def good(self):
        with self._models_lock:
            with _DEVICE_LOCK:
                pass
'''), "lock-order")
    assert len(found) == 1
    assert found[0].symbol == "D.bad"


def test_lock_order_flags_observed_inversion():
    _, found = run_rules({"serve/fleet.py": '''
import threading

class F:
    _a_lock = threading.Lock()
    _b_lock = threading.Lock()
    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass
    def two(self):
        with self._b_lock:
            with self._a_lock:
                pass
'''}, "lock-order")
    assert rule_ids(found) == ["lock-order", "lock-order"]
    assert "inversion" in found[0].message


def test_lock_order_sees_multi_item_with():
    # `with A, B:` acquires B while holding A — the single-statement
    # spelling must flag exactly like the nested one.
    _, found = run_rules(_daemon('''
import threading
_DEVICE_LOCK = threading.Lock()

class D:
    _models_lock = threading.Lock()
    def bad(self):
        with _DEVICE_LOCK, self._models_lock:
            pass
'''), "lock-order")
    assert len(found) == 1
    assert "_models_lock" in found[0].message


# ---------------------------------------------------------------------------
# family 2: use-after-donate
# ---------------------------------------------------------------------------


def test_use_after_donate_flags_read_after_donation():
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, batches, state):
    update = streaming_update(mesh)
    out = update(state, batches[0], None)
    return state, out  # state was donated: this read is a use-after-free
''',
    }, "use-after-donate")
    assert rule_ids(found) == ["use-after-donate"]
    assert "state" in found[0].message


def test_use_after_donate_passes_rebinding_fold():
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, batches, state):
    update = streaming_update(mesh)
    for b in batches:
        state = update(state, b, None)
    return state
''',
    }, "use-after-donate")
    assert found == []


def test_use_after_donate_flags_loop_without_rebind():
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, batches, state):
    update = streaming_update(mesh)
    for b in batches:
        update(state, b, None)  # next iteration re-reads the dead buffer
''',
    }, "use-after-donate")
    assert rule_ids(found) == ["use-after-donate"]
    assert "loop" in found[0].message


def test_use_after_donate_ignores_mutually_exclusive_branch():
    # A read of the donated name in the ELSE arm of the branch holding
    # the donating call can never see the dead buffer — not a finding;
    # a read AFTER the whole if (reachable from the donating arm) is.
    files = {
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, b, state, fast):
    update = streaming_update(mesh)
    if fast:
        out = update(state, b, None)
        return out
    else:
        return state
''',
    }
    _, found = run_rules(files, "use-after-donate")
    assert found == []
    files["models/pca.py"] = '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, b, state, fast):
    update = streaming_update(mesh)
    if fast:
        out = update(state, b, None)
    return state  # reachable after the donating arm: use-after-free
'''
    _, found = run_rules(files, "use-after-donate")
    assert rule_ids(found) == ["use-after-donate"]


def test_use_after_donate_tuple_unpack_rebind_heals():
    # Multi-output donated folds rebind via tuple unpack — healed.
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, batches, state):
    update = streaming_update(mesh)
    n = 0
    for b in batches:
        state, n = update(state, b, None)
    return state, n
''',
    }, "use-after-donate")
    assert found == []


def test_use_after_donate_sees_finally_block():
    # try/finally: the finally body executes AFTER the donating call —
    # a read of the donated name there is a real use-after-free.
    _, found = run_rules({
        "ops/gram.py": GRAM_FIXTURE,
        "models/pca.py": '''
from spark_rapids_ml_tpu.ops.gram import streaming_update

def fit(mesh, b, state, log):
    update = streaming_update(mesh)
    try:
        out = update(state, b, None)
    finally:
        log(state.shape)
    return out
''',
    }, "use-after-donate")
    assert rule_ids(found) == ["use-after-donate"]


def test_device_lock_closure_does_not_inherit_enclosing_with():
    # A closure DEFINED under `with _DEVICE_LOCK` runs later, when the
    # lock is long released: the dispatch inside it must still flag.
    _, found = run_rules(_daemon('''
import threading
from spark_rapids_ml_tpu.ops.gram import streaming_update
_DEVICE_LOCK = threading.Lock()

class Job:
    def __init__(self, mesh):
        self.update = streaming_update(mesh)
    def defer(self, schedule, s, x, m):
        with _DEVICE_LOCK:
            def cb():
                return self.update(s, x, m)
            schedule(cb)
'''), "device-lock")
    assert rule_ids(found) == ["device-lock"]
    assert found[0].symbol == "Job.defer.cb"


# ---------------------------------------------------------------------------
# family 3: determinism
# ---------------------------------------------------------------------------


def test_unsorted_iter_twins():
    bad = {"ops/fold.py": '''
def merge(parts):
    total = 0
    for k, v in parts.items():
        total += v
    return total
'''}
    good = {"ops/fold.py": '''
def merge(parts):
    total = 0
    for k, v in sorted(parts.items()):
        total += v
    return total
'''}
    _, found = run_rules(bad, "unsorted-iter")
    assert rule_ids(found) == ["unsorted-iter"]
    _, found = run_rules(good, "unsorted-iter")
    assert found == []


def test_unsorted_iter_scope_and_precision():
    # Outside the bitwise modules (and off the daemon fold paths) the
    # rule is silent; literal-ordered local dicts and key-addressed
    # dict→dict rebuilds are deterministic by construction.
    _, found = run_rules({
        "serve/client.py": '''
def render(d):
    return [v for _, v in d.items()]
''',
        "ops/tables.py": '''
def build(arrays):
    want = {"a": 1, "b": 2}
    out = []
    for name, shape in want.items():
        out.append((name, shape))
    rekeyed = {k: float(v) for k, v in arrays.items()}
    return out, rekeyed
''',
    }, "unsorted-iter")
    assert found == []


def test_unsorted_iter_flags_set_iteration_on_fold_path():
    _, found = run_rules({"serve/daemon.py": '''
def merge_peers(peers):
    acc = []
    for p in set(peers):
        acc.append(p)
    return acc
'''}, "unsorted-iter")
    assert rule_ids(found) == ["unsorted-iter"]


def test_wallclock_entropy_twins():
    bad = {"models/kmeans.py": '''
import time
import numpy as np

def fit(x):
    t = time.time()
    noise = np.random.rand(4)
    return t, noise
'''}
    good = {"models/kmeans.py": '''
import numpy as np

def fit(x, seed):
    rng = np.random.default_rng(seed)
    return rng.random(4)
'''}
    _, found = run_rules(bad, "wallclock-entropy")
    assert sorted(rule_ids(found)) == ["wallclock-entropy", "wallclock-entropy"]
    _, found = run_rules(good, "wallclock-entropy")
    assert found == []


def test_wallclock_entropy_ignores_non_bitwise_modules():
    _, found = run_rules({"serve/client.py": '''
import time

def backoff():
    return time.time()
'''}, "wallclock-entropy")
    assert found == []


# ---------------------------------------------------------------------------
# family 4: wire contract
# ---------------------------------------------------------------------------

DAEMON_WIRE = '''
_KNOWN_OPS = frozenset(("ping", "feed"))

def dispatch(op, conn):
    if op == "ping":
        protocol.send_json(conn, {"ok": True})
    elif op == "fe" + "ed":
        protocol.send_json(conn, {"ok": True, "rows": 1})
    elif op == f"fin{'alize'}":
        protocol.send_json(conn, {"ok": True})
'''


def test_wire_op_clamp_sees_through_concatenation_and_fstrings():
    project, found = run_rules(
        {"serve/daemon.py": DAEMON_WIRE},
        "wire-op-clamp",
        protocol_doc="ping feed",
    )
    msgs = [f.message for f in found]
    # "finalize" (built via f-string) is neither clamped nor documented;
    # "feed" (built via concatenation) is both.
    assert any('"finalize" is dispatched but missing' in m for m in msgs)
    assert any("absent from docs/protocol.md" in m for m in msgs)
    assert not any('"feed"' in m for m in msgs)


def test_wire_op_clamp_clean_when_clamped_and_documented():
    src = DAEMON_WIRE.replace('("ping", "feed")', '("ping", "feed", "finalize")')
    _, found = run_rules(
        {"serve/daemon.py": src},
        "wire-op-clamp",
        protocol_doc="ping feed finalize",
    )
    assert found == []


def test_ack_contract_flags_removed_field_only():
    files = {"serve/daemon.py": '''
def _identity(self):
    return {"id": 1, "boot_id": 2}

def answer(self, conn):
    protocol.send_json(conn, {"ok": True, "rows": 3, **self._identity()})
'''}
    # A snapshot field the daemon no longer answers → finding.
    _, found = run_rules(
        files, "ack-contract",
        contract={"version": 1, "ack_fields": ["ok", "rows", "id", "boot_id", "gone"]},
    )
    assert rule_ids(found) == ["ack-contract"]
    assert '"gone"' in found[0].message
    # Additive drift (code answers MORE than the snapshot) → note, not a
    # finding: the contract is "only ever add".
    project, found = run_rules(
        files, "ack-contract",
        contract={"version": 1, "ack_fields": ["ok", "rows"]},
    )
    assert found == []
    assert any("additive" in n for n in project.notes)


def test_ack_field_collection_precision():
    """Variable-bound acks (the health/model_status shape) ARE collected
    — literal assignment plus dict-grown keys on the sent name — while
    subscript stores on UNRELATED dicts are NOT: over-collection would
    mask a removed ack field behind any identically-named key."""
    from spark_rapids_ml_tpu.tools.analyze import Module, collect_ack_fields

    mod = Module("serve/daemon.py", '''
def answer(self, conn, m):
    status = {"ok": True, "exists": m is not None}
    if m is not None:
        status["aot"] = 1
    unrelated = {}
    unrelated["rows"] = 3
    protocol.send_json(conn, status)
''')
    assert collect_ack_fields(mod) == {"ok", "exists", "aot"}


def test_package_contract_snapshot_is_in_sync():
    """The checked-in snapshot must stay a subset of what the daemon
    answers (removal = break) AND must not silently rot: every snapshot
    field is still answered today."""
    contract = json.loads(analyze.CONTRACT_PATH.read_text())
    project = pkg_project()
    daemon = [m for m in project.modules if m.relpath == "serve/daemon.py"][0]
    have = analyze.collect_ack_fields(daemon)
    assert set(contract["ack_fields"]) <= have
    assert len(contract["ack_fields"]) >= 20  # the real ack surface


# ---------------------------------------------------------------------------
# ported regex gates
# ---------------------------------------------------------------------------


def test_bare_print_twins():
    _, found = run_rules({
        "core/x.py": 'def f():\n    print("hi")\n',
        "tools/cli.py": 'def f():\n    print("hi")\n',
        "spark/entry.py": 'if __name__ == "__main__":\n    print("hi")\n',
    }, "bare-print")
    assert [f.file for f in found] == ["core/x.py"]


def test_bare_collective_twins():
    _, found = run_rules({
        "ops/gram.py": 'def f(x):\n    return lax.psum(x, "data")\n',
        "parallel/mapreduce.py": 'def f(x):\n    return lax.psum(x, "data")\n',
        "ops/doc.py": '"""mentions lax.psum in prose only"""\n',
    }, "bare-collective")
    assert [f.file for f in found] == ["ops/gram.py"]


def test_socket_timeout_twins():
    _, found = run_rules({"serve/client.py": '''
import socket

def bad(addr):
    return socket.create_connection(addr)

def good(addr):
    return socket.create_connection(addr, timeout=5.0)

def also_good(addr, t):
    return socket.create_connection(addr, t)
'''}, "socket-timeout")
    assert len(found) == 1
    assert found[0].symbol == "bad"


# ---------------------------------------------------------------------------
# suppression: pragmas, baseline round-trip, seeded violation
# ---------------------------------------------------------------------------


def test_inline_pragma_suppresses_exactly_its_rule():
    files = {"ops/fold.py": '''
def merge(parts):
    total = 0
    for k, v in parts.items():  # srml: disable=unsorted-iter
        total += v
    for k, v in parts.items():
        total += v
    return total
'''}
    project = Project(files=files)
    found = project.run(rules=["unsorted-iter"])
    assert len(found) == 1
    assert found[0].line == 6  # only the un-pragma'd loop


def test_baseline_round_trip_and_stale_warning():
    bad = {"ops/fold.py": '''
def merge(parts):
    return [v for k, v in parts.items()]
'''}
    clean = {"ops/fold.py": '''
def merge(parts):
    return [v for k, v in sorted(parts.items())]
'''}
    # 1. finding exists
    project = Project(files=bad)
    raw = project.run(rules=["unsorted-iter"])
    assert len(raw) == 1
    # 2. accepted into the baseline → suppressed
    accepted = Baseline.from_findings(raw)
    project = Project(files=bad)
    assert project.run(rules=["unsorted-iter"], baseline=accepted) == []
    assert project.notes == []
    # 3. offending code removed → the baseline entry goes stale (warned,
    #    so the ratchet only ever shrinks)
    project = Project(files=clean)
    stale_base = Baseline.from_findings(raw)
    assert project.run(rules=["unsorted-iter"], baseline=stale_base) == []
    assert any("stale baseline entry" in n for n in project.notes)
    # 4. a NEW finding in an already-baselined symbol still fails: the
    #    count bounds acceptance.
    two = {"ops/fold.py": '''
def merge(parts):
    a = [v for k, v in parts.items()]
    b = [k for k, v in parts.items()]
    return a + b
'''}
    project = Project(files=two)
    found = project.run(rules=["unsorted-iter"], baseline=Baseline.from_findings(raw))
    assert len(found) == 1


def test_baseline_is_reusable_across_runs():
    # Matched counts are per-run state: one loaded Baseline must keep
    # suppressing when reused (the natural way to script the API).
    files = {"ops/fold.py": '''
def merge(parts):
    return [v for k, v in parts.items()]
'''}
    accepted = Baseline.from_findings(Project(files=files).run(rules=["unsorted-iter"]))
    for _ in range(2):
        project = Project(files=files)
        assert project.run(rules=["unsorted-iter"], baseline=accepted) == []
        assert project.notes == []


def test_rewrite_baseline_preserves_out_of_scope_entries():
    """A --rule-restricted --write-baseline must not un-accept entries
    of rules it never evaluated (or files a path filter excluded)."""
    files = {"ops/fold.py": '''
def merge(parts):
    return [v for k, v in parts.items()]
'''}
    project = Project(files=files)
    accepted = Baseline(entries=[
        # Out of scope below: a different rule, and a file not analyzed.
        {"rule": "device-lock", "file": "serve/daemon.py",
         "symbol": "Job.fold", "count": 2},
        # In scope and still live: kept at its matched count.
        {"rule": "unsorted-iter", "file": "ops/fold.py",
         "symbol": "merge", "count": 1},
        # In scope but stale: dropped by the rewrite (the ratchet).
        {"rule": "unsorted-iter", "file": "ops/fold.py",
         "symbol": "gone_fn", "count": 1},
    ])
    findings = project.run(rules=["unsorted-iter"], baseline=accepted)
    assert findings == []
    merged = analyze.rewrite_baseline(
        project, accepted, findings, selected_rules=["unsorted-iter"]
    )
    assert merged.entries == {
        ("device-lock", "serve/daemon.py", "Job.fold"): 2,
        ("unsorted-iter", "ops/fold.py", "merge"): 1,
    }


def test_seeded_violation_in_scratch_daemon_is_caught():
    """The acceptance-criteria drill: splice a device dispatch outside
    _DEVICE_LOCK into a scratch copy of the REAL daemon.py and the gate
    must catch it."""
    files = Project.package_files()
    files["serve/daemon.py"] += '''

def _scratch_unlocked_dispatch(self, state, xs, ms):
    return self.update(state, xs, ms)
'''
    project = Project(files=files)
    found = project.run(rules=["device-lock"], baseline=Baseline.load())
    assert len(found) == 1
    assert found[0].symbol == "_scratch_unlocked_dispatch"


# ---------------------------------------------------------------------------
# the tier-1 gate + CLI
# ---------------------------------------------------------------------------


@pytest.mark.analyze
def test_whole_package_zero_unsuppressed_findings():
    """THE gate: every rule over the real tree, pragmas + baseline
    honored — a new violation anywhere in the package fails tier-1 here
    exactly like the historical lint gates."""
    project = pkg_project()
    findings = project.run(baseline=Baseline.load())
    assert findings == [], "\n" + analyze.format_findings(findings)


@pytest.mark.analyze
def test_baseline_has_no_stale_entries():
    """The ratchet: accepted findings whose code has been fixed must be
    removed from tools/analyze_baseline.json, so acceptance only shrinks."""
    project = pkg_project()
    project.run(baseline=Baseline.load())
    stale = [n for n in project.notes if "stale baseline entry" in n]
    assert stale == [], "\n".join(stale)


@pytest.mark.analyze
def test_cli_json_output():
    """The machine interface CI consumes: exit 0 + well-formed JSON."""
    proc = subprocess.run(
        [sys.executable, "-m", "spark_rapids_ml_tpu.tools.analyze", "--json"],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert len(payload["rules"]) >= 11


def test_rule_catalog_is_documented():
    """Every registered rule appears in docs/static_analysis.md (the
    operator-facing catalog) — a rule cannot land undocumented."""
    doc = (REPO / "docs" / "static_analysis.md").read_text()
    missing = [rid for rid in analyze.RULES if f"`{rid}`" not in doc]
    assert missing == [], f"rules missing from docs/static_analysis.md: {missing}"
