"""Headline benchmark: PCA.fit compute-path throughput, rows/sec/chip.

Measures the north-star fit workload (BASELINE.json: 100M×2048 f32, k=32 —
a dataset ≫ HBM, so the real algorithm is the STREAMING accumulate) on its
compute path:

  - per-batch fused count/colsum/Gram statistics with donated on-device
    accumulator state (the reference's dgemmCov hot loop,
    rapidsml_jni.cu:120-125, plus the device-side combiner its
    ``accumulateCov`` declared but never implemented — SURVEY.md §2.4),
    bfloat16 GEMM on the MXU with float32 accumulation. Batches are
    ingest-cast to the compute dtype at placement (the framework's
    quantize-on-ingest design: identical Gram numerics, half the transfer
    bytes) and the update runs the single-HBM-pass Pallas kernel that
    fuses the boundary row-mask and the column-sum into the GEMM
    (ops/pallas_kernels.gram_colsum_pallas);
  - one mean-centered finalize + on-device randomized top-k eigensolve +
    sign-flip (the reference's calSVD, rapidsml_jni.cu:215-269) — only the
    (d, k) result leaves the device.

The row batch is generated on device once and re-fed B times, so the number
isolates sustained device compute throughput; host→device feeding is
benchmarked separately in the bridge tests. rows/s = B·batch_rows / wall.

Baseline for ``vs_baseline``: the A100 cuML fit is GEMM-bound at 2·d²
flops/row; at ~110 TFLOP/s sustained TF32 that is ~13.1e6 rows/s. The
north-star target (BASELINE.md) is within 2× of A100 per chip, i.e.
vs_baseline >= 0.5.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``python bench.py --serve`` (or SRML_BENCH_SERVE=1) runs the SERVING
benchmark instead: N concurrent transform clients against one in-process
daemon, scheduler off then on (serve/scheduler.py), then on again with
the TELEMETRY PLANE hot (SLO evaluation ticking, span ring armed, a live
``telemetry_pull``/``trace_pull`` scraper — docs/observability.md), and
prints one JSON line with QPS, p50/p99 latency, and mean batch occupancy
for all modes plus ``telemetry_overhead`` (the telemetry run's fractional
QPS cost, gated < 2% by tools/perfcheck.py).

``python bench.py --chaos-elastic`` (or SRML_BENCH_CHAOS_ELASTIC=1)
runs the ELASTIC-DEGRADE micro-benchmark: a 3-daemon hub-protocol
kmeans fit whose peer daemon dies permanently mid-pass (stop, NO
restart — docs/protocol.md "Permanent daemon loss"). The record carries
time-to-recover (death probe + survivor rewind + the replayed pass on
the 3→2 topology), the replayed-row count, the recovery overhead
relative to a steady pass, and a bitwise check against an uninterrupted
fit on the surviving topology; tools/perfcheck.py gates
recovery-cost regressions against the CHAOS_r* trajectory.

``python bench.py --chaos-grow`` (or SRML_BENCH_CHAOS_GROW=1) runs the
mirror-image ELASTIC-GROW micro-benchmark: a 2-daemon hub-protocol
kmeans fit that a third daemon JOINS at a pass boundary (one creating
set_iterate carrying the boundary iterate — docs/protocol.md "Mid-fit
daemon join"), runs grown for the middle passes, then shrinks back to
two at the next boundary. The record carries time-to-admit, the
rebalanced-row count, the grow overhead relative to a steady pass, and
a bitwise check against an uninterrupted static-topology fit;
tools/perfcheck.py check_chaos_grow gates it against the CHAOS_r*
trajectory (the two chaos families share the glob; mode+metric filters
separate them).

``python bench.py --chaos-partition`` (or SRML_BENCH_CHAOS_PARTITION=1)
runs the GOSSIP PARTITION-HEAL micro-benchmark: four daemons with live
gossip threads split into two islands that never hear of each other;
the losing island registers a model first, the winning island registers
AND rolls it forward (dominant epochs, the old version tombstoned), a
client bootstrapped from one losing-island seed routes traffic through
the whole split, and the heal is a single bridge gossip_push. The
record carries time-to-converge (bridge → all four FleetViews agree:
one active version, one epoch, the stale version tombstoned everywhere,
no resurrection) plus the routed/failed tallies from inside the split;
tools/perfcheck.py check_chaos_partition gates correctness absolutely
and convergence against the shared CHAOS_r* trajectory.

``python bench.py --forest`` (or SRML_BENCH_FOREST=1) runs the
TREE-ENSEMBLE benchmark: a RandomForest classifier fit (quantile
binning + fused per-depth histogram accumulate + vectorized split
scoring — the first non-GEMM workload record) plus warm-jit transform
QPS, differential against a sklearn-CPU RandomForest baseline when
installed (fit/transform speedups + an absolute accuracy gate);
tools/perfcheck.py check_forest gates it against the FOREST_r*
trajectory (SKIP-not-pass without history).

``python bench.py --serve --fleet`` (or SRML_BENCH_FLEET=1) runs the
FLEET benchmark: N replica daemons (each its own OS process — its own
Python runtime and device dispatch, the deployment shape) × M client
processes routing through serve/router.py, measured at 1 replica and at
N replicas on the same workload. The record carries per-replica-count
QPS/p50/p99 and the scaling efficiency QPS_N / (N × QPS_1) that
tools/perfcheck.py gates at ≥ 0.7 (FLEET_r* trajectory). In-process
smoke mode (SRML_BENCH_FLEET_INPROC=1) marks the record ``dryrun`` —
in-process replicas share one device lock, so its "scaling" proves
plumbing, never performance (perfcheck reads it as SKIP, not pass).
Subprocess records also embed a raw wire-fabric microphase (loopback
echo at the protocol's frame pattern, 1 vs N process pairs); when the
host's transport cannot even carry N × QPS_1 the record is marked
``wire_limited`` and perfcheck gates the FABRIC-RELATIVE efficiency
instead (see fleet_bench).
"""

import json
import os
import sys
import time

import numpy as np

A100_CUML_ROWS_PER_SEC = 13.1e6  # GEMM-bound estimate, see module docstring

# Env knobs exist for smoke-testing the bench itself on small hosts; the
# recorded benchmark always runs the defaults (the north-star shape).
D = int(os.environ.get("SRML_BENCH_D", 2048))
K = int(os.environ.get("SRML_BENCH_K", 32))
BATCH_ROWS = int(os.environ.get("SRML_BENCH_BATCH_ROWS", 1 << 18))  # 1.1 GB bf16
# 384 × 262144 = 100.7M rows — the north-star fit size (BASELINE.json
# config #2), which also amortizes the tunnel's fixed ~90 ms sync round-trip
# into the noise.
N_BATCHES = int(os.environ.get("SRML_BENCH_BATCHES", 384))


def _f32_parity_check() -> None:
    """Full-precision parity on THIS backend (round-4 advisor): the shipped
    TPU defaults auto-resolve to bfloat16/Pallas, so the float32 parity the
    CPU suite validates must also be exercised where the default flips.
    Runs the PCA fit path with compute_dtype=float32 on a small shape and
    asserts against the numpy float64 oracle (PCASuite.scala:80-87's
    sign-invariant tolerance philosophy). Raises on mismatch — a failed
    parity check fails the recorded bench run."""
    import jax
    import numpy as np

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.pca import fit_pca

    n, d, k = 8192, 256, 8
    rng = np.random.default_rng(7)
    x = (rng.standard_normal((n, d)) * rng.gamma(2.0, 1.0, d)).astype(np.float32)
    with config.option("compute_dtype", "float32"):
        sol = fit_pca(x, k=k, mean_center=True)
    pc = np.asarray(jax.device_get(sol.pc))
    xc = x.astype(np.float64) - x.mean(axis=0, dtype=np.float64)
    cov = xc.T @ xc / (n - 1)
    w, v = np.linalg.eigh(cov)
    ref = v[:, ::-1][:, :k]
    # Sign-invariant subspace agreement, column by column.
    dots = np.abs(np.sum(pc.astype(np.float64) * ref, axis=0))
    if not np.all(dots > 1 - 1e-3):  # not assert: python -O must not skip it
        raise RuntimeError(f"f32 parity failed on {jax.default_backend()}: "
                           f"|cos| = {dots}")


def main() -> None:
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.ops import gram as gram_ops
    from spark_rapids_ml_tpu.ops.eigh import pca_from_gram_randomized
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    _f32_parity_check()

    # Since round 4 these ARE the shipped TPU-auto defaults; pinned here so
    # the recorded number stays tied to this exact profile even if defaults
    # move.
    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")
    config.set("use_pallas", True)

    n_chips = len(jax.devices())
    mesh = make_mesh(model=1)

    # On-device data generation (no host transfer in the timed region),
    # ingest-cast to the compute dtype as the bridge does at placement.
    x = jax.random.normal(jax.random.key(0), (BATCH_ROWS, D), dtype=jnp.float32)
    x = x.astype(jnp.bfloat16)
    if n_chips > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(x, NamedSharding(mesh, P("data", None)))

    update = gram_ops.streaming_update_rows(
        mesh, compute_dtype="bfloat16", accum_dtype="float32"
    )

    from spark_rapids_ml_tpu.utils.xprof import ledgered_jit

    @ledgered_jit("bench.eig_finalize")
    def finalize(count, colsum, g):
        g, mean = gram_ops.finalize_gram(count, colsum, g, mean_center=True)
        return pca_from_gram_randomized(g, K)

    from spark_rapids_ml_tpu.utils import metrics
    from spark_rapids_ml_tpu.utils.profiling import trace_span

    fed_bytes = metrics.counter(
        "srml_bench_fed_bytes_total",
        "Row bytes folded through the bench's streaming update",
    )

    def fit(n_batches):
        # The same phase names fit_pca uses (the reference's NVTX names,
        # RapidsRowMatrix.scala:62,70): the spans land in
        # srml_phase_duration_seconds, so the BENCH record below carries
        # the per-phase breakdown, not just the headline total.
        state = gram_ops.init_stats(D, accum_dtype="float32")
        with trace_span("compute cov"):
            for _ in range(n_batches):
                state = update(state, x, BATCH_ROWS)
            # Sync before the span closes: jitted updates dispatch async,
            # and without the block the fold's device time would land in
            # the NEXT span — the finalize blamed for fold regressions.
            jax.block_until_ready(state)
            fed_bytes.inc(n_batches * BATCH_ROWS * D * 2)  # bf16 rows
        with trace_span("eig finalize"):
            pc, ev, _ = finalize(*state)
            return jax.device_get((pc, ev))  # (d, k) + (k,) — tiny

    from spark_rapids_ml_tpu.utils import xprof

    fit(2)  # warmup / compile
    # The warmup's ledger snapshot is the COMPILE story (every jit in the
    # fit compiles exactly here); the post-reset snapshot is the steady
    # state, where any compile at all is a storm tools/perfcheck.py flags.
    xla_warmup = _ledger_breakdown(xprof.snapshot())
    metrics.reset()  # the recorded snapshot covers ONLY the timed fit
    xprof.reset()

    t0 = time.perf_counter()
    pc, ev = fit(N_BATCHES)
    dt = time.perf_counter() - t0
    assert pc.shape == (D, K) and np.all(np.isfinite(pc))

    rows_per_sec_per_chip = N_BATCHES * BATCH_ROWS / dt / n_chips
    line = {
        "metric": f"pca_fit_streaming_rows_per_sec_per_chip_d{D}_k{K}",
        "value": round(rows_per_sec_per_chip, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(rows_per_sec_per_chip / A100_CUML_ROWS_PER_SEC, 4),
        "metrics": _metrics_breakdown(metrics.snapshot()),
        "xla": {
            "warmup": xla_warmup,
            "steady": _ledger_breakdown(xprof.snapshot()),
            "device_timing": bool(config.get("device_timing")),
        },
    }
    if os.environ.get("SRML_BENCH_INGEST", "") in ("1", "true"):
        line.update(_ingest_inclusive(update))
    print(json.dumps(line))


def _metrics_breakdown(snap: dict) -> dict:
    """Registry snapshot → the compact breakdown each BENCH record
    embeds: per-phase span durations + bytes moved. Perf trajectory
    records then say WHERE a regression landed (fold vs finalize), not
    just that the headline moved."""
    phases = {}
    for s in snap.get("srml_phase_duration_seconds", {}).get("samples", []):
        phases[s["labels"].get("phase", "?")] = {
            "count": s["count"],
            "sum_s": round(float(s["sum"]), 4),
        }
    fed = snap.get("srml_bench_fed_bytes_total", {}).get("samples", [])
    return {
        "phases": phases,
        "fed_bytes": int(fed[0]["value"]) if fed else 0,
    }


def _ledger_breakdown(snap: dict) -> dict:
    """Jit-ledger snapshot (utils/xprof.py) → the per-fn device-cost
    attribution each BENCH record embeds: compile s vs execute s, model
    flops/bytes (XLA cost analysis), achieved flops/s and bytes/s in
    SRML_DEVICE_TIMING runs. This is the breakdown tools/perfcheck.py
    gates on — a regression record says WHICH jit slowed or started
    compile-storming, not just that the headline moved."""
    out = {}
    for fn, a in snap.items():
        out[fn] = {
            "calls": a["calls"],
            "compiles": a["compiles"],
            "compile_s": round(a["compile_s"], 4),
            "cache_misses": a["cache_misses"],
            "execute_s": round(a["execute_s"], 4),
            "flops": sum(
                r["flops"] * r["calls"]
                for r in a["signatures"] if r["flops"] is not None
            ),
            "bytes": sum(
                r["bytes_accessed"] * r["calls"]
                for r in a["signatures"] if r["bytes_accessed"] is not None
            ),
            "flops_per_s": a["flops_per_s"],
            "bytes_per_s": a["bytes_per_s"],
        }
    return out


def _ingest_inclusive(update):
    """Optional ingest-inclusive measurement (SRML_BENCH_INGEST=1): real
    host Arrow batches through bridge/arrow + device_put, double-buffered
    against the device fold — the end-to-end feed the compute-only
    headline deliberately excludes (r2 review weak #5). On the dev
    harness device_put crosses the axon tunnel at single-digit MB/s; the
    ``ingest_tunneled`` flag marks such runs (same heuristic as
    bench_ingest.py) so the number is read as the tunnel's, not the
    architecture's.
    """
    import time

    import jax
    import pyarrow as pa

    from spark_rapids_ml_tpu.bridge.arrow import (
        matrix_to_list_column,
        table_column_to_matrix,
    )
    from spark_rapids_ml_tpu.ops import gram as gram_ops

    rows = int(os.environ.get("SRML_BENCH_INGEST_ROWS", 1 << 16))
    n_b = int(os.environ.get("SRML_BENCH_INGEST_BATCHES", 8))
    rng = np.random.default_rng(0)
    host = rng.standard_normal((rows, D), dtype=np.float32)
    tables = [
        pa.table({"features": matrix_to_list_column(host)}) for _ in range(2)
    ]

    import ml_dtypes

    def put(i):
        mat = table_column_to_matrix(tables[i % 2], "features")
        # Quantize-on-ingest: cast to bfloat16 ON THE HOST so the wire
        # carries 2 bytes/element (the design the headline documents);
        # a device-side cast would transfer f32 and double the bytes.
        return jax.device_put(mat.astype(ml_dtypes.bfloat16))

    state = gram_ops.init_stats(D, accum_dtype="float32")
    # Timer starts BEFORE the first put: all n_b conversions/transfers are
    # inside the window (an outside-t0 warm put would credit n_b batches
    # while timing n_b − 1).
    t0 = time.perf_counter()
    nxt = put(0)
    for i in range(n_b):
        cur = nxt
        if i + 1 < n_b:
            nxt = put(i + 1)  # overlap next transfer with this fold
        state = update(state, cur, rows)
    jax.device_get(state[0])  # sync (block_until_ready unreliable here)
    dt = time.perf_counter() - t0
    bps = n_b * rows * D * 2 / dt
    return {
        "ingest_rows_per_sec": round(n_b * rows / dt, 1),
        "ingest_batch_rows": rows,
        "ingest_tunneled": bool(bps < 1e9),
    }


def multichip_bench() -> None:
    """Pod-scale FIT benchmark (``--multichip``; replaces the MULTICHIP_r*
    dryruns with a measured record): a real PCA streaming fit and a real
    k-means Lloyd fit on a 1-device mesh and an N-device data mesh, same
    total work, with per-phase timing (fold / step / finalize) plus a raw
    (d, d) all-reduce microphase — the collective the on-mesh reduction
    rides (docs/mesh.md). Prints ONE JSON line.

    Scaling efficiency: on real multi-chip hardware the N-device ideal is
    N× the 1-device throughput; on a SIMULATED mesh (CPU host platform
    split into N virtual devices — same silicon) the ideal is the
    1-device throughput itself, so the number reads as "fraction of
    single-device throughput kept after sharding + collectives". The
    record carries ``simulated`` so tools/perfcheck.py gates like against
    like; the ≥0.8 floor is the acceptance bar either way."""
    n_want = int(os.environ.get("SRML_BENCH_MULTICHIP_DEVICES", 8))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # Before the jax import: a CPU host splits into n_want virtual
        # devices (ignored by real TPU backends — their device count is
        # physical).
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_want}"
        ).strip()

    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.ops import gram as gram_ops
    from spark_rapids_ml_tpu.ops.eigh import pca_from_gram_randomized
    from spark_rapids_ml_tpu.parallel import mapreduce as mpr
    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from spark_rapids_ml_tpu.utils import metrics, xprof
    from spark_rapids_ml_tpu.utils.xprof import ledgered_jit

    d = int(os.environ.get("SRML_BENCH_MULTICHIP_D", 512))
    k = int(os.environ.get("SRML_BENCH_MULTICHIP_K", 16))
    batch_rows = int(os.environ.get("SRML_BENCH_MULTICHIP_BATCH_ROWS", 1 << 16))
    n_batches = int(os.environ.get("SRML_BENCH_MULTICHIP_BATCHES", 24))
    km_k = int(os.environ.get("SRML_BENCH_MULTICHIP_KMEANS_K", 16))
    km_passes = int(os.environ.get("SRML_BENCH_MULTICHIP_KMEANS_PASSES", 3))

    devs = jax.devices()
    n_dev = min(len(devs), n_want)
    simulated = devs[0].platform == "cpu"

    from spark_rapids_ml_tpu.models.kmeans import (
        _stream_step_fn,
        apply_lloyd_update,
        stream_zero_state,
    )

    cd = str(jnp.dtype(config.get("compute_dtype")))
    ad = str(jnp.dtype(config.get("accum_dtype")))

    def run_fits(n: int) -> dict:
        """Both fits on an n-device data mesh; phase seconds + rows/s."""
        mesh = make_mesh(data=n, model=1, devices=devs[:n])
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.random.normal(
            jax.random.key(0), (batch_rows, d), dtype=jnp.float32
        ).astype(jnp.dtype(cd))
        x = jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS, None)))
        update = gram_ops.streaming_update_rows(
            mesh, compute_dtype=cd, accum_dtype=ad
        )

        @ledgered_jit(f"bench.multichip_finalize_{n}dev")
        def finalize(count, colsum, g):
            gg, _ = gram_ops.finalize_gram(count, colsum, g, mean_center=True)
            return pca_from_gram_randomized(gg, k)

        km_update = _stream_step_fn(mesh, km_k, cd, ad)
        centers0 = jax.device_put(
            jax.random.normal(jax.random.key(1), (km_k, d), dtype=jnp.dtype(ad))
        )
        mask = jax.device_put(
            jnp.ones((batch_rows,), jnp.dtype(cd)),
            NamedSharding(mesh, P(DATA_AXIS)),
        )

        # The raw-collective microphase: one all-reduce of the (d, d)
        # accumulator over the data axis — the exact reduction shape the
        # fused fold rides, isolated so the record names collective cost
        # separately from GEMM cost.
        allred = ledgered_jit(
            f"bench.multichip_allreduce_{n}dev",
            mpr.map_fn(
                lambda g: mpr.reduce_sum(g, DATA_AXIS),
                mesh,
                in_specs=P(),
                out_specs=P(),
                check_vma=False,
            ),
        )

        def pca_fit(batches: int):
            state = gram_ops.init_stats(d, accum_dtype=ad)
            for _ in range(batches):
                state = update(state, x, batch_rows)
            jax.block_until_ready(state)
            return state

        def km_fit(passes: int):
            centers = centers0
            for _ in range(passes):
                st = stream_zero_state(km_k, d, jnp.dtype(ad))
                for _ in range(max(n_batches // 2, 1)):
                    st = km_update(st, centers, x, mask)
                centers, moved2 = apply_lloyd_update(st[0], st[1], centers)
            jax.block_until_ready(centers)
            return centers

        # Warmup: compile everything outside the timed region — TWO steps
        # per loop (like main()'s fit(2)): the second iteration's input is
        # the first's mesh-committed output, a distinct jit signature.
        # Then reset the jit ledger so this mesh's steady breakdown shows
        # compiles only if a shape leaked into the timed loops (the storm
        # gate tools/perfcheck.py applies to every record).
        state = pca_fit(2)
        jax.block_until_ready(finalize(*state))
        km_fit(2)
        gseed = jnp.zeros((d, d), jnp.dtype(ad))
        jax.block_until_ready(allred(allred(gseed)))
        warmup_xla = _ledger_breakdown(xprof.snapshot())
        xprof.reset()

        phases: dict = {}

        def timed(name, fn, *a):
            t0 = time.perf_counter()
            out = fn(*a)
            jax.block_until_ready(out)
            phases[name] = round(time.perf_counter() - t0, 4)
            return out

        state = timed("pca_fold", pca_fit, n_batches)
        timed("pca_finalize", finalize, *state)
        timed("kmeans_fold", km_fit, km_passes)

        reps = 16
        t0 = time.perf_counter()
        g = gseed
        for _ in range(reps):
            g = allred(g)
        jax.block_until_ready(g)
        phases["allreduce_dxd"] = round((time.perf_counter() - t0) / reps, 6)

        pca_rows = n_batches * batch_rows
        km_rows = km_passes * max(n_batches // 2, 1) * batch_rows
        steady_xla = _ledger_breakdown(xprof.snapshot())
        # Clear the ledger on the way out: the NEXT mesh's warmup
        # snapshot must not absorb this mesh's timed-loop entries (the
        # fn names are shared between meshes).
        xprof.reset()
        return {
            "phases": phases,
            "pca_rows_per_sec": round(
                pca_rows / (phases["pca_fold"] + phases["pca_finalize"]), 1
            ),
            "kmeans_rows_per_sec": round(km_rows / phases["kmeans_fold"], 1),
            "xla_warmup": warmup_xla,
            "xla_steady": steady_xla,
        }

    xprof.reset()  # per-mesh warmup/steady splits live in run_fits
    one = run_fits(1)
    many = run_fits(n_dev)
    # One record-level steady view for the storm gate: the two meshes
    # register distinct bench.* entries but SHARE the model-update ledger
    # names, so each mesh's steady is keyed under its device count.
    steady = {
        **{f"1dev:{fn}": a for fn, a in one.pop("xla_steady").items()},
        **{f"{n_dev}dev:{fn}": a for fn, a in many.pop("xla_steady").items()},
    }
    warmup = {
        **{f"1dev:{fn}": a for fn, a in one.pop("xla_warmup").items()},
        **{f"{n_dev}dev:{fn}": a for fn, a in many.pop("xla_warmup").items()},
    }

    def eff(key: str) -> float:
        ideal = one[key] * (1.0 if simulated else n_dev)
        return round(many[key] / ideal, 4) if ideal else 0.0

    pca_eff, km_eff = eff("pca_rows_per_sec"), eff("kmeans_rows_per_sec")
    line = {
        "metric": f"multichip_fit_rows_per_sec_d{d}_k{k}",
        "value": many["pca_rows_per_sec"],
        "unit": "rows/s",
        "n_devices": n_dev,
        "simulated": simulated,
        "dryrun": False,
        "scaling_efficiency": min(pca_eff, km_eff),
        "pca_efficiency": pca_eff,
        "kmeans_efficiency": km_eff,
        "one_device": one,
        "n_device": many,
        "xla": {
            "warmup": warmup,
            "steady": steady,
            "device_timing": bool(config.get("device_timing")),
        },
        "metrics": _metrics_breakdown(metrics.snapshot()),
    }
    print(json.dumps(line))


def serve_bench() -> None:
    """Serving-plane benchmark: N concurrent transform clients against
    one daemon, micro-batching scheduler off vs on (the PR-5 acceptance
    number: batching must raise QPS on the same workload), then on WITH
    the telemetry plane hot — SLO burn-rate evaluation ticking fast, the
    journal span ring armed, and a concurrent wire scraper draining
    ``telemetry_pull`` + cursored ``trace_pull`` the way ``tools/top
    --fleet --telemetry`` does. Emits ONE JSON line with every mode's
    QPS + latency quantiles, the scheduler run's mean batch occupancy,
    ``telemetry_overhead`` (fractional QPS cost of the telemetry run vs
    the plain scheduler-on run; tools/perfcheck.py gates it < 2%), and
    the standard per-phase metrics breakdown."""
    import contextlib
    import threading

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.pca import PCA
    from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon
    from spark_rapids_ml_tpu.utils import metrics

    d = int(os.environ.get("SRML_BENCH_SERVE_D", 256))
    k = int(os.environ.get("SRML_BENCH_SERVE_K", 16))
    clients = int(os.environ.get("SRML_BENCH_SERVE_CLIENTS", 8))
    reqs = int(os.environ.get("SRML_BENCH_SERVE_REQS", 40))
    rows = int(os.environ.get("SRML_BENCH_SERVE_ROWS", 64))
    rng = np.random.default_rng(0)
    data = rng.standard_normal((4096, d)).astype(np.float32)
    model = PCA().setK(k).fit({"features": data})
    arrays = model._model_data()
    queries = rng.standard_normal((clients, rows, d)).astype(np.float32)

    def run(batching: bool, telemetry: bool = False) -> dict:
        metrics.reset()
        lat: list = []
        lat_lock = threading.Lock()
        errors: list = []
        opts = [("serve_batching", batching)]
        if telemetry:
            # The telemetry plane at its most expensive supported
            # setting: an SLO objective to evaluate every 50 ms, plus
            # the wire scraper below. The span ring is armed in every
            # mode (the production default) — the delta measured here
            # is evaluation + scraping.
            opts += [
                ("slo_objectives", "transform:p99_ms=250@0.01"),
                ("telemetry_eval_interval_s", 0.05),
            ]
        with contextlib.ExitStack() as stack:
            for key, val in opts:
                stack.enter_context(config.option(key, val))
            with DataPlaneDaemon() as daemon:
                host, port = daemon.address
                with DataPlaneClient(host, port) as c0:
                    c0.ensure_model("bench-serve", "pca", arrays)
                    if batching:
                        c0.warmup("bench-serve", n_cols=d, dtype="float32")
                    else:  # same warm jit caches for the off mode
                        c0.transform("bench-serve", queries[0])
                scrape_stop = threading.Event()
                pulls = [0]

                def scraper() -> None:
                    # What tools/top --fleet --telemetry does to every
                    # replica, at an aggressive cadence: full telemetry
                    # export + cursored trace drain, on its own
                    # connection, competing with the serving traffic.
                    cursor = 0
                    with DataPlaneClient(host, port) as sc:
                        while not scrape_stop.wait(0.05):
                            sc.telemetry_pull()
                            cursor = int(
                                sc.trace_pull(cursor).get("seq") or cursor
                            )
                            pulls[0] += 1

                scrape_thread = None
                if telemetry:
                    scrape_thread = threading.Thread(
                        target=scraper, name="bench-telemetry-scraper",
                        daemon=True,
                    )
                    scrape_thread.start()
                barrier = threading.Barrier(clients)

                def worker(i: int) -> None:
                    # A failed worker must fail the BENCH record: silently
                    # dropping its requests would still divide by the full
                    # clients*reqs and print a wrong QPS.
                    mine = []
                    try:
                        with DataPlaneClient(host, port) as c:
                            barrier.wait()
                            for _ in range(reqs):
                                t0 = time.perf_counter()
                                c.transform("bench-serve", queries[i])
                                mine.append(time.perf_counter() - t0)
                    except BaseException as e:
                        barrier.abort()  # peers fail fast, never hang
                        with lat_lock:
                            errors.append(e)
                        raise
                    with lat_lock:
                        lat.extend(mine)

                threads = [
                    threading.Thread(target=worker, args=(i,))
                    for i in range(clients)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                if scrape_thread is not None:
                    scrape_stop.set()
                    scrape_thread.join(timeout=10)
        if errors:
            raise RuntimeError(
                f"{len(errors)}/{clients} serve-bench workers failed "
                f"(batching={batching})"
            ) from errors[0]
        lat.sort()
        occ = metrics.snapshot().get("srml_scheduler_batch_rows", {})
        samples = occ.get("samples", [])
        total = sum(s["sum"] for s in samples)
        count = sum(s["count"] for s in samples)
        out = {
            "qps": round(clients * reqs / wall, 1),
            "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
            "p99_ms": round(lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3, 3),
        }
        if count:
            out["mean_batch_occupancy"] = round(total / count, 2)
        if telemetry:
            out["scrapes"] = pulls[0]
        return out

    off = run(False)
    metrics.reset()
    on = run(True)
    on_breakdown = _metrics_breakdown(metrics.snapshot())
    metrics.reset()
    tel = run(True, telemetry=True)
    overhead = (
        round(max(0.0, 1.0 - tel["qps"] / on["qps"]), 4)
        if on["qps"] else None
    )
    print(json.dumps({
        "metric": f"serve_transform_qps_d{d}_k{k}_c{clients}_b{rows}",
        # Headline value = the production configuration's QPS
        # (scheduler on, telemetry plane at its defaults): what the
        # perfcheck throughput gate tracks against the trajectory.
        "value": on["qps"],
        "unit": "transforms/s",
        "clients": clients,
        "batch_rows": rows,
        "scheduler_off": off,
        "scheduler_on": on,
        "telemetry_on": tel,
        "telemetry_overhead": overhead,
        "speedup": round(on["qps"] / off["qps"], 3) if off["qps"] else None,
        "metrics": on_breakdown,
    }))


def chaos_elastic_bench() -> None:
    """``--chaos-elastic``: the recovery-cost micro-record for the
    elastic fit (docs/protocol.md "Permanent daemon loss").

    Three in-process daemons drive a hub-protocol kmeans fit (the same
    feed/commit → export/merge → step → set_iterate sequence the Spark
    estimator runs); the peer holding a third of the partitions is
    STOPPED mid-pass and never restarted. The bench then performs the
    estimator's degrade unit — liveness probe to deadline exhaustion,
    survivor rewind to the last boundary iterate, the full pass replayed
    with the dead daemon's partitions rerouted — and times it. Integer-
    valued data makes every fold exact, so the record self-verifies: the
    degraded fit's centers must be bitwise-equal to an uninterrupted fit
    on the surviving 2-daemon topology. One JSON line; perfcheck gates
    ``recovery_overhead``/``value`` against the CHAOS_r* trajectory."""
    from spark_rapids_ml_tpu.serve.client import DataPlaneClient
    from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon

    d = int(os.environ.get("SRML_BENCH_ELASTIC_D", 64))
    k = int(os.environ.get("SRML_BENCH_ELASTIC_K", 8))
    part_rows = int(os.environ.get("SRML_BENCH_ELASTIC_PART_ROWS", 32768))
    passes = max(int(os.environ.get("SRML_BENCH_ELASTIC_PASSES", 3)), 2)
    death_timeout = float(
        os.environ.get("SRML_BENCH_ELASTIC_DEATH_TIMEOUT_S", 1.0)
    )
    n_parts = 6
    rng = np.random.default_rng(7)
    centers0 = rng.integers(-12, 13, size=(k, d)) * 4
    n = n_parts * part_rows
    x = (
        centers0[rng.integers(0, k, size=(n,))]
        + rng.integers(-1, 2, size=(n, d))
    ).astype(np.float64)
    parts = [np.ascontiguousarray(p) for p in np.array_split(x, n_parts)]
    seed_batch = x[: 32 * k]
    params = {"k": k, "seed": 11}

    def client(daemon):
        return DataPlaneClient(
            *daemon.address, timeout=60.0, max_op_attempts=2,
            backoff_base_s=0.02, backoff_max_s=0.2,
        )

    def feed_pass(job, routing, it):
        for pid, c in routing.items():
            c.feed(job, parts[pid], algo="kmeans", partition=pid,
                   pass_id=it, params=params)
            c.commit(job, partition=pid, pass_id=it)

    def reduce_step_sync(job, primary, peers):
        for pc in peers:
            arrays, meta = pc.export_state(job)
            primary.merge_state(
                job, arrays, rows=int(meta["pass_rows"]), algo="kmeans",
                n_cols=d, params=params,
            )
        info = primary.step(job)
        arrays, it_n = primary.get_iterate(job)
        for pc in peers:
            pc.set_iterate(job, arrays, it_n)
        return info, (arrays, it_n)

    record: dict = {
        "metric": f"chaos_elastic_replay_rows_per_s_d{d}_k{k}",
        "unit": "rows/s",
        "mode": "chaos_elastic",
        "n_daemons": 3,
        "n_survivors": 2,
        "rows": n,
        "passes": passes,
        "death_timeout_s": death_timeout,
    }
    da = DataPlaneDaemon(ttl=3600.0).start()
    db = DataPlaneDaemon(ttl=3600.0).start()
    dc_ = DataPlaneDaemon(ttl=3600.0).start()
    ca, cb, cc = client(da), client(db), client(dc_)
    try:
        # Oracle: the surviving topology (a holds the victim's
        # partitions), uninterrupted — also the steady-pass clock.
        job = "elastic-oracle"
        steady = []
        for c in (ca, cc):
            c.seed_kmeans(job, seed_batch, k=k, params=params)
        routing2 = {pid: (cc if pid >= 4 else ca) for pid in range(n_parts)}
        for it in range(passes):
            t0 = time.perf_counter()
            feed_pass(job, routing2, it)
            reduce_step_sync(job, ca, [cc])
            steady.append(time.perf_counter() - t0)
        oracle, _ = ca.finalize(job, {}, drop=False)
        ca.drop(job)
        steady_pass_s = min(steady)

        # Degraded run: 3 daemons; the victim dies mid-pass-1 for good.
        job = "elastic-degrade"
        for c in (ca, cb, cc):
            c.seed_kmeans(job, seed_batch, k=k, params=params)
        routing3 = {
            pid: (cc if pid >= 4 else cb if pid >= 2 else ca)
            for pid in range(n_parts)
        }
        feed_pass(job, routing3, 0)
        _, ledger = reduce_step_sync(job, ca, [cb, cc])
        # Pass 1 opens normally, then the victim vanishes under it.
        for pid in (0, 1):
            ca.feed(job, parts[pid], algo="kmeans", partition=pid,
                    pass_id=1, params=params)
            ca.commit(job, partition=pid, pass_id=1)
        db.stop()  # the permanent death — nothing ever restarts it
        failed = False
        try:
            cb.feed(job, parts[2], algo="kmeans", partition=2, pass_id=1,
                    params=params)
        except Exception:
            failed = True
        assert failed, "the dead daemon accepted a feed?"
        # The degrade unit, timed end to end: classify → rewind → replay.
        t0 = time.perf_counter()
        probe_t0 = time.perf_counter()
        dead = False
        try:
            with DataPlaneClient(
                *db.address, timeout=60.0, op_deadline_s=death_timeout,
                max_op_attempts=8, backoff_base_s=0.02, backoff_max_s=0.2,
            ) as probe:
                probe.ping()
        except Exception:
            dead = True
        probe_s = time.perf_counter() - probe_t0
        assert dead, "the liveness probe answered for a stopped daemon"
        arrays, it_n = ledger
        ca.set_iterate(job, arrays, it_n)
        cc.set_iterate(job, arrays, it_n)
        routing_shrunk = {
            pid: (cc if pid >= 4 else ca) for pid in range(n_parts)
        }
        feed_pass(job, routing_shrunk, 1)
        _, ledger = reduce_step_sync(job, ca, [cc])
        time_to_recover = time.perf_counter() - t0
        for it in range(2, passes):
            feed_pass(job, routing_shrunk, it)
            reduce_step_sync(job, ca, [cc])
        degraded, _ = ca.finalize(job, {}, drop=False)
        ca.drop(job)
        cc.drop(job)

        record.update({
            "value": round(n / time_to_recover, 1),
            "time_to_recover_s": round(time_to_recover, 4),
            "probe_s": round(probe_s, 4),
            "replayed_rows": n,
            "steady_pass_s": round(steady_pass_s, 4),
            "recovery_overhead": round(time_to_recover / steady_pass_s, 3),
            "bitwise_equal_oracle": bool(
                np.array_equal(degraded["centers"], oracle["centers"])
            ),
        })
    finally:
        for c in (ca, cb, cc):
            c.close()
        for daemon in (da, db, dc_):
            daemon.stop()
    print(json.dumps(record))


def chaos_grow_bench() -> None:
    """``--chaos-grow``: the scale-UP micro-record for the elastic fit
    (docs/protocol.md "Mid-fit daemon join") — the mirror image of
    ``--chaos-elastic``'s 3→2 degrade.

    Two in-process daemons drive a hub-protocol kmeans fit; at the first
    pass boundary a THIRD daemon appears and is admitted the way the
    estimator's grow path admits it — one creating ``set_iterate``
    carrying the boundary iterate plus the algo/n_cols/params creation
    fields (the same PR 4 ledger replay uses) — and a third of the
    partitions rebalance onto it for the middle passes. At the next
    boundary the fleet shrinks back to two (the joiner's partials are
    merged at the boundary, then it simply stops being routed to and is
    stopped), so one record exercises grow AND shrink. Integer-valued
    data makes every fold exact, so the record self-verifies: the grown
    2→3→2 fit's centers must be bitwise-equal to an uninterrupted fit on
    the static 2-daemon topology. Reported: ``time_to_admit_s`` (the
    admission handshake alone), ``rebalanced_rows`` (rows moved onto the
    joiner), ``grow_overhead`` (admit + first grown pass / steady pass).
    One JSON line; perfcheck's ``check_chaos_grow`` gates correctness
    absolutely and the cost numbers against the CHAOS_r* trajectory."""
    from spark_rapids_ml_tpu.serve.client import DataPlaneClient
    from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon

    d = int(os.environ.get("SRML_BENCH_GROW_D", 64))
    k = int(os.environ.get("SRML_BENCH_GROW_K", 8))
    part_rows = int(os.environ.get("SRML_BENCH_GROW_PART_ROWS", 32768))
    passes = max(int(os.environ.get("SRML_BENCH_GROW_PASSES", 3)), 3)
    n_parts = 6
    rng = np.random.default_rng(7)
    centers0 = rng.integers(-12, 13, size=(k, d)) * 4
    n = n_parts * part_rows
    x = (
        centers0[rng.integers(0, k, size=(n,))]
        + rng.integers(-1, 2, size=(n, d))
    ).astype(np.float64)
    parts = [np.ascontiguousarray(p) for p in np.array_split(x, n_parts)]
    seed_batch = x[: 32 * k]
    params = {"k": k, "seed": 11}

    def client(daemon):
        return DataPlaneClient(
            *daemon.address, timeout=60.0, max_op_attempts=2,
            backoff_base_s=0.02, backoff_max_s=0.2,
        )

    def feed_pass(job, routing, it):
        for pid, c in routing.items():
            c.feed(job, parts[pid], algo="kmeans", partition=pid,
                   pass_id=it, params=params)
            c.commit(job, partition=pid, pass_id=it)

    def reduce_step_sync(job, primary, peers):
        for pc in peers:
            arrays, meta = pc.export_state(job)
            primary.merge_state(
                job, arrays, rows=int(meta["pass_rows"]), algo="kmeans",
                n_cols=d, params=params,
            )
        info = primary.step(job)
        arrays, it_n = primary.get_iterate(job)
        for pc in peers:
            pc.set_iterate(job, arrays, it_n)
        return info, (arrays, it_n)

    record: dict = {
        "metric": f"chaos_grow_admit_rows_per_s_d{d}_k{k}",
        "unit": "rows/s",
        "mode": "chaos_grow",
        "n_daemons": 2,
        "n_grown": 3,
        "rows": n,
        "passes": passes,
    }
    da = DataPlaneDaemon(ttl=3600.0).start()
    dc_ = DataPlaneDaemon(ttl=3600.0).start()
    ca, cc = client(da), client(dc_)
    db = None
    cb = None
    try:
        # Oracle: the static 2-daemon topology, uninterrupted — also
        # the steady-pass clock the grow overhead is measured against.
        job = "grow-oracle"
        steady = []
        for c in (ca, cc):
            c.seed_kmeans(job, seed_batch, k=k, params=params)
        routing2 = {pid: (cc if pid >= 3 else ca) for pid in range(n_parts)}
        for it in range(passes):
            t0 = time.perf_counter()
            feed_pass(job, routing2, it)
            reduce_step_sync(job, ca, [cc])
            steady.append(time.perf_counter() - t0)
        oracle, _ = ca.finalize(job, {}, drop=False)
        ca.drop(job)
        steady_pass_s = min(steady)

        # Grown run: pass 0 on two daemons, then the joiner appears at
        # the boundary and takes partitions 2-3 for the middle passes.
        job = "grow-elastic"
        for c in (ca, cc):
            c.seed_kmeans(job, seed_batch, k=k, params=params)
        feed_pass(job, routing2, 0)
        _, ledger = reduce_step_sync(job, ca, [cc])

        t0 = time.perf_counter()
        db = DataPlaneDaemon(ttl=3600.0).start()
        cb = client(db)
        # The admission handshake: ONE creating set_iterate seeds the
        # joiner with the boundary iterate (same creation fields the
        # quarantine-replay ledger carries) — no seed_kmeans, no feed.
        admit_t0 = time.perf_counter()
        arrays, it_n = ledger
        cb.set_iterate(job, arrays, it_n, algo="kmeans", n_cols=d,
                       params=params)
        time_to_admit = time.perf_counter() - admit_t0
        routing3 = {
            pid: (cc if pid >= 4 else cb if pid >= 2 else ca)
            for pid in range(n_parts)
        }
        rebalanced_rows = sum(
            len(parts[pid]) for pid, c in routing3.items() if c is cb
        )
        feed_pass(job, routing3, 1)
        _, ledger = reduce_step_sync(job, ca, [cb, cc])
        time_to_grow = time.perf_counter() - t0

        # Grown middle passes, then shrink at the boundary: the
        # joiner's partials were merged by the reduce above, so the
        # last pass simply routes around it — no rewind, no replay.
        for it in range(2, passes - 1):
            feed_pass(job, routing3, it)
            reduce_step_sync(job, ca, [cb, cc])
        cb.close()
        cb = None
        db.stop()
        db = None
        feed_pass(job, routing2, passes - 1)
        reduce_step_sync(job, ca, [cc])
        grown, _ = ca.finalize(job, {}, drop=False)
        ca.drop(job)
        cc.drop(job)

        record.update({
            "value": round(rebalanced_rows / time_to_grow, 1),
            "time_to_admit_s": round(time_to_admit, 4),
            "time_to_grow_s": round(time_to_grow, 4),
            "rebalanced_rows": rebalanced_rows,
            "steady_pass_s": round(steady_pass_s, 4),
            "grow_overhead": round(time_to_grow / steady_pass_s, 3),
            "bitwise_equal_oracle": bool(
                np.array_equal(grown["centers"], oracle["centers"])
            ),
        })
    finally:
        for c in (ca, cb, cc):
            if c is not None:
                c.close()
        for daemon in (da, db, dc_):
            if daemon is not None:
                daemon.stop()
    print(json.dumps(record))


def chaos_partition_bench() -> None:
    """``--chaos-partition``: the gossip partition-heal micro-record
    for the fleet control plane (docs/protocol.md "Fleet gossip &
    bootstrap") — the serving-plane sibling of the elastic chaos pair.

    Four daemons with LIVE gossip threads form two islands that never
    hear of each other (each island's controller only ever pushes to
    its own pair, and gossip peers are drawn from each daemon's own
    view, so the split needs no firewall). Island B registers the model
    first; island A registers it and rolls it to v2 AFTER — so A's
    records dominate under the ``(epoch, boot_id)`` merge rule and v1
    carries a tombstone. A routed client bootstrapped from ONE island-B
    seed serves traffic throughout the split; every response must
    succeed and be bitwise-stable (a partition degrades freshness,
    never correctness). The heal is ONE bridge ``gossip_push`` from an
    island-A view into an island-B daemon; anti-entropy carries it the
    rest of the way. Reported: ``time_to_converge_s`` (bridge push →
    all four views agree: active v2, one record epoch, v1 tombstoned
    everywhere, four live replicas — the record self-verifies that the
    losing island's v1 never resurrects), plus the routed/failed/
    mismatched traffic tallies from inside the split. One JSON line;
    perfcheck's ``check_chaos_partition`` gates correctness absolutely
    and convergence time against the CHAOS_r* trajectory."""
    import threading

    from spark_rapids_ml_tpu.serve.client import DataPlaneClient
    from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon
    from spark_rapids_ml_tpu.serve.fleet import ModelFleet
    from spark_rapids_ml_tpu.serve.router import FleetClient

    d = int(os.environ.get("SRML_BENCH_PARTITION_D", 64))
    k = int(os.environ.get("SRML_BENCH_PARTITION_K", 8))
    rows = int(os.environ.get("SRML_BENCH_PARTITION_ROWS", 64))
    interval = float(os.environ.get("SRML_BENCH_PARTITION_INTERVAL_S", 0.05))
    fanout = int(os.environ.get("SRML_BENCH_PARTITION_FANOUT", 2))
    split_s = float(os.environ.get("SRML_BENCH_PARTITION_SPLIT_S", 0.5))
    deadline = float(os.environ.get("SRML_BENCH_PARTITION_DEADLINE_S", 30.0))
    model = "bench-partition"

    rng = np.random.default_rng(0)
    # Fabricated projections (the fleet_bench idiom — a (d, k) payload
    # needs no fit); v2 is a different shape so a flip is observable.
    arrays_v1 = {
        "pc": rng.standard_normal((d, k)).astype(np.float64),
        "mean": np.zeros((d,), np.float64),
    }
    arrays_v2 = {
        "pc": rng.standard_normal((d, k - 2)).astype(np.float64),
        "mean": np.zeros((d,), np.float64),
    }
    q = rng.standard_normal((rows, d)).astype(np.float64)

    record: dict = {
        "metric": "chaos_partition_converge_d4",
        "unit": "s",
        "mode": "chaos_partition",
        "n_daemons": 4,
        "gossip_interval_s": interval,
        "gossip_fanout": fanout,
    }
    daemons = [
        DataPlaneDaemon(
            ttl=3600.0, gossip_interval_s=interval, gossip_fanout=fanout,
        ).start()
        for _ in range(4)
    ]
    island_a, island_b = daemons[:2], daemons[2:]
    stop = threading.Event()
    routed = [0]
    failed = [0]
    mismatched = [0]

    def traffic() -> None:
        # A fresh operator box: ONE island-B seed, no endpoint roster.
        ref = None
        seed = "%s:%d" % island_b[0].address
        with FleetClient.from_seeds([seed]) as fc:
            while not stop.is_set():
                try:
                    got = np.asarray(
                        fc.transform(model, q, route_key="bench")["output"]
                    )
                except Exception:
                    failed[0] += 1
                    continue
                if ref is None:
                    ref = got
                elif not np.array_equal(got, ref):
                    mismatched[0] += 1
                routed[0] += 1

    try:
        # Island B first: its v1 records carry the OLDER epochs.
        with ModelFleet([d_.address for d_ in island_b]) as fb:
            fb.register(model, "pca", arrays_v1, version=1)
        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        # Island A second, and it rolls forward — both controllers live
        # in this process so they share one Lamport clock and A's
        # register + rollout strictly dominate B's stale v1 records.
        with ModelFleet([d_.address for d_ in island_a]) as fa:
            fa.register(model, "pca", arrays_v1, version=1)
            fa.rollout(model, "pca", arrays_v2, version=2, warm=False)
        time.sleep(split_s)  # let traffic route inside the split
        stop.set()
        t.join(timeout=60)

        def converged() -> bool:
            epochs = set()
            for dm in daemons:
                rec = dm.fleet_view.model(model)
                if rec is None or rec.get("active_version") != 2:
                    return False
                if rec.get("intent") is not None:
                    return False
                if "1" not in (rec.get("tombstones") or {}):
                    return False
                if len(dm.fleet_view.replicas(liveness="up")) != 4:
                    return False
                epochs.add(int(rec["epoch"]))
            return len(epochs) == 1

        # The heal: ONE bridge push A→B; the gossip threads do the rest.
        t0 = time.perf_counter()
        with DataPlaneClient(*island_b[0].address, timeout=10.0) as bridge:
            bridge.gossip_push(island_a[0].fleet_view.to_wire())
        while not converged():
            if time.perf_counter() - t0 > deadline:
                break
            time.sleep(interval / 4)
        time_to_converge = time.perf_counter() - t0

        record.update({
            "value": round(time_to_converge, 4),
            "time_to_converge_s": round(time_to_converge, 4),
            "converged": converged(),
            "routed_during_partition": routed[0],
            "failed_during_partition": failed[0],
            "mismatched_during_partition": mismatched[0],
            "tombstones_clean": all(
                "1" in (dm.fleet_view.model(model) or {}).get(
                    "tombstones", {}
                )
                for dm in daemons
            ),
            "split_s": split_s,
        })
    finally:
        stop.set()
        for dm in daemons:
            dm.stop()
    print(json.dumps(record))


def forest_bench() -> None:
    """``--forest``: histogram tree-ensemble throughput (the first
    non-GEMM workload record — FOREST_r*).

    Fits a RandomForest classifier (models/random_forest.py: quantile
    binning + fused per-depth histogram accumulate + vectorized split
    scoring, all level-synchronous on device) on a clustered synthetic
    classification set and measures

      * ``value``: fit SCAN throughput, rows/s — rows x depth-passes
        over the fit wall clock (each pass re-scans the dataset, the
        honest analogue of the streaming-fit rows/s headline);
      * ``transform_rows_per_s``: bucketed ``predict_matrix`` QPS over
        repeated batches (warm jit — serving-path throughput);
      * a held-out ``accuracy`` self-check, differential against a
        sklearn-CPU RandomForest baseline when sklearn is installed
        (``baseline.impl: "sklearn"``; ``accuracy_ok`` = ours within
        0.05 of the baseline — an ABSOLUTE correctness gate for
        tools/perfcheck.py check_forest, not history-relative).

    One JSON line; ``tools/perfcheck.py`` gates fit/transform
    throughput against the FOREST_r* trajectory (SKIP-not-pass without
    history) and the accuracy gate absolutely."""
    import jax

    from spark_rapids_ml_tpu.models.random_forest import (
        RandomForestClassificationModel,
        fit_random_forest_classifier,
    )

    n = int(os.environ.get("SRML_BENCH_FOREST_ROWS", 200_000))
    d = int(os.environ.get("SRML_BENCH_FOREST_COLS", 32))
    trees = int(os.environ.get("SRML_BENCH_FOREST_TREES", 8))
    depth = int(os.environ.get("SRML_BENCH_FOREST_DEPTH", 6))
    bins = int(os.environ.get("SRML_BENCH_FOREST_BINS", 32))
    classes = int(os.environ.get("SRML_BENCH_FOREST_CLASSES", 4))
    n_test = max(n // 10, 1024)
    rng = np.random.default_rng(11)
    centers = rng.normal(size=(classes, d)) * 6.0
    y_all = rng.integers(0, classes, size=n + n_test)
    x_all = (
        centers[y_all] + rng.normal(size=(n + n_test, d))
    ).astype(np.float32)
    x, y = x_all[:n], y_all[:n]
    x_test, y_test = x_all[n:], y_all[n:]

    def fit_ours():
        t0 = time.perf_counter()
        sol = fit_random_forest_classifier(
            x, y, n_classes=classes, num_trees=trees, max_depth=depth,
            max_bins=bins, seed=5,
        )
        return sol, time.perf_counter() - t0

    # Warmup fit compiles the per-depth programs; the timed fit
    # measures steady dispatch (the compile-storm split every BENCH
    # record keeps).
    fit_ours()
    sol, fit_s = fit_ours()
    model = RandomForestClassificationModel(arrays=sol.arrays)
    acc = float(np.mean(model.predict(x_test) == y_test))

    batch = x_test[:4096] if n_test >= 4096 else x_test
    model.predict(batch)  # warm the predict ladder
    reps = max(int(2_000_000 // max(batch.shape[0], 1)), 5)
    t0 = time.perf_counter()
    for _ in range(reps):
        model.predict(batch)
    transform_s = time.perf_counter() - t0
    transform_rps = reps * batch.shape[0] / transform_s

    baseline: dict = {"impl": None}
    speedup_fit = speedup_transform = None
    accuracy_ok = True
    try:
        from sklearn.ensemble import RandomForestClassifier as SkRF

        t0 = time.perf_counter()
        sk = SkRF(
            n_estimators=trees, max_depth=depth, random_state=5, n_jobs=-1
        ).fit(x, y)
        sk_fit_s = time.perf_counter() - t0
        sk.predict(batch)
        t0 = time.perf_counter()
        for _ in range(max(reps // 4, 2)):
            sk.predict(batch)
        sk_tr_s = time.perf_counter() - t0
        sk_rps = max(reps // 4, 2) * batch.shape[0] / sk_tr_s
        sk_acc = float(sk.score(x_test, y_test))
        baseline = {
            "impl": "sklearn",
            "fit_s": round(sk_fit_s, 4),
            "transform_rows_per_s": round(sk_rps, 1),
            "accuracy": round(sk_acc, 4),
        }
        speedup_fit = round(sk_fit_s / fit_s, 3)
        speedup_transform = round(transform_rps / sk_rps, 3)
        accuracy_ok = acc >= sk_acc - 0.05
    except ImportError:
        # No sklearn on this image: the accuracy gate falls back to an
        # absolute floor on the easy synthetic shape.
        accuracy_ok = acc >= 0.9

    record = {
        "metric": (
            f"forest_fit_rows_per_s_n{n}_d{d}_t{trees}"
            f"_depth{depth}_b{bins}"
        ),
        "unit": "rows/s",
        "mode": "forest",
        "value": round(n * sol.n_passes / fit_s, 1),
        "rows": n,
        "n_cols": d,
        "trees": trees,
        "max_depth": depth,
        "max_bins": bins,
        "n_classes": classes,
        "passes": sol.n_passes,
        "fit_s": round(fit_s, 4),
        "transform_rows_per_s": round(transform_rps, 1),
        "accuracy": round(acc, 4),
        "accuracy_ok": bool(accuracy_ok),
        "baseline": baseline,
        "speedup_fit": speedup_fit,
        "speedup_transform": speedup_transform,
        "backend": jax.default_backend(),
    }
    print(json.dumps(record))


def kernels_bench() -> None:
    """``--kernels``: fused-vs-unfused microbench, ONE JSON record line
    per kernel — the per-kernel receipt behind the fusion PR's headline.

    Each record carries the fused path's throughput (``value``), the
    unfused XLA two-step's (``unfused_rows_per_s``), and their ratio
    (``speedup``); ``tools/perfcheck.py check_kernels`` gates the fused
    path as NEVER-SLOWER-THAN-UNFUSED on the same backend. Off-TPU the
    fused kernels run the Pallas interpreter, which measures nothing
    about the TPU kernel — those records are marked ``interpret`` and
    perfcheck reads them as SKIP, never pass (the shapes also shrink to
    smoke size there). Kernels covered: the single-pass streaming
    count/colsum/Gram (``gram_colsum_pallas`` vs the XLA mask two-step)
    and the streaming distance+top-k (``dist_topk_pallas`` vs
    ``sq_euclidean`` → ``lax.top_k``)."""
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops import gram as gram_ops
    from spark_rapids_ml_tpu.ops import pallas_kernels as pk
    from spark_rapids_ml_tpu.ops.distances import sq_euclidean
    from spark_rapids_ml_tpu.utils.xprof import ledgered_jit

    backend = jax.default_backend()
    interpret = backend != "tpu"
    tpu = not interpret
    n = int(os.environ.get("SRML_BENCH_KERNELS_ROWS",
                           1 << 17 if tpu else 1 << 12))
    d = int(os.environ.get("SRML_BENCH_KERNELS_COLS", 1024 if tpu else 256))
    q = int(os.environ.get("SRML_BENCH_KERNELS_QUERIES", 1024 if tpu else 64))
    k = int(os.environ.get("SRML_BENCH_KERNELS_K", 16 if tpu else 8))
    reps = int(os.environ.get("SRML_BENCH_KERNELS_REPS", 8 if tpu else 2))
    cd = jnp.bfloat16 if tpu else jnp.float32
    cd_name = jnp.dtype(cd).name

    x = jax.random.normal(jax.random.key(0), (n, d), jnp.float32).astype(cd)
    queries = jax.random.normal(
        jax.random.key(1), (q, d), jnp.float32
    ).astype(cd)
    ids = jnp.arange(n, dtype=jnp.int32)
    mask = jnp.ones((n,), jnp.float32)

    def timed(fn, *args) -> float:
        jax.block_until_ready(fn(*args))  # compile + warm outside the clock
        t0 = time.perf_counter()
        out = None
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    @ledgered_jit("bench.kernels_gram_fused")
    def gram_fused(xb):
        return pk.gram_colsum_pallas(xb, n, interpret=interpret)

    @ledgered_jit("bench.kernels_gram_unfused")
    def gram_unfused(xb):
        return gram_ops.local_stats(
            xb, compute_dtype=cd_name, accum_dtype="float32",
            use_pallas=False,
        )

    @ledgered_jit("bench.kernels_topk_fused")
    def topk_fused(qs, xb):
        return pk.dist_topk_pallas(qs, xb, ids, mask, k, interpret=interpret)

    @ledgered_jit("bench.kernels_topk_unfused")
    def topk_unfused(qs, xb):
        d2 = sq_euclidean(qs, xb, accum_dtype=jnp.float32)
        neg, idx = jax.lax.top_k(-d2, k)
        return -neg, idx

    for kernel, fused_s, unfused_s, rows, shape in (
        (
            "gram_colsum",
            timed(gram_fused, x),
            timed(gram_unfused, x),
            n,
            f"n{n}_d{d}_{cd_name}",
        ),
        (
            "dist_topk",
            timed(topk_fused, queries, x),
            timed(topk_unfused, queries, x),
            n,  # db rows scanned per query batch
            f"n{n}_d{d}_q{q}_k{k}_{cd_name}",
        ),
    ):
        fused_rps = rows / fused_s
        unfused_rps = rows / unfused_s
        print(json.dumps({
            "metric": f"kernel_{kernel}_{shape}",
            "mode": "kernels",
            "kernel": kernel,
            "value": round(fused_rps, 1),
            "unit": "rows/s",
            "unfused_rows_per_s": round(unfused_rps, 1),
            "speedup": round(fused_rps / unfused_rps, 4),
            "fused_s": round(fused_s, 6),
            "unfused_s": round(unfused_s, 6),
            "backend": backend,
            "interpret": interpret,
        }))


def _fleet_daemon_worker() -> None:
    """``--fleet-daemon`` subcommand: one replica daemon as its own OS
    process (the deployment unit). Prints ``READY <port>``; serves until
    stdin closes — the parent's handle drop is the shutdown signal, so
    an aborted bench never leaks the process (tests/daemon_worker.py's
    contract).

    ``SRML_BENCH_FLEET_CPUS`` (a comma-separated core list) pins this
    replica's CPU affinity BEFORE the jax import sizes its threadpools:
    on a real fleet each replica owns its own host's silicon, so a
    shared-box measurement must give each replica a fixed disjoint core
    slice — otherwise one daemon's XLA threadpool absorbs the whole
    machine and "adding replicas" just re-partitions the same cores,
    measuring nothing."""
    cpus = os.environ.get("SRML_BENCH_FLEET_CPUS")
    if cpus and hasattr(os, "sched_setaffinity"):
        os.sched_setaffinity(0, {int(c) for c in cpus.split(",")})

    import jax

    jax.config.update("jax_platforms", "cpu")

    from spark_rapids_ml_tpu.serve import DataPlaneDaemon

    daemon = DataPlaneDaemon(host="127.0.0.1", port=0, ttl=600.0).start()
    print(f"READY {daemon.address[1]}", flush=True)
    sys.stdin.read()
    daemon.stop()


def _fleet_client_worker() -> None:
    """``--fleet-client`` subcommand: one load-generating client process
    running ``SRML_BENCH_FLEET_THREADS`` request loops (each its own
    FleetClient — the router is single-threaded by contract; threads
    overlap the wire wait, which is most of a small request's latency).
    Each loop routes ``SRML_BENCH_FLEET_REQS`` transforms with fresh
    route keys (uniform spread), then the worker prints ONE JSON line of
    per-request latencies. Prints ``READY`` after warmup and waits for
    ``GO`` on stdin so the parent can open every worker's timed window
    together."""
    import threading

    # Same affinity contract as the daemon worker: load generators are
    # pinned OFF the replica cores (and identically in the 1-replica and
    # N-replica phases), so adding replicas changes replica resources
    # and nothing else.
    cpus = os.environ.get("SRML_BENCH_FLEET_CPUS")
    if cpus and hasattr(os, "sched_setaffinity"):
        os.sched_setaffinity(0, {int(c) for c in cpus.split(",")})

    import jax

    jax.config.update("jax_platforms", "cpu")

    from spark_rapids_ml_tpu.serve.fleet import ModelFleet

    endpoints = os.environ["SRML_BENCH_FLEET_ENDPOINTS"].split(",")
    model = os.environ.get("SRML_BENCH_FLEET_MODEL", "bench-fleet")
    reqs = int(os.environ.get("SRML_BENCH_FLEET_REQS", 50))
    rows = int(os.environ.get("SRML_BENCH_FLEET_ROWS", 64))
    d = int(os.environ.get("SRML_BENCH_FLEET_D", 256))
    threads_n = int(os.environ.get("SRML_BENCH_FLEET_THREADS", 2))
    seed = int(os.environ.get("SRML_BENCH_FLEET_SEED", 0))
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((rows, d)).astype(np.float32)

    fleet = ModelFleet([(e.rsplit(":", 1)[0], int(e.rsplit(":", 1)[1]))
                        for e in endpoints])
    # The table needs the model's active version; the parent registered
    # v1 on every replica — mirror that registration table-side only
    # (arrays are only needed for in-band repair, which the bench skips).
    fleet.table.install(model, 1, "pca", {}, {})
    fleet.table.activate(model, 1)
    # Round-robin STICKY keys, one per replica: hashing a fresh nonce
    # per request is uniform on average but binomially imbalanced at any
    # instant (some replica queues while another idles); a throughput
    # client cycles a key per ring member instead — still pure
    # client-side routing, now perfectly balanced. Failover semantics
    # are unchanged.
    ring = fleet.table.ring
    keys: list = []
    probe = 0
    want = set(ring.members)
    while want:
        k = f"rr-{probe}"
        probe += 1
        owner = ring.primary(k)
        if owner in want:
            want.discard(owner)
            keys.append(k)
    clients = [fleet.client() for _ in range(threads_n)]
    for c in clients:
        c.transform(model, q)  # warm each loop's route + sockets
    print("READY", flush=True)
    for line in sys.stdin:
        if line.strip() == "GO":
            break
    lat: list = []
    lock = threading.Lock()

    def loop(client, offset: int) -> None:
        mine = []
        for n in range(reqs):
            t0 = time.perf_counter()
            client.transform(model, q,
                             route_key=keys[(offset + n) % len(keys)])
            mine.append(time.perf_counter() - t0)
        with lock:
            lat.extend(mine)

    ts = [threading.Thread(target=loop, args=(c, i))
          for i, c in enumerate(clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for c in clients:
        c.close()
    fleet.close()
    print(json.dumps({"latencies": lat}), flush=True)


_ECHO_SERVER = """
import socket, sys
req_bytes, resp_bytes = int(sys.argv[1]), int(sys.argv[2])
hdr = b"h" * 128
resp = b"r" * resp_bytes
srv = socket.socket(); srv.bind(("127.0.0.1", 0)); srv.listen(4)
print(srv.getsockname()[1], flush=True)
conn, _ = srv.accept()
want = 256 + req_bytes  # header frame + payload frame, like a transform
with conn:
    while True:
        got = 0
        while got < want:
            data = conn.recv(1 << 20)
            if not data:
                raise SystemExit(0)
            got += len(data)
        conn.sendall(hdr)
        conn.sendall(resp)
"""

_ECHO_CLIENT = """
import socket, sys, time
port, req_bytes, resp_bytes, secs = (
    int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), float(sys.argv[4])
)
hdr = b"h" * 256
payload = b"a" * req_bytes
want = 128 + resp_bytes
n = 0
with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    stop = time.monotonic() + secs
    while time.monotonic() < stop:
        s.sendall(hdr)
        s.sendall(payload)
        got = 0
        while got < want:
            data = s.recv(1 << 20)
            if not data:
                raise SystemExit(1)
            got += len(data)
        n += 1
print(n)
"""


def _wire_fabric_scaling(n: int, req_bytes: int, resp_bytes: int,
                         secs: float = 2.0) -> dict:
    """Raw loopback request/response scaling, 1 vs n PROCESS pairs with
    the serving protocol's frame pattern (header+payload up,
    header+arrays down, real sizes) — the fleet twin of --multichip's
    raw allreduce microphase. On a real kernel this is ~linear and huge;
    on a sandboxed/virtualized network stack it is the hard ceiling
    every replica shares, and the fleet record must say so rather than
    let the environment read as a fleet-layer regression."""
    import subprocess

    def run(pairs: int) -> float:
        servers = [
            subprocess.Popen(
                [sys.executable, "-c", _ECHO_SERVER, str(req_bytes),
                 str(resp_bytes)],
                stdout=subprocess.PIPE, text=True,
            )
            for _ in range(pairs)
        ]
        ports = [int(s.stdout.readline()) for s in servers]
        clients = [
            subprocess.Popen(
                [sys.executable, "-c", _ECHO_CLIENT, str(p), str(req_bytes),
                 str(resp_bytes), str(secs)],
                stdout=subprocess.PIPE, text=True,
            )
            for p in ports
        ]
        total = sum(int(c.communicate()[0]) for c in clients)
        for s in servers:
            s.kill()
        return total / secs

    one = run(1)
    many = run(n)
    return {
        "pairs": n, "req_bytes": req_bytes, "resp_bytes": resp_bytes,
        "reqs_per_s_1": round(one, 1), "reqs_per_s_n": round(many, 1),
        "efficiency": round(many / (n * one), 4) if one else 0.0,
    }


def fleet_bench() -> None:
    """Fleet-serving benchmark (module docstring): QPS at 1 replica vs
    N replicas, same M-client workload, scaling efficiency recorded and
    gated (tools/perfcheck.py ``check_serve_fleet``).

    Single-box honesty: every replica of a single-box measurement
    shares the host's loopback stack, so the record also measures the
    RAW WIRE FABRIC's own process-scaling (an echo microphase at the
    request payload size — the fleet twin of --multichip's raw
    allreduce microphase). A fabric that itself scales below the floor
    marks the record ``wire_limited``: the absolute efficiency gate
    SKIPs (never a pass — the environment, not the fleet, is the
    ceiling) and the FABRIC-RELATIVE efficiency (QPS scaling divided by
    wire scaling) is gated instead, isolating what the fleet LAYER
    costs on top of whatever transport it rides. Replica daemons are
    additionally core-pinned (disjoint slices, clients on the
    remainder) so on hosts where affinity binds, one replica cannot
    absorb the whole box's compute."""
    import subprocess
    import threading

    from spark_rapids_ml_tpu.serve.fleet import ModelFleet

    d = int(os.environ.get("SRML_BENCH_FLEET_D", 256))
    k = int(os.environ.get("SRML_BENCH_FLEET_K", 16))
    n_replicas = int(os.environ.get("SRML_BENCH_FLEET_REPLICAS", 4))
    clients = int(os.environ.get("SRML_BENCH_FLEET_CLIENTS", 8))
    threads_per = int(os.environ.get("SRML_BENCH_FLEET_THREADS", 2))
    reqs = int(os.environ.get("SRML_BENCH_FLEET_REQS", 50))
    rows = int(os.environ.get("SRML_BENCH_FLEET_ROWS", 64))
    inproc = os.environ.get("SRML_BENCH_FLEET_INPROC", "") in ("1", "true")
    # Cores pinned per replica daemon (0 = no pinning): each replica
    # models a host that owns a FIXED silicon slice — without disjoint
    # affinity one daemon's XLA threadpool spans the whole box and the
    # 1-replica baseline already uses all the compute the N-replica run
    # would (see _fleet_daemon_worker).
    cpus_per = int(os.environ.get("SRML_BENCH_FLEET_CPUS_PER_REPLICA", 2))
    # Total concurrent request loops (and the request count the run must
    # account for, to the request): in-process smoke mode runs plain
    # threads, so threads_per applies to the subprocess mode only.
    loops = clients * (1 if inproc else threads_per)

    rng = np.random.default_rng(0)
    # Fabricated projection — the serving plane only needs a model
    # artifact, and a (d, k) payload needs no fit.
    arrays = {
        "pc": rng.standard_normal((d, k)).astype(np.float64),
        "mean": np.zeros((d,), np.float64),
    }

    def spawn_daemons(n: int):
        if inproc:
            from spark_rapids_ml_tpu.serve import DataPlaneDaemon

            daemons = [DataPlaneDaemon().start() for _ in range(n)]
            return daemons, [d_.address for d_ in daemons]
        procs = []
        addrs = []
        for i in range(n):
            env = dict(os.environ)
            if cpus_per > 0 and hasattr(os, "sched_setaffinity"):
                cores = sorted(os.sched_getaffinity(0))
                slice_ = [
                    str(cores[c % len(cores)])
                    for c in range(i * cpus_per, (i + 1) * cpus_per)
                ]
                env["SRML_BENCH_FLEET_CPUS"] = ",".join(slice_)
            p = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--fleet-daemon"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
            )
            procs.append(p)
        for p in procs:
            line = p.stdout.readline()
            assert line.startswith("READY"), f"daemon worker said {line!r}"
            addrs.append(("127.0.0.1", int(line.split()[1])))
        return procs, addrs

    def stop_daemons(handles):
        for h in handles:
            if inproc:
                h.stop()
            else:
                h.stdin.close()
        if not inproc:
            for h in handles:
                h.wait(timeout=30)

    def run(n: int) -> dict:
        handles, addrs = spawn_daemons(n)
        try:
            with ModelFleet(addrs) as fleet:
                fleet.register("bench-fleet", "pca", arrays, version=1)
            endpoints = ",".join(f"{h}:{p}" for h, p in addrs)
            lat: list = []
            if inproc:
                from spark_rapids_ml_tpu.serve.fleet import (
                    ModelFleet as _Fleet,
                )

                fleet = _Fleet(addrs)
                fleet.table.install("bench-fleet", 1, "pca", {}, {})
                fleet.table.activate("bench-fleet", 1)
                q = rng.standard_normal((rows, d)).astype(np.float32)
                fcs = [fleet.client() for _ in range(clients)]
                for fc in fcs:
                    fc.transform("bench-fleet", q)
                lock = threading.Lock()
                barrier = threading.Barrier(clients + 1)

                def worker(fc):
                    mine = []
                    barrier.wait()
                    for _ in range(reqs):
                        t0 = time.perf_counter()
                        fc.transform("bench-fleet", q)
                        mine.append(time.perf_counter() - t0)
                    with lock:
                        lat.extend(mine)

                threads = [threading.Thread(target=worker, args=(fc,))
                           for fc in fcs]
                for t in threads:
                    t.start()
                barrier.wait()
                t0 = time.perf_counter()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                for fc in fcs:
                    fc.close()
                fleet.close()
            else:
                env = {
                    **os.environ,
                    "SRML_BENCH_FLEET_ENDPOINTS": endpoints,
                    "SRML_BENCH_FLEET_REQS": str(reqs),
                    "SRML_BENCH_FLEET_ROWS": str(rows),
                    "SRML_BENCH_FLEET_D": str(d),
                    "SRML_BENCH_FLEET_THREADS": str(threads_per),
                }
                if cpus_per > 0 and hasattr(os, "sched_setaffinity"):
                    # Clients live on the cores NO replica phase will
                    # pin (the top n_replicas*cpus_per are reserved),
                    # so client resources are identical at 1 and N
                    # replicas and never contend with replica cores.
                    cores = sorted(os.sched_getaffinity(0))
                    reserved = min(n_replicas * cpus_per, len(cores) - 1)
                    client_cores = cores[reserved:] or cores
                    env["SRML_BENCH_FLEET_CPUS"] = ",".join(
                        str(c) for c in client_cores
                    )
                workers = []
                for i in range(clients):
                    workers.append(subprocess.Popen(
                        [sys.executable, os.path.abspath(__file__),
                         "--fleet-client"],
                        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                        text=True,
                        env={**env, "SRML_BENCH_FLEET_SEED": str(i)},
                        cwd=os.path.dirname(os.path.abspath(__file__)),
                    ))
                for w in workers:
                    line = w.stdout.readline()
                    assert line.strip() == "READY", f"client said {line!r}"
                t0 = time.perf_counter()
                for w in workers:
                    w.stdin.write("GO\n")
                    w.stdin.flush()
                outs = [w.stdout.readline() for w in workers]
                wall = time.perf_counter() - t0
                for w, out in zip(workers, outs):
                    w.stdin.close()
                    w.wait(timeout=30)
                    lat.extend(json.loads(out)["latencies"])
            assert len(lat) == loops * reqs, (
                f"lost requests: {len(lat)} != {loops * reqs}"
            )
            lat.sort()
            return {
                "qps": round(loops * reqs / wall, 1),
                "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                "p99_ms": round(
                    lat[min(int(len(lat) * 0.99), len(lat) - 1)] * 1e3, 3
                ),
            }
        finally:
            stop_daemons(handles)

    trials = int(os.environ.get("SRML_BENCH_FLEET_TRIALS", 2))

    def best(n: int) -> dict:
        # Best-of-N trials: on a shared box the scheduler-noise floor is
        # large, and a throughput record should report what the stack
        # sustains, not what a noisy neighbor left of it.
        return max((run(n) for _ in range(max(trials, 1))),
                   key=lambda r: r["qps"])

    one = best(1)
    many = best(n_replicas)
    eff = round(many["qps"] / (n_replicas * one["qps"]), 4) if one["qps"] else 0.0
    record = {
        "metric": f"serve_fleet_transform_qps_d{d}_k{k}_c{clients}_b{rows}",
        "value": many["qps"],
        "unit": "transforms/s",
        "n_replicas": n_replicas,
        "clients": clients,
        "threads_per_client": 1 if inproc else threads_per,
        "cpus_per_replica": 0 if inproc else cpus_per,
        "batch_rows": rows,
        "dryrun": inproc,
        "scaling_efficiency": eff,
        "replicas": {"1": one, str(n_replicas): many},
    }
    if not inproc:
        # The wire-fabric microphase (docstring): what the host's raw
        # loopback can carry at this workload's frame pattern, 1 vs N
        # process pairs. The FEASIBLE ideal on this host is
        # min(N x QPS_1, fabric capacity at N pairs) — a record whose
        # fabric cannot even carry N x QPS_1 is `wire_limited`: the
        # absolute efficiency gate is unmeasurable (the environment,
        # not the fleet, is the ceiling) and perfcheck gates the
        # fabric-relative efficiency QPS_N / feasible instead.
        wire = _wire_fabric_scaling(
            n_replicas, rows * d * 4, rows * k * 8
        )
        record["wire"] = wire
        ideal = n_replicas * one["qps"]
        feasible = min(ideal, wire["reqs_per_s_n"]) or 1.0
        record["wire_limited"] = wire["reqs_per_s_n"] < ideal
        record["fabric_relative_efficiency"] = round(
            many["qps"] / feasible, 4
        )
    print(json.dumps(record))


if __name__ == "__main__":
    if "--fleet-daemon" in sys.argv:
        _fleet_daemon_worker()
    elif "--fleet-client" in sys.argv:
        _fleet_client_worker()
    elif "--fleet" in sys.argv or os.environ.get(
        "SRML_BENCH_FLEET", ""
    ) in ("1", "true"):
        fleet_bench()
    elif "--chaos-elastic" in sys.argv or os.environ.get(
        "SRML_BENCH_CHAOS_ELASTIC", ""
    ) in ("1", "true"):
        chaos_elastic_bench()
    elif "--chaos-grow" in sys.argv or os.environ.get(
        "SRML_BENCH_CHAOS_GROW", ""
    ) in ("1", "true"):
        chaos_grow_bench()
    elif "--chaos-partition" in sys.argv or os.environ.get(
        "SRML_BENCH_CHAOS_PARTITION", ""
    ) in ("1", "true"):
        chaos_partition_bench()
    elif "--serve" in sys.argv or os.environ.get("SRML_BENCH_SERVE", "") in (
        "1", "true"
    ):
        serve_bench()
    elif "--multichip" in sys.argv or os.environ.get(
        "SRML_BENCH_MULTICHIP", ""
    ) in ("1", "true"):
        multichip_bench()
    elif "--forest" in sys.argv or os.environ.get(
        "SRML_BENCH_FOREST", ""
    ) in ("1", "true"):
        forest_bench()
    elif "--kernels" in sys.argv or os.environ.get(
        "SRML_BENCH_KERNELS", ""
    ) in ("1", "true"):
        kernels_bench()
    else:
        main()
