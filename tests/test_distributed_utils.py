"""Distributed runtime init, retry utils, and training summaries."""

import random
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu.parallel import distributed
from spark_rapids_ml_tpu.utils.retry import decorrelated_jitter, with_retries


def test_initialize_single_process_noop():
    assert distributed.initialize_cluster() == 0
    assert distributed.is_initialized()


def test_global_mesh(devices):
    mesh = distributed.global_mesh(model=2)
    assert mesh.shape["model"] == 2
    assert mesh.shape["data"] * 2 == len(devices)


def test_process_local_rows_single():
    start, stop = distributed.process_local_rows(100)
    assert (start, stop) == (0, 100)


def test_with_retries_succeeds_after_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert with_retries(flaky, max_attempts=5, base_delay_s=0.001) == "ok"
    assert calls["n"] == 3


def test_with_retries_exhausts():
    def always_fails():
        raise OSError("permanent")

    with pytest.raises(OSError):
        with_retries(always_fails, max_attempts=2, base_delay_s=0.001)


def test_with_retries_non_retryable_raises_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("deterministic bug")

    with pytest.raises(ValueError):
        with_retries(bad, max_attempts=5, base_delay_s=0.001)
    assert calls["n"] == 1


def test_decorrelated_jitter_bounds_and_decorrelation():
    """Delays stay within [base, cap] and two seeded sequences diverge —
    the anti-thundering-herd property (executors retrying a restarted
    daemon must not march in lockstep powers of two)."""
    base, cap = 0.05, 2.0

    def walk(seed, n=64):
        rng = random.Random(seed)
        d, out = base, []
        for _ in range(n):
            d = decorrelated_jitter(d, base, cap, rng)
            out.append(d)
        return out

    a, b = walk(1), walk(2)
    for d in a + b:
        assert base <= d <= cap
    assert a != b  # decorrelated: different clients, different schedules
    assert walk(1) == walk(1)  # but each is reproducible


def test_with_retries_caps_delay():
    """A long failure streak never sleeps past max_delay_s per attempt."""
    calls = {"n": 0}

    def fails_then_ok():
        calls["n"] += 1
        if calls["n"] < 5:
            raise OSError("transient")
        return "ok"

    start = time.monotonic()
    assert with_retries(
        fails_then_ok, max_attempts=6, base_delay_s=0.001,
        max_delay_s=0.01, rng=random.Random(0),
    ) == "ok"
    # 4 sleeps, each ≤ 0.01 s — far under the uncapped exponential sum.
    assert time.monotonic() - start < 1.0


def test_with_retries_deadline_bounds_total_time():
    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise OSError("transient")

    start = time.monotonic()
    with pytest.raises(OSError):
        with_retries(
            always_fails, max_attempts=1000, base_delay_s=0.05,
            max_delay_s=0.05, deadline_s=0.2, rng=random.Random(0),
        )
    elapsed = time.monotonic() - start
    assert elapsed < 2.0  # bounded by the deadline, not the 1000 attempts
    assert calls["n"] < 50


# ---------------------------------------------------------------------------
# Training summaries
# ---------------------------------------------------------------------------


def test_linear_regression_summary(rng, mesh8):
    from spark_rapids_ml_tpu import LinearRegression

    x = rng.normal(size=(500, 6))
    w = rng.normal(size=6)
    y = x @ w + 1.0 + 0.1 * rng.normal(size=500)
    model = LinearRegression(mesh=mesh8).fit({"features": x, "label": y})
    s = model.summary
    assert s is not None
    # Differential check vs direct residuals.
    resid = y - (x @ model.coefficients + model.intercept)
    rss = float(resid @ resid)
    assert abs(s.rss - rss) < 1e-6 * max(rss, 1)
    assert abs(s.rmse - np.sqrt(rss / 500)) < 1e-8
    ybar = y.mean()
    r2_ref = 1 - rss / float((y - ybar) @ (y - ybar))
    assert abs(s.r2 - r2_ref) < 1e-8
    assert s.r2 > 0.99


def test_kmeans_summary(rng, mesh8):
    from spark_rapids_ml_tpu import KMeans

    x = rng.normal(size=(300, 4))
    model = KMeans(mesh=mesh8).setK(3).fit({"features": x})
    assert model.hasSummary
    assert model.summary.k == 3
    assert model.summary.trainingCost == model.trainingCost
    assert model.summary.numIter >= 1


def test_logreg_summary(rng, mesh8):
    from spark_rapids_ml_tpu import LogisticRegression

    x = rng.normal(size=(200, 4))
    y = (x[:, 0] > 0).astype(float)
    model = LogisticRegression(mesh=mesh8).setRegParam(0.01).fit(
        {"features": x, "label": y}
    )
    s = model.summary
    assert s is not None and s.loss is not None
    # Loss must equal the objective at the fitted params.
    z = x @ model.coefficients + float(np.asarray(model.intercept).reshape(-1)[0])
    per = np.logaddexp(0, z) - y * z
    obj = per.mean() + 0.005 * 0.01 / 0.01 * 0  # reg term added below
    obj = per.mean() + 0.5 * 0.01 * (model.coefficients @ model.coefficients)
    assert abs(s.loss - obj) < 1e-8
