"""Fleet control plane: replicated models + zero-downtime version rollout.

serve/router.py routes requests; this module manages WHAT they route to:
one model registered as versioned replicas on N daemons, and the
register → warm → flip → drain sequence that swaps a live model version
without dropping a request (ROADMAP item 3; docs/protocol.md "Fleet &
versioned serving").

The lifecycle of one rollout, v1 → v2:

1. **register v2** under its versioned daemon name (``model@v2`` — the
   routing table's ``reg_name`` convention) on every live replica. v1
   keeps serving untouched; a replica that fails registration is marked
   dead (the router already skips it) and the rollout proceeds with the
   rest — a fleet with one dead member must still be upgradeable.
2. **warm** each registration through the PR 5/7 warmup ladder (the
   ``warmup`` wire op; with ``serve_warmup_on_register`` the daemon did
   it inside the registration ack already and this pass is a no-op),
   so the first routed v2 request is a dispatch, not a jit compile.
3. **atomically flip**: one ``RoutingTable.activate`` call moves the
   active version and bumps the fleet epoch. Requests that snapshotted
   before the flip finish on v1 (their pinned version); requests after
   it route to v2. No request ever sees a mixed state: the snapshot is
   one lock-protected read, and the versioned daemon names make
   cross-version answers structurally impossible.
4. **drain v1**: wait (``fleet_drain_timeout_s``) for the in-flight v1
   refcount to reach zero, then ``drop_model`` v1 everywhere and retire
   it from the table. A drain timeout leaves v1 registered (and says
   so) rather than yanking arrays out from under a live request.

``ModelFleet`` is the driver/operator-side object; it is single-threaded
like the admin clients it holds. Serving traffic goes through
``fleet.client()`` — one :class:`~.router.FleetClient` per worker
thread, all sharing this fleet's routing table and health view.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from spark_rapids_ml_tpu.serve import gossip as gossip_mod
from spark_rapids_ml_tpu.serve import protocol
from spark_rapids_ml_tpu.serve.client import DataPlaneClient
from spark_rapids_ml_tpu.serve.daemon import _model_width
from spark_rapids_ml_tpu.serve.router import (
    FleetClient,
    RoutingTable,
    bootstrap_table,
)
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils import flight
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.logging import get_logger

logger = get_logger("serve.fleet")

__all__ = ["ModelFleet", "FleetRolloutError"]

#: Fleet control-plane telemetry (docs/observability.md).
_M_REPLICAS = metrics_mod.gauge(
    "srml_fleet_replicas",
    "Replicas serving a model's active version, by model (set at "
    "register/rollout time)",
)
_M_EPOCH = metrics_mod.gauge(
    "srml_fleet_version_epoch",
    "The fleet routing epoch, by model (bumps on every version flip)",
)
_M_REGISTRATIONS = metrics_mod.counter(
    "srml_fleet_registrations_total",
    "Per-replica version registrations, by outcome (ok|error)",
)
_M_ROLLOUTS = metrics_mod.counter(
    "srml_fleet_rollouts_total",
    "Version rollouts, by outcome (ok|partial — some replica failed "
    "registration and was routed around)",
)
_M_DRAINS = metrics_mod.counter(
    "srml_fleet_drains_total",
    "Retired-version drains, by outcome (drained|timeout)",
)


class FleetRolloutError(RuntimeError):
    """No replica accepted the new version — the rollout did NOT flip;
    the old version keeps serving."""




class ModelFleet:
    """Replicated versioned model serving across N daemons.

    ``endpoints``: ``[(host, port)]`` (or ``"host:port"`` strings) of
    the replica daemons. All replicas are equals — there is no primary;
    the consistent-hash ring (router.py) spreads models and traffic.
    """

    def __init__(
        self,
        endpoints=None,
        token: Optional[str] = None,
        vnodes: Optional[int] = None,
        client_kwargs: Optional[Dict[str, Any]] = None,
        table: Optional[RoutingTable] = None,
    ):
        if table is None:
            table = RoutingTable(endpoints, vnodes=vnodes)
        elif endpoints is not None:
            raise ValueError("pass endpoints OR a pre-built table, not both")
        self._table = table
        self._token = token
        # Admin-op client settings: fail a dead replica in seconds (it
        # gets marked dead and routed around), don't heal for minutes.
        kw: Dict[str, Any] = {
            "timeout": 10.0, "op_deadline_s": 20.0, "max_op_attempts": 2,
        }
        kw.update(client_kwargs or {})
        self._client_kwargs = kw
        self._clients: Dict[str, DataPlaneClient] = {}
        self._lock = threading.Lock()  # serializes admin ops per fleet
        # Gossip half (serve/gossip.py): the controller keeps its own
        # FleetView and pushes every control-plane write (registration,
        # each rollout phase's intent, membership changes) to the
        # replicas, which gossip it onward — so the fleet's state
        # SURVIVES this object. A successor controller rebuilds from
        # any one daemon (from_seeds) and resumes (resume_rollout).
        self._view = gossip_mod.FleetView()
        self._controller_id = f"ctl-{uuid.uuid4().hex[:12]}"
        self._identities: Dict[str, Dict[str, Any]] = {}

    @classmethod
    def from_seeds(
        cls,
        seeds=None,
        token: Optional[str] = None,
        vnodes: Optional[int] = None,
        client_kwargs: Optional[Dict[str, Any]] = None,
    ) -> "ModelFleet":
        """A control plane bootstrapped from ONE seed daemon's gossiped
        FleetView (router.bootstrap_table) — how a SUCCESSOR controller
        (or any operator tool) takes over a running fleet with no
        endpoint roster and no surviving predecessor. Version entries
        adopted this way are payload-less; serving keeps working, and
        :meth:`resume_rollout` can finish or abort an interrupted
        rollout from the gossiped intent."""
        t = bootstrap_table(seeds, token=token, vnodes=vnodes)
        fleet = cls(token=token, client_kwargs=client_kwargs, table=t)
        return fleet

    # -- lifecycle ---------------------------------------------------------

    @property
    def table(self) -> RoutingTable:
        return self._table

    @property
    def view(self) -> gossip_mod.FleetView:
        """The controller's own gossiped FleetView (tools/top, the
        autoscaler's membership telemetry)."""
        return self._view

    def close(self) -> None:
        for c in self._clients.values():
            c.close()
        self._clients.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def client(self, **kwargs) -> FleetClient:
        """A routing client sharing this fleet's table and health view.
        One per worker thread (FleetClient is single-threaded)."""
        kwargs.setdefault("token", self._token)
        return FleetClient(self._table, **kwargs)

    def _client(self, key: str) -> DataPlaneClient:
        c = self._clients.get(key)
        if c is None:
            r = self._table.replica(key)
            c = DataPlaneClient(
                r.host, r.port, token=self._token, **self._client_kwargs
            )
            self._clients[key] = c
        return c

    # -- gossip sync (serve/gossip.py; docs/protocol.md) --------------------

    def _refresh_replica_records(self) -> None:
        """Write the table's CURRENT members into the controller's view
        as replica records (identity pulled once per replica and
        cached). A replica whose identity cannot be read is skipped —
        the daemons' own start()-time records cover it via gossip."""
        for r in self._table.replicas():
            ident = self._identities.get(r.key)
            if ident is None:
                try:
                    ident = self._client(r.key).server_info()
                except (OSError, protocol.ProtocolError, RuntimeError):
                    continue
                self._identities[r.key] = ident
            sid = str(ident.get("id") or r.key)
            self._view.observe_replica(
                sid, r.key, str(ident.get("boot_id") or ""), liveness="up"
            )

    def _push_view(self) -> int:
        """Push the controller's FleetView to every live replica and
        merge each ack's view back (push-pull), best effort per
        replica. With per-daemon gossip threads running this just
        shortens convergence; with them disabled
        (``gossip_interval_s=0`` — unit tests, single-host fleets) this
        synchronous push IS the gossip. Returns replicas reached."""
        self._refresh_replica_records()
        wire = self._view.to_wire()
        pushed = 0
        for r in self._table.replicas():
            try:
                ack = self._client(r.key).gossip_push(wire)
            except (OSError, protocol.ProtocolError, RuntimeError) as e:
                logger.warning(
                    "gossip push to replica %s failed (its own gossip "
                    "thread will catch it up): %s", r.key, e,
                )
                continue
            remote = ack.get("view")
            if isinstance(remote, dict):
                self._view.merge(remote)
            pushed += 1
        return pushed

    def _publish_model(
        self, model: str, tombstone_versions=(),
    ) -> None:
        """Gossip one model's CURRENT table state — active version,
        fleet epoch, rollout intent (None = no rollout in flight) —
        to the fleet."""
        try:
            v, e, _ = self._table.snapshot(model)
        except KeyError:
            v, e = None, 0
        self._view.set_model(
            model, v, e, self._controller_id,
            intent=self._table.intent(model),
            tombstone_versions=tuple(tombstone_versions),
        )
        self._push_view()

    def _set_intent(
        self, model: str, from_v: Optional[int], to_v: int, phase: str,
    ) -> None:
        """Write + gossip a rollout-intent record BEFORE the phase it
        names runs, then cross the ``fleet.rollout`` fault site — the
        crash-safety contract: a controller that dies inside any phase
        has already told the fleet what it was doing, so a successor
        can complete or abort (docs/protocol.md "Fleet gossip &
        bootstrap")."""
        self._table.set_intent(model, {
            "model": model,
            "from_version": None if from_v is None else int(from_v),
            "to_version": int(to_v),
            "phase": phase,
            "by": self._controller_id,
            "at": float(time.time()),
        })
        self._publish_model(model)
        faults.checkpoint("fleet.rollout")

    # -- registration + rollout --------------------------------------------

    def _register_on_replicas(
        self, model: str, version: int, algo: str,
        arrays: Dict[str, np.ndarray], params: Dict[str, Any],
        warm: bool,
    ) -> Dict[str, List[str]]:
        """Register (and optionally warm) one version on every replica.
        Returns {"ok": [replica keys], "failed": [replica keys]}; failed
        replicas are marked dead so the router skips them."""
        reg_name = self._table.reg_name(model, version)
        # The daemon's own registration-width rule (ONE copy — a drifted
        # mirror here would silently skip the warmup for an algo whose
        # payload key changed); None skips the eager warmup.
        width = _model_width(algo, arrays)
        ok: List[str] = []
        failed: List[str] = []
        for r in self._table.replicas():
            try:
                c = self._client(r.key)
                c.ensure_model(
                    reg_name, algo, arrays, params=params, version=version,
                )
                if warm and width is not None:
                    # The PR 5/7 bucket-ladder pre-compile. On a daemon
                    # that already warmed inside ensure_model
                    # (serve_warmup_on_register) this reports compiled=0;
                    # with batching disabled it is an honest no-op.
                    c.warmup(reg_name, n_cols=width, dtype="float32")
                self._table.mark_alive(r.key)
                _M_REGISTRATIONS.inc(outcome="ok")
                ok.append(r.key)
            except (OSError, protocol.ProtocolError, RuntimeError) as e:
                _M_REGISTRATIONS.inc(outcome="error")
                self._table.mark_dead(
                    r.key, f"registration of {reg_name} failed: {e}",
                    recheck_s=1.0,
                )
                logger.warning(
                    "replica %s failed %s v%d registration (marked dead, "
                    "routing around it): %s", r.key, model, version, e,
                )
                failed.append(r.key)
        return {"ok": ok, "failed": failed}

    def register(
        self,
        model: str,
        algo: str,
        arrays: Dict[str, np.ndarray],
        params: Optional[Dict[str, Any]] = None,
        version: int = 1,
        warm: bool = True,
    ) -> Dict[str, Any]:
        """Register a model's FIRST served version on every replica and
        activate it. Returns ``{"version", "epoch", "replicas",
        "failed"}``. Raises :class:`FleetRolloutError` when no replica
        accepted it (the table stays without an active version)."""
        with self._lock:
            version = int(version)
            self._table.install(model, version, algo, arrays, params)
            res = self._register_on_replicas(
                model, version, algo, arrays, dict(params or {}), warm
            )
            if not res["ok"]:
                self._table.retire(model, version)
                raise FleetRolloutError(
                    f"no replica accepted {model!r} v{version} "
                    f"({len(res['failed'])} failed)"
                )
            epoch = self._table.activate(model, version)
            _M_REPLICAS.set(len(res["ok"]), model=model)
            _M_EPOCH.set(epoch, model=model)
            # Gossip the new model record so a client can bootstrap
            # (and a restarted replica re-learn) from any daemon.
            self._publish_model(model)
            return {
                "version": version, "epoch": epoch,
                "replicas": len(res["ok"]), "failed": res["failed"],
            }

    def rollout(
        self,
        model: str,
        algo: str,
        arrays: Dict[str, np.ndarray],
        params: Optional[Dict[str, Any]] = None,
        version: Optional[int] = None,
        warm: bool = True,
        drain_timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Zero-downtime version swap (module docstring): register the
        next version everywhere, warm it, atomically flip, drain and
        drop the old one. Returns ``{"version", "previous", "epoch",
        "replicas", "failed", "drained"}``."""
        from spark_rapids_ml_tpu import config

        with self._lock:
            old_v, _, old_reg = self._table.snapshot(model)
            new_v = int(version) if version is not None else old_v + 1
            if new_v == old_v:
                raise ValueError(
                    f"rollout version {new_v} is already the active "
                    f"version of {model!r}"
                )
            # Every phase below gossips its intent BEFORE it runs
            # (_set_intent): a controller that dies mid-phase leaves a
            # record any successor can act on — registering/warming
            # abort cleanly (nothing flipped), flipped/draining
            # complete (resume_rollout).
            self._set_intent(model, old_v, new_v, "registering")
            self._table.install(model, new_v, algo, arrays, params)
            res = self._register_on_replicas(
                model, new_v, algo, arrays, dict(params or {}), warm=False
            )
            if not res["ok"]:
                # Nothing flipped: v_old keeps serving, the failed
                # install is retired so a retry starts clean.
                self._table.retire(model, new_v)
                self._table.set_intent(model, None)
                self._publish_model(model)
                _M_ROLLOUTS.inc(outcome="error")
                # An aborted rollout is an incident: snapshot the
                # context NOW, while the failed registrations are still
                # in the span ring (no-op without a default recorder).
                flight.record("rollout_abort", {
                    "model": model, "phase": "registering",
                    "version": new_v, "failed": list(res["failed"]),
                })
                raise FleetRolloutError(
                    f"no replica accepted {model!r} v{new_v}; "
                    f"v{old_v} keeps serving"
                )
            if warm:
                self._set_intent(model, old_v, new_v, "warming")
                width = _model_width(algo, arrays)
                if width is not None:
                    reg_name = self._table.reg_name(model, new_v)
                    for key in list(res["ok"]):
                        try:
                            self._client(key).warmup(
                                reg_name, n_cols=width, dtype="float32"
                            )
                        except (OSError, protocol.ProtocolError,
                                RuntimeError) as e:
                            # Same policy as a failed registration:
                            # mark it dead and route around it.
                            self._table.mark_dead(
                                key, f"warmup of {reg_name} failed: {e}",
                                recheck_s=1.0,
                            )
                            res["ok"].remove(key)
                            res["failed"].append(key)
                    if not res["ok"]:
                        self._table.retire(model, new_v)
                        self._table.set_intent(model, None)
                        self._publish_model(model)
                        _M_ROLLOUTS.inc(outcome="error")
                        flight.record("rollout_abort", {
                            "model": model, "phase": "warming",
                            "version": new_v,
                            "failed": list(res["failed"]),
                        })
                        raise FleetRolloutError(
                            f"every replica failed warming {model!r} "
                            f"v{new_v}; v{old_v} keeps serving"
                        )
            # THE flip: one atomic table write. Every request from here
            # snapshots v_new; every in-flight request keeps its v_old
            # pin and its v_old daemon registration.
            self._set_intent(model, old_v, new_v, "flipped")
            epoch = self._table.activate(model, new_v)
            _M_REPLICAS.set(len(res["ok"]), model=model)
            _M_EPOCH.set(epoch, model=model)
            _M_ROLLOUTS.inc(outcome="ok" if not res["failed"] else "partial")
            logger.info(
                "flipped %s to v%d (epoch %d) on %d replica(s)",
                model, new_v, epoch, len(res["ok"]),
            )
            # Drain: let pinned v_old requests finish before their
            # arrays are dropped. A timeout leaves v_old registered —
            # stale registrations cost memory, yanked arrays cost
            # correctness.
            self._set_intent(model, old_v, new_v, "draining")
            timeout = float(
                config.get("fleet_drain_timeout_s")
                if drain_timeout_s is None else drain_timeout_s
            )
            drained = self._table.wait_drained(model, old_v, timeout)
            _M_DRAINS.inc(outcome="drained" if drained else "timeout")
            if drained:
                for r in self._table.replicas():
                    try:
                        self._client(r.key).drop_model(old_reg)
                    except (OSError, protocol.ProtocolError, RuntimeError):
                        pass  # dead replica: its registry died with it
                self._table.retire(model, old_v)
            else:
                logger.warning(
                    "drain of %s v%d timed out after %.1fs with %d "
                    "request(s) in flight; its registrations stay up",
                    model, old_v, timeout,
                    self._table.inflight(model, old_v),
                )
            # Rollout finished: clear the gossiped intent, tombstone
            # the drained version so no bootstrap re-adopts it.
            self._table.set_intent(model, None)
            self._publish_model(
                model, tombstone_versions=((old_v,) if drained else ()),
            )
            return {
                "version": new_v, "previous": old_v, "epoch": epoch,
                "replicas": len(res["ok"]), "failed": res["failed"],
                "drained": drained,
            }

    def resume_rollout(
        self,
        model: str,
        drain_timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Finish — or cleanly abort — a rollout whose controller died,
        from the gossiped ``rollout_intent`` record (usually on a fleet
        built with :meth:`from_seeds`). The intent's phase decides:

        * ``registering``/``warming`` — nothing flipped; ABORT: drop
          the half-registered to-version everywhere, clear the intent.
          The old version never stopped serving.
        * ``flipped``/``draining`` — the fleet was told the flip was
          happening; COMPLETE: make the to-version active (the flip is
          idempotent — re-activating the already-active version just
          re-bumps the epoch), drain and drop the from-version, clear
          the intent.

        Returns ``{"action": "aborted"|"completed"|"none", ...}``.
        """
        from spark_rapids_ml_tpu import config

        with self._lock:
            intent = self._table.intent(model)
            if not intent:
                return {"action": "none", "model": model}
            phase = str(intent.get("phase") or "")
            to_v = int(intent["to_version"])
            from_v = intent.get("from_version")
            from_v = None if from_v is None else int(from_v)
            if phase in ("registering", "warming"):
                reg = self._table.reg_name(model, to_v)
                for r in self._table.replicas():
                    try:
                        self._client(r.key).drop_model(reg)
                    except (OSError, protocol.ProtocolError, RuntimeError):
                        pass  # never registered there, or dead replica
                try:
                    self._table.retire(model, to_v)
                except (KeyError, ValueError):
                    pass  # never installed locally (successor table)
                self._table.set_intent(model, None)
                self._publish_model(model, tombstone_versions=(to_v,))
                logger.warning(
                    "aborted interrupted rollout of %s to v%d (died in "
                    "phase %r before the flip); v%s keeps serving",
                    model, to_v, phase, from_v,
                )
                flight.record("rollout_abort", {
                    "model": model, "phase": phase, "version": to_v,
                    "previous": from_v, "via": "resume_rollout",
                })
                return {
                    "action": "aborted", "model": model, "phase": phase,
                    "version": to_v, "previous": from_v,
                }
            if phase not in ("flipped", "draining"):
                raise ValueError(
                    f"unknown rollout-intent phase {phase!r} for "
                    f"{model!r}"
                )
            self._table.ensure_version(model, to_v)
            try:
                cur_v, epoch, _ = self._table.snapshot(model)
            except KeyError:
                cur_v, epoch = None, 0
            if cur_v != to_v:
                epoch = self._table.activate(model, to_v)
            # Publish the (re-)flip BEFORE dropping the from-version's
            # registrations: a client still pinned to it that races the
            # drop resyncs from a view that already names the new
            # active, instead of re-pinning the version being dropped.
            self._publish_model(model)
            timeout = float(
                config.get("fleet_drain_timeout_s")
                if drain_timeout_s is None else drain_timeout_s
            )
            drained = True
            if from_v is not None:
                drained = self._table.wait_drained(model, from_v, timeout)
                _M_DRAINS.inc(outcome="drained" if drained else "timeout")
                if drained:
                    old_reg = self._table.reg_name(model, from_v)
                    for r in self._table.replicas():
                        try:
                            self._client(r.key).drop_model(old_reg)
                        except (OSError, protocol.ProtocolError,
                                RuntimeError):
                            pass
                    try:
                        self._table.retire(model, from_v)
                    except (KeyError, ValueError):
                        pass
            self._table.set_intent(model, None)
            _M_EPOCH.set(epoch, model=model)
            self._publish_model(
                model,
                tombstone_versions=(
                    (from_v,) if drained and from_v is not None else ()
                ),
            )
            logger.warning(
                "completed interrupted rollout of %s to v%d (died in "
                "phase %r after the flip; drained=%s)",
                model, to_v, phase, drained,
            )
            return {
                "action": "completed", "model": model, "phase": phase,
                "version": to_v, "previous": from_v, "epoch": epoch,
                "drained": drained,
            }

    # -- elastic membership (serve/autoscaler.py drives these) --------------

    def scale_out(self, endpoint, warm: bool = True) -> Dict[str, Any]:
        """Admit a new replica daemon into the fleet: register AND warm
        every model's ACTIVE version on it first, then add it to the
        ring — admission is the flip (router.RoutingTable.add_replica),
        so the first request routed to the newcomer finds a warm
        registration. The payloads come from the routing table's
        version entries (the same source the in-band repair uses); a
        newcomer that fails any registration is NOT admitted."""
        if isinstance(endpoint, str):
            host, _, port = endpoint.rpartition(":")
            host, port = host or "127.0.0.1", int(port)
        else:
            host, port = endpoint[0], int(endpoint[1])
        key = f"{host}:{port}"
        with self._lock:
            seeded: List[str] = []
            c = DataPlaneClient(
                host, port, token=self._token, **self._client_kwargs
            )
            try:
                for model in self._table.models():
                    v, _, reg_name = self._table.snapshot(model)
                    info = self._table.version_info(model, v)
                    c.ensure_model(
                        reg_name, info["algo"], info["arrays"],
                        params=info["params"], version=v,
                    )
                    width = _model_width(info["algo"], info["arrays"])
                    if warm and width is not None:
                        c.warmup(reg_name, n_cols=width, dtype="float32")
                    _M_REGISTRATIONS.inc(outcome="ok")
                    seeded.append(model)
            except (OSError, protocol.ProtocolError, RuntimeError) as e:
                _M_REGISTRATIONS.inc(outcome="error")
                c.close()
                raise FleetRolloutError(
                    f"replica {key} failed pre-admission seeding of "
                    f"{model!r} — not admitted: {e}"
                ) from e
            self._table.add_replica((host, port))
            self._clients[key] = c
            n = len(self._table.replicas())
            for model in seeded:
                _M_REPLICAS.set(n, model=model)
            logger.info(
                "scaled OUT: replica %s admitted with %d model(s) "
                "seeded and warm (%d replicas in the ring)",
                key, len(seeded), n,
            )
            # Gossip the grown membership (and seed the newcomer's view
            # with the fleet's model records in the same push).
            self._push_view()
            return {"replica": key, "models": seeded, "replicas": n}

    def scale_in(
        self,
        key: Optional[str] = None,
        drain_timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Retire one replica without dropping a request: remove it
        from the ring (no NEW request routes to it), then roll every
        active model forward one version on the REMAINING replicas —
        the rollout's drain barrier waits out every request pinned to
        the old version, including those in flight on the victim, and
        only then drops the old registrations. Returns ``{"replica",
        "drained", "rollouts"}``; ``drained=False`` means some pinned
        request outlived the timeout — the victim daemon must stay UP
        until a later drain finishes (stopping it would be the dropped
        request the barrier exists to prevent).

        With no ``key`` the least-loaded live replica is chosen."""
        if key is None:
            live = [r for r in self._table.replicas() if r.alive]
            if not live:
                raise ValueError("no live replica to scale in")
            key = min(live, key=lambda r: (r.load(), r.key)).key
        # Capture the victim's gossip identity while it is still a
        # member — its record must flip to a tombstone, not vanish.
        victim = self._identities.get(key)
        if victim is None:
            try:
                victim = self._client(key).server_info()
            except (OSError, protocol.ProtocolError, RuntimeError):
                victim = None
        self._table.remove_replica(key)
        rollouts: Dict[str, Any] = {}
        drained = True
        for model in self._table.models():
            v, _, _ = self._table.snapshot(model)
            info = self._table.version_info(model, v)
            res = self.rollout(
                model, info["algo"], info["arrays"],
                params=info["params"], drain_timeout_s=drain_timeout_s,
            )
            rollouts[model] = res
            drained = drained and bool(res["drained"])
        with self._lock:
            c = self._clients.pop(key, None)
            if c is not None:
                c.close()
            self._identities.pop(key, None)
            if victim is not None and victim.get("id"):
                self._view.tombstone_replica(str(victim["id"]))
            n = len(self._table.replicas())
            # Gossip the shrunk membership so no bootstrapping client
            # ever admits the retiree into its ring again.
            self._push_view()
        logger.info(
            "scaled IN: replica %s retired (%d replicas remain; "
            "drained=%s)", key, n, drained,
        )
        return {
            "replica": key, "drained": drained, "rollouts": rollouts,
            "replicas": n,
        }

    # -- observability ------------------------------------------------------

    def status(self, model: Optional[str] = None) -> Dict[str, Any]:
        """Operator view: per-replica liveness/health plus (with
        ``model``) which replicas hold the active version's
        registration. Polls health live; a dead replica reports its
        last error instead."""
        versions: Dict[str, Any] = {}
        reg_name = None
        if model is not None:
            try:
                v, e, reg_name = self._table.snapshot(model)
                versions = {
                    "active": v, "epoch": e,
                    "installed": self._table.versions(model),
                }
            except KeyError:
                versions = {"active": None, "epoch": 0, "installed": []}
        replicas = {}
        for r in self._table.replicas():
            entry: Dict[str, Any] = {"alive": r.alive}
            try:
                h = self._client(r.key).health()
                self._table.mark_alive(r.key, h)
                entry["alive"] = True
                entry["health"] = {
                    k: h.get(k) for k in
                    ("id", "boot_id", "queue_depth", "served_models", "busy")
                }
                if reg_name is not None:
                    entry["has_active_version"] = bool(
                        self._client(r.key).model_exists(reg_name)
                    )
            except (OSError, protocol.ProtocolError, RuntimeError) as e:
                self._table.mark_dead(r.key, str(e), recheck_s=1.0)
                entry["alive"] = False
                entry["error"] = str(e)
            replicas[r.key] = entry
        out: Dict[str, Any] = {"replicas": replicas}
        if model is not None:
            out["model"] = {"name": model, **versions}
        return out
