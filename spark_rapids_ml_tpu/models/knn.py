"""Nearest neighbors: exact brute-force (distributed) and IVF-Flat (approx).

BASELINE.json config #5: "Approx-KNN IVF-Flat on 10M×768 SBERT embeddings
(Pallas distance kernel, multi-host v5e-64)". TPU-first design:

* **Exact** (``NearestNeighbors``): the database is row-sharded over the
  ``data`` mesh axis. Each device computes its local (q, m_local) distance
  tile via the Gram trick (one MXU GEMM), takes a local top-k with
  ``lax.top_k``, then candidates (k per device per query) are all-gathered
  over ICI and merged with a second top-k. Communication is O(q·k·devices),
  independent of database size — the same "reduce a small partial, not the
  data" bet as the reference's Gram-partials design (SURVEY.md §3.1).
* **Approx** (``ApproximateNearestNeighbors``, IVF-Flat): a KMeans coarse
  quantizer (reusing models/kmeans.py) partitions the database into nlist
  inverted lists, padded dense to (nlist, maxlen, d) so everything is
  static-shaped — XLA-friendly, no ragged structures. Query execution is
  two-strategy (see ``_ivf_query_fn``): a dense masked block scan (exact
  within probed lists) when a large fraction of lists is probed, else
  ScaNN-style capacity-bucketed query grouping — batched per-list GEMMs
  over only the assigned queries (residual-encoded against the list
  centroids), a 4k-wide approximate shortlist, and an exact f32 rerank.

Output convention follows spark-rapids-ml's NearestNeighbors:
``kneighbors(queries) -> (distances, indices)`` with Euclidean distances.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.core.dataset import as_matrix
from spark_rapids_ml_tpu.core.params import (
    Estimator,
    HasFeaturesCol,
    HasSeed,
    Model,
    ParamDecl,
    ParamValidators,
    TypeConverters,
)
from spark_rapids_ml_tpu.core.persistence import MLReadable, MLWritable
from spark_rapids_ml_tpu.ops.distances import fused_topk_fits, sq_euclidean
from spark_rapids_ml_tpu.ops.pallas_kernels import (
    ivf_scan_select_pallas,
    probe_select_pallas,
)
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, default_mesh
from spark_rapids_ml_tpu.parallel import mapreduce as mr
from spark_rapids_ml_tpu.parallel.sharding import (
    bucket_rows,
    pad_rows,
    row_sharding,
)
from spark_rapids_ml_tpu.utils.profiling import trace_span
from spark_rapids_ml_tpu.parallel.compat import shard_map
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit


# ---------------------------------------------------------------------------
# Exact brute-force
# ---------------------------------------------------------------------------


def _exact_fused_enabled() -> bool:
    """The production gate for the fused exact-kneighbors kernel: the
    ``use_pallas`` config on a TPU backend, f32 accumulation (the kernel
    emits f32 scores). Tests force the kernel off-backend by passing
    ``use_pallas=True`` to :func:`_exact_knn_fn` directly (the kernel then
    runs in interpret mode — the same pattern as ``ann_fused_scan="on"``)."""
    from spark_rapids_ml_tpu.ops.gram import _pallas_backend_ok

    return bool(
        _pallas_backend_ok()
        and jnp.dtype(config.get("accum_dtype")) == jnp.float32
    )


@functools.lru_cache(maxsize=32)
def _exact_knn_fn(mesh: Mesh, k: int, cd: str, ad: str, metric: str = "l2",
                  use_pallas: bool = False):
    """metric "l2": ascending squared-Euclidean (callers post-process to
    euclidean/sqeuclidean/cosine — the latter two are monotone transforms
    on appropriately normalized inputs). metric "ip": descending inner
    product (MIPS); returned "distances" are the similarities.

    ``use_pallas``: route the l2 shard scan through the fused streaming
    distance+top-k kernel (ops/pallas_kernels.dist_topk_pallas) — the
    (q, m_local) distance matrix never reaches HBM and the per-shard
    selection is exact with (distance, id) tie-breaking, bitwise the
    ``merge_topk`` order. Off-TPU the kernel runs in interpret mode
    (goldens); infeasible shapes fall back to the XLA two-step in-trace."""
    compute_dtype = jnp.dtype(cd)
    accum_dtype = jnp.dtype(ad)

    def shard(db, mask, row_ids, queries):
        # db: (m_local, d) this device's database shard; queries replicated;
        # row_ids: (m_local,) the shard's rows' ORIGINAL indices (-1 = pad).
        # An explicit id map rather than shard_id*m_local + local arithmetic:
        # multi-process ingestion pads at each process's tail, so padded
        # positions are interleaved and arithmetic ids would be wrong.
        m_local = db.shape[0]
        # A shard can hold fewer rows than k; its local candidate list is
        # then all of its rows. The union of per-shard top-min(k, m_local)
        # still contains the global top-k (k <= n total valid rows).
        kl = min(k, m_local)
        if (
            use_pallas
            and metric == "l2"
            and fused_topk_fits(
                queries.shape[0], m_local, db.shape[1], kl, accum_dtype
            )
        ):
            from spark_rapids_ml_tpu.ops.pallas_kernels import dist_topk_pallas

            fd, fi = dist_topk_pallas(
                queries.astype(compute_dtype), db.astype(compute_dtype),
                row_ids, mask, kl,
                interpret=jax.default_backend() != "tpu",
            )
            return mr.reduce_topk(fd.astype(accum_dtype), fi, k, DATA_AXIS)
        if metric == "ip":
            from spark_rapids_ml_tpu.ops.gram import mm_precision

            with mm_precision(compute_dtype):
                d2 = -jnp.einsum(
                    "qd,md->qm",
                    queries.astype(compute_dtype),
                    db.astype(compute_dtype),
                    preferred_element_type=accum_dtype,
                )  # negated: the shared min-merge machinery then applies
        else:
            d2 = sq_euclidean(
                queries.astype(compute_dtype), db.astype(compute_dtype),
                accum_dtype=accum_dtype,
            )  # (q, m_local)
        # Masked-out (padding) rows get +inf so they never win.
        d2 = jnp.where(mask[None, :] > 0, d2, jnp.inf)
        neg, local_idx = jax.lax.top_k(-d2, kl)  # (q, kl)
        global_idx = row_ids[local_idx]
        # Merge candidates from all shards: the pool holds >= k valid
        # entries because padding is tail-only.
        return mr.reduce_topk(-neg, global_idx, k, DATA_AXIS)

    f = shard_map(
        shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(P(), P()),
        check_vma=False,  # gathered candidates are value-replicated
    )
    return ledgered_jit("knn.exact_topk", f)


# APPEND-ONLY: ANN model payloads persist the fit metric as an ordinal into
# this tuple (_model_data "fit_metric"), so existing positions are an
# on-disk contract — add new metrics at the END.
KNN_METRICS = ("euclidean", "sqeuclidean", "cosine", "inner_product")


def merge_topk(
    dists, ids, k: int, descending: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side top-k merge of per-shard kneighbors results — the
    daemon-level twin of the O(q·k·devices) ``all_gather`` merges inside
    ``_exact_knn_fn`` / ``_ivf_query_fn_sharded``, for shards that live in
    DIFFERENT processes/hosts (one entry per daemon serving a slice of the
    database). Exactness: as long as each shard returns its local
    top-min(k, shard_rows), the union of candidates contains the global
    top-k, so the merge is exact given exact shard answers (the reference's
    any-number-of-executors reduce property, RapidsRowMatrix.scala:139).

    ``dists``/``ids``: sequences of (q, k_i) arrays (k_i may differ — a
    shard smaller than k contributes all its rows). ``descending`` for
    similarity metrics (inner_product). Invalid entries (id −1, distance
    +inf ascending / −inf descending) sort last; ties break toward the
    smaller row id.

    Merged distances come back in the shards' common dtype (f32 shards →
    f32 out, the single-daemon dtype — ADVICE r5(c)): the merge itself
    runs in f64 only so that comparisons are exact, and the selected
    values are bit-identical to the shard's own answer after the cast."""
    out_dtype = np.result_type(*[np.asarray(d).dtype for d in dists])
    D = np.concatenate([np.asarray(d, np.float64) for d in dists], axis=1)
    I = np.concatenate([np.asarray(i, np.int64) for i in ids], axis=1)
    if D.shape[1] < k:
        raise ValueError(
            f"merged candidate pool {D.shape[1]} < k = {k}; every shard "
            "must return min(k, its rows) candidates"
        )
    key = -D if descending else D
    # Row-wise lexsort: last key is primary (distance), id breaks ties;
    # a shard's not-found tail (±inf) keys sort past every real candidate.
    order = np.lexsort((I, key), axis=-1)[:, :k]
    return (
        np.take_along_axis(D, order, axis=1).astype(out_dtype, copy=False),
        np.take_along_axis(I, order, axis=1),
    )


def _normalized_rows(
    x: np.ndarray, zero_slot: int = 0, eps: float = 1e-12
) -> np.ndarray:
    """Cosine-metric preprocessing: unit rows + TWO augmentation columns.

    A zero row becomes a unit vector in augmentation column ``zero_slot``
    (0 for database/index rows, 1 for queries): orthogonal to every real
    vector AND to the other side's zero vectors, so its cosine distance is
    exactly 1 — matching sklearn's normalize()-then-dot semantics. A plain
    zero-stays-zero embedding would report 0.5 (= ‖q−0‖²/2), silently
    ranking zero rows ABOVE genuinely dissimilar neighbors."""
    x = np.asarray(x, np.float32 if x.dtype != np.float64 else np.float64)
    nrm = np.linalg.norm(x, axis=1, keepdims=True)
    out = np.concatenate(
        [x / np.maximum(nrm, eps), np.zeros((x.shape[0], 2), x.dtype)], axis=1
    )
    out[nrm[:, 0] <= eps, x.shape[1] + zero_slot] = 1.0
    return out


class _NNParams(HasFeaturesCol, HasSeed):
    k = ParamDecl(
        "k",
        "number of neighbors to return (> 0)",
        TypeConverters.toInt,
        validator=ParamValidators.gt(0),
    )
    metric = ParamDecl(
        "metric",
        "distance metric: euclidean (default), sqeuclidean, cosine, or "
        "inner_product (exact KNN only; returns similarities descending)",
        TypeConverters.toString,
        validator=ParamValidators.inList(KNN_METRICS),
    )

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(k=5, featuresCol="features", seed=0, metric="euclidean")

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def getMetric(self) -> str:
        return self.getOrDefault(self.metric)


class NearestNeighbors(Estimator, _NNParams, MLWritable, MLReadable):
    """Exact brute-force KNN; ``fit`` indexes the database."""

    _uid_prefix = "NearestNeighbors"

    def __init__(self, uid=None, mesh: Optional[Mesh] = None):
        super().__init__(uid=uid)
        self._mesh = mesh

    def setK(self, value: int) -> "NearestNeighbors":
        return self._set(k=value)

    def setMetric(self, value: str) -> "NearestNeighbors":
        return self._set(metric=value)

    def _copy_extra_state(self, source):
        self._mesh = getattr(source, "_mesh", None)

    def _fit(self, dataset) -> "NearestNeighborsModel":
        x = as_matrix(dataset, self.getFeaturesCol())
        model = NearestNeighborsModel(database=np.asarray(x), mesh=self._mesh)
        model.uid = self.uid
        self._copy_params_to(model)
        return model


class NearestNeighborsModel(Model, _NNParams, MLWritable, MLReadable):
    _uid_prefix = "NearestNeighborsModel"
    # device-resident index state rebuilds via _ensure_index after unpickle
    _transient_attrs = (
        "_mesh", "_db_sharded", "_db_mask", "_db_ids", "_n_global",
        "_index_rep",
    )

    def __init__(self, database: Optional[np.ndarray] = None, mesh=None, uid=None):
        super().__init__(uid=uid)
        self.database = None if database is None else np.asarray(database)
        self._mesh = mesh
        self._db_sharded = None
        self._db_mask = None
        self._db_ids = None
        self._n_global = None
        self._index_rep = None

    def _model_data(self):
        return {"database": self.database}

    @classmethod
    def _from_model_data(cls, uid, data):
        return cls(database=data["database"], uid=uid)

    def _copy_extra_state(self, source):
        self.database = source.database
        self._mesh = getattr(source, "_mesh", None)

    def _ensure_index(self, mesh):
        metric = self.getMetric()
        # Only the cosine boundary changes the SHARDED DATA (the
        # augmented-normalized copy); euclidean/sqeuclidean/inner_product
        # all shard the raw rows — switching among them must not repeat a
        # multi-GB reshard.
        rep = "cosine" if metric == "cosine" else "raw"
        if getattr(self, "_index_rep", None) != rep:
            self._db_sharded = None
            self._index_rep = rep
        if self._db_sharded is None:
            from spark_rapids_ml_tpu.parallel.sharding import shard_rows

            n_local = self.database.shape[0]
            if jax.process_count() > 1:
                # Multi-process: `database` is this process's local slice;
                # its original-row-id range starts after lower ranks' rows.
                from jax.experimental import multihost_utils as mhu

                counts = np.asarray(
                    mhu.process_allgather(np.asarray([n_local]))
                ).reshape(-1)
                lo = int(counts[: jax.process_index()].sum())
            else:
                lo = 0
            db = (
                _normalized_rows(self.database, zero_slot=0)
                if metric == "cosine"
                else self.database
            )
            self._db_sharded, self._db_mask, self._n_global = shard_rows(
                db, mesh
            )
            # Explicit id map; +1 shift so shard_rows's zero-padding decodes
            # to -1 (a real row 0 must stay distinguishable from padding).
            ids, _, _ = shard_rows(
                np.arange(lo + 1, lo + n_local + 1, dtype=np.int32),
                mesh,
                with_mask=False,
            )
            self._db_ids = ids - 1

    def kneighbors(
        self, queries: np.ndarray, k: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (distances (q, k), indices (q, k)) under ``metric``:
        euclidean (default) / sqeuclidean / cosine ascending, or
        inner_product DESCENDING (the "distances" are the similarities —
        the MIPS convention).

        Multi-process: every process passes the SAME query batch and its
        own local database slice was used at fit; returned indices are
        global row positions (concatenation order of the process slices).
        """
        if self.database is None:
            raise RuntimeError("model has no database (unfitted?)")
        k = self.getK() if k is None else k
        mesh = self._mesh or default_mesh()
        self._ensure_index(mesh)
        n = self._n_global
        if not 0 < k <= n:
            raise ValueError(f"k = {k} out of range (0, numRows = {n}]")
        metric = self.getMetric()
        queries = np.asarray(queries)
        if metric == "cosine":
            queries = _normalized_rows(queries, zero_slot=1)
        q = queries.shape[0]
        bucket = bucket_rows(q, 64)
        qp, _ = pad_rows(queries, bucket)
        with trace_span("knn query"):
            from spark_rapids_ml_tpu.parallel.sharding import replicated_array

            fn = _exact_knn_fn(
                mesh, k, config.get("compute_dtype"), config.get("accum_dtype"),
                metric="ip" if metric == "inner_product" else "l2",
                use_pallas=_exact_fused_enabled(),
            )
            d2, idx = jax.device_get(
                fn(self._db_sharded, self._db_mask, self._db_ids,
                   replicated_array(qp, mesh))
            )
        idx = idx[:q].astype(np.int64)
        if metric == "inner_product":
            # d2 holds NEGATED products (the shared ascending merge); the
            # +inf of never-found slots decodes to -inf similarity.
            return -d2[:q], idx
        if metric == "sqeuclidean":
            return np.maximum(d2[:q], 0), idx
        if metric == "cosine":
            # rows and queries are unit vectors: ||q-x||^2 = 2 - 2cos,
            # so the cosine distance (1 - cos) is half the squared L2.
            return np.clip(d2[:q] / 2.0, 0, None), idx
        return np.sqrt(np.maximum(d2[:q], 0)), idx

    def _serve_aot_plan(self, n_rows, n_cols, dtype="float32", k=None):
        """AOT-at-registration plan (serve/daemon.py; see PCAModel's):
        the sharded exact-kneighbors program, lowered against the
        device-RESIDENT index arrays plus an abstract replicated query
        spec. Serve buckets are powers of two ≥ 64, exactly the row
        counts ``kneighbors`` pads to, so the primed shape IS the served
        shape. ``k`` defaults to the fitted k (what the scheduler keys
        un-k'd traffic to). ``_ensure_index`` here DELIBERATELY
        front-loads the index's device upload into the registration warm
        — the ack's "servable at full speed" contract covers residency,
        not just compiles; the first query would pay it otherwise."""
        if self.database is None:
            return None
        if int(n_cols) != int(self.database.shape[1]):
            raise ValueError(
                f"warmup n_cols={int(n_cols)} does not match the "
                f"index's fitted width {int(self.database.shape[1])}"
            )
        from jax.sharding import NamedSharding

        mesh = self._mesh or default_mesh()
        self._ensure_index(mesh)
        metric = self.getMetric()
        k = self.getK() if k is None else int(k)
        fn = _exact_knn_fn(
            mesh, k, config.get("compute_dtype"), config.get("accum_dtype"),
            metric="ip" if metric == "inner_product" else "l2",
            use_pallas=_exact_fused_enabled(),
        )
        # MIRROR kneighbors' query padding (max(64, next-pow2)), not the
        # raw scheduler bucket: a sub-64 or non-pow2 ladder entry would
        # otherwise prime a shape the query path never dispatches.
        qspec = jax.ShapeDtypeStruct(
            (bucket_rows(int(n_rows), 64), int(self._db_sharded.shape[1])),
            jnp.dtype(dtype),
            sharding=NamedSharding(mesh, P()),
        )
        return [(fn, (self._db_sharded, self._db_mask, self._db_ids, qspec))]

    def _transform(self, dataset):
        x = as_matrix(dataset, self.getFeaturesCol())
        dists, idx = self.kneighbors(x)
        from spark_rapids_ml_tpu.core.dataset import with_column

        out = with_column(dataset, "knn_distances", dists)
        return with_column(out, "knn_indices", idx)


# ---------------------------------------------------------------------------
# IVF-Flat approximate
# ---------------------------------------------------------------------------


class IVFFlatIndex(NamedTuple):
    centroids: np.ndarray  # (nlist, d)
    lists: np.ndarray  # (nlist, maxlen, d) padded points
    list_ids: np.ndarray  # (nlist, maxlen) original row ids, -1 = pad
    list_mask: np.ndarray  # (nlist, maxlen) 1.0 valid


# Padded-list capacity bound, as a multiple of the mean list size n/nlist.
# The rectangular (nlist, maxlen, d) device layout pays nlist×maxlen×d for
# the HOTTEST list: on clustered data (the data IVF exists for) the coarse
# quantizer routinely drops several natural clusters into one list and a
# maxlen of 20-30× the mean follows — a 24 GB index for 3 GB of rows.
# Lists are therefore capacity-bounded: rows past a list's cap spill to
# their next-nearest centroid (FAISS keeps ragged lists instead; a fixed
# cap is the TPU-native answer, same trade as the query side's bucket
# capacity C). A query probing nprobe lists generally probes the spill
# target too, so the recall cost is small — and the scan cost drops with
# maxlen, so balance is also a throughput win.
IVF_MAX_LOAD_FACTOR = 2.0
_IVF_SPILL_CANDIDATES = 4


def _ivf_assign_chunk_fns(nlist: int):
    """The two chunked quantizer-assignment jits shared by the host and
    device IVF builders, with the fused Pallas routes behind the standard
    ``use_pallas`` gate: the primary assignment rides
    ``assign_min_dist_pallas`` (distance tile + argmin fused — the (m,
    nlist) matrix never reaches HBM) and the spill-candidate pass rides the
    EXACT ``dist_topk_pallas`` (replacing the XLA ``approx_min_k``'s 0.95
    recall, whose only consumer is capacity balancing — exact preference
    order is strictly better there). Infeasible shapes (a remainder chunk,
    a non-lane-aligned nlist) fall back to the XLA ops in-trace."""
    from spark_rapids_ml_tpu.ops.gram import _pallas_backend_ok

    T = min(_IVF_SPILL_CANDIDATES, nlist)

    @ledgered_jit("knn.ivf_assign")
    def _argmin_chunk(chunk, centroids):
        # The kmeans gate owns this kernel's full applicability story
        # (f32, d ≤ 512 VMEM bound, tile divisibility); the extra m % 8
        # keeps a sub-1024 REMAINDER chunk (where m % min(1024, m) is
        # vacuously 0) off the non-sublane-aligned block shapes the
        # kernel's other callers never exercise.
        from spark_rapids_ml_tpu.models.kmeans import _pallas_assign_applicable

        m = chunk.shape[0]
        if m % 8 == 0 and _pallas_assign_applicable(
            m, nlist, chunk.shape[1], jnp.float32
        ):
            from spark_rapids_ml_tpu.ops.pallas_kernels import (
                assign_min_dist_pallas,
            )

            idx, _ = assign_min_dist_pallas(
                chunk, centroids, interpret=jax.default_backend() != "tpu"
            )
            return idx
        d2 = sq_euclidean(chunk, centroids, accum_dtype=jnp.float32)
        return jnp.argmin(d2, axis=1).astype(jnp.int32)

    @ledgered_jit("knn.ivf_candidates")
    def _cand_chunk(chunk, centroids):
        m = chunk.shape[0]
        if _pallas_backend_ok() and fused_topk_fits(
            m, nlist, chunk.shape[1], T
        ):
            from spark_rapids_ml_tpu.ops.pallas_kernels import dist_topk_pallas

            _, idx = dist_topk_pallas(
                chunk, centroids,
                jnp.arange(nlist, dtype=jnp.int32),
                jnp.ones((nlist,), jnp.float32), T,
                interpret=jax.default_backend() != "tpu",
            )
            return idx
        d2 = sq_euclidean(chunk, centroids, accum_dtype=jnp.float32)
        # approx_min_k, not top_k: exact top-k lowers to a full per-row
        # sort of the nlist-wide row — minutes at 1M×1024 — and the
        # preference order only feeds capacity balancing (the primary
        # assignment stays an EXACT argmin).
        _, idx = jax.lax.approx_min_k(d2, T, recall_target=0.95)
        return idx.astype(jnp.int32)

    return _argmin_chunk, _cand_chunk


def _balance_assignments(cand: np.ndarray, nlist: int, cap: int) -> np.ndarray:
    """Greedy capacity-bounded assignment from preference-ordered
    candidates ``cand`` (n, T): round t gives every still-unassigned row
    its t-th nearest list while capacity remains; leftovers after T rounds
    fill the least-loaded lists (guaranteed to fit: cap·nlist ≥ n)."""
    n, T = cand.shape
    assign = np.full(n, -1, np.int64)
    load = np.zeros(nlist, np.int64)
    pending = np.arange(n)
    for t in range(T):
        want = cand[pending, t].astype(np.int64)
        order = np.argsort(want, kind="stable")
        sw = want[order]
        run_start = np.searchsorted(sw, np.arange(nlist))
        pos_in_run = np.arange(len(sw)) - run_start[sw]
        ok = pos_in_run < np.maximum(cap - load[sw], 0)
        assign[pending[order[ok]]] = sw[ok]
        load += np.bincount(sw[ok], minlength=nlist)
        pending = pending[order[~ok]]
        if pending.size == 0:
            break
    if pending.size:
        spare = np.maximum(cap - load, 0)
        order = np.argsort(-spare, kind="stable")  # least-loaded lists first
        slots = np.repeat(order, spare[order])
        assign[pending] = slots[: pending.size]
    return assign


def _ivf_cap(n: int, nlist: int) -> int:
    """Per-list row capacity: load-factor × mean, floored so cap·nlist ≥ n."""
    return max(int(np.ceil(IVF_MAX_LOAD_FACTOR * n / nlist)), -(-n // nlist))


def _balanced_refine(get_cand, recenter, nlist: int, cap: int, rounds: int = 3):
    """Balanced-Lloyd refinement shared by the host and device builders:
    alternate capacity-greedy assignment with centroid recomputation FROM
    the balanced assignment. The recentering is what keeps recall: plain
    spill leaves a hot centroid mid-mega-cluster and scatters its overflow
    to far lists, while a recentred quantizer moves centroids toward their
    bounded share of the data, so spill targets become genuinely near rows
    (balanced k-means). ``get_cand()`` → (n, T) preference-ordered
    candidates for the CURRENT centroids; ``recenter(assign)`` updates the
    builder's centroids. Returns the final balanced (n,) assignment."""
    for _ in range(rounds):
        assign = _balance_assignments(np.asarray(get_cand()), nlist, cap)
        recenter(assign)
    return _balance_assignments(np.asarray(get_cand()), nlist, cap)


def build_ivf_flat(
    x: np.ndarray,
    nlist: int,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    train_rows: int = 2_000_000,
    centroids: Optional[np.ndarray] = None,
    train_data: Optional[np.ndarray] = None,
) -> IVFFlatIndex:
    """Train the coarse quantizer and bucket the database into padded lists.

    The quantizer uses random init (the IVF convention — a k-means++ pass
    with nlist in the hundreds is nlist sequential host passes over the
    sample for no recall benefit at this k) and trains on at most
    ``train_rows`` sampled rows — FAISS's convention: quantizer quality
    saturates at a few hundred points per list, and training on the full
    database would force it through HBM 10+ times for nothing (the
    assignment pass below still covers every row, in chunks).

    ``centroids``: a pretrained (nlist, d) quantizer — the shard-consistent
    build for an index spanning daemons (every daemon buckets ITS rows
    against the SAME centroids, so a query's probe set selects the same
    lists everywhere and the cross-daemon top-k union is the single-index
    candidate set). The provided quantizer is FROZEN: capacity balancing
    may still spill rows to their next-nearest list, but never recenters —
    recentering would diverge the shards' quantizers.

    ``train_data``: an explicit quantizer training set that REPLACES the
    local sample — the cross-shard fix for sharded builds (ADVICE
    r5(b)): training on this shard's rows alone skews the shared
    centroids toward whatever locality-sticky routing parked here, so
    the driver samples every daemon (``sample_rows`` op) and hands the
    union to the quantizer-owning build. Ignored when ``centroids`` is
    given (a pretrained quantizer never retrains).
    """
    from spark_rapids_ml_tpu.models.kmeans import fit_kmeans

    x = np.asarray(x)
    frozen = centroids is not None
    if frozen:
        centroids = np.asarray(centroids, np.float32)
        if centroids.shape != (nlist, x.shape[1]):
            raise ValueError(
                f"pretrained centroids shape {centroids.shape} != "
                f"({nlist}, {x.shape[1]})"
            )
    else:
        if train_rows < nlist:
            raise ValueError(
                f"train_rows = {train_rows} must be >= nlist = {nlist} "
                f"(the quantizer needs at least one training row per list)"
            )
        pool = x if train_data is None else np.asarray(train_data, x.dtype)
        if train_data is not None:
            if pool.ndim != 2 or pool.shape[1] != x.shape[1]:
                raise ValueError(
                    f"train_data shape {pool.shape} does not match the "
                    f"database width {x.shape[1]}"
                )
            if pool.shape[0] < nlist:
                raise ValueError(
                    f"train_data has {pool.shape[0]} rows < nlist = "
                    f"{nlist} (one training row per list minimum)"
                )
        if pool.shape[0] > train_rows:
            # shuffle=False: Floyd's O(train_rows) sampling — the default
            # shuffles a full O(n) permutation, ~800 MB at 100M rows, for an
            # ordering k-means training doesn't care about.
            pick = np.random.default_rng(seed).choice(
                pool.shape[0], train_rows, replace=False, shuffle=False
            )
            sample = pool[pick]
        else:
            sample = pool
        sol = fit_kmeans(
            sample, k=nlist, max_iter=10, seed=seed, init="random", mesh=mesh
        )
        centroids = sol.centers
    # Device-side assignment (the n·nlist·d FLOPs belong on the MXU — at
    # 1M×768×1024 the host-numpy version is minutes of CPU); only the
    # (n,) argmin comes back. The scatter into padded lists stays on host.
    n = x.shape[0]
    T = min(_IVF_SPILL_CANDIDATES, nlist)
    cdev = jnp.asarray(centroids, jnp.float32)
    _argmin_chunk, _cand_chunk = _ivf_assign_chunk_fns(nlist)

    step = 1 << 18

    def _chunked(fn, width):
        out = np.empty((n, width) if width > 1 else (n,), dtype=np.int32)
        for i in range(0, n, step):
            chunk = jnp.asarray(x[i : i + step], jnp.float32)
            out[i : i + step] = np.asarray(fn(chunk, cdev))
        return out

    @ledgered_jit("knn.ivf_recenter")
    def _recenter_chunk(xc, ac, sums, cnt):
        onehot = jax.nn.one_hot(ac, nlist, dtype=jnp.bfloat16)
        sums = sums + jax.lax.dot_general(
            onehot, xc.astype(jnp.bfloat16), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        cnt = cnt + jnp.sum(onehot.astype(jnp.float32), axis=0)
        return sums, cnt

    def _recenter(assign_np, cdev):
        sums = jnp.zeros((nlist, x.shape[1]), jnp.float32)
        cnt = jnp.zeros((nlist,), jnp.float32)
        for i in range(0, n, step):
            sums, cnt = _recenter_chunk(
                jnp.asarray(x[i : i + step], jnp.float32),
                jnp.asarray(assign_np[i : i + step], jnp.int32),
                sums, cnt,
            )
        return jnp.where((cnt > 0)[:, None],
                         sums / jnp.maximum(cnt, 1.0)[:, None], cdev)

    assign = _chunked(_argmin_chunk, 1).astype(np.int64)
    counts = np.bincount(assign, minlength=nlist)
    cap = _ivf_cap(n, nlist)
    if int(counts.max()) > cap:
        def _recenter_cb(assign_np):
            nonlocal cdev
            cdev = _recenter(assign_np, cdev)

        if frozen:
            # No recentering (the quantizer is shared across shards):
            # capacity-spill against the fixed preference order only.
            assign = _balance_assignments(
                np.asarray(_chunked(_cand_chunk, T)), nlist, cap
            )
        else:
            assign = _balanced_refine(
                lambda: _chunked(_cand_chunk, T), _recenter_cb, nlist, cap
            )
            centroids = np.asarray(jax.device_get(cdev), dtype=centroids.dtype)
        counts = np.bincount(assign, minlength=nlist)
    maxlen = max(int(counts.max()), 1)
    d = x.shape[1]
    lists = np.zeros((nlist, maxlen, d), dtype=x.dtype)
    list_ids = np.full((nlist, maxlen), -1, dtype=np.int64)
    # Vectorized bucketing: sort rows by list, then each row's slot within
    # its list is its rank minus the list's start offset. The random
    # tiebreak SHUFFLES each list's internal order: the query path's
    # positional partial top-k (approx_min_k) assumes near-neighbors are
    # spread across row positions, and insertion-ordered databases (e.g.
    # generated or ingested cluster-by-cluster) violate that adversarially.
    shuffle = np.random.default_rng(seed ^ 0x5EED).permutation(n)
    order = shuffle[np.argsort(assign[shuffle], kind="stable")]
    sorted_assign = assign[order]
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    slots = np.arange(n) - starts[sorted_assign]
    lists[sorted_assign, slots] = x[order]
    list_ids[sorted_assign, slots] = order
    list_mask = (list_ids >= 0).astype(np.float32)
    return IVFFlatIndex(centroids, lists, list_ids, list_mask)


def build_ivf_flat_device(
    x,
    nlist: int,
    seed: int = 0,
    train_rows: int = 2_000_000,
    centroids=None,
    train_data=None,
) -> IVFFlatIndex:
    """Device-side IVF-Flat build for data already resident on device.

    ``build_ivf_flat`` buckets on the host — right when the database
    arrives as host numpy, but a pure round-trip when rows are already on
    device (generated there, or fed by the data-plane daemon): 2×3 GB
    over PCIe/tunnel plus host-speed fancy indexing. Here everything —
    quantizer Lloyd iterations, assignment, the sort-based bucketing
    scatter — runs on device; only the (nlist,) counts come back to fix
    the static ``maxlen``. Returns an IVFFlatIndex whose fields are
    device arrays (same container; the model's device-index cache accepts
    either).
    """
    from spark_rapids_ml_tpu.models.kmeans import _lloyd_fn
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    x = jnp.asarray(x, jnp.float32)
    n, d = x.shape
    key = jax.random.key(seed)
    k_samp, k_init, k_shuf = jax.random.split(key, 3)
    frozen = centroids is not None
    if frozen:
        # Pretrained shard-consistent quantizer (see build_ivf_flat):
        # bucket against it, never retrain/recenter.
        centroids = jnp.asarray(centroids, jnp.float32)
        if centroids.shape != (nlist, d):
            raise ValueError(
                f"pretrained centroids shape {centroids.shape} != ({nlist}, {d})"
            )
    else:
        # train_data: explicit cross-shard training set (see
        # build_ivf_flat — ADVICE r5(b)); replaces the local sample.
        pool = x if train_data is None else jnp.asarray(train_data, jnp.float32)
        if train_data is not None and (pool.ndim != 2 or pool.shape[1] != d):
            raise ValueError(
                f"train_data shape {pool.shape} does not match the "
                f"database width {d}"
            )
        n_pool = pool.shape[0]
        n_train = min(n_pool, train_rows)
        if n_train < nlist:
            raise ValueError(
                f"effective train rows = {n_train} must be >= nlist = {nlist} "
                f"(the quantizer needs at least one training row per list)"
            )
        sample = (
            pool[jax.random.choice(k_samp, n_pool, (n_train,), replace=False)]
            if n_pool > train_rows
            else pool
        )
        centers0 = sample[
            jax.random.choice(k_init, n_train, (nlist,), replace=False)
        ]
        mesh = make_mesh(data=1, model=1, devices=list(x.devices())[:1])
        fn = _lloyd_fn(
            mesh, nlist, 10, 1e-4, config.get("compute_dtype"),
            config.get("accum_dtype"),
            use_pallas=bool(config.get("use_pallas")),
        )
        centroids, _, _ = fn(sample, jnp.ones((n_train,), jnp.float32), centers0)
        centroids = centroids.astype(jnp.float32)

    _argmin_chunk, _cand_chunk = _ivf_assign_chunk_fns(nlist)

    # Chunked assignment for ANY n (a whole-x call would materialize the
    # (n, nlist) distance matrix); at most two compiled shapes (full chunk
    # + remainder).
    step = 1 << 18

    def _chunked(fn, centroids):
        return (
            jnp.concatenate(
                [
                    fn(jax.lax.slice_in_dim(x, i, min(i + step, n)), centroids)
                    for i in range(0, n, step)
                ]
            )
            if n > step
            else fn(x, centroids)
        )

    @ledgered_jit("knn.ivf_recenter")
    def _recenter_chunk(xc, ac, sums, cnt):
        # One-hot MXU matmul, not scatter-add: the (chunk, nlist) one-hot
        # GEMM is milliseconds where a 1M-row scatter is minutes.
        onehot = jax.nn.one_hot(ac, nlist, dtype=jnp.bfloat16)
        sums = sums + jax.lax.dot_general(
            onehot, xc.astype(jnp.bfloat16), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        cnt = cnt + jnp.sum(onehot.astype(jnp.float32), axis=0)
        return sums, cnt

    def _recenter(assign, centroids):
        sums = jnp.zeros((nlist, d), jnp.float32)
        cnt = jnp.zeros((nlist,), jnp.float32)
        for i in range(0, n, step):
            sums, cnt = _recenter_chunk(
                jax.lax.slice_in_dim(x, i, min(i + step, n)),
                jax.lax.slice_in_dim(assign, i, min(i + step, n)),
                sums, cnt,
            )
        return jnp.where(
            (cnt > 0)[:, None], sums / jnp.maximum(cnt, 1.0)[:, None], centroids
        )

    assign = _chunked(_argmin_chunk, centroids)
    counts = jnp.zeros((nlist,), jnp.int32).at[assign].add(1)
    natural_max = int(jax.device_get(counts.max()))
    cap = _ivf_cap(n, nlist)
    if natural_max > cap:
        # Balanced-Lloyd refinement (_balanced_refine); the (n, T) int32
        # candidate round-trip to the host balancer is tiny next to the
        # index.
        def _recenter_cb(assign_np):
            nonlocal centroids
            centroids = _recenter(jnp.asarray(assign_np, jnp.int32), centroids)

        if frozen:  # shared quantizer: capacity-spill only, no recenter
            assign_np = _balance_assignments(
                np.asarray(_chunked(_cand_chunk, centroids)), nlist, cap
            )
        else:
            assign_np = _balanced_refine(
                lambda: _chunked(_cand_chunk, centroids), _recenter_cb,
                nlist, cap,
            )
        assign = jnp.asarray(assign_np, jnp.int32)
        counts = jnp.zeros((nlist,), jnp.int32).at[assign].add(1)
        maxlen = max(int(jax.device_get(counts.max())), 1)
    else:
        maxlen = max(natural_max, 1)  # static for the jit below

    @functools.partial(
        ledgered_jit, "knn.ivf_bucketize", static_argnames=("maxlen",)
    )
    def _bucketize(x, assign, counts, key, maxlen):
        # Same sort-based scatter as the host build, including the random
        # tiebreak shuffle that spreads near-neighbors across row slots.
        shuffle = jax.random.permutation(key, n)
        order = shuffle[jnp.argsort(assign[shuffle], stable=True)]
        sorted_assign = assign[order]
        starts = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts[:-1]).astype(jnp.int32)]
        )
        slots = jnp.arange(n, dtype=jnp.int32) - starts[sorted_assign]
        lists = (
            jnp.zeros((nlist, maxlen, d), x.dtype)
            .at[sorted_assign, slots].set(x[order])
        )
        list_ids = (
            jnp.full((nlist, maxlen), -1, jnp.int32)
            .at[sorted_assign, slots].set(order.astype(jnp.int32))
        )
        return lists, list_ids, (list_ids >= 0).astype(jnp.float32)

    lists, list_ids, list_mask = _bucketize(x, assign, counts, k_shuf, maxlen)
    return IVFFlatIndex(centroids, lists, list_ids, list_mask)


def _bucketed_capacity(q: int, nprobe: int, nlist: int, slack: float) -> int:
    """Per-list query capacity C, lane-rounded.

    Base: ceil(q*nprobe/nlist * slack) — expected per-list load times a
    slack for load fluctuations (relative headroom shrinks with the mean
    load λ = q*nprobe/nlist: (slack−1)·√λ sigmas for a Poisson load).

    A ceil(q/nprobe) floor additionally guarantees nprobe*C >= q — under
    the rank-rotated eviction order even a batch of IDENTICAL queries
    keeps at least one probed list per query — but only while that floor
    costs ≤ 4× the base capacity (i.e. nlist ≤ 4·slack·nprobe²). Beyond
    that the worst-case insurance would multiply every average-case
    query's FLOPs by nlist/(slack·nprobe²), so it is skipped: extremely
    correlated batches with tiny nprobe relative to nlist can then drop
    whole queries — raise nprobe, slack, or split the batch.
    At C == q nothing can ever be dropped.
    """
    base = int(np.ceil(q * nprobe / nlist * slack))
    floor = int(np.ceil(q / nprobe))
    cap = max(base, floor) if floor <= 4 * base else base
    return min(q, max(8, ((cap + 7) // 8) * 8))


def _probe_select_fits(nlist: int, d: int, qb: int) -> bool:
    """Feasibility gate for probe_select_pallas: the packed position bits
    must fit (nlist ≤ 65536 after 8-padding) and the resident centroid
    panel + (nlist, qb) f32 distance tile must fit VMEM."""
    nl8 = -(-nlist // 8) * 8
    if max(1, (nl8 - 1).bit_length()) > 16:
        return False
    return (nl8 * (d + qb + 1) + d * qb) * 4 <= 48 * 2**20


def _fused_scan_fits(C: int, maxlen: int, d: int, compute_dtype) -> bool:
    """VMEM feasibility gate for ivf_scan_select_pallas's ``auto`` mode:
    per grid step the kernel holds the (C_pad, d) query block, the
    (maxlen_pad, d) row block (each double-buffered by the pipeline) and
    the f32 (maxlen_pad, C_pad) score tile."""
    c_pad = -(-C // 128) * 128
    ml = -(-maxlen // 8) * 8
    e = jnp.dtype(compute_dtype).itemsize
    return 2 * (c_pad * d + ml * d) * e + ml * c_pad * 4 <= 10 * 2**20


def _bucketed_core(
    queries, probe, probe_d2, lists, list_ids, list_mask, resid_norms,
    n_valid, k: int, nprobe: int, C: int, compute_dtype, accum_dtype,
    list_block: int = 16, shortlist_mult: int = 2, rerank: bool = True,
    *, lists_lo, centroids, fused: str = "auto", rerank_width: int = 0,
    extract: str = "wide", _debug_stage=None,
):
    """The capacity-bucketed scorer over ONE device's lists.

    ``probe``: (q, nprobe) list indices INTO ``lists``; -1 marks pairs this
    device does not own (the sharded executor localizes global probe ids
    and marks the rest -1 — they are dropped here and satisfied by the
    owning device). ``probe_d2``: (q, nprobe) f32 ‖q − c_probe‖² from the
    probe stage. Returns (dists (q, k) exact f32 ascending, ids (q, k);
    +inf/-1 where fewer than k candidates exist locally).

    **Residual scoring** (FAISS's IVF convention, doubly needed at
    bfloat16): clustered data has ‖row‖ ≫ ‖row − c_list‖, so scoring raw
    rows at bf16 buries the within-list margins under rounding noise
    proportional to the LARGE absolute magnitudes — measured recall@10
    collapse 0.99 → 0.64 on clustered 128-d data. Instead
    ‖q − row‖² = ‖δ‖² − 2(q − c)·δ + ‖q − c‖² with δ = row − c_list: the
    GEMM runs on the SMALL residual operands (bf16 noise scales with
    them), the last term is the probe stage's per-(q, list) constant
    (added at candidate gather-back — it cannot change a within-list
    argmin), and the exact f32 rerank still reads the raw rows.

    ``lists_lo``: compute-dtype RESIDUAL copy of ``lists``
    (lists − centroids[:, None, :]) for the scan GEMMs — index data,
    cached on device by the model next to ``resid_norms`` (the f32
    per-row ‖δ‖²). At bfloat16 it halves the scan's HBM traffic AND drops
    the per-block cast. The public query() wrappers build both when a
    caller has no cache. ``centroids``: this device's (nlist, d) f32
    centroid rows, for the per-block query-residual subtraction.
    ``list_block=16`` keeps each block's (block, C, maxlen) distance tile
    small enough to stay on-chip between the GEMM and the shortlist
    selection — measured 4× faster than 32 at the bench shape (block=8
    over-fragments the pipeline and loses it back).

    See _ivf_query_fn's docstring for the full algorithm: eviction-ordered
    capacity bucketing, batched per-list-block GEMMs, position-only scan,
    and the exact f32 rerank.
    """
    q = queries.shape[0]
    nlist, maxlen, d = lists.shape
    n_pairs = q * nprobe

    # --- bucket (query, list) pairs by list with capacity C ---
    # Eviction order when a hot list overflows its capacity, least
    # valuable dropped first: (1) padding queries (rows >= n_valid) never
    # hold capacity at all; (2) higher probe rank — a query's least
    # promising list costs the least recall; (3) within a rank, a
    # RANK-KEYED rotated query order so correlated query batches spread
    # across their probed lists instead of the same C winners taking
    # every list.
    #
    # SORT-FREE slot assignment (replaced a 131k-element argsort that was
    # the single most expensive bucketing op): the (rank-major,
    # rot-within-rank) priority order is a FIXED, data-independent
    # permutation of the pairs, so a pair's slot is simply the number of
    # EARLIER same-list pairs along that static sequence — a chunked
    # prefix-count: per-chunk list histograms (scatter-add) + exclusive
    # cumsum across chunks + an in-chunk (S, S) equality/triangle count
    # the VPU eats whole. Pure elementwise/reduce work instead of a sort.
    # Non-owned pairs (probe < 0) and padding queries take the sentinel
    # list id ``nlist``: they count only against the sentinel row and
    # never hold capacity.
    S = 512
    n_seq = -(-n_pairs // S) * S
    seq_i = jnp.arange(n_seq, dtype=jnp.int32)
    r_seq = seq_i // q  # probe rank of sequence position (pad ranks >= nprobe)
    q_seq = (seq_i % q - r_seq * C) % q  # rank-keyed rotation, inverted
    valid_seq = r_seq < nprobe
    l_seq = jnp.where(
        valid_seq,
        probe.reshape(-1)[
            jnp.where(valid_seq, q_seq * nprobe + r_seq, 0)
        ],
        -1,
    )
    l_seq = jnp.where((l_seq >= 0) & (q_seq < n_valid), l_seq, nlist)
    ch = n_seq // S
    lc = l_seq.reshape(ch, S)
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
        < jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
    )  # strict lower triangle: earlier-in-chunk mask
    within = jnp.sum(
        (lc[:, :, None] == lc[:, None, :]) & tri[None],
        axis=2,
        dtype=jnp.int32,
    ).reshape(-1)
    hist = jnp.zeros((ch, nlist + 1), jnp.int32).at[seq_i // S, l_seq].add(1)
    base = jnp.cumsum(hist, axis=0) - hist  # exclusive over earlier chunks
    slot_seq = base[seq_i // S, l_seq] + within
    keep = (slot_seq < C) & (l_seq < nlist)
    bucket_q = (
        jnp.full((nlist, C), -1, jnp.int32)
        .at[jnp.where(keep, l_seq, nlist), jnp.where(keep, slot_seq, 0)]
        .set(q_seq, mode="drop")
    )
    # Per original (query, probe) pair: its slot in its list (-1 =
    # dropped). Pair (qq, r) sits at the STATIC sequence position
    # r·q + rot(qq, r) — a constant-index gather, no inverse scatter.
    qq = jnp.arange(q, dtype=jnp.int32)[:, None]
    rr = jnp.arange(nprobe, dtype=jnp.int32)[None, :]
    i_pair = rr * q + (qq + rr * C) % q
    pair_slot = jnp.where(keep, slot_seq, -1)[i_pair]
    pair_list = jnp.where(probe >= 0, probe, 0)  # dropped pairs masked via pair_slot
    if _debug_stage == "bucket":
        # Profiling cut (benchmarks/profile_ivf_stages.py): everything up
        # to and including the bucketing counts/scatters stays live; the
        # scan and selection are dropped.
        live = (
            bucket_q.sum() + pair_slot.sum() + hist.sum()
        ).astype(accum_dtype)
        return (
            probe_d2[:, :k].astype(accum_dtype) + live,
            jnp.broadcast_to(pair_list[:, :1], (q, k)).astype(jnp.int64),
        )

    nblk = -(-nlist // list_block)
    pad = nblk * list_block - nlist
    lists_p = jnp.pad(lists, ((0, pad), (0, 0), (0, 0)))
    lists_lo_p = jnp.pad(lists_lo, ((0, pad), (0, 0), (0, 0)))
    cent_p = jnp.pad(centroids.astype(jnp.float32), ((0, pad), (0, 0)))
    ids_p = jnp.pad(list_ids, ((0, pad), (0, 0)), constant_values=-1)
    msk_p = jnp.pad(list_mask, ((0, pad), (0, 0)))
    bq_p = jnp.pad(bucket_q, ((0, pad), (0, 0)), constant_values=-1)
    # Masked residual norms (precomputed index data): padded rows carry a
    # huge norm so they never win a top-k.
    norms_p = jnp.pad(resid_norms.astype(accum_dtype), ((0, pad), (0, 0)))
    r2_all = jnp.where(msk_p > 0, norms_p, jnp.asarray(1e30, accum_dtype))
    # mult·k-wide per-(list, slot) shortlist: selection runs on the
    # compute dtype's noisy scores; the exact rerank recovers boundary
    # swaps. Width is the bf16 recall/speed dial (config
    # ann_shortlist_mult): noisy scores push true neighbors below the
    # within-list cut, and widening the cut is what recovers them —
    # measured on clustered 128-d data, mult 2 → recall@10 0.92 at ~115k
    # q/s/chip, mult 4 → 0.98 at ~65k (f32 scans sit at the 0.99 probing
    # ceiling already at mult 2).
    fused = str(fused).lower()
    if fused not in ("auto", "on", "off"):
        raise ValueError(
            f"ann_fused_scan={fused!r}: expected 'auto', 'on' or 'off'"
        )
    # The kernel computes and emits f32 scores: float64 accum configs
    # (supported by the XLA path) must not silently lose precision.
    f32_ok = jnp.dtype(accum_dtype) != jnp.float64
    use_fused = _debug_stage in (None, "rerank_norescore") and (
        (fused == "on" and f32_ok)
        or (
            fused == "auto"
            and f32_ok
            and jax.default_backend() == "tpu"
            and _fused_scan_fits(C, maxlen, d, compute_dtype)
        )
    )
    # Exact selection needs no shortlist slack when its scores answer
    # directly (the global top-k is contained in exact per-(list, slot)
    # top-k): blk_k = k halves the fused kernel's extraction passes AND
    # the gather-back pool. The rerank path keeps the mult·k width — its
    # slack absorbs bf16 score-vs-f32-rank mismatch, which exactness of
    # the *selection* cannot remove.
    # Extraction width is the rerank-on speed/recall dial (round-4 stage
    # profile: the fused kernel's per-slot extraction cost scales with
    # blk_k). Round-5 same-run sweep at the bench point (k=10, exact-GT
    # recall@10 beside each): extract 10 ("narrow") 183k @ 0.9577; 12 →
    # 177k @ 0.9700; 14 → 169k @ 0.9706; 20 ("wide" = mult·k) → 153k @
    # 0.9706 — the rerank's R = 2k selection caps what extra extraction
    # can feed it, so ~1.2k captures the full rescue at +16% q/s.
    # "auto" (default) = ceil(1.2·k) under fused rerank; an integer sets
    # the width in rows; "narrow"/"wide" = k / mult·k — config
    # ann_extract. The XLA (non-fused) scan always extracts mult·k: its
    # APPROXIMATE per-slot selection needs the slack exactness removes.
    ext = str(extract).lower()
    ext_rows = int(ext) if ext.isascii() and ext.isdigit() else None
    if ext_rows is None and ext not in ("auto", "wide", "narrow"):
        raise ValueError(
            f"ann_extract={extract!r}: expected 'auto', 'wide', 'narrow' "
            "or an integer row width"
        )
    if use_fused:
        if not rerank:
            blk_k = min(k, maxlen)  # exact selection answers directly
        elif ext_rows is not None:
            blk_k = min(max(ext_rows, k), maxlen)
        elif ext == "narrow":
            blk_k = min(k, maxlen)
        elif ext == "wide":
            blk_k = min(shortlist_mult * k, maxlen)
        else:  # auto: ceil(1.2·k), the measured rerank frontier point
            blk_k = min(-(-12 * k // 10), maxlen)
    else:
        blk_k = min(shortlist_mult * k, maxlen)
    if nprobe * blk_k < k:
        raise ValueError(
            f"k={k} exceeds the bucketed candidate pool nprobe*maxlen="
            f"{nprobe * maxlen}; raise nprobe or use mode='dense'"
        )

    if use_fused:
        # Fused Pallas scan+selection (ops/pallas_kernels.py): per-list
        # residual GEMM + EXACT per-slot top-blk_k in one kernel, the
        # (maxlen, C) score tile VMEM-resident. The per-(list, slot) query
        # residuals are pre-gathered OUTSIDE the kernel — dynamic row
        # gathers don't belong inside; XLA fuses gather + f32 subtract +
        # compute-dtype cast into one loop writing the bf16 buffer the
        # kernel then streams sequentially. (The same hoist measured
        # no-effect for the XLA scan — benchmarks/README.md — because
        # there the gather cost merely moves; the kernel REQUIRES it.)
        # C stays at its 8-multiple: Mosaic masks the non-128 lane tail of
        # the (maxlen, C) score tile, and NOT padding C to 128 saves 25%
        # of the pre-gather + qv streaming HBM traffic at the bench shape.
        qv_all = (
            queries.astype(jnp.float32)[jnp.maximum(bq_p, 0)]
            - cent_p[:, None, :]
        ).astype(compute_dtype)  # (nlist_p, C, d)
        fd, fp = ivf_scan_select_pallas(
            qv_all, lists_lo_p, r2_all.astype(jnp.float32), blk_k,
            keep_pad=True, interpret=jax.default_backend() != "tpu",
        )
        # (nlist_p, C, blk_k_pad) for the gather-back epilogue, KEEPING
        # the kernel's 8-multiple selection-lane pad: gathering aligned
        # rows and slicing to blk_k after measured ~1.7x faster than
        # slicing first (the slice materializes an unaligned-row copy).
        res_d = jnp.swapaxes(fd, 1, 2).astype(accum_dtype)
        res_p = jnp.swapaxes(fp, 1, 2)
    else:
        def _block_d2(b):
            """One list-block's (L, C, maxlen) within-list scores — shared
            by the real scan body and the scan_nosel profiling cut so the
            two measure the identical scoring pipeline."""
            qidx = jax.lax.dynamic_slice(bq_p, (b * list_block, 0), (list_block, C))
            # Query residuals q − c_list, formed in f32 BEFORE the compute-
            # dtype cast: bf16-rounding q and c separately leaves absolute-
            # magnitude noise that does not cancel in the subtraction.
            cent = jax.lax.dynamic_slice(cent_p, (b * list_block, 0), (list_block, d))
            qv = (
                queries.astype(jnp.float32)[jnp.maximum(qidx, 0)]  # (L, C, d)
                - cent[:, None, :]
            ).astype(compute_dtype)
            rows = jax.lax.dynamic_slice(
                lists_lo_p, (b * list_block, 0, 0), (list_block, maxlen, d)
            )
            r2 = jax.lax.dynamic_slice(r2_all, (b * list_block, 0), (list_block, maxlen))
            # Batched MXU GEMM: each list scores only its assigned queries.
            # Full precision for f32 compute (TPU's DEFAULT is bf16-mantissa).
            from spark_rapids_ml_tpu.ops.gram import mm_precision

            with mm_precision(compute_dtype):
                qr = jnp.einsum(
                    "lcd,lmd->lcm", qv, rows, preferred_element_type=accum_dtype
                )
            # Within-list ranking score ‖δ‖² − 2(q−c)·δ: the per-(query, list)
            # ‖q−c‖² constant joins at gather-back (it cannot change a
            # within-list argmin) and the rerank restores true distances.
            return r2[:, None, :] - 2.0 * qr  # (L, C, maxlen)

        def body(_, b):
            d2 = _block_d2(b)
            # 0.95 within-list recall: recall_target=1.0 degenerates to a
            # full per-row sort (4x the einsum+selection cost); misses
            # concentrate at the k-th boundary and the 2k shortlist +
            # rerank absorbs them.
            # (Round-3 negative result: an exact min+argmin pre-reduction
            # over size-8 groups measured 3x SLOWER — the 8-wide group
            # axis lands on the 128-lane dimension and wastes 15/16 of
            # every vreg — and cost ~2% recall from within-list winner
            # collisions. See benchmarks/README.md.)
            bd, bpos = jax.lax.approx_min_k(
                d2.reshape(list_block * C, maxlen), blk_k, recall_target=0.95
            )
            # Positions, not ids: the in-scan per-row id gather measured
            # ~2x the GEMM+selection cost; ids resolve once for winners.
            return _, (
                bd.reshape(list_block, C, blk_k),
                bpos.reshape(list_block, C, blk_k).astype(jnp.int32),
            )

        def body_nosel(_, b):
            # Profiling cut (_debug_stage="scan_nosel"): the einsum + d2
            # stay live (same _block_d2 as the real body), the
            # approx_min_k selection is replaced by a slice.
            d2 = _block_d2(b)
            return _, (
                d2[:, :, :blk_k],
                jnp.broadcast_to(
                    jax.lax.broadcasted_iota(jnp.int32, (1, 1, blk_k), 2),
                    (list_block, C, blk_k),
                ),
            )

        _, (res_d, res_p) = jax.lax.scan(
            body_nosel if _debug_stage == "scan_nosel" else body,
            None, jnp.arange(nblk),
        )
        res_d = res_d.reshape(nblk * list_block, C, blk_k)
        res_p = res_p.reshape(nblk * list_block, C, blk_k)
        if _debug_stage in ("scan", "scan_nosel"):
            # Profiling cut: bucketing + the blocked residual-GEMM scan
            # stay live; candidate gather-back and final selection dropped.
            live = (res_d.sum() + res_p.sum().astype(accum_dtype)).astype(accum_dtype)
            return (
                probe_d2[:, :k].astype(accum_dtype)
                + live
                + (bucket_q.sum() + pair_slot.sum()).astype(accum_dtype),
                jnp.broadcast_to(pair_list[:, :1], (q, k)).astype(jnp.int64),
            )

    # Gather each query's candidates back from its (list, slot) buckets,
    # completing the residual identity with the probe stage's ‖q−c‖² term
    # so scores are comparable ACROSS lists at the shortlist top-k.
    ps = jnp.maximum(pair_slot, 0)
    # [..., :blk_k]: no-op for the XLA path; drops the fused kernel's
    # selection-lane pad AFTER the aligned gather (see above).
    cand_d = (
        res_d[pair_list, ps][..., :blk_k]
        + probe_d2.astype(accum_dtype)[:, :, None]
    )
    cand_pos = res_p[pair_list, ps][..., :blk_k]
    dropped = (pair_slot < 0)[:, :, None]
    cand_d = jnp.where(dropped, jnp.inf, cand_d).reshape(q, nprobe * blk_k)
    cand_pos = jnp.where(dropped, 0, cand_pos).reshape(q, nprobe * blk_k)
    cand_list = jnp.broadcast_to(
        pair_list[:, :, None], (q, nprobe, blk_k)
    ).reshape(q, nprobe * blk_k)
    if not rerank:
        # Residual-identity scores ARE comparable across lists (the probe
        # term was added above); answering from them skips the (q, R, d)
        # raw-row gather — the most expensive post-scan op (1.3-1.8x q/s
        # for 0.005-0.017 recall@10; 1.8x / -0.017 measured at the
        # clustered 768-d bench shape — config ann_rerank).
        # approx_min_k, not top_k: top_k over the (q, nprobe·blk_k) pool
        # is a full per-row sort (see gt path); the 0.99-target partial
        # reduce answers the same queries measurably faster.
        bd, pos = jax.lax.approx_min_k(cand_d, k, recall_target=0.99)
        neg = -bd
        wl = jnp.take_along_axis(cand_list, pos, axis=1)
        wp = jnp.take_along_axis(cand_pos, pos, axis=1)
        ids_k = ids_p[wl, wp]
        # Padded-row candidates carry the finite r2 sentinel (~1e30), not
        # inf — map them to the documented (+inf, -1) missing contract.
        missing = jnp.isinf(neg) | (ids_k < 0)
        win_ids = jnp.where(missing, -1, ids_k)
        return jnp.where(missing, jnp.inf, jnp.maximum(-neg, 0.0)), win_ids
    # Exact rerank (the ScaNN two-stage): select an R = width·k shortlist
    # by approximate score, rescore exactly in f32 from the stored rows.
    # The (q, R, d) raw-row gather is the dominant rerank cost and scales
    # linearly with R. Auto width: 2·mult for the approx XLA scan (sized
    # for its PartialReduce selection noise), mult for the fused kernel —
    # with EXACT per-slot selection the extra pool bought nothing
    # (measured same-run at the bench shape: rw 4 → 132.9k q/s, rw 2 →
    # 148.7k, recall@10 0.9706 identical to 4 decimals).
    auto_w = shortlist_mult if use_fused else 2 * shortlist_mult
    R = min((rerank_width or auto_w) * k, nprobe * blk_k)
    negd_R, posR = jax.lax.approx_min_k(cand_d, R, recall_target=0.99)
    negR = -negd_R
    wl = jnp.take_along_axis(cand_list, posR, axis=1)  # (q, R)
    wp = jnp.take_along_axis(cand_pos, posR, axis=1)
    # Flat single-level id gather (same lesson as the row gather below:
    # the 2-level [wl, wp] form lowers poorly in-graph).
    ids_R = ids_p.reshape(-1)[wl * maxlen + wp]  # (q, R); -1 = padded row
    # (Round-4 negative result: rescoring from the bf16 residual
    # reconstruction c + r̃ — dropping the raw f32 lists from the graph —
    # measured BOTH slower (141 vs 151k q/s: two gathers + extra
    # elementwise beat one f32 row gather, which is cheap) and lower
    # recall (0.9653 vs 0.9706). The f32 row gather stays.)
    if _debug_stage == "rerank_norescore":
        # Profiling cut: R-selection + id resolution live, the (q, R, d)
        # row gather + exact rescore dropped — isolates the rescore's
        # IN-GRAPH cost (standalone it measures ~0.02 ms).
        exact_d = jnp.where(ids_R < 0, jnp.inf, -negR)
    else:
        # Flat single-level row gather: the 2-level [wl, wp] batched
        # gather lowers poorly inside the full query graph (measured
        # ~2.9 ms in-graph vs 0.02 ms standalone); flattening to one
        # row-index into the (nlist·maxlen, d) view gives XLA the simple
        # leading-axis row-gather emitter.
        rows_R = lists_p.reshape(-1, d)[wl * maxlen + wp].astype(accum_dtype)
        diff = rows_R - queries.astype(accum_dtype)[:, None, :]
        exact_d = jnp.sum(diff * diff, axis=2)  # (q, R) — direct, exact f32
    exact_d = jnp.where((ids_R < 0) | jnp.isinf(-negR), jnp.inf, exact_d)
    neg, pos = jax.lax.top_k(-exact_d, k)
    win_ids = jnp.where(jnp.isinf(neg), -1, jnp.take_along_axis(ids_R, pos, axis=1))
    return jnp.maximum(-neg, 0.0), win_ids


def _residual_index_data(lists, centroids, compute_dtype, chunk: int = 64):
    """(resid_norms f32, lists_lo compute-dtype) for the bucketed scan —
    the residual-encoded index-side device data (see _bucketed_core).
    ``lists`` may have more rows than ``centroids`` (sharding pad): pad
    centroids with zeros — pad lists are never probed.

    Large single-device indexes stream through a ``lax.map`` over list
    chunks: the f32 residual intermediate of a multi-GB index would
    otherwise transiently double the index's HBM footprint."""
    nlist, maxlen, d = lists.shape
    cpad = jnp.pad(
        jnp.asarray(centroids, jnp.float32),
        ((0, nlist - centroids.shape[0]), (0, 0)),
    )
    single = getattr(lists.sharding, "num_devices", 1) == 1 if hasattr(
        lists, "sharding"
    ) else True
    while chunk > 1 and nlist % chunk:
        chunk //= 2  # largest power-of-two divisor; 1 always divides
    if single and nlist % chunk == 0 and lists.size * 4 > 2**30:
        def f(args):
            lb, cb = args
            r = lb.astype(jnp.float32) - cb[:, None, :]
            return jnp.sum(jnp.square(r), axis=2), r.astype(compute_dtype)

        norms, lo = jax.lax.map(
            f,
            (
                lists.reshape(nlist // chunk, chunk, maxlen, d),
                cpad.reshape(nlist // chunk, chunk, d),
            ),
        )
        return norms.reshape(nlist, maxlen), lo.reshape(nlist, maxlen, d)
    resid = lists.astype(jnp.float32) - cpad[:, None, :]
    return jnp.sum(jnp.square(resid), axis=2), resid.astype(compute_dtype)


@functools.lru_cache(maxsize=32)
def _ivf_query_fn(k: int, nprobe: int, cd: str, ad: str, mode: str = "auto",
                  slack: float = 1.5, shortlist_mult: int = 2,
                  rerank: bool = True, fused: str = "auto",
                  rerank_width: int = 0, extract: str = "wide",
                  _debug_stage=None):
    """Build the jitted IVF query executor.

    Two TPU execution strategies, both avoiding the GPU-idiomatic per-query
    list gather (a (q, nprobe, maxlen, d) intermediate, gather-bound on TPU):

    * ``dense`` — every block of lists is scored against EVERY query with one
      (q, d) × (d, block·maxlen) MXU GEMM; non-probed (query, list) pairs are
      masked to +inf. Bandwidth-optimal (the database streams through HBM
      exactly once per query batch) and exact within probed lists, but pays
      nlist/nprobe× the probed FLOPs — the right trade when a large fraction
      of lists is probed.
    * ``bucketed`` — ScaNN-style query grouping: queries are bucketed by
      probed list with a fixed per-list capacity C, each list block scores
      only its assigned queries with a batched (block, C, d) × (block, d,
      maxlen) GEMM, and per-(list, slot) top-k candidates are gathered back
      per query for the final merge. FLOPs ≈ slack × the probed work — at
      nprobe/nlist = 1/32 that is ~16× fewer than dense. Capacity overflow
      (C per _bucketed_capacity: slack-scaled expected load, with a
      bounded identical-query coverage floor) drops a query's coverage of
      an over-subscribed list — the standard fixed-capacity ANN trade; C
      clamps at q, where no drops are possible.

    ``mode="auto"`` picks dense when nprobe·4 ≥ nlist (probing ≥ a quarter of
    the lists: FLOP waste ≤ 4× and exactness is kept — this covers the
    nprobe = nlist "exact" configuration), else bucketed.
    """
    compute_dtype = jnp.dtype(cd)
    accum_dtype = jnp.dtype(ad)
    LIST_BLOCK = 32

    @ledgered_jit("knn.ivf_query_dense")
    def query_dense(centroids, lists, list_ids, list_mask, queries):
        q = queries.shape[0]
        nlist, maxlen, d = lists.shape
        qc = queries.astype(compute_dtype)
        cd2 = sq_euclidean(qc, centroids.astype(compute_dtype), accum_dtype=accum_dtype)
        _, probe = jax.lax.top_k(-cd2, nprobe)  # (q, nprobe)
        # (q, nlist) probe-membership mask.
        probe_mask = (
            jnp.zeros((q, nlist), jnp.bool_)
            .at[jnp.arange(q)[:, None], probe]
            .set(True)
        )

        nblk = -(-nlist // LIST_BLOCK)
        pad = nblk * LIST_BLOCK - nlist
        lists_p = jnp.pad(lists, ((0, pad), (0, 0), (0, 0)))
        ids_p = jnp.pad(list_ids, ((0, pad), (0, 0)), constant_values=-1)
        msk_p = jnp.pad(list_mask, ((0, pad), (0, 0)))
        pm_p = jnp.pad(probe_mask, ((0, 0), (0, pad)))

        def body(carry, b):
            best_d, best_i = carry  # (q, k) running top-k
            rows = jax.lax.dynamic_slice(
                lists_p, (b * LIST_BLOCK, 0, 0), (LIST_BLOCK, maxlen, d)
            ).reshape(LIST_BLOCK * maxlen, d)
            ids = jax.lax.dynamic_slice(
                ids_p, (b * LIST_BLOCK, 0), (LIST_BLOCK, maxlen)
            ).reshape(LIST_BLOCK * maxlen)
            msk = jax.lax.dynamic_slice(
                msk_p, (b * LIST_BLOCK, 0), (LIST_BLOCK, maxlen)
            ).reshape(LIST_BLOCK * maxlen)
            pm = jax.lax.dynamic_slice(
                pm_p, (0, b * LIST_BLOCK), (q, LIST_BLOCK)
            )  # (q, LIST_BLOCK)
            d2 = sq_euclidean(
                qc, rows.astype(compute_dtype), accum_dtype=accum_dtype
            )  # (q, LIST_BLOCK·maxlen) — the MXU GEMM
            keep = pm[:, :, None] & (msk.reshape(LIST_BLOCK, maxlen) > 0)[None]
            d2 = jnp.where(keep.reshape(q, -1), d2, jnp.inf)
            # TPU-native partial top-k per block (exact top_k sorts the whole
            # 12k-wide row and dominates the query time). recall_target=1.0
            # keeps the PartialReduce lowering but guarantees exact recall,
            # preserving the exact-within-probed-lists IVF contract; the only
            # approximation in this method stays the probing itself. A block
            # contributes at most LIST_BLOCK*maxlen candidates, so clamp the
            # per-block k to that (the cross-block merge restores full k).
            blk_k = min(k, LIST_BLOCK * maxlen)
            blk_d, blk_pos = jax.lax.approx_min_k(d2, blk_k, recall_target=1.0)
            blk_i = ids[blk_pos]  # (q, blk_k) gather from the block's ids
            cat_d = jnp.concatenate([best_d, blk_d], axis=1)
            cat_i = jnp.concatenate([best_i, blk_i], axis=1)
            neg, pos = jax.lax.top_k(-cat_d, k)
            return (-neg, jnp.take_along_axis(cat_i, pos, axis=1)), None

        init = (
            jnp.full((q, k), jnp.inf, accum_dtype),
            jnp.full((q, k), -1, ids_p.dtype),
        )
        (dists, ids), _ = jax.lax.scan(body, init, jnp.arange(nblk))
        return dists, ids

    @ledgered_jit("knn.ivf_probe")
    def probe_bucketed(centroids, queries):
        # Fused probe kernel (same gate family as the scan kernel): f32
        # centroid GEMM + EXACT packed-key top-nprobe per query in one
        # Pallas call — removes both the XLA approx_min_k's cost (the
        # probe stage's dominant op) and its recall_target=0.95
        # approximation, making probe coverage exact. f64 accum configs
        # and non-dividing query blocks fall through to the XLA path.
        fu = str(fused).lower()
        q = queries.shape[0]
        nlist_, d_ = centroids.shape
        qb = min(512, q)
        # "on" means "use wherever representable" (same semantics as the
        # scan gate's f64 carve-out): infeasible shapes — f64 accum,
        # non-dividing query batches, nlist past the packed-key bits or
        # the VMEM tile — fall through to the XLA probe either way.
        use_kernel = (
            fu == "on"
            or (fu == "auto" and jax.default_backend() == "tpu")
        ) and (
            jnp.dtype(accum_dtype) != jnp.float64
            and q % qb == 0
            and _probe_select_fits(nlist_, d_, qb)
        )
        if use_kernel:
            probe, probe_d2 = probe_select_pallas(
                centroids, queries, nprobe, block_q=qb,
                interpret=jax.default_backend() != "tpu",
            )
            return probe, probe_d2
        from spark_rapids_ml_tpu.ops.gram import mm_precision

        # Full-f32 centroid distances: the values feed the residual
        # identity's cross-list ‖q−c‖² term, where bf16-magnitude noise
        # would corrupt the candidate shortlist ordering. The GEMM is
        # (q, nlist, d) — trivial FLOPs next to the selection.
        with mm_precision(jnp.float32):
            cd2 = sq_euclidean(
                queries.astype(jnp.float32), centroids.astype(jnp.float32),
                accum_dtype=jnp.float32,
            )
        # Probing is this executor's approximation already; an exact top_k
        # here costs more than the whole list scan (it sorts every
        # (q, nlist) row), so select probes approximately too — misses are
        # distant lists that contribute the least recall.
        probe_d2, probe = jax.lax.approx_min_k(cd2, nprobe, recall_target=0.95)
        return probe.astype(jnp.int32), probe_d2

    @ledgered_jit("knn.ivf_query_bucketed")
    def core_bucketed(queries, probe, probe_d2, centroids, lists, list_ids,
                      list_mask, n_valid, resid_norms, lists_lo):
        q = queries.shape[0]
        nlist = lists.shape[0]
        C = _bucketed_capacity(q, nprobe, nlist, slack)
        if _debug_stage == "dispatch":
            # Near-noop cut: measures the per-call dispatch floor of the
            # two-jit probe+core pipeline (on the dev tunnel this is
            # several ms per call; ~100 µs on a production host).
            return (
                queries[:, :k].astype(jnp.dtype(ad)),
                probe[:, :k].astype(jnp.int64),
            )
        if _debug_stage == "probe":
            return (
                probe_d2[:, :k].astype(jnp.dtype(ad)),
                probe[:, :k].astype(jnp.int64),
            )
        return _bucketed_core(
            queries, probe, probe_d2, lists, list_ids, list_mask,
            resid_norms, n_valid, k, nprobe, C, compute_dtype, accum_dtype,
            list_block=16, shortlist_mult=shortlist_mult, rerank=rerank,
            lists_lo=lists_lo, centroids=centroids, fused=fused,
            rerank_width=rerank_width, extract=extract,
            _debug_stage=_debug_stage,
        )

    @ledgered_jit("knn.ivf_probe_trivial")
    def _probe_trivial(centroids, queries):
        # Profiling stand-in for probe_bucketed (_debug_stage="dispatch"):
        # data-dependent but ~zero compute, so the two-jit pipeline's
        # dispatch overhead is measured WITHOUT the probe GEMM/selection
        # (the earlier cut returned real probe output and folded the
        # probe's device time into the "floor").
        probe = jnp.broadcast_to(
            jax.lax.broadcasted_iota(jnp.int32, (1, nprobe), 1),
            (queries.shape[0], nprobe),
        ) + (queries[:, :1] * 0).astype(jnp.int32)
        return probe, queries[:, :nprobe].astype(jnp.float32) * 0.0

    def query_bucketed(centroids, lists, list_ids, list_mask, queries, n_valid,
                       resid_norms, lists_lo):
        # Two dispatches, not one fused jit: XLA schedules the monolithic
        # probe+scan+rerank graph measurably worse (+20% wall) than the
        # same stages compiled separately and pipelined by async dispatch.
        probe_fn = (
            _probe_trivial if _debug_stage == "dispatch" else probe_bucketed
        )
        probe, probe_d2 = probe_fn(centroids, queries)
        return core_bucketed(
            queries, probe, probe_d2, centroids, lists, list_ids, list_mask,
            n_valid, resid_norms, lists_lo,
        )

    def query(centroids, lists, list_ids, list_mask, queries,
              n_valid=None, resid_norms=None, lists_lo=None):
        # Host-side dispatch on the index shape (static under each jit).
        # n_valid: true query count when the batch is padded (default: all
        # rows are real). resid_norms / lists_lo: precomputed index-side
        # device data (f32 Σ(row−c)² and the compute-dtype RESIDUAL scan
        # copy) — computed here per call if absent; serving callers cache
        # them (the model does, via _ensure_dev_index).
        dense_auto = (
            nprobe * 4 >= lists.shape[0]
            and jnp.dtype(compute_dtype) == jnp.float32
        )
        # At bfloat16 compute the dense executor's raw-magnitude scores
        # suffer the recall collapse residual encoding exists to fix (its
        # "exact within probed lists" contract only holds at f32), so auto
        # routes everything to the bucketed executor there — with nprobe
        # near nlist its capacity clamps at q and it degenerates to a
        # dense-FLOPs scan WITH residual scoring + exact rerank.
        if mode == "dense" or (mode == "auto" and dense_auto):
            return query_dense(centroids, lists, list_ids, list_mask, queries)
        if n_valid is None:
            n_valid = queries.shape[0]
        if resid_norms is None or lists_lo is None:
            resid_norms, lists_lo = _residual_index_data(
                lists, centroids, compute_dtype
            )
        return query_bucketed(
            centroids, lists, list_ids, list_mask, queries,
            jnp.asarray(n_valid, jnp.int32), resid_norms, lists_lo,
        )

    return query


@functools.lru_cache(maxsize=32)
def _ivf_query_fn_sharded(
    k: int, nprobe: int, cd: str, ad: str, mesh: Mesh, slack: float = 1.5,
    shortlist_mult: int = 2,
    rerank: bool = True, fused: str = "auto", rerank_width: int = 0,
    extract: str = "wide",
):
    """Sharded IVF query: inverted lists sharded over the ``data`` mesh
    axis (BASELINE.json config #5's multi-host shape — a 10M×768 database
    does not fit one chip).

    Under ``shard_map``, every device probes the replicated centroids
    (identical (q, nprobe) global probe set), localizes the probe ids to
    its own list range (non-owned pairs marked -1 and satisfied by their
    owning device), runs the capacity-bucketed scorer over its local
    lists, and the per-device (q, k) exact-reranked candidates merge with
    one ``all_gather`` over ICI + a final top-k — communication is
    O(q·k·devices), independent of database size, the same merge shape as
    the exact KNN. Always the bucketed (approximate) executor; list ids
    stay global so returned ids need no translation.
    """
    compute_dtype = jnp.dtype(cd)
    accum_dtype = jnp.dtype(ad)
    n_data = mesh.shape[DATA_AXIS]

    def shard(cent_pad, lists, list_ids, list_mask, resid_norms, lists_lo,
              queries, n_valid, n_real):
        # cent_pad: (nlist_padded, d) f32 centroids, zero-padded to the
        # sharded list count and replicated; pad lists (columns >= n_real)
        # are masked to +inf so they are never probed.
        q = queries.shape[0]
        nlist_local = lists.shape[0]
        from spark_rapids_ml_tpu.ops.gram import mm_precision

        with mm_precision(jnp.float32):  # exact ‖q−c‖² (see probe_bucketed)
            cd2 = sq_euclidean(
                queries.astype(jnp.float32), cent_pad, accum_dtype=jnp.float32
            )
        pad_col = jax.lax.broadcasted_iota(jnp.int32, cd2.shape, 1) >= n_real
        cd2 = jnp.where(pad_col, jnp.inf, cd2)
        # Approximate probe selection, same trade as the single-device
        # bucketed executor (every device computes the identical set).
        probe_d2, probe = jax.lax.approx_min_k(cd2, nprobe, recall_target=0.95)
        probe = probe.astype(jnp.int32)  # global list ids, replicated
        lo = jax.lax.axis_index(DATA_AXIS).astype(jnp.int32) * nlist_local
        local = (probe >= lo) & (probe < lo + nlist_local)
        probe_local = jnp.where(local, probe - lo, -1)
        cent_local = jax.lax.dynamic_slice(
            cent_pad, (lo, jnp.zeros((), lo.dtype)), (nlist_local, cent_pad.shape[1])
        )
        C = _bucketed_capacity(q, nprobe, nlist_local * n_data, slack)
        dists, ids = _bucketed_core(
            queries, probe_local, probe_d2, lists, list_ids, list_mask,
            resid_norms, n_valid, k, nprobe, C, compute_dtype, accum_dtype,
            shortlist_mult=shortlist_mult, rerank=rerank,
            lists_lo=lists_lo, centroids=cent_local, fused=fused,
            rerank_width=rerank_width, extract=extract,
        )
        # Merge the per-device top-k: O(q·k·devices) over ICI.
        return mr.reduce_topk(dists, ids, k, DATA_AXIS)

    f = shard_map(
        shard,
        mesh=mesh,
        in_specs=(
            P(),
            P(DATA_AXIS, None, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS, None, None),
            P(),
            P(),
            P(),
        ),
        out_specs=(P(), P()),
        check_vma=False,  # gathered candidates are value-replicated
    )
    jitted = ledgered_jit("knn.ivf_query_sharded", f)

    def query(centroids, lists, list_ids, list_mask, queries,
              n_valid=None, resid_norms=None, lists_lo=None):
        if n_valid is None:
            n_valid = queries.shape[0]
        if resid_norms is None or lists_lo is None:
            resid_norms, lists_lo = _residual_index_data(
                lists, centroids, compute_dtype
            )
        nlist_pad = lists.shape[0]
        cent_pad = jnp.pad(
            jnp.asarray(centroids, jnp.float32),
            ((0, nlist_pad - centroids.shape[0]), (0, 0)),
        )
        return jitted(
            cent_pad, lists, list_ids, list_mask, resid_norms, lists_lo,
            queries, jnp.asarray(n_valid, jnp.int32),
            jnp.asarray(centroids.shape[0], jnp.int32),
        )

    return query


class _ANNParams(_NNParams):
    nlist = ParamDecl(
        "nlist",
        "number of IVF inverted lists (> 0)",
        TypeConverters.toInt,
        validator=ParamValidators.gt(0),
    )
    nprobe = ParamDecl(
        "nprobe",
        "number of lists probed per query (> 0)",
        TypeConverters.toInt,
        validator=ParamValidators.gt(0),
    )

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(nlist=32, nprobe=4)

    def getNlist(self) -> int:
        return self.getOrDefault(self.nlist)

    def getNprobe(self) -> int:
        return self.getOrDefault(self.nprobe)


class ApproximateNearestNeighbors(Estimator, _ANNParams, MLWritable, MLReadable):
    """IVF-Flat approximate KNN (spark-rapids-ml ApproximateNearestNeighbors
    shape, algorithm="ivfflat")."""

    _uid_prefix = "ApproximateNearestNeighbors"

    def __init__(self, uid=None, mesh: Optional[Mesh] = None):
        super().__init__(uid=uid)
        self._mesh = mesh

    def setK(self, value: int) -> "ApproximateNearestNeighbors":
        return self._set(k=value)

    def setNlist(self, value: int) -> "ApproximateNearestNeighbors":
        return self._set(nlist=value)

    def setNprobe(self, value: int) -> "ApproximateNearestNeighbors":
        return self._set(nprobe=value)

    def setMetric(self, value: str) -> "ApproximateNearestNeighbors":
        return self._set(metric=value)

    def _copy_extra_state(self, source):
        self._mesh = getattr(source, "_mesh", None)

    def _fit(self, dataset) -> "ApproximateNearestNeighborsModel":
        metric = self.getMetric()
        if metric == "inner_product":
            raise ValueError(
                "metric='inner_product' is supported by the exact "
                "NearestNeighbors only (IVF-Flat partitions by L2 "
                "proximity; MIPS needs a different quantizer)"
            )
        x = np.asarray(as_matrix(dataset, self.getFeaturesCol()))
        if metric == "cosine":
            # The index stores the UNIT-normalized (augmented) rows: L2 on
            # them is a monotone transform of cosine distance, so the
            # whole IVF machinery (quantizer, residual scan, rerank)
            # applies as-is.
            x = _normalized_rows(x, zero_slot=0)
        with trace_span("ivf build"):
            index = build_ivf_flat(
                x, nlist=self.getNlist(), seed=self.getSeed(), mesh=self._mesh
            )
        model = ApproximateNearestNeighborsModel(index=index)
        model.uid = self.uid
        self._copy_params_to(model)
        model._index_metric = metric
        return model


class ApproximateNearestNeighborsModel(Model, _ANNParams, MLWritable, MLReadable):
    _uid_prefix = "ApproximateNearestNeighborsModel"
    # device index + residual cache rebuild via _ensure_dev_index on use.
    # _index_metric is NOT transient: the metric's normalization is baked
    # into the stored lists, so it travels with the index (pickle AND
    # save/load) rather than re-deriving from the mutable metric param —
    # a _set(metric=...) after load must hit the built-under guard, not
    # silently mis-score (round-3 advisor finding).
    _transient_attrs = ("_mesh", "_dev_index", "_resid_cache", "_shard_mesh")

    def __init__(self, index: Optional[IVFFlatIndex] = None, uid=None):
        super().__init__(uid=uid)
        self.index = index
        self._dev_index = None  # device-resident index cache
        self._resid_cache = None  # bucketed executor's residual data (lazy)
        self._shard_mesh = None  # set by shard_index()

    def _model_data(self):
        data = {
            "centroids": self.index.centroids,
            "lists": self.index.lists,
            "list_ids": self.index.list_ids.astype(np.float64),
            "list_mask": self.index.list_mask,
        }
        fit_metric = getattr(self, "_index_metric", None)
        if fit_metric is not None:
            # Persisted as a KNN_METRICS ordinal (the payload store is
            # numeric); legacy saves without it fall back to the param.
            data["fit_metric"] = np.array(
                [KNN_METRICS.index(fit_metric)], dtype=np.float64
            )
        return data

    @classmethod
    def _from_model_data(cls, uid, data):
        index = IVFFlatIndex(
            centroids=data["centroids"],
            lists=data["lists"],
            list_ids=data["list_ids"].astype(np.int64),
            list_mask=data["list_mask"],
        )
        model = cls(index=index, uid=uid)
        code = data.get("fit_metric")
        if code is not None:
            model._index_metric = KNN_METRICS[int(np.asarray(code).reshape(-1)[0])]
        return model

    def _copy_extra_state(self, source):
        self.index = source.index
        self._dev_index = None
        self._resid_cache = None
        self._index_metric = getattr(source, "_index_metric", None)
        # Re-run the sharded placement (it pads nlist to a device multiple
        # — an invariant _ensure_dev_index alone would not restore).
        src_mesh = getattr(source, "_shard_mesh", None)
        self._shard_mesh = None
        if src_mesh is not None and self.index is not None:
            self.shard_index(src_mesh)

    def shard_index(self, mesh: Optional[Mesh] = None) -> "ApproximateNearestNeighborsModel":
        """Shard the inverted lists over the mesh's ``data`` axis — the
        capacity path for databases ≫ one chip's HBM (BASELINE.json config
        #5: 10M×768 on multi-host). nlist pads to a device multiple (pad
        lists are never probed: the centroid set stays unpadded). Queries
        then execute with the sharded bucketed executor (approximate:
        probing + capacity + 0.95-recall shortlists + exact rerank) and an
        O(q·k·devices) all_gather merge. Returns self (fluent)."""
        mesh = mesh or default_mesh()
        n_data = mesh.shape[DATA_AXIS]
        idx = self.index
        nlist = idx.lists.shape[0]
        pad = (-nlist) % n_data
        from jax.sharding import NamedSharding

        def put(arr, spec, pad_width, fill=0):
            if pad:
                arr = np.pad(arr, pad_width, constant_values=fill)
            return jax.device_put(arr, NamedSharding(mesh, spec))

        lists = put(idx.lists, P(DATA_AXIS, None, None), ((0, pad), (0, 0), (0, 0)))
        ids = put(idx.list_ids, P(DATA_AXIS, None), ((0, pad), (0, 0)), fill=-1)
        mask = put(idx.list_mask, P(DATA_AXIS, None), ((0, pad), (0, 0)))
        cent = jax.device_put(np.asarray(idx.centroids), NamedSharding(mesh, P()))
        self._dev_index = (cent, lists, ids, mask)
        self._resid_cache = None  # built lazily, keyed by compute_dtype
        self._shard_mesh = mesh
        return self

    def _ensure_dev_index(self):
        """Upload the index to device ONCE per model — the reference
        re-uploads its model matrix every batch (SURVEY.md §3.2,
        rapidsml_jni.cu:85); repeated query batches here reuse residents."""
        if self._dev_index is None:
            self._dev_index = (
                jnp.asarray(self.index.centroids),
                jnp.asarray(self.index.lists),
                jnp.asarray(self.index.list_ids),
                jnp.asarray(self.index.list_mask),
            )
        return self._dev_index

    def _ensure_resid_data(self, cd):
        """The bucketed executor's residual scan copy + norms, built lazily
        (dense-dispatch queries never pay its +50% index HBM) and KEYED BY
        compute dtype — a config change between queries rebuilds it rather
        than silently scanning at the stale precision."""
        cd = jnp.dtype(cd)
        cache = getattr(self, "_resid_cache", None)
        if cache is None or cache[0] != cd:
            cent, lists = self._dev_index[0], self._dev_index[1]
            self._resid_cache = (cd, *_residual_index_data(lists, cent, cd))
        return self._resid_cache[1], self._resid_cache[2]

    def kneighbors(
        self, queries: np.ndarray, k: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Approximate (distances, indices) under ``metric`` — euclidean
        (default) / sqeuclidean / cosine — ascending.

        IVF semantics: only the ``nprobe`` nearest lists are searched. If the
        probed lists hold fewer than k valid points for some query, the tail
        entries of that query's result carry index -1 and distance +inf
        ("fewer than k found" — same convention as IVF in cuML/FAISS).

        Precision note: with ``ann_rerank`` off, the fused TPU scan
        (``ann_fused_scan`` auto/on) returns distances quantized to ~24−⌈log₂
        maxlen⌉ mantissa bits — its exact selection packs candidate ids into
        the low bits of the f32 score key. Neighbor IDs are unaffected and
        the default rerank recomputes full-precision distances; set
        ``ann_fused_scan="off"`` if rerank-off configs need full-f32 values.
        """
        if self.index is None:
            raise RuntimeError("model has no index (unfitted?)")
        k = self.getK() if k is None else k
        n_db = int(self.index.list_mask.sum())
        if not 0 < k <= n_db:
            raise ValueError(f"k = {k} out of range (0, numRows = {n_db}]")
        nprobe = min(self.getNprobe(), self.index.centroids.shape[0])
        pool = nprobe * self.index.lists.shape[1]
        if pool < k:
            raise ValueError(
                f"candidate pool nprobe*maxlen = {pool} < k = {k}; "
                f"increase nprobe (or nlist granularity)"
            )
        metric = self.getMetric()
        fit_metric = getattr(self, "_index_metric", None)
        if fit_metric is None:
            # Loaded/legacy model: the persisted metric param IS the fit
            # metric (it was copied from the estimator at fit).
            fit_metric = metric
            self._index_metric = fit_metric
        if metric != fit_metric:
            raise ValueError(
                f"index was built under metric={fit_metric!r}; the "
                f"normalization is baked into the stored lists, so refit "
                f"to query with metric={metric!r}"
            )
        queries = np.asarray(queries)
        if metric == "cosine":
            queries = _normalized_rows(queries, zero_slot=1)  # index at fit
        q = queries.shape[0]
        bucket = bucket_rows(q, 64)
        qp, _ = pad_rows(queries, bucket)
        with trace_span("ivf query"):
            if self._shard_mesh is not None:
                fn = _ivf_query_fn_sharded(
                    k, nprobe, config.get("compute_dtype"),
                    config.get("accum_dtype"), self._shard_mesh,
                    shortlist_mult=int(config.get("ann_shortlist_mult")),
                    rerank=bool(config.get("ann_rerank")),
                    fused=str(config.get("ann_fused_scan")),
                    rerank_width=int(config.get("ann_rerank_width")),
                    extract=str(config.get("ann_extract")),
                )
            else:
                fn = _ivf_query_fn(
                    k, nprobe, config.get("compute_dtype"),
                    config.get("accum_dtype"),
                    shortlist_mult=int(config.get("ann_shortlist_mult")),
                    rerank=bool(config.get("ann_rerank")),
                    fused=str(config.get("ann_fused_scan")),
                    rerank_width=int(config.get("ann_rerank_width")),
                    extract=str(config.get("ann_extract")),
                )
            cent, lists, ids_dev, mask = self._ensure_dev_index()
            cd = jnp.dtype(config.get("compute_dtype"))
            # Mirror the executor's dispatch: dense (f32, wide probing)
            # never reads the residual cache — don't build it.
            dense = (
                self._shard_mesh is None
                and nprobe * 4 >= lists.shape[0]
                and cd == jnp.float32
            )
            rnorms, lists_lo = (None, None) if dense else self._ensure_resid_data(cd)
            d2, ids = jax.device_get(
                fn(cent, lists, ids_dev, mask, jnp.asarray(qp),
                   n_valid=q, resid_norms=rnorms, lists_lo=lists_lo)
            )
        ids = ids[:q].astype(np.int64)
        if metric == "sqeuclidean":
            return np.maximum(d2[:q], 0), ids
        if metric == "cosine":
            # unit rows: cosine distance = ||q - x||^2 / 2 (see exact path)
            return np.clip(d2[:q] / 2.0, 0, None), ids
        return np.sqrt(np.maximum(d2[:q], 0)), ids

    def _transform(self, dataset):
        x = as_matrix(dataset, self.getFeaturesCol())
        dists, idx = self.kneighbors(x)
        from spark_rapids_ml_tpu.core.dataset import with_column

        out = with_column(dataset, "knn_distances", dists)
        return with_column(out, "knn_indices", idx)
