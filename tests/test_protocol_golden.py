"""Frozen-protocol conformance: replay the recorded v1 byte transcript.

The fixture ``fixtures/protocol_v1.bin`` is the exact byte stream a v1
client emitted at freeze time (see ``make_protocol_golden.py``). These
tests are the executable form of docs/protocol.md's compatibility
promise: a third-party client built against the v1 frames keeps working.

If a test here fails, the wire contract broke — either revert the
breaking change or bump ``protocol.PROTOCOL_VERSION`` and re-freeze
(``python -m tests.make_protocol_golden``) as a deliberate major change.
"""

import os
import socket

import numpy as np
import pytest

from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon
from spark_rapids_ml_tpu.serve import protocol

from tests.make_protocol_golden import FIXTURE, golden_matrix, transcript


@pytest.fixture
def daemon(mesh8):
    with DataPlaneDaemon(mesh=mesh8) as d:
        yield d


def test_fixture_is_committed():
    assert os.path.exists(FIXTURE), (
        "tests/fixtures/protocol_v1.bin is missing — it is a FROZEN "
        "artifact and must be committed, not regenerated per-run"
    )


def test_generator_matches_committed_fixture():
    """The in-repo generator and the committed bytes must agree frame by
    frame; drift means someone edited the generator without re-freezing
    (or vice versa). JSON frames are compared as parsed objects (key
    order is not part of the contract) and Arrow payload frames
    semantically (the contract requires *a valid Arrow IPC stream*, not
    specific bytes — a pyarrow upgrade may legitimately re-encode)."""
    import io
    import json

    import pyarrow as pa

    from tests.make_protocol_golden import transcript_frames

    frames, _ = transcript_frames()
    with open(FIXTURE, "rb") as f:
        committed = f.read()
    stream = io.BytesIO(committed)

    def next_committed_frame():
        header = stream.read(4)
        assert len(header) == 4, "fixture truncated"
        (n,) = __import__("struct").unpack(">I", header)
        payload = stream.read(n)
        assert len(payload) == n, "fixture truncated mid-frame"
        return payload

    for kind, generated in frames:
        recorded = next_committed_frame()
        if kind == "json":
            assert json.loads(generated) == json.loads(recorded)
        else:
            with pa.ipc.open_stream(generated) as r:
                gen_table = r.read_all()
            with pa.ipc.open_stream(recorded) as r:
                rec_table = r.read_all()
            assert gen_table.equals(rec_table)
    assert stream.read() == b"", "fixture has extra frames"


def test_replay_golden_transcript(daemon):
    """Byte-replay the frozen session; assert every response."""
    with open(FIXTURE, "rb") as f:
        stream = f.read()
    _, expect = transcript()

    sock = socket.create_connection(daemon.address, timeout=60)
    try:
        sock.sendall(stream)
        results = []
        for kind, checks in expect:
            resp = protocol.recv_json(sock)
            assert resp is not None, "daemon closed mid-transcript"
            for key, want in checks.items():
                assert resp.get(key) == want, (
                    f"response {resp} missing/mismatched {key}={want!r}"
                )
            if kind == "arrays":
                results.append((protocol.recv_arrays(sock, resp), resp))
    finally:
        sock.close()

    # Numeric conformance: the two PCA finalizes (eager vs partitioned
    # exactly-once) must agree with each other and with the local oracle.
    (eager, _), (part, _), (km, _) = results
    x = golden_matrix()
    xc = x - x.mean(axis=0)
    evals, evecs = np.linalg.eigh(xc.T @ xc / (x.shape[0] - 1))
    order = np.argsort(evals)[::-1]
    pc_oracle = evecs[:, order[:2]]
    for arrays in (eager, part):
        assert arrays["pc"].shape == (3, 2)
        np.testing.assert_allclose(
            np.abs(arrays["pc"]), np.abs(pc_oracle), atol=1e-8
        )
    np.testing.assert_allclose(eager["pc"], part["pc"], atol=1e-12)
    assert km["centers"].shape == (2, 3)
    assert int(km["n_iter"][0]) == 2
    assert np.isfinite(km["cost"][0])


def test_version_mismatch_rejected_with_message(daemon):
    sock = socket.create_connection(daemon.address, timeout=30)
    try:
        protocol.send_json(sock, {"v": 99, "op": "status", "job": "x"})
        resp = protocol.recv_json(sock)
        assert resp is not None and resp["ok"] is False
        assert f"v{protocol.PROTOCOL_VERSION}" in resp["error"]
        assert "protocol version mismatch" in resp["error"]
    finally:
        sock.close()


def test_versionless_request_rejected(daemon):
    sock = socket.create_connection(daemon.address, timeout=30)
    try:
        protocol.send_json(sock, {"op": "status", "job": "x"})
        resp = protocol.recv_json(sock)
        assert resp is not None and resp["ok"] is False
        assert "protocol version mismatch" in resp["error"]
    finally:
        sock.close()


def test_version_mismatch_with_payload_keeps_framing(daemon):
    """A rejected feed must drain its payload frame so the connection
    stays usable for the next (valid) request."""
    from tests.make_protocol_golden import _ipc_bytes

    sock = socket.create_connection(daemon.address, timeout=30)
    try:
        protocol.send_json(
            sock, {"v": 99, "op": "feed", "job": "x", "algo": "pca"}
        )
        protocol.send_frame(sock, _ipc_bytes(golden_matrix()))
        resp = protocol.recv_json(sock)
        assert resp is not None and resp["ok"] is False
        # connection still aligned: a valid ping succeeds on the same socket
        protocol.send_json(sock, {"v": protocol.PROTOCOL_VERSION, "op": "ping"})
        resp2 = protocol.recv_json(sock)
        assert resp2 is not None and resp2["ok"] is True
    finally:
        sock.close()


def test_ping_is_version_exempt_and_echoes_version(daemon):
    sock = socket.create_connection(daemon.address, timeout=30)
    try:
        protocol.send_json(sock, {"op": "ping"})  # no v at all
        resp = protocol.recv_json(sock)
        # subset check: response fields are additive under v1 (clients
        # must ignore unknown fields — e.g. the instance "id")
        assert resp is not None
        assert resp["ok"] is True and resp["v"] == protocol.PROTOCOL_VERSION
    finally:
        sock.close()


def test_metrics_op_is_additive_v1(daemon):
    """The `metrics` op is additive under v1 (docs/protocol.md): JSON
    and prometheus formats answer under the frozen version, histogram
    buckets are cumulative with a +Inf terminal, an unknown format
    errors WITHOUT desyncing the connection, and the op rides the same
    request framing every other control op uses."""
    with DataPlaneClient(*daemon.address) as c:
        c.feed("metrics-live", golden_matrix(), algo="pca")
        snap = c.metrics()
        feed_lat = [
            s for s in snap["srml_daemon_request_seconds"]["samples"]
            if s["labels"]["op"] == "feed"
        ]
        assert feed_lat and feed_lat[0]["count"] >= 1
        assert feed_lat[0]["buckets"]["+Inf"] == feed_lat[0]["count"]
        rx = [
            s for s in snap["srml_daemon_rx_bytes_total"]["samples"]
            if s["labels"]["op"] == "feed"
        ]
        assert rx and rx[0]["value"] > 0
        text = c.metrics(format="prometheus")
        assert "# TYPE srml_daemon_requests_total counter" in text
        c.drop("metrics-live")

    sock = socket.create_connection(daemon.address, timeout=30)
    try:
        protocol.send_json(
            sock,
            {"v": protocol.PROTOCOL_VERSION, "op": "metrics", "format": "nope"},
        )
        resp = protocol.recv_json(sock)
        assert resp is not None and resp["ok"] is False
        assert "unknown metrics format" in resp["error"]
        # connection still aligned: null format means json (the v1
        # omitted-or-null rule) and succeeds on the same socket
        protocol.send_json(
            sock,
            {"v": protocol.PROTOCOL_VERSION, "op": "metrics", "format": None},
        )
        resp2 = protocol.recv_json(sock)
        assert resp2 is not None and resp2["ok"] is True
        assert resp2["v"] == protocol.PROTOCOL_VERSION
        assert isinstance(resp2["metrics"], dict)
    finally:
        sock.close()


def test_live_client_speaks_the_frozen_version(daemon):
    """Today's DataPlaneClient must emit v1 requests the golden daemon
    accepts — ties the library to the document."""
    with DataPlaneClient(*daemon.address) as c:
        assert c.ping()
        c.feed("live", golden_matrix(), algo="pca")
        arrays = c.finalize_pca("live", k=2)
        assert arrays["pc"].shape == (3, 2)


def test_replay_serving_transcript(daemon):
    """Replay the frozen serving-ops byte transcript (ensure_model /
    transform / model_status / knn build-and-serve / kneighbors /
    drop_model) and assert every response, including numeric conformance
    of the served transform and the daemon-built index."""
    from tests.make_protocol_golden import (
        FIXTURE_SERVING,
        golden_pc,
        serving_transcript,
    )

    assert os.path.exists(FIXTURE_SERVING), (
        "tests/fixtures/protocol_v1_serving.bin must be committed"
    )
    with open(FIXTURE_SERVING, "rb") as f:
        stream = f.read()
    _, expect = serving_transcript()

    sock = socket.create_connection(daemon.address, timeout=120)
    try:
        sock.sendall(stream)
        results = []
        for kind, checks in expect:
            resp = protocol.recv_json(sock)
            assert resp is not None, "daemon closed mid-transcript"
            for key, want in checks.items():
                assert resp.get(key) == want, (
                    f"response {resp} missing/mismatched {key}={want!r}"
                )
            if kind == "arrays":
                results.append(protocol.recv_arrays(sock, resp))
    finally:
        sock.close()

    transform_out, knn_build, knn_query = results
    x = golden_matrix()
    # served PCA transform: y = x @ pc, exactly
    np.testing.assert_allclose(
        transform_out["output"], x @ golden_pc(), atol=1e-10
    )
    assert int(knn_build["n_rows"][0]) == 8
    assert int(knn_build["n_cols"][0]) == 3
    # daemon-built exact index: self is nearest, partition-major ids
    np.testing.assert_array_equal(knn_query["indices"][:, 0], [0, 1, 2])
    np.testing.assert_allclose(knn_query["distances"][:, 0], 0.0, atol=1e-3)


def test_replay_multihost_transcript(daemon):
    """Replay the frozen multi-host-ops byte transcript (feed_raw /
    export_state / get_iterate / set_iterate) and assert every response.
    Numeric conformance: feed_raw-fed bytes ARE the Arrow-fed bytes, so
    the raw-fed and partitioned-raw-fed PCA finalizes must be identical,
    and the linreg finalize must recover the planted coefficients."""
    from tests.make_protocol_golden import (
        FIXTURE_MULTIHOST,
        multihost_transcript,
    )

    assert os.path.exists(FIXTURE_MULTIHOST), (
        "tests/fixtures/protocol_v1_multihost.bin must be committed"
    )
    with open(FIXTURE_MULTIHOST, "rb") as f:
        stream = f.read()
    _, expect = multihost_transcript()

    sock = socket.create_connection(daemon.address, timeout=120)
    try:
        sock.sendall(stream)
        results = []
        for kind, checks in expect:
            resp = protocol.recv_json(sock)
            assert resp is not None, "daemon closed mid-transcript"
            for key, want in checks.items():
                assert resp.get(key) == want, (
                    f"response {resp} missing/mismatched {key}={want!r}"
                )
            if kind == "arrays":
                results.append(protocol.recv_arrays(sock, resp))
    finally:
        sock.close()

    (export, pca_raw, pca_raw2, linreg, iterate,
     shard_a, shard_b, knn_a, knn_b) = results
    assert export, "export_state returned no state arrays"
    np.testing.assert_allclose(pca_raw["pc"], pca_raw2["pc"], atol=1e-12)
    np.testing.assert_allclose(
        linreg["coefficients"], [1.0, -2.0, 3.0], atol=1e-6
    )
    np.testing.assert_allclose(float(linreg["intercept"][0]), 0.5, atol=1e-6)
    assert iterate["centers"].shape == (2, 3)
    # Sharded-KNN extensions: shard A hands back its trained quantizer;
    # both shards answer in the GLOBAL partition-major id space (A holds
    # rows 0-3, B rows 4-7), so a caller-side top-k merge needs no
    # translation; the queried rows ARE shard A's first two rows.
    assert shard_a["centroids"].shape == (2, 3)
    assert int(shard_b["n_rows"][0]) == 4
    ids_a = np.asarray(knn_a["indices"])
    ids_b = np.asarray(knn_b["indices"])
    assert set(ids_a.ravel()) <= set(range(0, 4))
    assert set(ids_b.ravel()) <= set(range(4, 8))
    assert ids_a[:, 0].tolist() == [0, 1]  # self-hits, globally numbered


def test_multihost_generator_matches_committed_fixture():
    """Frame-by-frame drift check for the multihost transcript."""
    import io
    import json as _json
    import struct

    import pyarrow as pa

    from tests.make_protocol_golden import (
        FIXTURE_MULTIHOST,
        multihost_transcript_frames,
    )

    frames, _ = multihost_transcript_frames()
    with open(FIXTURE_MULTIHOST, "rb") as f:
        committed = f.read()
    stream = io.BytesIO(committed)
    for kind, generated in frames:
        header = stream.read(4)
        (n,) = struct.unpack(">I", header)
        recorded = stream.read(n)
        if kind == "json":
            assert _json.loads(generated) == _json.loads(recorded)
        elif kind == "arrow":
            with pa.ipc.open_stream(generated) as r:
                gen_t = r.read_all()
            with pa.ipc.open_stream(recorded) as r:
                rec_t = r.read_all()
            assert gen_t.equals(rec_t)
        else:
            assert generated == recorded
    assert stream.read() == b"", "fixture has extra frames"


def test_serving_generator_matches_committed_fixture():
    """Frame-by-frame drift check for the serving transcript (JSON frames
    semantic, arrow/raw payloads byte-compared — raw buffers are plain
    C-order arrays with no encoder variance)."""
    import io
    import json as _json
    import struct

    import pyarrow as pa

    from tests.make_protocol_golden import (
        FIXTURE_SERVING,
        serving_transcript_frames,
    )

    frames, _ = serving_transcript_frames()
    with open(FIXTURE_SERVING, "rb") as f:
        committed = f.read()
    stream = io.BytesIO(committed)
    for kind, generated in frames:
        header = stream.read(4)
        (n,) = struct.unpack(">I", header)
        recorded = stream.read(n)
        if kind == "json":
            assert _json.loads(generated) == _json.loads(recorded)
        elif kind == "arrow":
            with pa.ipc.open_stream(generated) as r:
                gen_t = r.read_all()
            with pa.ipc.open_stream(recorded) as r:
                rec_t = r.read_all()
            assert gen_t.equals(rec_t)
        else:  # raw array buffer
            assert generated == recorded
    assert stream.read() == b""
