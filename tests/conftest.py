"""Test harness: virtual 8-device CPU mesh + float64 parity mode.

This is the "fake backend" testing capability the reference lacks
(SURVEY.md §4): multi-device sharding tests with no hardware, via
``--xla_force_host_platform_device_count``. Environment must be set before
jax import, hence the top-of-conftest placement.

float64 is enabled so differential tests against NumPy/sklearn oracles can
assert at the reference's absTol 1e-5 (PCASuite.scala:80-87); a separate
test exercises the float32 TPU-native mode with wider tolerance.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the image pre-sets a TPU platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "True")
# Per-SESSION persistent compilation cache, inherited by every spawned
# worker process (daemon workers, multiproc ranks, forkserver tasks): the
# 2-OS-process tests compile identical programs in both workers — a shared
# cache turns the twin's compile into a disk hit. Ephemeral dir: a fresh
# ``pytest`` run measures honest first-compile cost once, not stale state.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    os.environ["JAX_COMPILATION_CACHE_DIR"] = tempfile.mkdtemp(
        prefix="srml-jax-cache-"
    )
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
# Package dtype defaults for parity testing (overridden per-test via
# config.option for float32-mode tests).
os.environ.setdefault("SRML_TPU_ACCUM_DTYPE", "float64")
os.environ.setdefault("SRML_TPU_COMPUTE_DTYPE", "float64")

import jax  # noqa: E402

# The image's sitecustomize registers the TPU backend and sets
# jax.config.jax_platforms directly, which beats the env var — override the
# config itself (must happen before the first backend touch).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from spark_rapids_ml_tpu.parallel.mesh import make_mesh  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {devs}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    return make_mesh(data=8, model=1)


@pytest.fixture(scope="session")
def mesh4x2(devices):
    return make_mesh(data=4, model=2)


@pytest.fixture(scope="session")
def mesh1(devices):
    return make_mesh(data=1, model=1, devices=devices[:1])


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# ---------------------------------------------------------------------------
# Shared subprocess daemon workers (VERDICT carry #7: test wall clock).
# The recovery/chaos/fleet/elastic flagships each need real OS-process
# daemons (tests/daemon_worker.py), and each spawn pays a ~4 s jax
# import. The helper centralizes the spawn env (f64 parity profile —
# bitwise contracts against the parent session's oracles need it) and
# the module-scoped pair fixture amortizes two long-lived workers across
# a module's flagships for the roles that are never killed: fault-free
# oracles and surviving peers. Tests that kill or restart a daemon still
# spawn their own victims.
# ---------------------------------------------------------------------------

import subprocess  # noqa: E402
import sys  # noqa: E402


def _launch_daemon_worker(port=0, state_dir=None, fault_spec=None,
                          extra_env=None):
    """Start one tests/daemon_worker.py subprocess WITHOUT waiting for
    its READY line (callers that spawn several overlap the ~4 s jax
    imports by deferring the reads). The ONE place the worker env is
    built: SRML_* stripped, then the parent session's f64 parity profile
    pinned — worker-side folds must be bitwise-comparable with
    in-session oracles, and a drift between two spawn sites would break
    every worker-vs-oracle contract silently. ``extra_env`` overlays
    LAST (telemetry tests configure SRML_SLO_*/SRML_INCIDENT_* knobs on
    the worker; the parity profile still wins unless overridden
    explicitly)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if not k.startswith("SRML_")}
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "True"
    env["SRML_TPU_ACCUM_DTYPE"] = "float64"
    env["SRML_TPU_COMPUTE_DTYPE"] = "float64"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    if fault_spec:
        env["SRML_FAULT_PLAN"] = fault_spec
    if extra_env:
        env.update({str(k): str(v) for k, v in extra_env.items()})
    argv = [
        sys.executable,
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "daemon_worker.py"),
        str(port),
    ]
    if state_dir is not None:
        argv.append(str(state_dir))
    return subprocess.Popen(
        argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        cwd=repo_root, env=env, text=True,
    )


def _read_ready(proc) -> int:
    line = proc.stdout.readline().strip()
    assert line.startswith("READY "), line
    return int(line.split()[1])


def spawn_daemon_worker(port=0, state_dir=None, fault_spec=None,
                        extra_env=None):
    """One worker subprocess (READY <port> contract, stdin-close
    shutdown). Returns (proc, port)."""
    proc = _launch_daemon_worker(port, state_dir, fault_spec, extra_env)
    return proc, _read_ready(proc)


def stop_daemon_worker(proc) -> None:
    """Polite shutdown (stdin close); kill as the fallback."""
    try:
        if proc.poll() is None:
            proc.stdin.close()
            proc.wait(timeout=15)
    except Exception:
        proc.kill()


@pytest.fixture(scope="module")
def worker_daemon_pair():
    """Two long-lived subprocess daemons shared across a module's
    flagships for never-killed roles (oracle fits, surviving peers).
    Both spawn before either READY line is read so the jax imports
    overlap. Use UNIQUE job/model names per test — the daemons live for
    the whole module."""
    procs = [_launch_daemon_worker() for _ in range(2)]
    try:
        yield [(proc, _read_ready(proc)) for proc in procs]
    finally:
        for proc in procs:
            stop_daemon_worker(proc)
