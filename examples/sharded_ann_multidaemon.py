"""Pod-scale ANN: one IVF index SHARDED across daemons (round 5).

BASELINE config #5 (10M×768 on v5e-64) does not fit one host: a v5e-64
pod is 16 host VMs × 4 chips, one data-plane daemon per host. The
Spark-fed path (`SparkApproximateNearestNeighbors.fit`) does everything
below automatically whenever executors feed more than one daemon; this
example drives the same protocol by hand so the moving parts are visible
(docs/protocol.md "Sharded index across daemons", docs/ann-capacity.md):

1. each daemon accumulates the partitions ITS executors fed (row data
   never crosses hosts);
2. the first daemon's `finalize` trains the coarse quantizer and hands
   back the (nlist, d) centroids — O(nlist·d) on the wire;
3. every other daemon finalizes against those FROZEN centroids, so all
   shards bucket into the same list space;
4. `row_id_base` translates each shard's local row positions to global
   partition-major ids — every shard answers in one id space;
5. queries fan out to every shard and merge top-k host-side
   (`models/knn.merge_topk` — exact for the union, the daemon-level twin
   of the device-mesh all_gather merge).

Run: python examples/sharded_ann_multidaemon.py
"""

import os
import sys

if __package__ in (None, ""):  # direct script run
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from spark_rapids_ml_tpu.models.knn import merge_topk
from spark_rapids_ml_tpu.serve import DataPlaneClient, DataPlaneDaemon


def main() -> None:
    rng = np.random.default_rng(0)
    kc, d, k, nlist = 16, 64, 5, 32
    centers = rng.normal(size=(kc, d)) * 8
    x = np.concatenate(
        [c + rng.normal(size=(400, d)) for c in centers]
    ).astype(np.float32)
    x = x[rng.permutation(len(x))]
    queries = x[:32]

    # Two daemons — in production, one per TPU host VM.
    with DataPlaneDaemon() as da, DataPlaneDaemon() as db:
        ca = DataPlaneClient(*da.address)
        cb = DataPlaneClient(*db.address)

        # 1. executors feed their host-local daemon (partitions 0-1 → A,
        #    2-3 → B); global id base = cumulative partition row counts.
        parts = np.array_split(x, 4)
        base = {
            str(i): int(sum(len(p) for p in parts[:i])) for i in range(4)
        }
        for pid, client in ((0, ca), (1, ca), (2, cb), (3, cb)):
            client.feed("ann-fit", parts[pid], algo="knn", partition=pid)
            client.commit("ann-fit", partition=pid)

        # 2. first shard trains the quantizer and returns it…
        info_a = ca.finalize_knn(
            "ann-fit", register_as="ann-idx", mode="ivf", nlist=nlist,
            nprobe=8, row_id_base={p: base[p] for p in ("0", "1")},
            return_centroids=True,
        )
        # 3. …which the peer build buckets against, frozen.
        info_b = cb.finalize_knn(
            "ann-fit", register_as="ann-idx", mode="ivf", nlist=nlist,
            nprobe=8, row_id_base={p: base[p] for p in ("2", "3")},
            centroids=info_a["centroids"],
        )
        shard_rows = [int(info_a["n_rows"][0]), int(info_b["n_rows"][0])]
        print("shards:", shard_rows, "rows — index never left the daemons")

        # 4+5. fan out the query batch, merge top-k by distance.
        per = [
            c.kneighbors("ann-idx", queries, k=min(k, n))
            for c, n in ((ca, shard_rows[0]), (cb, shard_rows[1]))
        ]
        dists, ids = merge_topk(
            [d_ for d_, _ in per], [i_ for _, i_ in per], k
        )
        print("top-1 self-hits:", int((ids[:, 0] == np.arange(32)).sum()),
              "/ 32")
        assert (ids[:, 0] == np.arange(32)).all()

        ca.drop_model("ann-idx"), cb.drop_model("ann-idx")
        ca.close(), cb.close()


if __name__ == "__main__":
    main()
