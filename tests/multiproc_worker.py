"""Worker for the multi-process distributed test (see test_multiprocess.py).

Each process: initialize the distributed runtime (our wrapper), build the
global mesh, materialize ONLY its local row slice, run the sharded PCA fit,
and have process 0 print the result as JSON. This is the multi-node
coverage the reference lacks entirely (SURVEY.md §4: "no
multi-executor/multi-node test").
"""

import json
import os
import sys


def main() -> None:
    proc_id = int(sys.argv[1])
    n_procs = int(sys.argv[2])
    port = sys.argv[3]

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from spark_rapids_ml_tpu.parallel.distributed import (
        global_mesh,
        initialize_cluster,
        process_local_rows,
    )

    initialize_cluster(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=n_procs,
        process_id=proc_id,
    )
    assert jax.process_count() == n_procs

    import numpy as np

    from spark_rapids_ml_tpu.models.pca import fit_pca

    # Deterministic dataset; every process computes the full array but
    # feeds only its local slice (how a real loader would behave).
    rng = np.random.default_rng(0)
    n, d, k = 603, 16, 3  # odd count: exercises uneven per-process padding
    x = rng.normal(size=(n, d)) * np.logspace(0, -1.0, d)
    lo, hi = process_local_rows(n)

    mesh = global_mesh()
    sol = fit_pca(x[lo:hi], k=k, mean_center=True, mesh=mesh)

    # STREAMED multi-host fit (VERDICT round-1 gap #5): each process
    # streams only its local slice, in UNEVEN batch counts (process 0
    # gets 3 batches, process 1 gets 2) — lockstep_batches levels them.
    from spark_rapids_ml_tpu.models.pca import fit_pca_stream

    local = x[lo:hi]
    n_batches = 3 if proc_id == 0 else 2
    stream = np.array_split(local, n_batches)
    ssol = fit_pca_stream(iter(stream), k=k, n_cols=d, mesh=mesh)

    # Multi-host STREAMED KMeans: local streams with uneven batch counts;
    # allgathered init sample makes every process compute the same centers.
    from spark_rapids_ml_tpu.models.kmeans import fit_kmeans_stream

    ksol = fit_kmeans_stream(
        lambda: iter(np.array_split(local.astype(np.float32), n_batches)),
        k=3, n_cols=d, max_iter=5, seed=0,
    )

    # Multi-host STREAMED LogReg: local (x, y) streams in lockstep.
    from spark_rapids_ml_tpu.models.logistic_regression import fit_logistic_stream

    w_true = np.linspace(-1, 1, d)
    y = (x @ w_true > 0).astype(np.float64)
    ylocal = y[lo:hi]

    def labeled():
        xs = np.array_split(local.astype(np.float32), n_batches)
        ys = np.array_split(ylocal, n_batches)
        return iter(zip(xs, ys))

    lsol = fit_logistic_stream(labeled, n_cols=d, reg=1e-3, max_iter=8)

    # Exact KNN: each process indexes its local slice; queries identical
    # everywhere; returned ids are global row positions.
    from spark_rapids_ml_tpu.models.knn import NearestNeighbors

    queries = x[:7]  # every process passes the same batch
    model = NearestNeighbors(mesh=mesh).setK(5).fit({"features": x[lo:hi]})
    dists, idx = model.kneighbors(queries)

    if jax.process_index() == 0:
        print(
            json.dumps(
                {
                    "pc": np.asarray(sol.pc).tolist(),
                    "ev": np.asarray(sol.explained_variance).tolist(),
                    "n_rows": sol.n_rows,
                    "stream_pc": np.asarray(ssol.pc).tolist(),
                    "stream_n_rows": ssol.n_rows,
                    "kmeans_centers": np.asarray(ksol.centers).tolist(),
                    "kmeans_n_rows": ksol.n_rows,
                    "logreg_coef": np.asarray(lsol.coefficients).tolist(),
                    "logreg_n_rows": lsol.n_rows,
                    "knn_idx": np.asarray(idx).tolist(),
                    "knn_d": np.asarray(dists).tolist(),
                }
            )
        )


if __name__ == "__main__":
    main()
