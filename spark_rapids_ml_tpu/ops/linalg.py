"""Dense solves for the normal-equations model family.

The reference has no solver beyond eigendecomposition; LinearRegression /
LogisticRegression (BASELINE.json configs) need SPD solves of the d×d system
(XᵀX + λI)w = Xᵀy. Cholesky is the MXU-friendly choice; a diagonal-jitter
retry guards near-singular systems without data-dependent Python control
flow (the retry is branchless: solve once with jitter chosen by a
finiteness check on the first factorization).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def solve_spd(a: jax.Array, b: jax.Array, reg: float = 0.0) -> jax.Array:
    """Solve (a + reg·I) x = b for symmetric positive (semi-)definite a."""
    d = a.shape[0]
    eye = jnp.eye(d, dtype=a.dtype)
    a_reg = a + reg * eye

    factor = jnp.linalg.cholesky(a_reg)
    ok = jnp.all(jnp.isfinite(factor))
    # Branchless fallback: re-factor with jitter scaled to the diagonal when
    # the plain factorization failed (NaNs from a non-PD matrix).
    jitter = 1e-6 * jnp.maximum(jnp.max(jnp.abs(jnp.diag(a_reg))), 1.0)
    factor2 = jnp.linalg.cholesky(a_reg + jitter * eye)
    chol = jnp.where(ok, factor, factor2)
    y = jax.scipy.linalg.solve_triangular(chol, b, lower=True)
    return jax.scipy.linalg.solve_triangular(chol.T, y, lower=False)
