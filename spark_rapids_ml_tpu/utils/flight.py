"""Flight recorder: triggered incident bundles from in-memory context.

An incident (a deadline-breach storm, a shed cascade, an aborted
rollout, an injected fault, a dying process) is exactly the moment the
usual pull-based telemetry fails you: by the time someone scrapes, the
storm is over and the process may be gone. The recorder inverts the
direction — each daemon already holds a bounded in-memory ring of
recent journal events (utils/journal.py ``ring_arm``) and a rolling
per-op metrics delta; a **trigger** atomically dumps everything it
holds as one JSON *incident bundle* under ``state_dir/incidents/``::

    incident-<unix_ms>-<reason>.json
    { "kind": "srml_incident_bundle", "v": 1,
      "reason": …, "detail": …, "ts": …, "pid": …,
      "identity": {…daemon id/boot_id/address…},
      "fingerprint": "<config fingerprint>",
      "events":  [ …journal ring, newest last… ],   "seq": <last seq>,
      "metrics": { …registry snapshot, with exemplars… },
      "op_deltas": { op: {total, err, shed} over the recorder window },
      "xprof":   { …jit-ledger snapshot… },
      "gossip":  { …FleetView wire… } | null }

``tools/trace.py`` loads a bundle as a normal trace source (its
``events`` are ordinary journal lines), so a bundle from a daemon that
was SIGKILL'd five minutes ago stitches into the fleet trace like a
live ``trace_pull`` answer.

Triggers are debounced per reason (``incident_min_interval_s``), the
directory is capped (``incident_max_bundles``, oldest deleted), writes
are tmp-file + rename atomic, and every failure path is swallowed after
one log line — the recorder must never take the daemon down. The
daemon's telemetry thread drives the automatic triggers (SLO breach,
shed storm, deadline-breach rate — serve/daemon.py); fault-site hits
arrive via ``faults.subscribe``; controllers call :func:`record` at
interesting moments (rollout abort) against the process-default
recorder.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from spark_rapids_ml_tpu.utils.logging import get_logger

__all__ = ["FlightRecorder", "set_default", "record", "load_bundle"]

logger = get_logger("utils.flight")

BUNDLE_KIND = "srml_incident_bundle"


class FlightRecorder:
    """One per daemon process (or any process worth black-boxing).

    ``providers`` maps bundle field names to zero-arg callables
    returning JSON-able values — the daemon wires ``gossip`` to its
    FleetView and ``identity`` to its id/boot_id/address; a provider
    that raises contributes ``null``.
    """

    def __init__(
        self,
        state_dir: Optional[str] = None,
        providers: Optional[Dict[str, Callable[[], Any]]] = None,
    ):
        self.state_dir = str(state_dir) if state_dir else None
        self.providers = dict(providers or {})
        self._lock = threading.Lock()
        self._last_by_reason: Dict[str, float] = {}
        #: Rolling per-op stats baseline (ts, {op: {total, err, shed}}):
        #: refreshed by observe(); bundles report deltas against it.
        self._baseline: Optional[Tuple[float, Dict[str, Any]]] = None
        self._fatal_armed = False

    # -- rolling metrics delta ---------------------------------------

    def observe(self, snap: Dict[str, Any], now: Optional[float] = None
                ) -> Dict[str, Dict[str, float]]:
        """Feed one metrics snapshot (the telemetry tick). Returns the
        per-op deltas since the previous observe — the same numbers the
        daemon's automatic triggers rate-check — and rolls the baseline
        forward."""
        from spark_rapids_ml_tpu.utils.slo import _op_stats

        if now is None:
            now = time.time()
        stats = _op_stats(snap)
        deltas: Dict[str, Dict[str, float]] = {}
        with self._lock:
            prev = self._baseline[1] if self._baseline else {}
            for op, cur in stats.items():
                old = prev.get(op, {})
                deltas[op] = {
                    "total": cur["total"] - float(old.get("total", 0.0)),
                    "err": cur["err"] - float(old.get("err", 0.0)),
                    "shed": cur["shed"] - float(old.get("shed", 0.0)),
                }
            self._baseline = (
                now,
                {op: {k: v for k, v in cur.items() if k != "buckets"}
                 for op, cur in stats.items()},
            )
        return deltas

    # -- triggering ---------------------------------------------------

    def trigger(
        self,
        reason: str,
        detail: Optional[Dict[str, Any]] = None,
        force: bool = False,
    ) -> Optional[str]:
        """Dump one bundle for ``reason`` (debounced per reason unless
        ``force``). Returns the bundle path, or None when not dumped
        (no state_dir, cap 0, debounced, or a swallowed write error)."""
        from spark_rapids_ml_tpu import config

        if self.state_dir is None:
            return None
        cap = int(config.get("incident_max_bundles") or 0)
        if cap <= 0:
            return None
        now = time.time()
        with self._lock:
            if not force:
                min_gap = float(config.get("incident_min_interval_s") or 0.0)
                last = self._last_by_reason.get(reason, 0.0)
                if now - last < min_gap:
                    return None
            self._last_by_reason[reason] = now
        try:
            return self._dump(reason, detail, now, cap)
        except Exception as e:  # never take the daemon down
            logger.warning("flight recorder: bundle for %r failed: %s",
                           reason, e)
            return None

    def _dump(self, reason: str, detail: Optional[Dict[str, Any]],
              now: float, cap: int) -> str:
        from spark_rapids_ml_tpu import config
        from spark_rapids_ml_tpu.utils import journal
        from spark_rapids_ml_tpu.utils import metrics as metrics_mod
        from spark_rapids_ml_tpu.utils import xprof

        events, seq = journal.tail(0)
        snap = metrics_mod.snapshot()
        with self._lock:
            base = self._baseline
        op_deltas: Dict[str, Any] = {}
        if base is not None:
            from spark_rapids_ml_tpu.utils.slo import _op_stats

            cur = _op_stats(snap)
            for op, row in cur.items():
                old = base[1].get(op, {})
                op_deltas[op] = {
                    "total": row["total"] - float(old.get("total", 0.0)),
                    "err": row["err"] - float(old.get("err", 0.0)),
                    "shed": row["shed"] - float(old.get("shed", 0.0)),
                    "window_s": now - base[0],
                }
        bundle: Dict[str, Any] = {
            "kind": BUNDLE_KIND,
            "v": 1,
            "reason": str(reason),
            "detail": detail,
            "ts": now,
            "pid": os.getpid(),
            "fingerprint": config.fingerprint(),
            "events": events,
            "seq": seq,
            "metrics": snap,
            "op_deltas": op_deltas,
            "xprof": xprof.snapshot(),
        }
        for name, provider in sorted(self.providers.items()):
            try:
                bundle[name] = provider()
            except Exception:
                bundle[name] = None

        inc_dir = os.path.join(self.state_dir, "incidents")
        os.makedirs(inc_dir, exist_ok=True)
        fname = f"incident-{int(now * 1000)}-{_slug(reason)}.json"
        path = os.path.join(inc_dir, fname)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, separators=(",", ":"), default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._rotate(inc_dir, cap)
        logger.info("flight recorder: incident bundle %s (%s, %d events)",
                    path, reason, len(events))
        return path

    @staticmethod
    def _rotate(inc_dir: str, cap: int) -> None:
        bundles = sorted(
            f for f in os.listdir(inc_dir)
            if f.startswith("incident-") and f.endswith(".json")
        )
        for stale in bundles[:-cap] if cap > 0 else []:
            try:
                os.remove(os.path.join(inc_dir, stale))
            except OSError:
                pass

    # -- fatal-teardown arming ---------------------------------------

    def arm_fatal(self) -> None:
        """Dump a ``fatal`` bundle on SIGTERM / interpreter exit, gated
        by ``incident_on_fatal``. SIGKILL is uncatchable by design —
        that case is covered by the bundles the AUTOMATIC triggers
        already dumped while the incident was unfolding."""
        from spark_rapids_ml_tpu import config

        if self._fatal_armed or not config.get("incident_on_fatal"):
            return
        self._fatal_armed = True
        import atexit

        atexit.register(self._on_fatal, "atexit")
        try:  # only the main thread may install signal handlers
            import signal

            prev = signal.getsignal(signal.SIGTERM)

            def _handler(signum, frame):
                self._on_fatal("sigterm")
                if callable(prev):
                    prev(signum, frame)
                else:
                    raise SystemExit(128 + signum)

            signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError, RuntimeError):
            pass

    def _on_fatal(self, what: str) -> None:
        self.trigger("fatal", {"via": what}, force=True)

    # -- fault-site subscription --------------------------------------

    def on_fault(self, site: str, kind: str) -> None:
        """``faults.subscribe`` adapter: an injected fault FIRING is an
        incident (the bundle lands before a crash-kind fault kills the
        process — faults notifies pre-perform)."""
        self.trigger("fault_site", {"site": site, "fault": kind})


def _slug(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)[:48]


#: Process-default recorder (the daemon installs its own at start):
#: lets distant layers — the fleet controller's rollout abort path —
#: record incidents without threading a recorder handle through.
_DEFAULT: Optional[FlightRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def set_default(rec: Optional[FlightRecorder]) -> None:
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = rec


def record(reason: str, detail: Optional[Dict[str, Any]] = None
           ) -> Optional[str]:
    """Trigger on the process-default recorder; no-op when none is
    installed (a controller without a state_dir just moves on)."""
    rec = _DEFAULT
    if rec is None:
        return None
    return rec.trigger(reason, detail)


def load_bundle(path: str) -> Dict[str, Any]:
    """Read one incident bundle back (tools/trace.py, tests)."""
    with open(path, "r", encoding="utf-8") as f:
        obj = json.load(f)
    if obj.get("kind") != BUNDLE_KIND:
        raise ValueError(f"{path}: not an incident bundle")
    return obj
