"""Core framework unit tests: Params contract, dataset abstraction,
config, checkpointing, profiling spans."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.core.dataset import as_column, as_matrix, num_rows, with_column
from spark_rapids_ml_tpu.core.params import (
    Params,
    ParamDecl,
    TypeConverters,
)


class Toy(Params):
    _uid_prefix = "Toy"
    alpha = ParamDecl("alpha", "a float knob", TypeConverters.toFloat)
    n = ParamDecl("n", "an int knob", TypeConverters.toInt)
    name = ParamDecl("name", "a string knob", TypeConverters.toString)

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(alpha=0.5)


# ---------------------------------------------------------------------------
# Params contract (ParamsSuite.checkParams analogue)
# ---------------------------------------------------------------------------


def test_param_defaults_and_set():
    t = Toy()
    assert t.getOrDefault("alpha") == 0.5
    assert not t.isSet(t.alpha) and t.hasDefault(t.alpha) and t.isDefined(t.alpha)
    t._set(alpha=0.9)
    assert t.getOrDefault(t.alpha) == 0.9 and t.isSet(t.alpha)
    t.clear(t.alpha)
    assert t.getOrDefault(t.alpha) == 0.5


def test_param_type_conversion():
    t = Toy()
    t._set(n=5.0)  # lossless float -> int ok
    assert t.getOrDefault("n") == 5
    with pytest.raises(TypeError):
        t._set(n=5.5)
    with pytest.raises(TypeError):
        t._set(n=True)
    with pytest.raises(TypeError):
        t._set(name=42)


def test_param_unknown_name():
    t = Toy()
    with pytest.raises(AttributeError):
        t.getParam("bogus")
    assert not t.hasParam("bogus")
    assert t.hasParam("alpha")


def test_param_undefined_get_raises():
    t = Toy()
    with pytest.raises(KeyError):
        t.getOrDefault("n")


def test_copy_preserves_uid_and_values():
    t = Toy()
    t._set(n=3)
    c = t.copy()
    assert c.uid == t.uid and c.getOrDefault("n") == 3
    c._set(n=4)
    assert t.getOrDefault("n") == 3  # independent maps


def test_copy_with_extra():
    t = Toy()
    c = t.copy({t.alpha: 0.1})
    assert c.getOrDefault("alpha") == 0.1 and t.getOrDefault("alpha") == 0.5


def test_explain_params():
    t = Toy()
    text = t.explainParams()
    assert "alpha" in text and "default: 0.5" in text and "undefined" in text


def test_uids_unique():
    assert Toy().uid != Toy().uid
    assert Toy().uid.startswith("Toy_")


# ---------------------------------------------------------------------------
# Dataset abstraction
# ---------------------------------------------------------------------------


def test_dataset_numpy():
    x = np.ones((4, 3))
    assert num_rows(x) == 4
    np.testing.assert_array_equal(as_matrix(x), x)
    with pytest.raises(TypeError):
        as_column(x, "label")


def test_dataset_dict():
    ds = {"features": np.ones((4, 3)), "label": np.arange(4.0)}
    assert num_rows(ds) == 4
    assert as_matrix(ds, "features").shape == (4, 3)
    np.testing.assert_array_equal(as_column(ds, "label"), np.arange(4.0))
    out = with_column(ds, "pred", np.zeros(4))
    assert "pred" in out and "pred" not in ds


def test_dataset_dict_object_vectors():
    ds = {"features": np.array([np.arange(3.0), np.arange(3.0) + 1], dtype=object)}
    m = as_matrix(ds, "features")
    assert m.shape == (2, 3)


def test_dataset_pandas():
    pd = pytest.importorskip("pandas")
    df = pd.DataFrame({"features": [np.arange(3.0), np.arange(3.0) + 1], "y": [0.0, 1.0]})
    assert num_rows(df) == 2
    assert as_matrix(df, "features").shape == (2, 3)
    out = with_column(df, "vec_out", np.ones((2, 2)))
    assert len(out["vec_out"][0]) == 2


def test_dataset_arrow_roundtrip():
    pa = pytest.importorskip("pyarrow")
    from spark_rapids_ml_tpu.bridge.arrow import matrix_to_list_column

    t = pa.table({"features": matrix_to_list_column(np.ones((5, 2)))})
    assert num_rows(t) == 5
    out = with_column(t, "out", np.zeros((5, 3)))
    assert out.column("out").type.list_size == 3
    # replacing an existing column
    out2 = with_column(out, "out", np.zeros((5, 4)))
    assert out2.column("out").type.list_size == 4


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------


def test_config_unknown_key():
    with pytest.raises(KeyError):
        config.get("bogus_key")
    with pytest.raises(KeyError):
        config.set("bogus_key", 1)


def test_config_option_restores_on_error():
    before = config.get("tracing")
    with pytest.raises(RuntimeError):
        with config.option("tracing", not before):
            raise RuntimeError("boom")
    assert config.get("tracing") == before


# ---------------------------------------------------------------------------
# Checkpoint / resume
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip(tmp_path):
    from spark_rapids_ml_tpu.core.checkpoint import load_state, save_state

    path = str(tmp_path / "ck.npz")
    assert load_state(path) is None
    save_state(path, {"g": np.eye(3)}, {"n_rows": 7})
    arrays, meta = load_state(path)
    np.testing.assert_array_equal(arrays["g"], np.eye(3))
    assert meta == {"n_rows": 7}


def _interrupted(batches, stop_at):
    for i, b in enumerate(batches):
        if i == stop_at:
            raise KeyboardInterrupt("preempted")
        yield b


def test_stream_fit_checkpoint_resume(rng, mesh8, tmp_path):
    import os

    from spark_rapids_ml_tpu.models.pca import fit_pca, fit_pca_stream

    x = rng.normal(size=(512, 10))
    batches = [x[i : i + 64] for i in range(0, 512, 64)]
    path = str(tmp_path / "stream.npz")
    # Simulate preemption after 5 of 8 batches (checkpoints at 2 and 4).
    with pytest.raises(KeyboardInterrupt):
        fit_pca_stream(_interrupted(batches, 5), k=3, n_cols=10, mesh=mesh8,
                       checkpoint_path=path, checkpoint_every=2)
    assert os.path.exists(path)
    # Resume with the full stream: must equal the uninterrupted fit.
    a = fit_pca_stream(batches, k=3, n_cols=10, mesh=mesh8,
                       checkpoint_path=path, checkpoint_every=2)
    assert a.n_rows == 512
    c = fit_pca(x, k=3, mesh=mesh8)
    np.testing.assert_allclose(a.pc, c.pc, atol=1e-8)
    # Success removes the checkpoint so a future fit starts fresh
    # (regression: stale state must never merge into different data).
    assert not os.path.exists(path)
    b = fit_pca_stream(batches, k=3, n_cols=10, mesh=mesh8,
                       checkpoint_path=path, checkpoint_every=2)
    np.testing.assert_allclose(a.pc, b.pc, atol=1e-10)


def test_stream_checkpoint_mismatched_cols(rng, mesh8, tmp_path):
    from spark_rapids_ml_tpu.models.pca import fit_pca_stream

    x = rng.normal(size=(128, 10))
    batches = [x[:64], x[64:]]
    path = str(tmp_path / "stream.npz")
    with pytest.raises(KeyboardInterrupt):
        fit_pca_stream(_interrupted(batches, 1), k=2, n_cols=10, mesh=mesh8,
                       checkpoint_path=path, checkpoint_every=1)
    with pytest.raises(ValueError, match="n_cols"):
        fit_pca_stream([x[:, :8]], k=2, n_cols=8, mesh=mesh8,
                       checkpoint_path=path)


def test_stream_checkpoint_every_validation(rng, mesh8, tmp_path):
    from spark_rapids_ml_tpu.models.pca import fit_pca_stream

    x = rng.normal(size=(64, 10))
    with pytest.raises(ValueError, match="checkpoint_every"):
        fit_pca_stream([x], k=2, n_cols=10, mesh=mesh8,
                       checkpoint_path=str(tmp_path / "c.npz"),
                       checkpoint_every=0)


# ---------------------------------------------------------------------------
# Profiling spans
# ---------------------------------------------------------------------------


def test_trace_span_timer():
    from spark_rapids_ml_tpu.utils.profiling import trace_span

    with trace_span("unit test span") as t:
        pass
    assert t.elapsed is not None and t.elapsed >= 0


def test_trace_span_with_tracing_enabled():
    from spark_rapids_ml_tpu.utils.profiling import trace_span

    with config.option("tracing", True):
        with trace_span("annotated span") as t:
            pass
    assert t.elapsed is not None


# ---------------------------------------------------------------------------
# Param validators (Spark ParamValidators parity — k uses gt(0) via Spark's
# PCAParams in the reference, RapidsPCA.scala:34)
# ---------------------------------------------------------------------------


def test_param_validators_reject_invalid():
    import spark_rapids_ml_tpu as srml

    with pytest.raises(ValueError, match="parameter k given invalid value 0"):
        srml.PCA().setK(0)
    with pytest.raises(ValueError, match="invalid value -1"):
        srml.KMeans().setK(-1)
    with pytest.raises(ValueError, match="initMode"):
        srml.KMeans().setInitMode("bogus")
    with pytest.raises(ValueError, match="regParam"):
        srml.LinearRegression().setRegParam(-0.5)
    with pytest.raises(ValueError, match="elasticNetParam"):
        srml.LinearRegression().setElasticNetParam(1.5)
    with pytest.raises(ValueError, match="maxIter"):
        srml.LogisticRegression().setMaxIter(-1)


def test_param_validators_accept_valid():
    import spark_rapids_ml_tpu as srml

    est = srml.PCA().setK(3)
    assert est.getK() == 3
    km = srml.KMeans().setK(2).setInitMode("random")
    assert km.getK() == 2
