"""Out-of-HBM KMeans: one host scan per Lloyd iteration.

The batch source is any callable returning a fresh iterator per call —
here a generator over synthetic shards; in production, an Arrow/Parquet
reader. Centers checkpoint each iteration; rerunning after an
interruption resumes at the saved iteration.
"""

import os
import sys

if __package__ in (None, ""):  # runnable without installation
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from spark_rapids_ml_tpu.models.kmeans import fit_kmeans_stream

rng = np.random.default_rng(0)
true_centers = rng.normal(size=(16, 128)) * 8


def batches():
    for i in range(20):  # 20 batches x 50k rows = 1M rows per scan
        yield (true_centers[rng.integers(0, 16, 50_000)]
               + rng.normal(size=(50_000, 128))).astype(np.float32)


sol = fit_kmeans_stream(
    batches, k=16, n_cols=128, max_iter=10, seed=0,
    checkpoint_path="/tmp/kmeans.ckpt",
)
print(f"{sol.n_iter} iterations over {sol.n_rows} rows; cost {sol.cost:.3e}")
