"""Chaos suite: the data plane under deterministic fault injection.

The claim under test (ISSUE 2 / the Podracer posture, arXiv:2104.06272):
hosts and connections fail ROUTINELY, and the fabric heals — a
partitioned fit driven through injected socket drops, truncated frames,
added latency, busy-shedding, and a daemon killed and restarted mid-job
still completes and produces EXACTLY the fault-free model. Faults are
injected through utils/faults.py checkpoints inside the real client /
wire / daemon / bridge code paths, not mocks.

Every test here asserts two things: the healed result is bit-identical
to the fault-free result, and the plan actually FIRED (a chaos test
whose faults never triggered proves nothing).
"""

import socket
import threading
import time

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.kmeans import fit_kmeans
from spark_rapids_ml_tpu.models.pca import fit_pca
from spark_rapids_ml_tpu.serve import DaemonBusy, DataPlaneClient, DataPlaneDaemon
from spark_rapids_ml_tpu.utils import faults
from spark_rapids_ml_tpu.utils.faults import FaultPlan

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_plan_leaks():
    """A leaked active plan would inject faults into every later test."""
    yield
    faults.deactivate()
    assert faults.active_plan() is None


def _client(daemon_or_addr, **kw):
    addr = (
        daemon_or_addr.address
        if hasattr(daemon_or_addr, "address") else daemon_or_addr
    )
    kw.setdefault("timeout", 15.0)
    kw.setdefault("backoff_base_s", 0.01)
    kw.setdefault("backoff_max_s", 0.2)
    kw.setdefault("max_op_attempts", 10)
    return DataPlaneClient(*addr, **kw)


# --------------------------- the chaos driver --------------------------------


def _drive_kmeans(addr, parts, k, seed, iters, job, attempt=0, **kw):
    """One seeded partitioned kmeans fit, driven the way the Spark wrapper
    drives it (seed → per-pass feed+commit → step → finalize). The
    client's self-healing absorbs connection faults; anything that still
    escapes is the caller's (fit-level) retry problem — exactly Spark's
    job-retry split."""
    seed_batch = np.concatenate(parts)[: max(10 * k, k)]
    with _client(addr, **kw) as c:
        c.seed_kmeans(job, seed_batch, k=k, params={"seed": seed})
        for it in range(iters):
            for pid, part in enumerate(parts):
                c.feed(job, part, algo="kmeans", partition=pid,
                       attempt=attempt, pass_id=it,
                       params={"k": k, "seed": seed})
                c.commit(job, partition=pid, attempt=attempt, pass_id=it)
            c.step(job)
        # The replay-safe finalize split (docs/protocol.md "Client retry
        # obligations"): read with drop=False — a replay after a
        # truncated response re-reads the same model — then drop
        # explicitly (idempotent).
        out, _ = c.finalize(job, {}, drop=False)
        c.drop(job)
        return out, dict(c.stats)


def _fit_with_job_retry(addr, parts, k, seed, iters, ensure_alive=None,
                        max_fit_attempts=8, **kw):
    """Fit-level retry around the chaos driver — the role Spark's job
    retry plays above task retry. A fresh job name per attempt: the fits
    are pure functions of (data, seed), so re-execution is always sound
    (the DrJAX-purity half of the resilience story)."""
    last = None
    for attempt in range(max_fit_attempts):
        if ensure_alive is not None:
            ensure_alive()
        try:
            return _drive_kmeans(
                addr, parts, k, seed, iters, job=f"chaos-{attempt}",
                attempt=attempt, **kw,
            )
        except (RuntimeError, OSError) as e:
            last = e
    raise AssertionError(
        f"fit did not complete in {max_fit_attempts} attempts: {last}"
    )


# ------------------------- in-process chaos runs -----------------------------


@pytest.fixture
def kdata(rng):
    x = (rng.normal(size=(240, 6)) + 3.0 * rng.integers(0, 3, size=(240, 1))
         ).astype(np.float64)
    return [np.ascontiguousarray(p) for p in np.array_split(x, 4)]


def test_chaos_kmeans_drops_latency_partial_frames_exact(kdata, mesh8):
    """The tentpole proof (in-process half): 10% op drops, partial
    frames on the wire, latency in the daemon and bridge — the healed
    fit's centers equal the fault-free run's bit-for-bit."""
    with DataPlaneDaemon(mesh=mesh8) as d:
        baseline, _ = _drive_kmeans(
            d.address, kdata, k=3, seed=7, iters=3, job="fault-free"
        )
        plan = (
            FaultPlan(seed=1234)
            .rule("client.op", "drop", p=0.10)
            .rule("wire.send_frame", "partial", p=0.04)
            .rule("daemon.op", "latency", p=0.25, delay_s=0.002)
            .rule("bridge.to_matrix", "latency", p=0.25, delay_s=0.002)
            .rule("client.connect", "refuse", p=0.05)
        )
        with faults.active(plan):
            healed, stats = _fit_with_job_retry(
                d.address, kdata, k=3, seed=7, iters=3
            )
        assert plan.fired, "chaos plan never fired — the run proved nothing"
        assert stats["reconnects"] > 0  # the healing actually ran
    np.testing.assert_array_equal(healed["centers"], baseline["centers"])
    assert healed["n_iter"] == baseline["n_iter"]
    # Sanity anchor: the daemon-fit centers match the in-memory oracle fit
    # under the same seed (both sides of the chaos comparison are real).
    ref = fit_kmeans(np.concatenate(kdata), k=3, seed=7, max_iter=3,
                     mesh=mesh8, tol=0.0)
    assert ref.centers.shape == healed["centers"].shape


def test_chaos_partitioned_pca_partial_frames_exact(rng, mesh8):
    """Single-pass path under frame truncation + drops: the staged
    commit protocol plus feed_id replay dedupe keeps accumulation
    exactly-once, so the healed PCA equals the clean fit exactly."""
    data = rng.normal(size=(480, 16)) * np.logspace(0, -1.5, 16)
    parts = np.array_split(data, 4)
    plan = (
        FaultPlan(seed=99)
        .rule("client.op", "drop", p=0.12)
        .rule("wire.send_frame", "partial", p=0.06)
    )
    with DataPlaneDaemon(mesh=mesh8) as d:
        with faults.active(plan), _client(d.address) as c:
            for pid, part in enumerate(parts):
                for sub in np.array_split(part, 2):
                    c.feed("pj", sub, algo="pca", partition=pid)
                c.commit("pj", partition=pid)
            assert c.status("pj")["rows"] == data.shape[0]
            # Replay-safe finalize: drop=False so a truncated-response
            # replay re-reads the model, then an idempotent explicit drop.
            out, _ = c.finalize(
                "pj", {"k": 3, "mean_center": True, "solver": None},
                drop=False,
            )
            c.drop("pj")
            stats = dict(c.stats)
    assert plan.fired and stats["reconnects"] > 0
    # The wire-level partial frames fired mid-request, so at least some
    # retries were true REPLAYS of an already-sent request.
    assert stats["replays"] > 0
    ref = fit_pca(data, k=3, mesh=mesh8)
    np.testing.assert_allclose(np.abs(out["pc"]), np.abs(ref.pc), atol=1e-8)
    np.testing.assert_allclose(out["mean"], ref.mean, atol=1e-10)


def test_faults_disabled_hooks_are_noops():
    """With no plan active every checkpoint is a global load + is-None
    test: nothing raises, nothing sleeps, nothing allocates."""
    assert faults.active_plan() is None
    assert faults.checkpoint("client.op") is None
    assert faults.truncation("wire.send_frame", 1024) is None
    start = time.perf_counter()
    for _ in range(100_000):
        faults.checkpoint("client.op")
    assert time.perf_counter() - start < 0.5  # ~µs/call; generous bound


def test_fault_plan_env_spec_roundtrip():
    plan = FaultPlan.from_spec(
        "seed=7;client.op:drop:p=0.5,times=2;daemon.op:crash:after=20,times=1"
    )
    assert plan.seed == 7
    drops = plan._rules["client.op"]
    assert drops[0].kind == "drop" and drops[0].p == 0.5 and drops[0].times == 2
    crash = plan._rules["daemon.op"][0]
    assert crash.after == 20 and crash.times == 1
    with pytest.raises(ValueError, match="bad fault rule"):
        FaultPlan.from_spec("nonsense")
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.from_spec("client.op:meteor")


def test_partial_rule_outside_wire_site_rejected():
    """A 'partial' rule anywhere but the framing layer would silently
    never fire — a chaos plan that proves nothing. Refused loudly."""
    with pytest.raises(ValueError, match="wire.send_frame"):
        FaultPlan(seed=0).rule("client.op", "partial", p=0.5)
    with pytest.raises(ValueError, match="wire.send_frame"):
        FaultPlan.from_spec("client.op:partial:p=0.5")


def test_op_deadline_bounds_blocked_recv():
    """The per-op deadline clamps the socket timeout of a blocked recv:
    a daemon that accepts but never replies costs ~deadline, not the
    full 30 s socket timeout per attempt."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)  # connections complete at TCP level; nothing ever answers
    try:
        c = DataPlaneClient(
            "127.0.0.1", srv.getsockname()[1], timeout=30.0,
            op_deadline_s=0.6, max_op_attempts=10,
            backoff_base_s=0.01, backoff_max_s=0.05,
        )
        start = time.monotonic()
        with pytest.raises(OSError):
            c.ping()
        assert time.monotonic() - start < 5.0  # deadline ruled, not 30 s
        c.close()
    finally:
        srv.close()


def test_fault_plan_deterministic_sequence():
    """Same seed → same firing sequence at a site (the 'deterministic'
    in deterministic fault injection)."""

    def seq(seed):
        plan = FaultPlan(seed=seed).rule("s", "drop", p=0.3)
        out = []
        for _ in range(64):
            try:
                plan.hit("s")
                out.append(0)
            except ConnectionError:
                out.append(1)
        return out

    assert seq(5) == seq(5)
    assert seq(5) != seq(6)  # astronomically unlikely to collide
    assert sum(seq(5)) > 0


# ------------------------- health & backpressure -----------------------------


def test_health_op_reports_load(mesh8, rng):
    data = rng.normal(size=(64, 8))
    with DataPlaneDaemon(mesh=mesh8) as d:
        with _client(d.address) as c:
            h0 = c.health()
            assert h0["active_jobs"] == 0 and not h0["busy"]
            assert h0["queue_depth"] >= 1  # this very connection
            assert h0["uptime_s"] >= 0.0
            c.feed("hj", data, algo="pca", partition=0)  # staged, uncommitted
            h1 = c.health()
            assert h1["active_jobs"] == 1
            assert h1["staged_bytes"] > 0
            c.commit("hj", partition=0)
            h2 = c.health()
            assert h2["staged_bytes"] == 0
            assert h2["served_models"] == 0
            assert h2["id"] == d.instance_id


def test_staged_bytes_watermark_sheds_then_recovers(mesh8, rng):
    """Over the staged-bytes watermark the daemon answers `busy` with a
    retry_after_s hint; the client honors it with jittered waits, and
    once a commit drains the stage the shed op goes through — graceful
    degradation, not thrash-until-timeout."""
    data = rng.normal(size=(64, 8))
    with DataPlaneDaemon(
        mesh=mesh8, max_staged_bytes=1, retry_after_s=0.05
    ) as d:
        with _client(d.address) as c1, _client(d.address) as c2:
            c1.feed("wj", data, algo="pca", partition=0)  # stage > 1 byte
            assert c2.health()["busy"]  # health never shed, reports it

            def drain():
                time.sleep(0.3)
                c1.commit("wj", partition=0)

            t = threading.Thread(target=drain)
            t.start()
            # Shed at first, then healed once the commit drains the stage.
            c2.feed("wj", data, algo="pca", partition=1)
            t.join()
            assert c2.stats["busy_waits"] > 0
            c2.commit("wj", partition=1)
            out = c2.finalize_pca("wj", k=2)
    ref = fit_pca(np.concatenate([data, data]), k=2, mesh=mesh8)
    np.testing.assert_allclose(np.abs(out["pc"]), np.abs(ref.pc), atol=1e-8)


def test_busy_without_client_patience_raises(mesh8, rng):
    """A client with no busy-wait budget surfaces DaemonBusy (with the
    hint attached) instead of spinning."""
    data = rng.normal(size=(64, 8))
    with DataPlaneDaemon(
        mesh=mesh8, max_staged_bytes=1, retry_after_s=0.05
    ) as d:
        with _client(d.address) as c:
            c.feed("bj", data, algo="pca", partition=0)
            c.stats["busy_waits"] = 0
            with pytest.raises(DaemonBusy) as ei:
                with _client(d.address, max_busy_wait_s=0.0) as c2:
                    c2.feed("bj", data, algo="pca", partition=1)
            assert ei.value.retry_after_s == pytest.approx(0.05)
            # Pressure-relieving ops are never shed: the commit passes
            # while the daemon is still over its watermark.
            c.commit("bj", partition=0)


def test_connection_watermark_sheds_heavy_ops(mesh8, rng):
    data = rng.normal(size=(16, 4))
    with DataPlaneDaemon(
        mesh=mesh8, max_connections=1, retry_after_s=0.03
    ) as d:
        with _client(d.address) as c1:
            assert c1.ping()  # holds connection #1
            with _client(d.address, max_busy_wait_s=0.0) as c2:
                # control ops pass; heavy ops shed while c1 stays open
                assert c2.ping()
                with pytest.raises(DaemonBusy):
                    c2.feed("cw", data, algo="pca", partition=0)
            # c2 closed; c1 still holds its slot. A patient client waits
            # the hint out and succeeds the moment c1 releases.
            t = threading.Thread(target=lambda: (time.sleep(0.2), c1.close()))
            t.start()
            with _client(d.address, max_busy_wait_s=30.0) as c3:
                c3.feed("cw", data, algo="pca", partition=0)
                c3.commit("cw", partition=0)
                assert c3.stats["busy_waits"] > 0
            t.join()


# ---------------- daemon killed and restarted mid-job (process) --------------


# Worker spawning is centralized in conftest.py (the f64-pinned env);
# the fault-free REFERENCE run shares the module-scoped worker pair
# instead of spawning its own (VERDICT carry #7).
from conftest import spawn_daemon_worker  # noqa: E402


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_chaos_daemon_crash_restart_mid_job_exact(rng, worker_daemon_pair):
    """The flagship: a daemon PROCESS with an env-activated
    crash-on-Nth-op plan dies abruptly (exit 17) mid-fit; a supervisor
    restarts it at the same address; client-side drops keep firing the
    whole time. The fit completes through fit-level retry + client
    healing and matches the fault-free run from an identical clean
    worker (the module's shared pair) exactly."""
    x = (rng.normal(size=(160, 5)) + 2.0 * rng.integers(0, 3, size=(160, 1))
         ).astype(np.float64)
    parts = [np.ascontiguousarray(p) for p in np.array_split(x, 4)]
    port = _free_port()
    procs = []
    try:
        # Fault-free reference from the shared clean worker.
        _, port_r = worker_daemon_pair[0]
        baseline, _ = _drive_kmeans(
            ("127.0.0.1", port_r), parts, k=3, seed=11, iters=3,
            job="chaos-flagship-ref",
        )

        # Chaos worker: dies abruptly on its 30th op, with latency before
        # that; the supervisor below restarts a clean one at the SAME port.
        state = {"proc": None, "crashed": False}

        def start(spec):
            p, _ = spawn_daemon_worker(port, fault_spec=spec)
            state["proc"] = p

        start("seed=5;daemon.op:crash:after=12,times=1;"
              "daemon.op:latency:p=0.2,delay_s=0.002")
        procs.append(state["proc"])

        def ensure_alive():
            p = state["proc"]
            if p.poll() is not None:
                if p.returncode == 17:
                    state["crashed"] = True  # the injected death happened
                start(None)  # supervised restart, same address, no faults
                procs.append(state["proc"])

        client_plan = FaultPlan(seed=21).rule("client.op", "drop", p=0.10)
        with faults.active(client_plan):
            healed, _ = _fit_with_job_retry(
                ("127.0.0.1", port), parts, k=3, seed=11, iters=3,
                ensure_alive=ensure_alive, timeout=10.0,
                max_op_attempts=6, backoff_max_s=0.1,
            )
        # give a just-crashed worker's exit a moment to be reaped
        for _ in range(100):
            if state["crashed"]:
                break
            p = state["proc"]
            if p.poll() is not None and p.returncode == 17:
                state["crashed"] = True
            time.sleep(0.05)
        assert state["crashed"], "the injected daemon crash never happened"
        assert client_plan.fired.get("client.op", 0) > 0
        np.testing.assert_array_equal(healed["centers"], baseline["centers"])
    finally:
        for p in procs:
            try:
                if p.poll() is None:
                    p.stdin.close()
                    p.wait(timeout=15)
            except Exception:
                p.kill()
