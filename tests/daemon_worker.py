"""Standalone data-plane daemon process for multi-host tests.

Spawned by tests/test_spark_multidaemon.py: each instance is one OS
process owning "its host's" daemon (the deployment unit of
spark/daemon_session.py), so the 2-daemon tests exercise real process
isolation — separate JAX runtimes, separate device state, TCP between
everything — not two registries in one interpreter.

Prints ``READY <port>`` on stdout once listening; serves until stdin
closes (the parent's handle drop is the shutdown signal, so an aborted
test never leaks the process).

``argv[1]`` (optional) pins the port — the chaos suite restarts a killed
daemon AT THE SAME ADDRESS, the way a supervised production daemon comes
back. ``argv[2]`` (optional) is a durable state directory: the recovery
suite SIGKILLs this worker and restarts a twin pointing at the same
directory, which must resurrect the jobs (serve/daemon.py crash
recovery). A ``SRML_FAULT_PLAN`` env spec is honored by the in-process
fault registry (utils/faults.py import-time activation), so a
crash-on-Nth-op rule makes this worker die the way a real daemon process
dies: abruptly, mid-traffic, exit code 17.
"""

import sys


def main() -> None:
    import jax

    # The dev image's sitecustomize pins the tunneled TPU platform; this
    # worker must run on host CPU like the test session (see sparksim).
    jax.config.update("jax_platforms", "cpu")

    from spark_rapids_ml_tpu.serve.daemon import DataPlaneDaemon

    port = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    state_dir = sys.argv[2] if len(sys.argv) > 2 else None
    daemon = DataPlaneDaemon(
        host="127.0.0.1", port=port, ttl=600.0, state_dir=state_dir
    ).start()
    print(f"READY {daemon.address[1]}", flush=True)
    sys.stdin.read()  # block until the parent closes our stdin
    daemon.stop()


if __name__ == "__main__":
    main()
