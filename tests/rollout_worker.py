"""Standalone fleet controller/client process for the gossip flagships.

Spawned by tests/test_gossip.py against daemons living in the PARENT
test process: this worker holds NO endpoint roster — it bootstraps
everything from the ONE seed address in argv, the way a fresh operator
box (or a supervisor-restarted controller) joins a running fleet. Two
modes:

* ``rollout <seed> <npz> <model> <version>`` — ``ModelFleet.from_seeds``
  then a v_old→v_new rollout using the ``v2.*`` arrays in the npz.
  With ``SRML_FAULT_PLAN=fleet.rollout:crash:...`` in the env this
  process dies abruptly (exit 17) at the chosen rollout-intent
  checkpoint — AFTER the phase's intent was gossiped, BEFORE its work
  ran: exactly the mid-rollout controller death the successor's
  ``resume_rollout`` must finish or abort. Prints ``DONE <json>`` when
  the plan lets it live.
* ``traffic <seed> <npz> <model> <count>`` — ``FleetClient.from_seeds``
  then routed transforms of the npz's ``q`` batch, each checked bitwise
  against its ``ref`` oracle; one ``OK <n>`` line per request
  (``count`` <= 0 loops forever — the parent SIGKILLs this mode
  mid-traffic and bootstraps a successor from a different seed).
"""

import json
import sys


def main() -> None:
    import jax

    # The dev image's sitecustomize pins the tunneled TPU platform; this
    # worker must run on host CPU like the test session (see sparksim).
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    mode, seed, npz_path, model = sys.argv[1:5]
    data = np.load(npz_path)

    if mode == "rollout":
        from spark_rapids_ml_tpu.serve.fleet import ModelFleet

        new_v = int(sys.argv[5])
        arrays = {
            k[len("v2."):]: data[k] for k in data.files
            if k.startswith("v2.")
        }
        with ModelFleet.from_seeds([seed]) as fleet:
            res = fleet.rollout(
                model, "pca", arrays, version=new_v, warm=False
            )
        print("DONE " + json.dumps(
            {"version": res["version"], "previous": res["previous"],
             "epoch": res["epoch"], "drained": res["drained"]}
        ), flush=True)
    elif mode == "traffic":
        from spark_rapids_ml_tpu.serve.router import FleetClient

        count = int(sys.argv[5])
        q, ref = data["q"], data["ref"]
        with FleetClient.from_seeds([seed]) as fc:
            n = 0
            while count <= 0 or n < count:
                out = fc.transform(model, q)
                got = np.asarray(out["output"])
                print(("OK" if np.array_equal(got, ref) else "MISMATCH")
                      + f" {n}", flush=True)
                n += 1
    else:
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
