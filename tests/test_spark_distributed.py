"""Distributed Spark-wrapper fit: executor-fed, no collect-to-driver.

The PCASuite analogue the reference runs through Spark's harness
(PCASuite.scala:42-88) — here through sparksim (real OS-process tasks,
real TCP to the daemon, Spark-identical retry semantics; see sparksim.py
for why not pyspark). Every fit asserts the driver materialized at most
the tiny seeding/schema probes, never the dataset — the property that
defines the reference's architecture (RapidsRowMatrix.scala:118-139).
"""

import numpy as np
import pytest

from spark_rapids_ml_tpu.models.kmeans import fit_kmeans
from spark_rapids_ml_tpu.models.linear_regression import fit_linear_regression
from spark_rapids_ml_tpu.models.logistic_regression import fit_logistic_regression
from spark_rapids_ml_tpu.models.pca import fit_pca
from spark_rapids_ml_tpu.spark import estimator as spark_est
from spark_rapids_ml_tpu.spark.estimator import (
    SparkKMeans,
    SparkLinearRegression,
    SparkLogisticRegression,
    SparkPCA,
)

from sparksim import SimDataFrame, simdf_from_numpy

spark_est.register_dataframe_type(SimDataFrame)


@pytest.fixture(autouse=True)
def _daemon_cleanup():
    yield
    from spark_rapids_ml_tpu.spark import daemon_session

    daemon_session.shutdown()


@pytest.fixture
def pca_data(rng):
    n, d = 800, 24
    basis = rng.normal(size=(d, d)) * np.logspace(0, -1.5, d)
    return (rng.normal(size=(n, d)) @ basis).astype(np.float64)


def test_spark_pca_fit_is_distributed_and_exact(pca_data, mesh8):
    df = simdf_from_numpy(pca_data, n_partitions=4)
    model = SparkPCA().setInputCol("features").setK(4).fit(df)
    # the dataset never reached the driver
    assert df.sparkSession.driver_rows_materialized == 0
    ref = fit_pca(pca_data, k=4, mesh=mesh8)
    np.testing.assert_allclose(np.abs(model.pc), np.abs(ref.pc), atol=1e-8)
    np.testing.assert_allclose(
        model.explainedVariance, ref.explained_variance, atol=1e-10
    )
    np.testing.assert_allclose(model.mean, ref.mean, atol=1e-10)


def test_spark_pca_fit_survives_task_retry(pca_data, mesh8):
    # partition 1's first attempt dies after feeding 1 batch (uncommitted);
    # partition 2's first TWO attempts die; Spark-style retries recover —
    # the final model must be bit-identical to the clean fit.
    df = simdf_from_numpy(
        pca_data, n_partitions=4, fail_plan={1: [1], 2: [0, 1]}
    )
    model = SparkPCA().setInputCol("features").setK(3).fit(df)
    ref = fit_pca(pca_data, k=3, mesh=mesh8)
    np.testing.assert_allclose(np.abs(model.pc), np.abs(ref.pc), atol=1e-8)
    np.testing.assert_allclose(model.mean, ref.mean, atol=1e-10)


def test_spark_pca_fit_survives_speculative_duplicates(pca_data, mesh8):
    # partition 0 runs twice (speculation) — daemon must not double-count
    df = simdf_from_numpy(pca_data, n_partitions=3, speculative=[0])
    model = SparkPCA().setInputCol("features").setK(3).fit(df)
    ref = fit_pca(pca_data, k=3, mesh=mesh8)
    np.testing.assert_allclose(np.abs(model.pc), np.abs(ref.pc), atol=1e-8)


def test_spark_linreg_fit_distributed_matches_core(rng, mesh8):
    n, d = 600, 12
    x = rng.normal(size=(n, d))
    w = rng.normal(size=(d,))
    y = x @ w + 0.5 + 0.01 * rng.normal(size=n)
    df = simdf_from_numpy(x, n_partitions=4, label=y)
    model = (
        SparkLinearRegression().setRegParam(1e-4).fit(df)
    )
    assert df.sparkSession.driver_rows_materialized == 0
    ref = fit_linear_regression(x, y, reg=1e-4, mesh=mesh8)
    np.testing.assert_allclose(model.coefficients, ref.coefficients, atol=1e-8)
    np.testing.assert_allclose(model.intercept, ref.intercept, atol=1e-8)
    assert model.summary.rmse == pytest.approx(ref.summary.rmse, abs=1e-8)


def test_spark_logreg_iterative_fit_matches_core(rng, mesh8):
    n, d = 600, 8
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d,))
    y = (x @ w > 0).astype(np.float64)
    df = simdf_from_numpy(x, n_partitions=3, label=y)
    model = (
        SparkLogisticRegression().setRegParam(1e-2).setMaxIter(20).fit(df)
    )
    assert df.sparkSession.driver_rows_materialized == 0
    ref = fit_logistic_regression(x, y, reg=1e-2, max_iter=20, mesh=mesh8)
    np.testing.assert_allclose(model.coefficients, ref.coefficients, atol=1e-4)
    np.testing.assert_allclose(model.intercept, ref.intercept, atol=1e-4)
    # the daemon loop ran real Newton passes
    assert model.summary.numIter >= 2


def test_spark_kmeans_iterative_fit_deterministic_and_good(rng, mesh8):
    # 4 well-separated blobs; the multi-pass Lloyd protocol must find them,
    # and two runs over differently-ordered partitions must agree exactly
    # (driver-side seeding).
    k, d = 4, 6
    centers_true = rng.normal(size=(k, d)) * 10
    x = np.concatenate(
        [centers_true[i] + rng.normal(size=(150, d)) * 0.3 for i in range(k)]
    ).astype(np.float32)
    perm = rng.permutation(len(x))
    x = x[perm]

    def run():
        # concurrency=1: run-to-run BITWISE equality of float sums needs
        # ordered commits; concurrent arrival reorders f32 folds exactly
        # as real Spark would (determinism there is up to commit order).
        df = simdf_from_numpy(x, n_partitions=3, concurrency=1)
        m = SparkKMeans().setK(k).setMaxIter(10).setSeed(5).fit(df)
        assert df.sparkSession.driver_rows_materialized <= 4096  # seed probe only
        return m

    m1, m2 = run(), run()
    np.testing.assert_array_equal(m1.centers, m2.centers)
    # every true blob center recovered to within the blob's spread
    dists = np.linalg.norm(
        m1.centers[:, None, :] - centers_true[None, :, :], axis=-1
    )
    assert dists.min(axis=0).max() < 0.5
    assert m1.summary.numIter >= 2


def test_spark_kmeans_retry_mid_pass(rng, mesh8):
    k, d = 3, 5
    centers_true = rng.normal(size=(k, d)) * 8
    x = np.concatenate(
        [centers_true[i] + rng.normal(size=(120, d)) * 0.2 for i in range(k)]
    ).astype(np.float32)
    # concurrency=1: bitwise clean-vs-flaky comparison on float sums
    # needs ordered commits (see the determinism test above).
    clean = simdf_from_numpy(x, n_partitions=3, concurrency=1)
    m_clean = SparkKMeans().setK(k).setMaxIter(4).setSeed(1).fit(clean)
    flaky = simdf_from_numpy(x, n_partitions=3, fail_plan={0: [1]},
                             concurrency=1)
    m_flaky = SparkKMeans().setK(k).setMaxIter(4).setSeed(1).fit(flaky)
    np.testing.assert_array_equal(m_clean.centers, m_flaky.centers)


def test_spark_transform_map_in_arrow_no_collect(pca_data, mesh8):
    df = simdf_from_numpy(pca_data, n_partitions=4)
    model = SparkPCA().setInputCol("features").setK(3).fit(df)
    base = df.sparkSession.driver_rows_materialized
    out_df = model.transform(df)
    # transform is lazy + distributed and the output schema is DERIVED
    # (input schema + declared output fields) — the round-1/2 limit(1)
    # schema-probe job is gone, so NOTHING reaches the driver.
    assert df.sparkSession.driver_rows_materialized - base == 0
    rows = out_df.collect()
    assert len(rows) == pca_data.shape[0]
    got = np.asarray([r["pca_features"] for r in rows])
    # Spark PCA transform does NOT mean-center (x @ pc, RapidsPCA.scala:159)
    want = pca_data @ model.pc
    np.testing.assert_allclose(np.abs(got), np.abs(want), atol=1e-6)


def test_spark_scaler_fit_distributed_matches_core(rng, mesh8):
    from spark_rapids_ml_tpu.models.scaler import StandardScaler
    from spark_rapids_ml_tpu.spark.estimator import SparkStandardScaler

    n, d = 700, 9
    x = (rng.normal(size=(n, d)) * np.logspace(0, 1, d) + 3.0).astype(np.float64)
    df = simdf_from_numpy(x, n_partitions=4)
    model = SparkStandardScaler().setWithMean(True).fit(df)
    assert df.sparkSession.driver_rows_materialized == 0
    ref = StandardScaler(mesh=mesh8).setWithMean(True).fit({"features": x})
    np.testing.assert_allclose(model.mean, ref.mean, atol=1e-8)
    np.testing.assert_allclose(model.std, ref.std, atol=1e-8)
    out = model.transform(df).collect()
    got = np.asarray([r["scaled_features"] for r in out])
    want = (x - ref.mean) / np.where(ref.std > 0, ref.std, 1.0)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_spark_fit_empty_dataframe_raises(mesh8):
    df = simdf_from_numpy(np.zeros((0, 4)), n_partitions=1)
    with pytest.raises(ValueError, match="empty"):
        SparkPCA().setInputCol("features").setK(2).fit(df)


def test_spark_transform_is_served_by_the_daemon(pca_data, mesh8):
    """VERDICT r2 missing #1: distributed transform must hit the TPU-host
    daemon (accelerator-resident model), not run silently on executor
    CPUs. Observable evidence: the driver-owned daemon's model registry
    holds the served copy after the action, and the projected output is
    exact."""
    from spark_rapids_ml_tpu.spark import daemon_session

    df = simdf_from_numpy(pca_data, n_partitions=4)
    model = SparkPCA().setInputCol("features").setK(3).fit(df)
    daemon = daemon_session._owned_daemon
    assert daemon is not None
    daemon._models.clear()
    rows = model.transform(df).collect()
    assert any(m.algo == "pca" for m in daemon._models.values()), (
        "transform batches never registered/used a served model — "
        "they ran executor-side"
    )
    got = np.asarray([r["pca_features"] for r in rows])
    np.testing.assert_allclose(np.abs(got), np.abs(pca_data @ model.pc), atol=1e-6)


def test_spark_transform_local_fallback_is_explicit(pca_data, mesh8, monkeypatch):
    """SRML_TRANSFORM_LOCAL=1 keeps the executor-CPU path available — as
    an explicit choice, never a silent default."""
    from spark_rapids_ml_tpu.spark import daemon_session

    df = simdf_from_numpy(pca_data, n_partitions=2)
    model = SparkPCA().setInputCol("features").setK(3).fit(df)
    daemon = daemon_session._owned_daemon
    daemon._models.clear()
    monkeypatch.setenv("SRML_TRANSFORM_LOCAL", "1")
    rows = model.transform(df).collect()
    assert not daemon._models, "local fallback must not touch the daemon"
    got = np.asarray([r["pca_features"] for r in rows])
    np.testing.assert_allclose(np.abs(got), np.abs(pca_data @ model.pc), atol=1e-6)


def test_spark_logreg_transform_daemon_columns(rng, mesh8):
    """LogReg serving returns Spark's three output columns with canonical
    types (rawPrediction/probability vectors, double prediction)."""
    n, d = 400, 6
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (x @ w + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    df = simdf_from_numpy(x, n_partitions=2, label=y)
    model = (
        SparkLogisticRegression().setMaxIter(8).fit(df)
    )
    rows = model.transform(df).collect()
    pred = np.asarray([r["prediction"] for r in rows])
    proba = np.asarray([r["probability"] for r in rows])
    raw = np.asarray([r["rawPrediction"] for r in rows])
    assert proba.shape == (n, 2) and raw.shape == (n, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-9)
    assert np.array_equal(pred, (proba[:, 1] > 0.5).astype(np.float64))
    # executor-fed fit + daemon-served scoring should classify well
    assert (pred == y).mean() > 0.95


def test_spark_kmeans_transform_daemon_prediction(rng, mesh8):
    k, d = 3, 5
    centers_true = rng.normal(size=(k, d)) * 8
    x = np.concatenate(
        [centers_true[i] + rng.normal(size=(100, d)) * 0.2 for i in range(k)]
    ).astype(np.float32)
    df = simdf_from_numpy(x, n_partitions=2)
    model = SparkKMeans().setK(k).setMaxIter(5).setSeed(0).fit(df)
    rows = model.transform(df).collect()
    pred = np.asarray([r["prediction"] for r in rows])
    assert pred.shape == (x.shape[0],)
    assert pred.dtype.kind == "i"
    # cluster labels agree with direct device prediction
    np.testing.assert_array_equal(pred, model.predict(x))


def test_spark_exact_knn_daemon_fed_no_collect(rng, mesh8):
    """VERDICT r2 missing #2: the KNN fit must not collect the dataset to
    the driver. Exact-KNN results through the daemon-resident index must
    match local brute force bit-for-bit, with global partition-major row
    ids."""
    from spark_rapids_ml_tpu.spark.estimator import SparkNearestNeighbors

    n, d, k = 600, 12, 5
    x = rng.normal(size=(n, d)).astype(np.float64)
    df = simdf_from_numpy(x, n_partitions=4)
    model = SparkNearestNeighbors().setK(k).fit(df)
    assert df.sparkSession.driver_rows_materialized == 0
    q = x[:32]
    dists, idx = model.kneighbors(q)
    # brute-force oracle (row ids = original order = partition-major);
    # the daemon stores the database in float32 (TPU-native), so the
    # oracle uses the same f32-rounded rows
    xf = x.astype(np.float32).astype(np.float64)
    d2 = ((q[:, None, :] - xf[None, :, :]) ** 2).sum(-1)
    want_idx = np.argsort(d2, axis=1, kind="stable")[:, :k]
    np.testing.assert_array_equal(np.sort(idx, axis=1), np.sort(want_idx, axis=1))
    np.testing.assert_allclose(
        dists, np.sqrt(np.take_along_axis(d2, idx.astype(int), axis=1)),
        atol=1e-5,
    )
    assert idx[:, 0].tolist() == list(range(32))  # self is nearest


def test_spark_exact_knn_transform_distributed(rng, mesh8):
    from spark_rapids_ml_tpu.spark.estimator import SparkNearestNeighbors

    n, d, k = 400, 8, 3
    x = rng.normal(size=(n, d)).astype(np.float64)
    df = simdf_from_numpy(x, n_partitions=3)
    model = SparkNearestNeighbors().setK(k).fit(df)
    qdf = simdf_from_numpy(x[:40], n_partitions=2)
    rows = model.transform(qdf).collect()
    assert len(rows) == 40
    idx = np.asarray([r["knn_indices"] for r in rows])
    assert idx.shape == (40, k)
    np.testing.assert_array_equal(idx[:, 0], np.arange(40))


def test_spark_ann_daemon_fed_build_and_query(rng, mesh8):
    """IVF build runs on the daemon (device quantizer + bucketize); the
    driver sees only O(1) stats; queries via the daemon reach high recall
    on clustered data."""
    from spark_rapids_ml_tpu.spark.estimator import SparkApproximateNearestNeighbors

    kc, d, k = 12, 16, 5
    centers = rng.normal(size=(kc, d)) * 10
    x = np.concatenate(
        [c + rng.normal(size=(80, d)) for c in centers]
    ).astype(np.float32)
    df = simdf_from_numpy(x, n_partitions=4)
    model = (
        SparkApproximateNearestNeighbors()
        .setK(k).setNlist(kc).setNprobe(kc)  # probe all: recall -> ~1
        .fit(df)
    )
    assert df.sparkSession.driver_rows_materialized == 0
    assert model.numRows == x.shape[0]
    q = x[:64]
    dists, idx = model.kneighbors(q)
    d2 = ((q[:, None, :].astype(np.float64) - x[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d2, axis=1, kind="stable")[:, :k]
    recall = np.mean(
        [len(set(idx[i]) & set(want[i])) / k for i in range(len(q))]
    )
    assert recall > 0.95
    # distributed query path returns the same columns
    qdf = simdf_from_numpy(q, n_partitions=2)
    rows = model.transform(qdf).collect()
    got = np.asarray([r["knn_indices"] for r in rows])
    np.testing.assert_array_equal(got, idx)


def test_spark_ann_daemon_cosine_metric(rng, mesh8):
    """The daemon-side IVF build must honor metric='cosine': rows are
    unit-normalized before the device build and queries normalize at
    serve time, so returned neighbors match brute-force cosine."""
    from spark_rapids_ml_tpu.spark.estimator import SparkApproximateNearestNeighbors

    kc, d, k = 8, 12, 5
    dirs = rng.normal(size=(kc, d))
    x = np.concatenate(
        [dr * rng.uniform(0.5, 3.0, size=(60, 1)) + 0.03 * rng.normal(size=(60, d)) for dr in dirs]
    ).astype(np.float32)
    df = simdf_from_numpy(x, n_partitions=3)
    model = (
        SparkApproximateNearestNeighbors()
        .setK(k).setNlist(kc).setNprobe(kc).setMetric("cosine")
        .fit(df)
    )
    q = x[:24]
    dists, idx = model.kneighbors(q)
    xn = x / np.linalg.norm(x, axis=1, keepdims=True)
    qn = q / np.linalg.norm(q, axis=1, keepdims=True)
    cos_d = 1.0 - qn @ xn.T
    want = np.argsort(cos_d, axis=1, kind="stable")[:, :k]
    recall = np.mean(
        [len(set(idx[i]) & set(want[i])) / k for i in range(len(q))]
    )
    assert recall > 0.9, recall
    assert np.all(dists[np.isfinite(dists)] <= 2 + 1e-5)


def test_spark_knn_fit_survives_task_retry(rng, mesh8):
    """Row blocks stage per (partition, attempt); a mid-partition death
    must not duplicate or lose rows."""
    from spark_rapids_ml_tpu.spark.estimator import SparkNearestNeighbors

    n, d, k = 300, 6, 4
    x = rng.normal(size=(n, d))
    clean = simdf_from_numpy(x, n_partitions=3)
    m1 = SparkNearestNeighbors().setK(k).fit(clean)
    flaky = simdf_from_numpy(x, n_partitions=3, fail_plan={1: [1]})
    m2 = SparkNearestNeighbors().setK(k).fit(flaky)
    q = x[:20]
    d1, i1 = m1.kneighbors(q)
    d2_, i2 = m2.kneighbors(q)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(d1, d2_, atol=0)


def test_spark_logreg_multiclass_fit_and_transform(rng, mesh8):
    """3-class labels route the distributed fit through the multinomial
    MM-Newton daemon protocol (n_classes probed with an O(1) Spark job)
    and the served transform returns C-wide probability vectors."""
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_multinomial_stream,
    )

    n, d, C = 600, 6, 3
    x = rng.normal(size=(n, d)).astype(np.float64)
    w = rng.normal(size=(d, C)) * 2
    y = np.argmax(x @ w, axis=1).astype(np.float64)
    df = simdf_from_numpy(x, n_partitions=3, label=y)
    model = (
        SparkLogisticRegression().setRegParam(1e-2).setMaxIter(8).fit(df)
    )
    assert df.sparkSession.driver_rows_materialized == 0
    assert model.coefficients.shape == (C, d)
    assert model.numClasses == C

    def src():
        return iter([(x[i : i + 200], y[i : i + 200]) for i in range(0, n, 200)])

    ref = fit_multinomial_stream(
        src, d, C, reg=1e-2, max_iter=8, tol=1e-6, mesh=mesh8
    )
    np.testing.assert_allclose(model.coefficients, ref.coefficients, atol=1e-6)
    rows = model.transform(df).collect()
    proba = np.asarray([r["probability"] for r in rows])
    pred = np.asarray([r["prediction"] for r in rows])
    assert proba.shape == (n, C)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-9)
    assert (pred == y).mean() > 0.95
