"""LinearRegression normal-equations throughput — BASELINE.json config #4
(Gram-matrix psum; the Criteo-scale shape class, d ≈ 1k dense).

Times the moment-accumulation hot loop (`_normal_eq_stats_fn`: fused
XᵀX / Xᵀy / Σx / Σy / Σy² with psum) on device-resident data — the same
partition-Gram pattern as PCA (SURVEY.md §7.6: "literally the PCA
reduction with an extra Xᵀy psum"). The d×d solve is a fixed cost
amortized over the dataset and excluded (measured in tests).

Baseline: Gram is 2·d² flops/row; A100 at ~110 TFLOP/s → 110e12/(2·1024²)
≈ 52.5e6 rows/s. vs_baseline >= 0.5 matches the north-star "within 2×".

Batches are device-resident bfloat16 (same convention as bench.py's
streaming PCA headline: a production ingest path device_puts the compute
dtype, and an f32-resident batch re-reads 2× the bytes every pass —
measured 20.9 → 14.8 ms/batch at 1M×1024). The fused one-HBM-pass Pallas
stats kernel is on (config use_pallas, linreg_stats_pallas); set
SRML_BENCH_AB_PALLAS=1 to emit a same-run XLA-path arm first.
"""

import os
import sys

if __package__ in (None, ""):  # direct script run: python benchmarks/bench_*.py
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

D = int(os.environ.get("SRML_BENCH_D", 1024))
ROWS = int(os.environ.get("SRML_BENCH_BATCH_ROWS", 1 << 19))  # 524288×1024 = 2.1 GB
REPS = int(os.environ.get("SRML_BENCH_REPS", 16))

A100_ROWS_PER_SEC = 110e12 / (2 * D * D)


def main() -> None:
    from benchmarks import setup_platform

    setup_platform()
    import jax
    import jax.numpy as jnp

    from benchmarks import emit
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.linear_regression import _normal_eq_stats_fn
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")
    config.set("use_pallas", True)

    n_chips = len(jax.devices())
    mesh = make_mesh(model=1)
    x = jax.random.normal(jax.random.key(0), (ROWS, D), dtype=jnp.bfloat16)
    y = jax.random.normal(jax.random.key(1), (ROWS,), dtype=jnp.float32)
    if n_chips > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        y = jax.device_put(y, NamedSharding(mesh, P("data")))
    mask = jnp.ones((ROWS,), dtype=jnp.float32)

    from benchmarks import slope_dt, sync

    def measure(use_pallas: bool) -> float:
        stats = _normal_eq_stats_fn(mesh, "bfloat16", "float32", use_pallas)

        def run(n):
            out = None
            for _ in range(n):
                out = stats(x, y, mask)
            sync(out)  # one sync; calls queue on device
            assert np.isfinite(float(out[5]))
            return out

        run(REPS); run(2 * REPS)
        dts = [slope_dt(run, REPS, 2 * REPS, warm=False) for _ in range(5)]
        return float(np.median(dts))

    if os.environ.get("SRML_BENCH_AB_PALLAS"):
        dt0 = measure(False)
        emit(
            f"linreg_ab_xla_rows_per_sec_per_chip_d{D}",
            ROWS / dt0 / n_chips, "rows/s/chip",
            (ROWS / dt0 / n_chips) / A100_ROWS_PER_SEC,
        )
    dt = measure(True)
    emit(
        f"linreg_normal_eq_rows_per_sec_per_chip_d{D}",
        ROWS / dt / n_chips,
        "rows/s/chip",
        (ROWS / dt / n_chips) / A100_ROWS_PER_SEC,
    )


if __name__ == "__main__":
    main()
