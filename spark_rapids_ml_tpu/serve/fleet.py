"""Fleet control plane: replicated models + zero-downtime version rollout.

serve/router.py routes requests; this module manages WHAT they route to:
one model registered as versioned replicas on N daemons, and the
register → warm → flip → drain sequence that swaps a live model version
without dropping a request (ROADMAP item 3; docs/protocol.md "Fleet &
versioned serving").

The lifecycle of one rollout, v1 → v2:

1. **register v2** under its versioned daemon name (``model@v2`` — the
   routing table's ``reg_name`` convention) on every live replica. v1
   keeps serving untouched; a replica that fails registration is marked
   dead (the router already skips it) and the rollout proceeds with the
   rest — a fleet with one dead member must still be upgradeable.
2. **warm** each registration through the PR 5/7 warmup ladder (the
   ``warmup`` wire op; with ``serve_warmup_on_register`` the daemon did
   it inside the registration ack already and this pass is a no-op),
   so the first routed v2 request is a dispatch, not a jit compile.
3. **atomically flip**: one ``RoutingTable.activate`` call moves the
   active version and bumps the fleet epoch. Requests that snapshotted
   before the flip finish on v1 (their pinned version); requests after
   it route to v2. No request ever sees a mixed state: the snapshot is
   one lock-protected read, and the versioned daemon names make
   cross-version answers structurally impossible.
4. **drain v1**: wait (``fleet_drain_timeout_s``) for the in-flight v1
   refcount to reach zero, then ``drop_model`` v1 everywhere and retire
   it from the table. A drain timeout leaves v1 registered (and says
   so) rather than yanking arrays out from under a live request.

``ModelFleet`` is the driver/operator-side object; it is single-threaded
like the admin clients it holds. Serving traffic goes through
``fleet.client()`` — one :class:`~.router.FleetClient` per worker
thread, all sharing this fleet's routing table and health view.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from spark_rapids_ml_tpu.serve import protocol
from spark_rapids_ml_tpu.serve.client import DataPlaneClient
from spark_rapids_ml_tpu.serve.daemon import _model_width
from spark_rapids_ml_tpu.serve.router import FleetClient, RoutingTable
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.logging import get_logger

logger = get_logger("serve.fleet")

__all__ = ["ModelFleet", "FleetRolloutError"]

#: Fleet control-plane telemetry (docs/observability.md).
_M_REPLICAS = metrics_mod.gauge(
    "srml_fleet_replicas",
    "Replicas serving a model's active version, by model (set at "
    "register/rollout time)",
)
_M_EPOCH = metrics_mod.gauge(
    "srml_fleet_version_epoch",
    "The fleet routing epoch, by model (bumps on every version flip)",
)
_M_REGISTRATIONS = metrics_mod.counter(
    "srml_fleet_registrations_total",
    "Per-replica version registrations, by outcome (ok|error)",
)
_M_ROLLOUTS = metrics_mod.counter(
    "srml_fleet_rollouts_total",
    "Version rollouts, by outcome (ok|partial — some replica failed "
    "registration and was routed around)",
)
_M_DRAINS = metrics_mod.counter(
    "srml_fleet_drains_total",
    "Retired-version drains, by outcome (drained|timeout)",
)


class FleetRolloutError(RuntimeError):
    """No replica accepted the new version — the rollout did NOT flip;
    the old version keeps serving."""




class ModelFleet:
    """Replicated versioned model serving across N daemons.

    ``endpoints``: ``[(host, port)]`` (or ``"host:port"`` strings) of
    the replica daemons. All replicas are equals — there is no primary;
    the consistent-hash ring (router.py) spreads models and traffic.
    """

    def __init__(
        self,
        endpoints,
        token: Optional[str] = None,
        vnodes: Optional[int] = None,
        client_kwargs: Optional[Dict[str, Any]] = None,
    ):
        self._table = RoutingTable(endpoints, vnodes=vnodes)
        self._token = token
        # Admin-op client settings: fail a dead replica in seconds (it
        # gets marked dead and routed around), don't heal for minutes.
        kw: Dict[str, Any] = {
            "timeout": 10.0, "op_deadline_s": 20.0, "max_op_attempts": 2,
        }
        kw.update(client_kwargs or {})
        self._client_kwargs = kw
        self._clients: Dict[str, DataPlaneClient] = {}
        self._lock = threading.Lock()  # serializes admin ops per fleet

    # -- lifecycle ---------------------------------------------------------

    @property
    def table(self) -> RoutingTable:
        return self._table

    def close(self) -> None:
        for c in self._clients.values():
            c.close()
        self._clients.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def client(self, **kwargs) -> FleetClient:
        """A routing client sharing this fleet's table and health view.
        One per worker thread (FleetClient is single-threaded)."""
        kwargs.setdefault("token", self._token)
        return FleetClient(self._table, **kwargs)

    def _client(self, key: str) -> DataPlaneClient:
        c = self._clients.get(key)
        if c is None:
            r = self._table.replica(key)
            c = DataPlaneClient(
                r.host, r.port, token=self._token, **self._client_kwargs
            )
            self._clients[key] = c
        return c

    # -- registration + rollout --------------------------------------------

    def _register_on_replicas(
        self, model: str, version: int, algo: str,
        arrays: Dict[str, np.ndarray], params: Dict[str, Any],
        warm: bool,
    ) -> Dict[str, List[str]]:
        """Register (and optionally warm) one version on every replica.
        Returns {"ok": [replica keys], "failed": [replica keys]}; failed
        replicas are marked dead so the router skips them."""
        reg_name = self._table.reg_name(model, version)
        # The daemon's own registration-width rule (ONE copy — a drifted
        # mirror here would silently skip the warmup for an algo whose
        # payload key changed); None skips the eager warmup.
        width = _model_width(algo, arrays)
        ok: List[str] = []
        failed: List[str] = []
        for r in self._table.replicas():
            try:
                c = self._client(r.key)
                c.ensure_model(
                    reg_name, algo, arrays, params=params, version=version,
                )
                if warm and width is not None:
                    # The PR 5/7 bucket-ladder pre-compile. On a daemon
                    # that already warmed inside ensure_model
                    # (serve_warmup_on_register) this reports compiled=0;
                    # with batching disabled it is an honest no-op.
                    c.warmup(reg_name, n_cols=width, dtype="float32")
                self._table.mark_alive(r.key)
                _M_REGISTRATIONS.inc(outcome="ok")
                ok.append(r.key)
            except (OSError, protocol.ProtocolError, RuntimeError) as e:
                _M_REGISTRATIONS.inc(outcome="error")
                self._table.mark_dead(
                    r.key, f"registration of {reg_name} failed: {e}",
                    recheck_s=1.0,
                )
                logger.warning(
                    "replica %s failed %s v%d registration (marked dead, "
                    "routing around it): %s", r.key, model, version, e,
                )
                failed.append(r.key)
        return {"ok": ok, "failed": failed}

    def register(
        self,
        model: str,
        algo: str,
        arrays: Dict[str, np.ndarray],
        params: Optional[Dict[str, Any]] = None,
        version: int = 1,
        warm: bool = True,
    ) -> Dict[str, Any]:
        """Register a model's FIRST served version on every replica and
        activate it. Returns ``{"version", "epoch", "replicas",
        "failed"}``. Raises :class:`FleetRolloutError` when no replica
        accepted it (the table stays without an active version)."""
        with self._lock:
            version = int(version)
            self._table.install(model, version, algo, arrays, params)
            res = self._register_on_replicas(
                model, version, algo, arrays, dict(params or {}), warm
            )
            if not res["ok"]:
                self._table.retire(model, version)
                raise FleetRolloutError(
                    f"no replica accepted {model!r} v{version} "
                    f"({len(res['failed'])} failed)"
                )
            epoch = self._table.activate(model, version)
            _M_REPLICAS.set(len(res["ok"]), model=model)
            _M_EPOCH.set(epoch, model=model)
            return {
                "version": version, "epoch": epoch,
                "replicas": len(res["ok"]), "failed": res["failed"],
            }

    def rollout(
        self,
        model: str,
        algo: str,
        arrays: Dict[str, np.ndarray],
        params: Optional[Dict[str, Any]] = None,
        version: Optional[int] = None,
        warm: bool = True,
        drain_timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Zero-downtime version swap (module docstring): register the
        next version everywhere, warm it, atomically flip, drain and
        drop the old one. Returns ``{"version", "previous", "epoch",
        "replicas", "failed", "drained"}``."""
        from spark_rapids_ml_tpu import config

        with self._lock:
            old_v, _, old_reg = self._table.snapshot(model)
            new_v = int(version) if version is not None else old_v + 1
            if new_v == old_v:
                raise ValueError(
                    f"rollout version {new_v} is already the active "
                    f"version of {model!r}"
                )
            self._table.install(model, new_v, algo, arrays, params)
            res = self._register_on_replicas(
                model, new_v, algo, arrays, dict(params or {}), warm
            )
            if not res["ok"]:
                # Nothing flipped: v_old keeps serving, the failed
                # install is retired so a retry starts clean.
                self._table.retire(model, new_v)
                _M_ROLLOUTS.inc(outcome="error")
                raise FleetRolloutError(
                    f"no replica accepted {model!r} v{new_v}; "
                    f"v{old_v} keeps serving"
                )
            # THE flip: one atomic table write. Every request from here
            # snapshots v_new; every in-flight request keeps its v_old
            # pin and its v_old daemon registration.
            epoch = self._table.activate(model, new_v)
            _M_REPLICAS.set(len(res["ok"]), model=model)
            _M_EPOCH.set(epoch, model=model)
            _M_ROLLOUTS.inc(outcome="ok" if not res["failed"] else "partial")
            logger.info(
                "flipped %s to v%d (epoch %d) on %d replica(s)",
                model, new_v, epoch, len(res["ok"]),
            )
            # Drain: let pinned v_old requests finish before their
            # arrays are dropped. A timeout leaves v_old registered —
            # stale registrations cost memory, yanked arrays cost
            # correctness.
            timeout = float(
                config.get("fleet_drain_timeout_s")
                if drain_timeout_s is None else drain_timeout_s
            )
            drained = self._table.wait_drained(model, old_v, timeout)
            _M_DRAINS.inc(outcome="drained" if drained else "timeout")
            if drained:
                for r in self._table.replicas():
                    try:
                        self._client(r.key).drop_model(old_reg)
                    except (OSError, protocol.ProtocolError, RuntimeError):
                        pass  # dead replica: its registry died with it
                self._table.retire(model, old_v)
            else:
                logger.warning(
                    "drain of %s v%d timed out after %.1fs with %d "
                    "request(s) in flight; its registrations stay up",
                    model, old_v, timeout,
                    self._table.inflight(model, old_v),
                )
            return {
                "version": new_v, "previous": old_v, "epoch": epoch,
                "replicas": len(res["ok"]), "failed": res["failed"],
                "drained": drained,
            }

    # -- elastic membership (serve/autoscaler.py drives these) --------------

    def scale_out(self, endpoint, warm: bool = True) -> Dict[str, Any]:
        """Admit a new replica daemon into the fleet: register AND warm
        every model's ACTIVE version on it first, then add it to the
        ring — admission is the flip (router.RoutingTable.add_replica),
        so the first request routed to the newcomer finds a warm
        registration. The payloads come from the routing table's
        version entries (the same source the in-band repair uses); a
        newcomer that fails any registration is NOT admitted."""
        if isinstance(endpoint, str):
            host, _, port = endpoint.rpartition(":")
            host, port = host or "127.0.0.1", int(port)
        else:
            host, port = endpoint[0], int(endpoint[1])
        key = f"{host}:{port}"
        with self._lock:
            seeded: List[str] = []
            c = DataPlaneClient(
                host, port, token=self._token, **self._client_kwargs
            )
            try:
                for model in self._table.models():
                    v, _, reg_name = self._table.snapshot(model)
                    info = self._table.version_info(model, v)
                    c.ensure_model(
                        reg_name, info["algo"], info["arrays"],
                        params=info["params"], version=v,
                    )
                    width = _model_width(info["algo"], info["arrays"])
                    if warm and width is not None:
                        c.warmup(reg_name, n_cols=width, dtype="float32")
                    _M_REGISTRATIONS.inc(outcome="ok")
                    seeded.append(model)
            except (OSError, protocol.ProtocolError, RuntimeError) as e:
                _M_REGISTRATIONS.inc(outcome="error")
                c.close()
                raise FleetRolloutError(
                    f"replica {key} failed pre-admission seeding of "
                    f"{model!r} — not admitted: {e}"
                ) from e
            self._table.add_replica((host, port))
            self._clients[key] = c
            n = len(self._table.replicas())
            for model in seeded:
                _M_REPLICAS.set(n, model=model)
            logger.info(
                "scaled OUT: replica %s admitted with %d model(s) "
                "seeded and warm (%d replicas in the ring)",
                key, len(seeded), n,
            )
            return {"replica": key, "models": seeded, "replicas": n}

    def scale_in(
        self,
        key: Optional[str] = None,
        drain_timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Retire one replica without dropping a request: remove it
        from the ring (no NEW request routes to it), then roll every
        active model forward one version on the REMAINING replicas —
        the rollout's drain barrier waits out every request pinned to
        the old version, including those in flight on the victim, and
        only then drops the old registrations. Returns ``{"replica",
        "drained", "rollouts"}``; ``drained=False`` means some pinned
        request outlived the timeout — the victim daemon must stay UP
        until a later drain finishes (stopping it would be the dropped
        request the barrier exists to prevent).

        With no ``key`` the least-loaded live replica is chosen."""
        if key is None:
            live = [r for r in self._table.replicas() if r.alive]
            if not live:
                raise ValueError("no live replica to scale in")
            key = min(live, key=lambda r: (r.load(), r.key)).key
        self._table.remove_replica(key)
        rollouts: Dict[str, Any] = {}
        drained = True
        for model in self._table.models():
            v, _, _ = self._table.snapshot(model)
            info = self._table.version_info(model, v)
            res = self.rollout(
                model, info["algo"], info["arrays"],
                params=info["params"], drain_timeout_s=drain_timeout_s,
            )
            rollouts[model] = res
            drained = drained and bool(res["drained"])
        with self._lock:
            c = self._clients.pop(key, None)
            if c is not None:
                c.close()
            n = len(self._table.replicas())
        logger.info(
            "scaled IN: replica %s retired (%d replicas remain; "
            "drained=%s)", key, n, drained,
        )
        return {
            "replica": key, "drained": drained, "rollouts": rollouts,
            "replicas": n,
        }

    # -- observability ------------------------------------------------------

    def status(self, model: Optional[str] = None) -> Dict[str, Any]:
        """Operator view: per-replica liveness/health plus (with
        ``model``) which replicas hold the active version's
        registration. Polls health live; a dead replica reports its
        last error instead."""
        versions: Dict[str, Any] = {}
        reg_name = None
        if model is not None:
            try:
                v, e, reg_name = self._table.snapshot(model)
                versions = {
                    "active": v, "epoch": e,
                    "installed": self._table.versions(model),
                }
            except KeyError:
                versions = {"active": None, "epoch": 0, "installed": []}
        replicas = {}
        for r in self._table.replicas():
            entry: Dict[str, Any] = {"alive": r.alive}
            try:
                h = self._client(r.key).health()
                self._table.mark_alive(r.key, h)
                entry["alive"] = True
                entry["health"] = {
                    k: h.get(k) for k in
                    ("id", "boot_id", "queue_depth", "served_models", "busy")
                }
                if reg_name is not None:
                    entry["has_active_version"] = bool(
                        self._client(r.key).model_exists(reg_name)
                    )
            except (OSError, protocol.ProtocolError, RuntimeError) as e:
                self._table.mark_dead(r.key, str(e), recheck_s=1.0)
                entry["alive"] = False
                entry["error"] = str(e)
            replicas[r.key] = entry
        out: Dict[str, Any] = {"replicas": replicas}
        if model is not None:
            out["model"] = {"name": model, **versions}
        return out
