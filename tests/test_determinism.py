"""Run-to-run determinism: the TPU-world substitute for sanitizers.

The reference's only concurrency-safety mechanism is per-thread default
CUDA streams (SURVEY.md §5 "race detection": compile flag
``CUDA_API_PER_THREAD_DEFAULT_STREAM``); on TPU, XLA owns ordering, so the
corresponding guarantee to pin down is bitwise run-to-run determinism of
every fit — two identical calls must produce identical bits, including
across the collective (psum/all_gather/ppermute) paths on the 8-device
mesh. A nondeterministic reduction order would show up here first.
"""

import numpy as np

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.models.kmeans import fit_kmeans
from spark_rapids_ml_tpu.models.knn import build_ivf_flat, _ivf_query_fn
from spark_rapids_ml_tpu.models.linear_regression import fit_linear_regression
from spark_rapids_ml_tpu.models.logistic_regression import fit_logistic_regression
from spark_rapids_ml_tpu.models.pca import fit_pca


def _bits(a) -> bytes:
    return np.ascontiguousarray(np.asarray(a)).tobytes()


def test_pca_bitwise_deterministic(rng, mesh8):
    x = rng.normal(size=(500, 24))
    a = fit_pca(x, k=4, mesh=mesh8)
    b = fit_pca(x, k=4, mesh=mesh8)
    assert _bits(a.pc) == _bits(b.pc)
    assert _bits(a.explained_variance) == _bits(b.explained_variance)


def test_pca_ring_bitwise_deterministic(rng):
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(data=4, model=2)
    x = rng.normal(size=(512, 32))
    with config.option("gram_algorithm", "ring"):
        a = fit_pca(x, k=4, mesh=mesh)
        b = fit_pca(x, k=4, mesh=mesh)
    assert _bits(a.pc) == _bits(b.pc)


def test_kmeans_bitwise_deterministic(rng, mesh8):
    x = rng.normal(size=(640, 16))
    a = fit_kmeans(x, k=5, max_iter=10, seed=3, mesh=mesh8)
    b = fit_kmeans(x, k=5, max_iter=10, seed=3, mesh=mesh8)
    assert _bits(a.centers) == _bits(b.centers)
    assert a.cost == b.cost and a.n_iter == b.n_iter


def test_linreg_bitwise_deterministic(rng, mesh8):
    x = rng.normal(size=(400, 12))
    y = x @ rng.normal(size=12) + 0.1 * rng.normal(size=400)
    a = fit_linear_regression(x, y, reg=1e-4, mesh=mesh8)
    b = fit_linear_regression(x, y, reg=1e-4, mesh=mesh8)
    assert _bits(a.coefficients) == _bits(b.coefficients)
    assert a.intercept == b.intercept


def test_logreg_bitwise_deterministic(rng, mesh8):
    x = rng.normal(size=(400, 12))
    y = (x @ rng.normal(size=12) > 0).astype(np.float64)
    a = fit_logistic_regression(x, y, reg=1e-3, max_iter=15, mesh=mesh8)
    b = fit_logistic_regression(x, y, reg=1e-3, max_iter=15, mesh=mesh8)
    assert _bits(a.coefficients) == _bits(b.coefficients)


def test_ivf_query_bitwise_deterministic(rng):
    import jax.numpy as jnp

    db = rng.normal(size=(1024, 16)).astype(np.float32)
    queries = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
    index = build_ivf_flat(db, nlist=64, seed=0)
    dev = [
        jnp.asarray(index.centroids, jnp.float32),
        jnp.asarray(index.lists),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
    ]
    q = _ivf_query_fn(10, 8, "float32", "float32", mode="bucketed")
    d1, i1 = q(*dev, queries)
    d2, i2 = q(*dev, queries)
    assert _bits(i1) == _bits(i2)
    assert _bits(d1) == _bits(d2)


def test_index_build_deterministic(rng):
    db = rng.normal(size=(1024, 16)).astype(np.float32)
    a = build_ivf_flat(db, nlist=32, seed=5)
    b = build_ivf_flat(db, nlist=32, seed=5)
    assert _bits(a.centroids) == _bits(b.centroids)
    assert _bits(a.list_ids) == _bits(b.list_ids)
