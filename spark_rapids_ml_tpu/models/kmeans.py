"""KMeans — Lloyd's algorithm as one compiled SPMD program.

Not present in the reference repo (PCA-only), but part of the capability
surface this framework targets (SURVEY.md §0 and BASELINE.json config #3:
"KMeans k=100 on 50M×256, pairwise-dist kernel + centroid allreduce over
ICI"). The architecture reuses the PCA frame (SURVEY.md §7 step 6): a
sharded partition kernel + psum + finalize.

TPU-first design decisions:

* The assignment step is one MXU GEMM (pairwise distances via the Gram
  trick, ops/distances.py), and the update step is another (one-hot
  assignments ᵀ @ points), so the whole Lloyd iteration is GEMM-bound.
* The ENTIRE Lloyd loop runs inside a single ``lax.while_loop`` under
  ``shard_map`` — centroids carry on device, per-iteration psums ride ICI,
  and nothing touches the host until convergence. This is the design the
  reference's per-task JNI-call pattern cannot express (SURVEY.md §3.4).
* Convergence = squared centroid movement ≤ tol², matching Spark MLlib's
  KMeans convergence criterion shape.
* Empty clusters keep their previous centroid (Spark behavior).

Init: "k-means++" on a host-side subsample (the classic D² weighting;
Spark's k-means|| is a distributed approximation of the same thing — for
the sizes where init dominates, the subsample bound keeps it O(sample·k·d)).
"random" picks k distinct rows.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.core.dataset import as_matrix, with_column
from spark_rapids_ml_tpu.core.params import (
    Estimator,
    HasFeaturesCol,
    HasMaxIter,
    HasPredictionCol,
    HasSeed,
    HasTol,
    Model,
    ParamDecl,
    ParamValidators,
    TypeConverters,
)
from spark_rapids_ml_tpu.core.persistence import MLReadable, MLWritable
from spark_rapids_ml_tpu.ops.distances import sq_euclidean
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, default_mesh
from spark_rapids_ml_tpu.parallel import mapreduce as mr
from spark_rapids_ml_tpu.parallel.sharding import pad_rows, shard_rows
from spark_rapids_ml_tpu.utils.profiling import trace_span
from spark_rapids_ml_tpu.parallel.compat import shard_map
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit


class KMeansSolution(NamedTuple):
    centers: np.ndarray  # (k, d)
    cost: float  # sum of squared distances to nearest center (training cost)
    n_iter: int
    n_rows: int


class KMeansSummary(NamedTuple):
    """Spark's KMeansSummary shape: trainingCost + iteration count."""

    trainingCost: float
    numIter: int
    k: int
    n_rows: int


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def _kmeans_plus_plus(x: np.ndarray, k: int, rng: np.random.Generator) -> np.ndarray:
    """Classic k-means++ D² seeding on a host subsample."""
    n = x.shape[0]
    sample = x if n <= 65536 else x[rng.choice(n, 65536, replace=False)]
    m = sample.shape[0]
    centers = np.empty((k, x.shape[1]), dtype=np.float64)
    centers[0] = sample[rng.integers(m)]
    d2 = np.sum((sample - centers[0]) ** 2, axis=1)
    for i in range(1, k):
        total = d2.sum()
        if total <= 0:
            centers[i:] = sample[rng.integers(m, size=k - i)]
            break
        probs = d2 / total
        centers[i] = sample[rng.choice(m, p=probs)]
        d2 = np.minimum(d2, np.sum((sample - centers[i]) ** 2, axis=1))
    return centers


def _random_init(x: np.ndarray, k: int, rng: np.random.Generator) -> np.ndarray:
    idx = rng.choice(x.shape[0], size=k, replace=False)
    return np.asarray(x[idx], dtype=np.float64)


# ---------------------------------------------------------------------------
# Lloyd loop (one compiled program)
# ---------------------------------------------------------------------------


def _pallas_assign_applicable(m_local: int, k: int, d: int, cd, use_pallas=None) -> bool:
    """Fused Pallas assignment path: TPU backend, f32, tile-divisible, and a
    feature width whose (block_m, d) tile fits VMEM."""
    from spark_rapids_ml_tpu.ops.gram import _pallas_backend_ok

    if not _pallas_backend_ok(use_pallas):
        return False
    bm = min(1024, m_local)
    bk = min(128, k)
    return (
        jnp.dtype(cd) == jnp.float32
        and d <= 512
        and m_local % bm == 0
        and k % bk == 0
    )


def _lloyd_block_n(m_local: int, d: int, k_pad: int, itemsize: int) -> int:
    """Largest row-block whose full kernel working set fits a conservative
    VMEM budget: double-buffered x tile + d2/onehot intermediates + the
    resident sums accumulator and centers block."""
    from spark_rapids_ml_tpu.ops.pallas_kernels import LLOYD_STEP_BLOCK_N

    for b in (16384, 8192, LLOYD_STEP_BLOCK_N, 2048, 1024, 512, 256, 128):
        if m_local % b:
            continue
        vmem = (
            2 * b * d * itemsize  # double-buffered x tile
            + 2 * b * k_pad * 4  # d2 + onehot f32 intermediates
            + k_pad * d * (4 + itemsize)  # sums accumulator + centers
        )
        if vmem <= 64 * 2**20:
            return b
    return 0


def _pallas_step_applicable(m_local: int, k: int, d: int, cd, use_pallas=None) -> bool:
    """Fused single-HBM-pass Lloyd step (ops/pallas_kernels.lloyd_step_pallas):
    TPU backend, bf16/f32 compute, lane-aligned d, block-divisible rows, and
    a full working set that fits VMEM (per _lloyd_block_n)."""
    from spark_rapids_ml_tpu.ops.gram import _pallas_backend_ok

    if not _pallas_backend_ok(use_pallas):
        return False
    from spark_rapids_ml_tpu.ops.pallas_kernels import _ceil_to

    k_pad = _ceil_to(k, 128)
    cd = jnp.dtype(cd)
    return (
        cd in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32))
        and d % 128 == 0
        and d <= 2048
        and k_pad <= 1024
        and _lloyd_block_n(m_local, d, k_pad, cd.itemsize) > 0
    )


@functools.lru_cache(maxsize=32)
def _lloyd_fn(
    mesh: Mesh, k: int, max_iter: int, tol: float, cd: str, ad: str, use_pallas: bool = False
):
    # `use_pallas` is the builder-time snapshot, threaded to the trace-time
    # gates (never re-read config inside the trace — lru_cache key must
    # match what actually compiled).
    compute_dtype = jnp.dtype(cd)
    accum_dtype = jnp.dtype(ad)
    from spark_rapids_ml_tpu.ops.pallas_kernels import _ceil_to

    k_pad = _ceil_to(k, 128)

    def lloyd_shard(x, mask, centers0):
        # The cast feeds pallas_call inputs, so XLA materializes the bf16
        # copy once before the loop on its own (measured: forcing it with
        # an optimization_barrier is ~20% SLOWER — it pins the layout and
        # defeats a fusion XLA otherwise applies).
        xc = x.astype(compute_dtype)
        maskc = mask.astype(accum_dtype)
        pallas_assign = _pallas_assign_applicable(
            x.shape[0], k, x.shape[1], compute_dtype, use_pallas
        )
        pallas_step = _pallas_step_applicable(
            x.shape[0], k, x.shape[1], compute_dtype, use_pallas
        )
        # Valid rows are a contiguous prefix of each shard (shard_rows pads
        # at the global tail), so the mask collapses to one row count.
        # Integer sum: an f32 sum of ones saturates at 2^24 rows/shard.
        nv_local = jnp.sum(mask.astype(jnp.int32))

        def shard_stats(centers):
            """Per-shard (sums (k, d), counts (k,)) for one Lloyd update."""
            if pallas_step:
                from spark_rapids_ml_tpu.ops.pallas_kernels import lloyd_step_pallas

                cpad = jnp.zeros((k_pad, x.shape[1]), compute_dtype)
                cpad = jax.lax.dynamic_update_slice(
                    cpad, centers.astype(compute_dtype), (0, 0)
                )
                sums, counts = lloyd_step_pallas(
                    xc,
                    cpad,
                    nv_local,
                    k=k,
                    block_n=_lloyd_block_n(
                        x.shape[0], x.shape[1], k_pad, compute_dtype.itemsize
                    ),
                )
                return sums[:k].astype(accum_dtype), counts[:k].astype(accum_dtype)
            assign, _ = _assign_min(centers)
            onehot = (
                jax.nn.one_hot(assign, k, dtype=compute_dtype)
                * maskc[:, None].astype(compute_dtype)
            )
            # (k, d) sums and (k,) counts — both MXU/VPU friendly.
            from spark_rapids_ml_tpu.ops.gram import mm_precision

            with mm_precision(compute_dtype):
                sums = jax.lax.dot_general(
                    onehot, xc, (((0,), (0,)), ((), ())),
                    preferred_element_type=accum_dtype,
                )
            counts = jnp.sum(onehot.astype(accum_dtype), axis=0)
            return sums, counts

        def _assign_min(centers):
            if pallas_assign:
                from spark_rapids_ml_tpu.ops.pallas_kernels import (
                    assign_min_dist_pallas,
                )

                assign, part_d = assign_min_dist_pallas(
                    xc, centers.astype(compute_dtype)
                )
                x2 = jnp.sum(jnp.square(xc.astype(accum_dtype)), axis=1)
                min_d2 = jnp.maximum(part_d + x2, 0.0)
            else:
                d2 = sq_euclidean(
                    xc, centers.astype(compute_dtype), accum_dtype=accum_dtype
                )
                assign = jnp.argmin(d2, axis=1)
                min_d2 = jnp.min(d2, axis=1)
            return assign, min_d2

        def update(centers):
            sums, counts = shard_stats(centers)
            sums = mr.reduce_sum(sums, DATA_AXIS)
            counts = mr.reduce_sum(counts, DATA_AXIS)
            return jnp.where(
                (counts > 0)[:, None], sums / jnp.maximum(counts, 1)[:, None], centers
            )

        def cond(carry):
            _, moved2, it = carry
            return jnp.logical_and(it < max_iter, moved2 > tol * tol)

        def body(carry):
            centers, _, it = carry
            new_centers = update(centers)
            moved2 = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1))
            return new_centers, moved2, it + 1

        centers0 = centers0.astype(accum_dtype)
        init = (centers0, jnp.array(jnp.inf, accum_dtype), 0)
        centers, _, n_iter = jax.lax.while_loop(cond, body, init)
        # Final training cost at the converged centers (one assignment pass;
        # the in-loop fused kernel doesn't materialize distances at all).
        _, min_d2 = _assign_min(centers)
        final_cost = mr.reduce_sum(jnp.sum(min_d2 * maskc), DATA_AXIS)
        return centers, final_cost, n_iter

    f = shard_map(
        lloyd_shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P()),
        out_specs=(P(), P(), P()),
        # pallas_call outputs carry no VMA annotation (same as ops/gram.py).
        check_vma=False,
    )
    return ledgered_jit("kmeans.lloyd", f)


def fit_kmeans(
    x: np.ndarray,
    k: int,
    max_iter: int = 20,
    tol: float = 1e-4,
    seed: int = 0,
    init: str = "k-means++",
    mesh: Optional[Mesh] = None,
) -> KMeansSolution:
    from spark_rapids_ml_tpu.parallel.sharding import require_single_process

    require_single_process("fit_kmeans (k-means++/random init samples local data)")
    mesh = mesh or default_mesh()
    x = np.asarray(x)
    n, d = x.shape
    if not 0 < k <= n:
        raise ValueError(f"k = {k} out of range (0, numRows = {n}]")
    rng = np.random.default_rng(seed)
    with trace_span("kmeans init"):
        if init == "k-means++":
            centers0 = _kmeans_plus_plus(x, k, rng)
        elif init == "random":
            centers0 = _random_init(x, k, rng)
        else:
            raise ValueError(f"unknown init mode {init!r} (k-means++|random)")
    with trace_span("lloyd"):
        xs, mask, n_true = shard_rows(x, mesh)
        fn = _lloyd_fn(
            mesh,
            k,
            max_iter,
            float(tol),
            config.get("compute_dtype"),
            config.get("accum_dtype"),
            use_pallas=bool(config.get("use_pallas")),
        )
        centers, cost, n_iter = jax.device_get(
            fn(xs, mask, jnp.asarray(centers0))
        )
    return KMeansSolution(
        centers=np.asarray(centers, dtype=np.float64),
        cost=float(cost),
        n_iter=int(n_iter),
        n_rows=n_true,
    )


# ---------------------------------------------------------------------------
# Streaming (out-of-HBM) Lloyd: one host scan per iteration
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _stream_step_fn(mesh: Mesh, k: int, cd: str, ad: str):
    """Jitted donated accumulate of one batch's Lloyd statistics at fixed
    centers: (state, centers, x, mask) -> state with
    state = (sums (k, d), counts (k,), cost ()).

    Uses the XLA assign path (not the fused Pallas step): streaming batches
    are modest, and materializing (batch, k) distances buys the running
    cost for free — convergence monitoring the fused kernel can't provide.
    """
    compute_dtype = jnp.dtype(cd)
    accum_dtype = jnp.dtype(ad)

    def shard(sums, counts, cost, centers, x, mask):
        from spark_rapids_ml_tpu.ops.gram import mm_precision

        xc = x.astype(compute_dtype)
        maskc = mask.astype(accum_dtype)
        d2 = sq_euclidean(
            xc, centers.astype(compute_dtype), accum_dtype=accum_dtype
        )
        assign = jnp.argmin(d2, axis=1)
        min_d2 = jnp.min(d2, axis=1)
        onehot = (
            jax.nn.one_hot(assign, k, dtype=compute_dtype)
            * maskc[:, None].astype(compute_dtype)
        )
        with mm_precision(compute_dtype):
            bs = jax.lax.dot_general(
                onehot, xc, (((0,), (0,)), ((), ())),
                preferred_element_type=accum_dtype,
            )
        bc = jnp.sum(onehot.astype(accum_dtype), axis=0)
        bcost = jnp.sum(min_d2 * maskc)
        return (
            sums + mr.reduce_sum(bs, DATA_AXIS),
            counts + mr.reduce_sum(bc, DATA_AXIS),
            cost + mr.reduce_sum(bcost, DATA_AXIS),
        )

    f = shard_map(
        shard,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P(), P()),
    )

    @functools.partial(ledgered_jit, "kmeans.streaming_update", donate_argnums=(0,))
    def update(state, centers, x, mask):
        return f(state[0], state[1], state[2], centers, x, mask)

    return update


def stream_zero_state(k: int, n_cols: int, accum_dtype) -> tuple:
    """Zero (sums, counts, cost) accumulator for one Lloyd pass — shared by
    fit_kmeans_stream and the data-plane daemon's iterative kmeans job."""
    ad = jnp.dtype(accum_dtype)
    return (
        jnp.zeros((k, n_cols), ad),
        jnp.zeros((k,), ad),
        jnp.zeros((), ad),
    )


def apply_lloyd_update(sums, counts, centers):
    """One Lloyd center update from a full pass's statistics.

    Empty clusters keep their previous centroid (Spark behavior). Returns
    (new_centers, moved² max over centers) — the single source of the
    update rule for both the in-process stream fit and the daemon.
    """
    new_centers = jnp.where(
        (counts > 0)[:, None], sums / jnp.maximum(counts, 1)[:, None], centers
    )
    moved2 = jnp.max(jnp.sum((new_centers - centers) ** 2, axis=1))
    return new_centers, moved2


def fit_kmeans_stream(
    batch_source,
    k: int,
    n_cols: int,
    max_iter: int = 20,
    tol: float = 1e-4,
    seed: int = 0,
    init: str = "k-means++",
    mesh: Optional[Mesh] = None,
    checkpoint_path: Optional[str] = None,
    init_sample_rows: int = 65536,
) -> KMeansSolution:
    """Lloyd's algorithm over a re-scannable stream of host row-batches —
    the capacity path for datasets ≫ HBM (BASELINE.json config #3:
    50M×256 is 51 GB f32, beyond a single chip).

    ``batch_source`` is a CALLABLE returning a fresh iterator of (rows, d)
    arrays; each Lloyd iteration consumes one full scan (that re-scan
    requirement is what distinguishes iterative streaming from the
    single-pass PCA/LinReg accumulators). Per batch, assignment +
    centroid-partials run sharded on device and fold into a donated (k, d)
    accumulator; only the (k, d) centers live across scans. One extra scan
    at the end computes the exact training cost at the final centers
    (Spark ``summary.trainingCost`` semantics, matching the in-memory fit).

    With ``checkpoint_path``, centers are persisted after every iteration
    and an interrupted fit resumes at the saved iteration (the
    preemption-safety gap noted in SURVEY.md §5 "failure detection").

    **Multi-host** (``jax.process_count() > 1``): ``batch_source`` yields
    THIS process's local stream; scans run in lockstep
    (``lockstep_batches`` — uneven stream lengths are fine) and the init
    sample is assembled from every host's stream head (allgathered, f32),
    so all processes compute identical centers. Checkpoints are written
    by process 0 and must be on a shared filesystem to resume.
    """
    from spark_rapids_ml_tpu.core import checkpoint as ckpt
    from spark_rapids_ml_tpu.parallel.sharding import lockstep_batches

    multiproc = jax.process_count() > 1
    if k <= 0:
        raise ValueError(f"k = {k} must be > 0")
    if init not in ("k-means++", "random"):
        raise ValueError(f"unknown init mode {init!r} (k-means++|random)")
    mesh = mesh or default_mesh()
    cd, ad = config.get("compute_dtype"), config.get("accum_dtype")
    update = _stream_step_fn(mesh, k, cd, ad)
    accum_dtype = jnp.dtype(ad)

    start_iter = 0
    centers = None
    restored = ckpt.load_state(checkpoint_path) if checkpoint_path else None
    if checkpoint_path:
        ckpt.require_consistent_visibility(restored)
    if restored is not None:
        arrays, meta = restored
        if meta.get("n_cols") != n_cols or meta.get("k") != k:
            raise ValueError(
                f"checkpoint at {checkpoint_path} is for k="
                f"{meta.get('k')}, n_cols={meta.get('n_cols')}, not ({k}, {n_cols})"
            )
        centers = np.asarray(arrays["centers"])
        start_iter = int(meta["it"])
    if centers is None:
        # Init on a bounded host sample drawn from the stream's head —
        # multi-host: every host contributes its share and the allgathered
        # global sample makes all processes compute IDENTICAL centers.
        rng = np.random.default_rng(seed)
        per = (
            -(-init_sample_rows // jax.process_count())
            if multiproc
            else init_sample_rows
        )
        head = []
        got = 0
        for batch in batch_source():
            head.append(np.asarray(batch))
            got += head[-1].shape[0]
            if got >= per:
                break
        local = (
            np.concatenate(head)[:per].astype(np.float32)
            if head
            else np.zeros((0, n_cols), np.float32)
        )
        if multiproc:
            from jax.experimental import multihost_utils as mhu

            counts = np.asarray(
                mhu.process_allgather(np.asarray([local.shape[0]]))
            ).reshape(-1)
            buf = np.zeros((per, n_cols), np.float32)
            buf[: local.shape[0]] = local
            gathered = np.asarray(mhu.process_allgather(buf))
            sample = np.concatenate(
                [gathered[p, : counts[p]] for p in range(len(counts))]
            )
        else:
            sample = local
        if sample.shape[0] == 0:
            raise ValueError("batch_source yielded no batches")
        if k > sample.shape[0]:
            raise ValueError(
                f"k = {k} exceeds the {sample.shape[0]}-row init sample; "
                f"raise init_sample_rows"
            )
        with trace_span("kmeans init"):
            centers = (
                _kmeans_plus_plus(sample, k, rng)
                if init == "k-means++"
                else _random_init(sample, k, rng)
            )

    def scan(centers_dev):
        state = stream_zero_state(k, n_cols, accum_dtype)
        n_rows = 0
        for batch in lockstep_batches(batch_source(), n_cols):
            # shard_rows pads, casts f64→f32 via the threaded native bridge
            # (halving host→device bytes for f64 sources), and places;
            # multi-process it assembles the global array from local rows.
            xs, ms, n_b = shard_rows(np.asarray(batch), mesh, dtype=np.float32)
            n_rows += n_b
            state = update(state, centers_dev, xs, ms)
        return state, n_rows

    n_true = 0
    n_iter = start_iter
    centers_dev = jnp.asarray(centers, accum_dtype)
    with trace_span("lloyd-stream"):
        for it in range(start_iter, max_iter):
            (sums, counts, _), n_true = scan(centers_dev)
            centers_dev, moved2 = apply_lloyd_update(sums, counts, centers_dev)
            moved2 = float(moved2)
            n_iter = it + 1
            if checkpoint_path and (not multiproc or jax.process_index() == 0):
                ckpt.save_state(
                    checkpoint_path,
                    {"centers": np.asarray(jax.device_get(centers_dev))},
                    {"it": n_iter, "k": k, "n_cols": n_cols},
                )
            if moved2 <= float(tol) ** 2:
                break
        # Exact cost at the final centers (one cost-only scan).
        (_, _, cost), n_true = scan(centers_dev)
    if checkpoint_path and (not multiproc or jax.process_index() == 0):
        import os

        if os.path.exists(checkpoint_path):
            os.unlink(checkpoint_path)
    return KMeansSolution(
        centers=np.asarray(jax.device_get(centers_dev), dtype=np.float64),
        cost=float(cost),
        n_iter=n_iter,
        n_rows=n_true,
    )


# ---------------------------------------------------------------------------
# Estimator / Model
# ---------------------------------------------------------------------------


class _KMeansParams(HasFeaturesCol, HasPredictionCol, HasMaxIter, HasTol, HasSeed):
    k = ParamDecl(
        "k",
        "number of clusters (> 0)",
        TypeConverters.toInt,
        validator=ParamValidators.gt(0),
    )
    initMode = ParamDecl(
        "initMode",
        "initialization: k-means++ | random",
        TypeConverters.toString,
        validator=ParamValidators.inList(["k-means++", "random"]),
    )

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(
            k=2,
            maxIter=20,
            tol=1e-4,
            seed=0,
            initMode="k-means++",
            featuresCol="features",
            predictionCol="prediction",
        )

    def getK(self) -> int:
        return self.getOrDefault(self.k)

    def getInitMode(self) -> str:
        return self.getOrDefault(self.initMode)


class KMeans(Estimator, _KMeansParams, MLWritable, MLReadable):
    """``KMeans().setK(100).fit(df)`` — Spark ML clustering API shape."""

    _uid_prefix = "KMeans"

    def __init__(self, uid=None, mesh: Optional[Mesh] = None):
        super().__init__(uid=uid)
        self._mesh = mesh

    def setK(self, value: int) -> "KMeans":
        return self._set(k=value)

    def setInitMode(self, value: str) -> "KMeans":
        return self._set(initMode=value)

    def _copy_extra_state(self, source):
        self._mesh = getattr(source, "_mesh", None)

    def _fit(self, dataset) -> "KMeansModel":
        x = as_matrix(dataset, self.getFeaturesCol())
        sol = fit_kmeans(
            x,
            k=self.getK(),
            max_iter=self.getMaxIter(),
            tol=self.getTol(),
            seed=self.getSeed(),
            init=self.getInitMode(),
            mesh=self._mesh,
        )
        model = KMeansModel(centers=sol.centers)
        model.uid = self.uid
        model._training_cost = sol.cost
        model._n_iter = sol.n_iter
        model._summary = KMeansSummary(
            trainingCost=sol.cost, numIter=sol.n_iter, k=self.getK(), n_rows=sol.n_rows
        )
        self._copy_params_to(model)
        return model


class KMeansModel(Model, _KMeansParams, MLWritable, MLReadable):
    """Fitted centers + predict(); ``summary.trainingCost`` equivalent."""

    _uid_prefix = "KMeansModel"

    def __init__(self, centers: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid=uid)
        self.centers = None if centers is None else np.asarray(centers)
        self._training_cost: Optional[float] = None
        self._n_iter: Optional[int] = None
        self._summary: Optional[KMeansSummary] = None
        self._predict_cache: dict = {}

    @property
    def summary(self) -> Optional[KMeansSummary]:
        return self._summary

    @property
    def hasSummary(self) -> bool:
        return self._summary is not None

    def clusterCenters(self) -> np.ndarray:
        return self.centers

    @property
    def trainingCost(self) -> Optional[float]:
        return self._training_cost

    def _model_data(self):
        return {"clusterCenters": self.centers}

    @classmethod
    def _from_model_data(cls, uid, data):
        return cls(centers=data["clusterCenters"], uid=uid)

    def _copy_extra_state(self, source):
        self.centers = source.centers
        self._training_cost = source._training_cost
        self._n_iter = source._n_iter
        self._summary = getattr(source, "_summary", None)
        self._predict_cache = {}

    def _predictor(self):
        key = (config.get("compute_dtype"), config.get("accum_dtype"))
        if key not in self._predict_cache:
            centers_dev = jnp.asarray(self.centers, dtype=jnp.dtype(key[0]))
            accum = jnp.dtype(key[1])

            @ledgered_jit("kmeans.predict")
            def predict(x):
                d2 = sq_euclidean(x.astype(centers_dev.dtype), centers_dev, accum_dtype=accum)
                return jnp.argmin(d2, axis=1).astype(jnp.int32)

            self._predict_cache[key] = predict
        return self._predict_cache[key]

    def predict(self, x: np.ndarray) -> np.ndarray:
        from spark_rapids_ml_tpu.parallel.sharding import run_bucketed

        return run_bucketed(self._predictor(), x)

    # Daemon serving contract (serve/daemon.py).
    _serve_algo = "kmeans"
    _serve_outputs = (("prediction", "predictionCol", "int"),)

    def _serve_aot_plan(self, n_rows, n_cols, dtype="float32", k=None):
        """AOT-at-registration plan (serve/daemon.py; see PCAModel's)."""
        if self.centers is None:
            return None
        d = int(np.asarray(self.centers).shape[1])
        if int(n_cols) != d:
            raise ValueError(
                f"warmup n_cols={int(n_cols)} does not match the "
                f"model's fitted width {d}"
            )
        from spark_rapids_ml_tpu.parallel.sharding import bucket_rows

        return [(
            self._predictor(),
            (jax.ShapeDtypeStruct(
                (bucket_rows(int(n_rows)), d), jnp.dtype(dtype)
            ),),
        )]

    def transform_matrix(self, x: np.ndarray) -> dict:
        """Role-keyed device transform (daemon ``transform`` op surface)."""
        if self.centers is None:
            raise RuntimeError("KMeansModel has no centers (unfitted?)")
        with trace_span("kmeans transform"):
            return {"prediction": self.predict(x)}

    def _transform(self, dataset):
        if self.centers is None:
            raise RuntimeError("KMeansModel has no centers (unfitted?)")
        x = as_matrix(dataset, self.getFeaturesCol())
        return with_column(dataset, self.getPredictionCol(), self.predict(x))
