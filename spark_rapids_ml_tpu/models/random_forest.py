"""RandomForest — histogram trees grown level-synchronously on device.

The first non-linear-algebra workload in the package (ROADMAP item 4a):
the cuML-era spark-rapids-ml surface is dominated by tree ensembles, and
their compute shape — per-node split histograms over BINNED features —
is a ``reduce_sum`` over the DrJAX primitives (parallel/mapreduce.py),
not a GEMM. The design keeps everything inside compiled programs
(ops/histogram.py):

* Features quantize once to uint8 bin ids against quantile-sketch edges
  (the edges ARE part of the model iterate, so every daemon in a
  distributed fit bins identically — the kmeans-seed pattern).
* All trees grow LEVEL-SYNCHRONOUSLY: one dataset pass per depth routes
  every row to its frontier node in every tree and accumulates ONE
  ``(tree, node, feature, bin, stat)`` histogram tensor — additive, so
  it rides the daemon merge / ``reduce_mesh`` plane completely
  unchanged, and the pass boundary (``step``) is exactly the Lloyd /
  Newton boundary the recovery + elastic machinery already snapshots.
* Split selection is one vectorized device program over every
  (node, feature, threshold) candidate (Gini / variance gain).
* The fitted forest is a dense ``(tree, node)`` heap table (children of
  i at 2i+1 / 2i+2); ``predict_matrix`` descends ALL trees by gather in
  one jitted program, bucketer-padded (``run_bucketed``) so it rides the
  serving scheduler and fleet plane like every other model.

Bootstrap bags are counter-based Poisson(1) weights keyed on each row's
(partition, offset) identity — deterministic under Spark task retries,
batch re-chunking, and daemon re-routing (ops/histogram.py).
"""

from __future__ import annotations

import math
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.core.dataset import as_column, as_matrix, with_column
from spark_rapids_ml_tpu.core.params import (
    Estimator,
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasSeed,
    Model,
    ParamDecl,
    ParamValidators,
    TypeConverters,
)
from spark_rapids_ml_tpu.core.persistence import MLReadable, MLWritable
from spark_rapids_ml_tpu.ops import histogram as hist_ops
from spark_rapids_ml_tpu.ops.histogram import LEAF, OPEN
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, default_mesh
from spark_rapids_ml_tpu.parallel.sharding import (
    pad_rows,
    row_sharding,
    run_bucketed,
)
from spark_rapids_ml_tpu.utils import metrics as metrics_mod
from spark_rapids_ml_tpu.utils.profiling import trace_span
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit

#: Forest telemetry (docs/observability.md catalogs these; the lint
#: gates require every hot path booked).
_M_FIT_PASSES = metrics_mod.counter(
    "srml_forest_fit_passes_total",
    "Level-synchronous histogram passes applied (one per tree depth), "
    "by role (classifier|regressor)",
)
_M_NODES_SPLIT = metrics_mod.counter(
    "srml_forest_nodes_split_total",
    "Frontier nodes split into children across all trees, by role",
)
_M_HIST_ROWS = metrics_mod.counter(
    "srml_forest_hist_rows_total",
    "Rows folded into per-node split histograms (each dataset pass "
    "counts every row once), by role",
)
_M_TRANSFORM_ROWS = metrics_mod.counter(
    "srml_forest_transform_rows_total",
    "Rows scored through forest predict/transform, by role",
)

#: Dense-heap bound: max_nodes = 2^(maxDepth+1) − 1 per tree, so the
#: node-table (and the deepest frontier histogram) stays addressable.
MAX_MAX_DEPTH = 16

#: In-memory fit row chunk: bounds the fused accumulate's transient
#: one-hot expansion (O(chunk · d · bins · stats)) the way streaming
#: fits bound their batches; the last partial chunk pads to the data
#: axis, so chunking never changes the (additive) histograms.
FIT_CHUNK_ROWS = 8192


class ForestCapacityError(ValueError):
    """A frontier histogram tensor over the per-device budget — raised
    at pass OPEN (job creation / step), never as a mid-pass OOM (the
    Gram-capacity contract, docs/mesh.md, for the tree shape).
    ``ValueError`` like ``GramCapacityError``: deterministic — a
    recovery replay cannot fix a too-large shape."""


class ForestSpec(NamedTuple):
    """Resolved creation params of one forest job — the single parse of
    the wire ``params`` dict shared by the in-memory fit, the daemon job
    and the split scorer (drift between them would desync replays)."""

    num_trees: int
    max_depth: int
    max_bins: int
    n_classes: int  # 0 = regression
    subset_m: int
    seed: int
    bootstrap: bool
    min_instances: int

    @property
    def n_stats(self) -> int:
        return self.n_classes if self.n_classes > 0 else 3

    @property
    def max_nodes(self) -> int:
        return (1 << (self.max_depth + 1)) - 1

    def role(self) -> str:
        return "classifier" if self.n_classes > 0 else "regressor"


def subset_size(strategy: str, n_cols: int, classifier: bool) -> int:
    """featureSubsetStrategy → per-node candidate-feature count (Spark
    ML semantics: auto = sqrt for classification, onethird for
    regression; also all|sqrt|onethird|log2, an integer count, or a
    (0, 1] fraction)."""
    s = str(strategy).strip().lower()
    if s == "auto":
        s = "sqrt" if classifier else "onethird"
    if s == "all":
        return n_cols
    if s == "sqrt":
        return max(1, int(math.ceil(math.sqrt(n_cols))))
    if s == "onethird":
        return max(1, n_cols // 3)
    if s == "log2":
        return max(1, int(math.floor(math.log2(max(n_cols, 2)))))
    try:
        v = float(s)
    except ValueError:
        raise ValueError(
            f"unknown featureSubsetStrategy {strategy!r} "
            "(auto|all|sqrt|onethird|log2|<int>|<fraction>)"
        ) from None
    if 0.0 < v <= 1.0 and "." in s:
        return max(1, int(math.ceil(v * n_cols)))
    if v >= 1.0 and v == int(v):
        return min(n_cols, int(v))
    raise ValueError(
        f"featureSubsetStrategy {strategy!r} must be a strategy name, an "
        "integer >= 1, or a fraction in (0, 1]"
    )


def forest_spec_from_params(params: Dict, n_cols: int) -> ForestSpec:
    """Validate + resolve one wire/constructor ``params`` dict
    (docs/protocol.md "The `rf` job algo"). Raises ``ValueError`` for
    out-of-range creation params — a first-feed-rejection class error,
    never a mid-fit surprise."""
    params = params or {}

    def _p(key, default, cast=int):
        # None-aware (never `or`): an EXPLICIT 0 must reach the range
        # validation below, not silently coerce to the default.
        v = params.get(key)
        return default if v is None else cast(v)

    num_trees = _p("num_trees", 20)
    max_depth = _p("max_depth", 5)
    max_bins = _p("max_bins", 32)
    n_classes = _p("n_classes", 0)
    seed = _p("seed", 0)
    bootstrap = _p("bootstrap", True, bool)
    min_instances = _p("min_instances", 1)
    strategy = _p("subset", "auto", str)
    if num_trees < 1:
        raise ValueError(f"num_trees = {num_trees} must be >= 1")
    if not 1 <= max_depth <= MAX_MAX_DEPTH:
        raise ValueError(
            f"max_depth = {max_depth} out of range [1, {MAX_MAX_DEPTH}] "
            "(dense (tree, node) heap tables)"
        )
    if not 2 <= max_bins <= 256:
        raise ValueError(
            f"max_bins = {max_bins} out of range [2, 256] (uint8 bin ids)"
        )
    if n_classes == 1 or n_classes < 0:
        raise ValueError(f"n_classes = {n_classes} must be 0 (regression) or >= 2")
    if min_instances < 1:
        raise ValueError(f"min_instances = {min_instances} must be >= 1")
    return ForestSpec(
        num_trees=num_trees,
        max_depth=max_depth,
        max_bins=max_bins,
        n_classes=n_classes,
        subset_m=subset_size(strategy, n_cols, n_classes > 0),
        seed=seed,
        bootstrap=bootstrap,
        min_instances=min_instances,
    )


def require_hist_capacity(spec: ForestSpec, depth: int, n_cols: int) -> None:
    """Refuse a frontier histogram over the per-device budget (config
    ``forest_hist_budget_mb`` / SRML_FOREST_HIST_BUDGET_MB) at the pass
    boundary that would allocate it — the forest twin of the Gram
    capacity gate (never a mid-pass OOM). The tensor is replicated on
    every device, so the budget is per device."""
    budget = int(config.get("forest_hist_budget_mb")) << 20
    itemsize = jnp.dtype(config.get("accum_dtype")).itemsize
    need = (
        spec.num_trees * (1 << depth) * n_cols * spec.max_bins
        * spec.n_stats * itemsize
    )
    if budget and need > budget:
        raise ForestCapacityError(
            f"the depth-{depth} frontier histogram "
            f"({spec.num_trees} trees x {1 << depth} nodes x {n_cols} "
            f"features x {spec.max_bins} bins x {spec.n_stats} stats = "
            f"{need >> 20} MiB) exceeds forest_hist_budget_mb "
            f"({budget >> 20} MiB); lower maxDepth/maxBins/numTrees or "
            "raise SRML_FOREST_HIST_BUDGET_MB"
        )


def init_forest_arrays(spec: ForestSpec, bin_edges: np.ndarray) -> Dict[str, np.ndarray]:
    """The depth-0 iterate: quantile edges + empty node tables with every
    root OPEN. These arrays ARE the wire iterate (get/set_iterate), the
    durable pass-boundary snapshot payload, and the driver recovery
    ledger entry — one layout everywhere (docs/protocol.md)."""
    edges = np.asarray(bin_edges, np.float64)
    if edges.ndim != 2 or edges.shape[1] != spec.max_bins - 1:
        raise ValueError(
            f"bin_edges shape {edges.shape} != (n_cols, {spec.max_bins - 1})"
        )
    T, N, S = spec.num_trees, spec.max_nodes, spec.n_stats
    feature = np.full((T, N), LEAF, np.int32)
    feature[:, 0] = OPEN
    return {
        "bin_edges": edges,
        "feature": feature,
        "threshold": np.zeros((T, N), np.int32),
        "value": np.zeros((T, N, S), np.float64),
        "depth": np.zeros((1,), np.int64),
    }


def validate_forest_arrays(
    arrays: Dict[str, np.ndarray], spec: ForestSpec, n_cols: int
) -> Dict[str, np.ndarray]:
    """Full shape validation at the iterate boundary (the set_iterate /
    durable-restore contract): a mis-shaped table installed here would
    otherwise crash opaquely inside the next pass's jitted update."""
    T, N, S = spec.num_trees, spec.max_nodes, spec.n_stats
    want = {
        "bin_edges": (n_cols, spec.max_bins - 1),
        "feature": (T, N),
        "threshold": (T, N),
        "value": (T, N, S),
        "depth": (1,),
    }
    out = {}
    for name, shape in want.items():
        a = arrays.get(name)
        if a is None:
            raise ValueError(f"forest iterate missing array {name!r}")
        a = np.asarray(a)
        if tuple(a.shape) != shape:
            raise ValueError(
                f"forest iterate array {name!r} shape {tuple(a.shape)} "
                f"!= {shape}"
            )
        out[name] = a
    depth = int(out["depth"][0])
    if not 0 <= depth <= spec.max_depth + 1:
        raise ValueError(
            f"forest iterate depth {depth} out of range "
            f"[0, {spec.max_depth + 1}]"
        )
    out["bin_edges"] = np.asarray(out["bin_edges"], np.float64)
    out["feature"] = np.asarray(out["feature"], np.int32)
    out["threshold"] = np.asarray(out["threshold"], np.int32)
    out["value"] = np.asarray(out["value"], np.float64)
    out["depth"] = np.asarray(out["depth"], np.int64)
    return out


def open_frontier_nodes(feature: np.ndarray, depth: int) -> int:
    """How many nodes await a split at ``depth`` (the driver's stop
    signal once it reaches 0)."""
    W = 1 << depth
    base = W - 1
    if base >= feature.shape[1]:
        return 0
    return int(np.sum(feature[:, base: base + W] == OPEN))


def row_identity_keys(partition: Optional[int], offset: int, n: int) -> np.ndarray:
    """uint32 bootstrap-bag identity keys for ``n`` rows starting at
    partition-relative ``offset`` — a pure function of (partition,
    offset), never of batch boundaries: task retries restart their
    stage at offset 0 and replay the identical keys, and a partition
    lands on the same keys whichever daemon it routes to."""
    pid = 0 if partition is None else int(partition)
    base = np.uint32((pid * 2654435761 + int(offset)) & 0xFFFFFFFF)
    with np.errstate(over="ignore"):  # uint32 wraparound is the point
        return (base + np.arange(n, dtype=np.uint32)).astype(np.uint32)


def accumulate_histogram(
    hist, tables: Dict[str, np.ndarray], x, y, mask, row_key,
    spec: ForestSpec, mesh: Mesh, n_valid: int,
):
    """Fold one placed batch into the frontier histogram — the ONE entry
    both the in-memory fit and the daemon job use (drift would break the
    single-daemon-oracle bitwise contract). Inputs are already padded +
    row-sharded; replicated table arrays upload per call (tiny next to
    the batch). ``n_valid`` is the unpadded row count (booking only)."""
    depth = int(tables["depth"][0])
    update = hist_ops.hist_update_fn(
        mesh, spec.num_trees, spec.max_bins, depth, spec.n_classes,
        spec.bootstrap, spec.seed, config.get("accum_dtype"),
    )
    _M_HIST_ROWS.inc(int(n_valid), role=spec.role())
    # Edges upload in the accumulation dtype EXPLICITLY: on a non-x64
    # runtime a bare f64 upload truncates to f32 anyway (with a warning
    # per batch); naming the dtype keeps fit and predict binning in the
    # same precision on every profile (f64 under the parity tests).
    accum = jnp.dtype(config.get("accum_dtype"))
    return update(
        hist,
        jnp.asarray(tables["bin_edges"], accum),
        jnp.asarray(tables["feature"]),
        jnp.asarray(tables["threshold"]),
        x, y, mask, row_key,
    )


def grow_level(
    tables: Dict[str, np.ndarray], hist, spec: ForestSpec,
) -> Dict[str, int]:
    """Apply one level's split decisions from the pass histogram: score
    every candidate on device, then write the (small, host-side) node
    tables — split features/thresholds on the frontier, child stats +
    OPEN/LEAF marks one level down. Mutates ``tables`` in place and
    advances ``depth``; returns ``{"open_nodes", "splits", "depth"}``.
    Call with the device lock held when the daemon owns the devices."""
    depth = int(tables["depth"][0])
    W = 1 << depth
    base = W - 1
    scorer = hist_ops.best_splits_fn(
        spec.num_trees, depth, spec.n_classes, spec.subset_m, spec.seed,
        spec.min_instances, config.get("accum_dtype"),
    )
    score, bf, bb, left, right, tot = (
        np.asarray(jax.device_get(a)) for a in scorer(hist)
    )
    score = np.where(np.isfinite(score), score, -np.inf)
    feat, thr, val = tables["feature"], tables["threshold"], tables["value"]
    fl = feat[:, base: base + W]  # basic slices: views, writes stick
    tl = thr[:, base: base + W]
    vl = val[:, base: base + W]
    open_mask = fl == OPEN
    clf = spec.n_classes > 0
    n_l = left.sum(-1) if clf else left[..., 0]
    n_r = right.sum(-1) if clf else right[..., 0]
    vl[open_mask] = tot[open_mask]
    can = (
        open_mask
        & (depth < spec.max_depth)
        & (score > 1e-12)
        & (n_l >= spec.min_instances)
        & (n_r >= spec.min_instances)
    )
    fl[open_mask & ~can] = LEAF
    fl[can] = bf[can]
    tl[can] = bb[can]
    opened = 0
    if depth < spec.max_depth and can.any():
        base2 = 2 * W - 1
        for side, stats, n_side in ((0, left, n_l), (1, right, n_r)):
            cf = feat[:, base2 + side: base2 + 2 * W: 2]
            cv = val[:, base2 + side: base2 + 2 * W: 2]
            cv[can] = stats[can]
            if clf:
                pure = (n_side - stats.max(-1)) <= 1e-9
            else:
                resid = stats[..., 2] - (
                    stats[..., 1] ** 2 / np.maximum(n_side, 1)
                )
                pure = resid <= 1e-12 * np.maximum(1.0, stats[..., 2])
            grow = (
                can
                & (depth + 1 < spec.max_depth)
                & (n_side >= 2 * spec.min_instances)
                & ~pure
            )
            cf[can] = np.where(grow, OPEN, LEAF)[can]
            opened += int(grow.sum())
    n_split = int(can.sum())
    _M_NODES_SPLIT.inc(n_split, role=spec.role())
    _M_FIT_PASSES.inc(role=spec.role())
    tables["depth"] = np.asarray([depth + 1], np.int64)
    return {"open_nodes": opened, "splits": n_split, "depth": depth + 1}


# ---------------------------------------------------------------------------
# In-memory fit (the single-process oracle of the daemon protocol)
# ---------------------------------------------------------------------------


class ForestSolution(NamedTuple):
    arrays: Dict[str, np.ndarray]
    n_classes: int
    n_rows: int
    n_passes: int


def _place_batch(x, y, mask, keys, mesh: Mesh):
    """Pad to the data-axis multiple and place row-sharded (the daemon
    fold's placement, shared so the in-memory fit compiles the same
    programs)."""
    n_data = mesh.shape[DATA_AXIS]
    xp, _ = pad_rows(np.asarray(x), n_data)
    pad = xp.shape[0] - x.shape[0]

    def padv(v, dtype):
        v = np.asarray(v, dtype).reshape(-1)
        return np.concatenate([v, np.zeros((pad,), dtype)]) if pad else v

    xs = jax.device_put(xp, row_sharding(mesh))
    v_sh = row_sharding(mesh, ndim=1)
    return (
        xs,
        jax.device_put(padv(y, np.float64), v_sh),
        jax.device_put(padv(mask, np.float32), v_sh),
        jax.device_put(padv(keys, np.uint32), v_sh),
    )


def _fit_forest(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: int,
    num_trees: int,
    max_depth: int,
    max_bins: int,
    feature_subset: str,
    seed: int,
    bootstrap: bool,
    min_instances: int,
    mesh: Optional[Mesh],
) -> ForestSolution:
    from spark_rapids_ml_tpu.parallel.sharding import require_single_process

    require_single_process(
        "fit_random_forest (quantile binning samples local data)"
    )
    mesh = mesh or default_mesh()
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64).reshape(-1)
    if x.ndim != 2 or x.shape[0] == 0:
        raise ValueError(f"features must be (n, d) with n > 0, got {x.shape}")
    if y.shape[0] != x.shape[0]:
        raise ValueError(
            f"labels length {y.shape[0]} != rows {x.shape[0]}"
        )
    n, d = x.shape
    spec = forest_spec_from_params(
        {
            "num_trees": num_trees, "max_depth": max_depth,
            "max_bins": max_bins, "n_classes": n_classes, "seed": seed,
            "bootstrap": bootstrap, "min_instances": min_instances,
            "subset": feature_subset,
        },
        n_cols=d,
    )
    if spec.n_classes > 0 and (
        np.any(y < 0) or np.any(y >= spec.n_classes) or np.any(y != np.floor(y))
    ):
        raise ValueError(
            f"classifier labels must be integers in [0, {spec.n_classes})"
        )
    with trace_span("forest binning"):
        cap = int(config.get("forest_seed_sample_rows"))
        edges = hist_ops.quantile_bin_edges(x[:cap], spec.max_bins)
    tables = init_forest_arrays(spec, edges)
    ad = config.get("accum_dtype")
    # Row identity for bootstrap bags: the whole matrix is "partition 0",
    # offset = row index — the daemon's (partition, offset) keying with
    # one partition, so a one-partition daemon fit reproduces this fit.
    keys = row_identity_keys(None, 0, n)
    mask = np.ones((n,), np.float32)
    n_passes = 0
    # Row-chunked passes: the fused accumulate's one-hot expansion is a
    # transient O(chunk·d·bins·stats) — chunking bounds it the way the
    # streaming fits bound their batches (the daemon path is naturally
    # chunked by feed batches). Numerically free: histograms are sums.
    chunk = FIT_CHUNK_ROWS
    placed = [
        _place_batch(
            x[i: i + chunk], y[i: i + chunk], mask[i: i + chunk],
            keys[i: i + chunk], mesh,
        )
        for i in range(0, n, chunk)
    ]
    with trace_span("forest grow"):
        for depth in range(spec.max_depth + 1):
            if open_frontier_nodes(tables["feature"], depth) == 0:
                break
            require_hist_capacity(spec, depth, d)
            hist = hist_ops.zero_hist(
                spec.num_trees, depth, d, spec.max_bins, spec.n_stats, ad
            )
            for (xs, ys, ms, ks), i in zip(placed, range(0, n, chunk)):
                hist = accumulate_histogram(
                    hist, tables, xs, ys, ms, ks, spec, mesh,
                    n_valid=min(chunk, n - i),
                )
            grow_level(tables, hist, spec)
            n_passes += 1
    arrays = dict(tables)
    arrays.pop("depth")
    arrays["n_classes"] = np.asarray([spec.n_classes], np.int64)
    return ForestSolution(
        arrays=arrays, n_classes=spec.n_classes, n_rows=n, n_passes=n_passes
    )


def fit_random_forest_classifier(
    x: np.ndarray,
    y: np.ndarray,
    n_classes: Optional[int] = None,
    num_trees: int = 20,
    max_depth: int = 5,
    max_bins: int = 32,
    feature_subset: str = "auto",
    seed: int = 0,
    bootstrap: bool = True,
    min_instances: int = 1,
    mesh: Optional[Mesh] = None,
) -> ForestSolution:
    """Gini-split random forest on binned features (Spark ML
    RandomForestClassifier semantics). ``n_classes=None`` infers
    ``max(y) + 1`` (>= 2)."""
    with trace_span("forest fit"):
        y = np.asarray(y, np.float64).reshape(-1)
        if n_classes is None:
            n_classes = max(int(np.max(y)) + 1 if y.size else 2, 2)
        return _fit_forest(
            x, y, int(n_classes), num_trees, max_depth, max_bins,
            feature_subset, seed, bootstrap, min_instances, mesh,
        )


def fit_random_forest_regressor(
    x: np.ndarray,
    y: np.ndarray,
    num_trees: int = 20,
    max_depth: int = 5,
    max_bins: int = 32,
    feature_subset: str = "auto",
    seed: int = 0,
    bootstrap: bool = True,
    min_instances: int = 1,
    mesh: Optional[Mesh] = None,
) -> ForestSolution:
    """Variance-split random forest on binned features (Spark ML
    RandomForestRegressor semantics)."""
    with trace_span("forest fit"):
        return _fit_forest(
            x, np.asarray(y, np.float64), 0, num_trees, max_depth,
            max_bins, feature_subset, seed, bootstrap, min_instances,
            mesh,
        )


# ---------------------------------------------------------------------------
# Prediction: descend all trees by gather in one jitted program
# ---------------------------------------------------------------------------


def _forest_predictor(arrays: Dict[str, np.ndarray], n_classes: int,
                      max_depth_hint: Optional[int] = None):
    """Jitted row-wise scorer with the tables device-resident: bins the
    batch, descends every tree to its leaf by repeated gather, and
    aggregates — mean of per-tree class distributions (argmax) for
    classification, mean of per-tree leaf means for regression. Returns
    role-keyed outputs (the daemon ``transform`` surface)."""
    # Tables upload in the accumulation dtype (matches the fit-time
    # binning precision; avoids per-call f64-truncation warnings on
    # non-x64 runtimes) — outputs cast back to f64 host-side.
    accum = jnp.dtype(config.get("accum_dtype"))
    edges = jnp.asarray(np.asarray(arrays["bin_edges"], np.float64), accum)
    feature = jnp.asarray(np.asarray(arrays["feature"], np.int32))
    threshold = jnp.asarray(np.asarray(arrays["threshold"], np.int32))
    value = jnp.asarray(np.asarray(arrays["value"], np.float64), accum)
    n_nodes = int(feature.shape[1])
    depth = (
        max_depth_hint if max_depth_hint is not None
        else max(int(math.ceil(math.log2(n_nodes + 1))) - 1, 1)
    )

    @ledgered_jit("random_forest.predict")
    def predict(x):
        bins = hist_ops.bin_matrix(x.astype(edges.dtype), edges)
        idx, _ = hist_ops.descend_to_frontier(bins, feature, threshold, depth)
        leaves = jnp.take_along_axis(
            value, idx[:, :, None].astype(jnp.int32), axis=1
        )  # (T, n, S)
        if n_classes > 0:
            counts = jnp.sum(leaves, axis=-1, keepdims=True)
            proba = jnp.mean(leaves / jnp.maximum(counts, 1.0), axis=0)
            pred = jnp.argmax(proba, axis=1).astype(accum)
            return pred, proba
        means = leaves[..., 1] / jnp.maximum(leaves[..., 0], 1.0)
        pred = jnp.mean(means, axis=0)
        return pred, pred[:, None]

    return predict


class _ForestModelBase(Model, MLWritable, MLReadable):
    """Shared fitted-forest surface: dense tables + jitted descend."""

    def __init__(self, arrays: Optional[Dict[str, np.ndarray]] = None,
                 uid=None):
        super().__init__(uid=uid)
        self.arrays = (
            None if arrays is None
            else {k: np.asarray(v) for k, v in arrays.items()}
        )
        self._summary = None
        self._predict_cache: dict = {}

    @property
    def numClasses(self) -> int:
        if self.arrays is None:
            return 0
        return int(np.asarray(self.arrays.get("n_classes", [0]))[0])

    @property
    def totalNumNodes(self) -> int:
        """Materialized nodes across all trees (internal + leaves):
        roots plus the children of every node that actually split — a
        vectorized level-order reachability sweep over the dense heap
        (O(maxDepth) numpy ops, not a Python walk of every slot)."""
        f = np.asarray(self.arrays["feature"])
        T, N = f.shape
        alive = np.zeros((T, N), bool)
        alive[:, 0] = True  # roots always materialize
        base, width = 0, 1
        while 2 * base + 2 < N:
            level = slice(base, base + width)
            split = alive[:, level] & (f[:, level] >= 0)
            base2 = 2 * base + 1
            alive[:, base2: base2 + 2 * width: 2] = split
            alive[:, base2 + 1: base2 + 2 * width: 2] = split
            base, width = base2, 2 * width
        return int(alive.sum())

    def getNumTrees(self) -> int:
        return int(np.asarray(self.arrays["feature"]).shape[0])

    def _model_data(self):
        return dict(self.arrays)

    @classmethod
    def _from_model_data(cls, uid, data):
        return cls(arrays=dict(data), uid=uid)

    def _copy_extra_state(self, source):
        self.arrays = source.arrays
        self._summary = getattr(source, "_summary", None)
        self._predict_cache = {}

    def _predictor(self):
        if self.arrays is None:
            raise RuntimeError("forest model has no trees (unfitted?)")
        key = (config.get("compute_dtype"), config.get("accum_dtype"))
        if key not in self._predict_cache:
            self._predict_cache[key] = _forest_predictor(
                self.arrays, self.numClasses
            )
        return self._predict_cache[key]

    def predict(self, x: np.ndarray) -> np.ndarray:
        fn = self._predictor()
        x = np.asarray(x)
        _M_TRANSFORM_ROWS.inc(
            int(x.shape[0]),
            role="classifier" if self.numClasses > 0 else "regressor",
        )
        return run_bucketed(lambda xb: fn(xb)[0], x)

    def _serve_aot_plan(self, n_rows, n_cols, dtype="float32", k=None):
        """AOT-at-registration plan (serve/daemon.py; see PCAModel's) —
        shared by the classifier and regressor surfaces (one jit serves
        both predict and predict_proba slices)."""
        if self.arrays is None:
            return None
        from spark_rapids_ml_tpu.parallel.sharding import bucket_rows

        d = int(np.asarray(self.arrays["bin_edges"]).shape[0])
        if int(n_cols) != d:
            raise ValueError(
                f"warmup n_cols={int(n_cols)} does not match the "
                f"model's fitted width {d}"
            )
        return [(
            self._predictor(),
            (jax.ShapeDtypeStruct(
                (bucket_rows(int(n_rows)), d), jnp.dtype(dtype)
            ),),
        )]

    def transform_matrix(self, x: np.ndarray) -> dict:
        """Role-keyed device transform (daemon ``transform`` op surface):
        bucketer-padded like every served model, so it coalesces through
        the serving scheduler unchanged."""
        if self.arrays is None:
            raise RuntimeError("forest model has no trees (unfitted?)")
        with trace_span("forest transform"):
            return {"prediction": np.asarray(self.predict(x), np.float64)}

    def _transform(self, dataset):
        if self.arrays is None:
            raise RuntimeError("forest model has no trees (unfitted?)")
        x = as_matrix(dataset, self.getFeaturesCol())
        return with_column(
            dataset, self.getPredictionCol(), self.predict(x)
        )


class _RandomForestParams(HasFeaturesCol, HasLabelCol, HasPredictionCol,
                          HasSeed):
    numTrees = ParamDecl(
        "numTrees", "number of trees (>= 1)", TypeConverters.toInt,
        validator=ParamValidators.gt(0),
    )
    maxDepth = ParamDecl(
        "maxDepth", f"maximum tree depth (1..{MAX_MAX_DEPTH})",
        TypeConverters.toInt, validator=ParamValidators.gt(0),
    )
    maxBins = ParamDecl(
        "maxBins", "feature-quantization bins (2..256; uint8 ids)",
        TypeConverters.toInt, validator=ParamValidators.gt(1),
    )
    featureSubsetStrategy = ParamDecl(
        "featureSubsetStrategy",
        "per-node candidate features: auto|all|sqrt|onethird|log2|<n>",
        TypeConverters.toString,
    )
    bootstrap = ParamDecl(
        "bootstrap", "Poisson(1) bootstrap bags per tree",
        TypeConverters.toBoolean,
    )
    minInstancesPerNode = ParamDecl(
        "minInstancesPerNode", "minimum rows each split side must keep",
        TypeConverters.toInt, validator=ParamValidators.gt(0),
    )

    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(
            numTrees=20,
            maxDepth=5,
            maxBins=32,
            featureSubsetStrategy="auto",
            bootstrap=True,
            minInstancesPerNode=1,
            seed=0,
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
        )

    def getNumTrees(self) -> int:
        return self.getOrDefault(self.numTrees)

    def getMaxDepth(self) -> int:
        return self.getOrDefault(self.maxDepth)

    def getMaxBins(self) -> int:
        return self.getOrDefault(self.maxBins)

    def getFeatureSubsetStrategy(self) -> str:
        return self.getOrDefault(self.featureSubsetStrategy)

    def getBootstrap(self) -> bool:
        return self.getOrDefault(self.bootstrap)

    def getMinInstancesPerNode(self) -> int:
        return self.getOrDefault(self.minInstancesPerNode)

    def setNumTrees(self, value: int):
        return self._set(numTrees=value)

    def setMaxDepth(self, value: int):
        return self._set(maxDepth=value)

    def setMaxBins(self, value: int):
        return self._set(maxBins=value)

    def setFeatureSubsetStrategy(self, value: str):
        return self._set(featureSubsetStrategy=value)

    def setBootstrap(self, value: bool):
        return self._set(bootstrap=value)

    def setMinInstancesPerNode(self, value: int):
        return self._set(minInstancesPerNode=value)


class RandomForestClassifier(Estimator, _RandomForestParams, MLWritable,
                             MLReadable):
    """``RandomForestClassifier().setNumTrees(50).fit(df)`` — Spark ML
    classification API shape over the histogram-tree core."""

    _uid_prefix = "RandomForestClassifier"

    def __init__(self, uid=None, mesh: Optional[Mesh] = None):
        super().__init__(uid=uid)
        self._mesh = mesh

    def _copy_extra_state(self, source):
        self._mesh = getattr(source, "_mesh", None)

    def _fit(self, dataset) -> "RandomForestClassificationModel":
        x = as_matrix(dataset, self.getFeaturesCol())
        y = as_column(dataset, self.getLabelCol())
        sol = fit_random_forest_classifier(
            x, y,
            num_trees=self.getNumTrees(),
            max_depth=self.getMaxDepth(),
            max_bins=self.getMaxBins(),
            feature_subset=self.getFeatureSubsetStrategy(),
            seed=self.getSeed(),
            bootstrap=self.getBootstrap(),
            min_instances=self.getMinInstancesPerNode(),
            mesh=self._mesh,
        )
        model = RandomForestClassificationModel(arrays=sol.arrays)
        model.uid = self.uid
        self._copy_params_to(model)
        return model


class RandomForestClassificationModel(_ForestModelBase, _RandomForestParams):
    _uid_prefix = "RandomForestClassificationModel"

    # Daemon serving contract (serve/daemon.py).
    _serve_algo = "rf_classifier"
    _serve_outputs = (("prediction", "predictionCol", "double"),)

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        fn = self._predictor()
        x = np.asarray(x)
        _M_TRANSFORM_ROWS.inc(int(x.shape[0]), role="classifier")
        return run_bucketed(lambda xb: fn(xb)[1], x)


class RandomForestRegressor(Estimator, _RandomForestParams, MLWritable,
                            MLReadable):
    """``RandomForestRegressor().setNumTrees(50).fit(df)`` — Spark ML
    regression API shape over the histogram-tree core."""

    _uid_prefix = "RandomForestRegressor"

    def __init__(self, uid=None, mesh: Optional[Mesh] = None):
        super().__init__(uid=uid)
        self._mesh = mesh

    def _copy_extra_state(self, source):
        self._mesh = getattr(source, "_mesh", None)

    def _fit(self, dataset) -> "RandomForestRegressionModel":
        x = as_matrix(dataset, self.getFeaturesCol())
        y = as_column(dataset, self.getLabelCol())
        sol = fit_random_forest_regressor(
            x, y,
            num_trees=self.getNumTrees(),
            max_depth=self.getMaxDepth(),
            max_bins=self.getMaxBins(),
            feature_subset=self.getFeatureSubsetStrategy(),
            seed=self.getSeed(),
            bootstrap=self.getBootstrap(),
            min_instances=self.getMinInstancesPerNode(),
            mesh=self._mesh,
        )
        model = RandomForestRegressionModel(arrays=sol.arrays)
        model.uid = self.uid
        self._copy_params_to(model)
        return model


class RandomForestRegressionModel(_ForestModelBase, _RandomForestParams):
    _uid_prefix = "RandomForestRegressionModel"

    # Daemon serving contract (serve/daemon.py).
    _serve_algo = "rf_regressor"
    _serve_outputs = (("prediction", "predictionCol", "double"),)
