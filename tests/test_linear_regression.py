"""LinearRegression differential tests vs numpy/sklearn closed forms."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import LinearRegression, LinearRegressionModel
from spark_rapids_ml_tpu.models.linear_regression import fit_linear_regression
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


@pytest.fixture
def regression_data(rng):
    n, d = 400, 12
    x = rng.normal(size=(n, d))
    w_true = rng.normal(size=d)
    y = x @ w_true + 2.5 + 0.01 * rng.normal(size=n)
    return x, y, w_true


def test_ols_matches_lstsq(regression_data, mesh8):
    x, y, _ = regression_data
    sol = fit_linear_regression(x, y, mesh=mesh8)
    xa = np.concatenate([x, np.ones((len(x), 1))], axis=1)
    ref = np.linalg.lstsq(xa, y, rcond=None)[0]
    np.testing.assert_allclose(sol.coefficients, ref[:-1], atol=1e-6)
    assert abs(sol.intercept - ref[-1]) < 1e-6


def test_no_intercept(regression_data, mesh8):
    x, y, _ = regression_data
    sol = fit_linear_regression(x, y, fit_intercept=False, mesh=mesh8)
    ref = np.linalg.lstsq(x, y, rcond=None)[0]
    np.testing.assert_allclose(sol.coefficients, ref, atol=1e-6)
    assert sol.intercept == 0.0


def test_ridge_matches_oracle(regression_data, mesh8):
    from oracles import ridge

    x, y, _ = regression_data
    lam = 0.3
    sol = fit_linear_regression(x, y, reg=lam, mesh=mesh8)
    # Spark's objective is 1/(2n)·RSS + λ/2·‖w‖²  ⇒  oracle alpha = λ·n.
    ref_w, ref_b = ridge(x, y, alpha=lam * len(x), fit_intercept=True)
    np.testing.assert_allclose(sol.coefficients, ref_w, atol=1e-5)
    assert abs(sol.intercept - ref_b) < 1e-5


def test_lasso_matches_oracle(regression_data, mesh8):
    from oracles import elastic_net

    x, y, _ = regression_data
    lam = 0.1
    sol = fit_linear_regression(
        x, y, reg=lam, elastic_net=1.0, max_iter=2000, mesh=mesh8
    )
    ref_w, ref_b = elastic_net(x, y, alpha=lam, l1_ratio=1.0, max_iter=10000)
    np.testing.assert_allclose(sol.coefficients, ref_w, atol=1e-4)
    assert abs(sol.intercept - ref_b) < 1e-4


def test_elastic_net_matches_oracle(regression_data, mesh8):
    from oracles import elastic_net

    x, y, _ = regression_data
    lam, alpha = 0.1, 0.5
    sol = fit_linear_regression(
        x, y, reg=lam, elastic_net=alpha, max_iter=2000, mesh=mesh8
    )
    ref_w, ref_b = elastic_net(x, y, alpha=lam, l1_ratio=alpha, max_iter=10000)
    np.testing.assert_allclose(sol.coefficients, ref_w, atol=1e-4)
    assert abs(sol.intercept - ref_b) < 1e-4


def test_shard_invariance(regression_data):
    x, y, _ = regression_data
    a = fit_linear_regression(x, y, mesh=make_mesh(data=1, model=1))
    b = fit_linear_regression(x, y, mesh=make_mesh(data=8, model=1))
    np.testing.assert_allclose(a.coefficients, b.coefficients, atol=1e-9)


def test_estimator_api_and_persistence(regression_data, mesh8, tmp_path):
    x, y, _ = regression_data
    ds = {"features": x, "label": y}
    lr = LinearRegression(mesh=mesh8).setRegParam(0.0)
    model = lr.fit(ds)
    out = model.transform(ds)
    resid = out["prediction"] - y
    assert np.sqrt(np.mean(resid**2)) < 0.05  # noise level is 0.01
    path = str(tmp_path / "lr")
    model.save(path)
    loaded = LinearRegressionModel.load(path)
    np.testing.assert_allclose(loaded.coefficients, model.coefficients, atol=1e-12)
    assert abs(loaded.intercept - model.intercept) < 1e-12


def test_shape_mismatch(mesh8, rng):
    with pytest.raises(ValueError):
        fit_linear_regression(rng.normal(size=(10, 3)), rng.normal(size=9), mesh=mesh8)
