"""KMeans — placeholder, implemented in the breadth pass."""

from spark_rapids_ml_tpu.core.params import Estimator, Model


class KMeans(Estimator):
    _uid_prefix = "KMeans"


class KMeansModel(Model):
    _uid_prefix = "KMeansModel"
