"""LogisticRegression differential tests vs sklearn."""

import numpy as np
import pytest

from spark_rapids_ml_tpu import LogisticRegression, LogisticRegressionModel
from spark_rapids_ml_tpu.models.logistic_regression import fit_logistic_regression
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


@pytest.fixture
def binary_data(rng):
    n, d = 600, 6
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    logits = x @ w + 0.5
    p = 1 / (1 + np.exp(-logits))
    y = (rng.uniform(size=n) < p).astype(np.float64)
    return x, y


@pytest.fixture
def multi_data(rng):
    n, d, c = 600, 5, 3
    x = rng.normal(size=(n, d))
    w = rng.normal(size=(d, c)) * 2
    y = np.argmax(x @ w + rng.normal(size=(n, c)) * 0.1, axis=1).astype(np.float64)
    return x, y


def test_binary_matches_oracle(binary_data, mesh8):
    from oracles import logreg

    x, y = binary_data
    lam = 0.01
    sol = fit_logistic_regression(x, y, reg=lam, mesh=mesh8)
    # Spark objective: 1/n Σ loss + λ/2 ‖w‖²  ⇒  oracle C = 1/(n·λ).
    ref = logreg(x, y, C=1.0 / (len(x) * lam), tol=1e-10, max_iter=5000)
    np.testing.assert_allclose(sol.coefficients, ref.coef_[0], atol=2e-4)
    np.testing.assert_allclose(sol.intercept, ref.intercept_[0], atol=2e-4)


def test_binary_unregularized_separates(mesh8, rng):
    # Nearly separable data, small reg to keep it finite.
    x = np.concatenate([rng.normal(size=(100, 3)) + 3, rng.normal(size=(100, 3)) - 3])
    y = np.concatenate([np.ones(100), np.zeros(100)])
    sol = fit_logistic_regression(x, y, reg=1e-3, mesh=mesh8)
    from spark_rapids_ml_tpu.models.logistic_regression import LogisticRegressionModel

    m = LogisticRegressionModel(coefficients=sol.coefficients, intercept=sol.intercept)
    acc = np.mean(m.predict(x) == y)
    assert acc > 0.99


def test_multinomial_matches_oracle(multi_data, mesh8):
    from oracles import logreg

    x, y = multi_data
    lam = 0.01
    sol = fit_logistic_regression(x, y, reg=lam, max_iter=3000, tol=1e-9, mesh=mesh8)
    ref = logreg(x, y, C=1.0 / (len(x) * lam), tol=1e-10, max_iter=5000)
    # Softmax parameters are identifiable only up to a per-feature constant
    # shift across classes; compare class-mean-centered coefficients.
    ours = sol.coefficients - sol.coefficients.mean(axis=0, keepdims=True)
    theirs = ref.coef_ - ref.coef_.mean(axis=0, keepdims=True)
    np.testing.assert_allclose(ours, theirs, atol=5e-3)
    acc_ours = np.mean(
        LogisticRegressionModel(
            coefficients=sol.coefficients, intercept=sol.intercept
        ).predict(x)
        == y
    )
    acc_ref = ref.score(x, y)
    assert acc_ours >= acc_ref - 0.01


def test_shard_invariance(binary_data):
    x, y = binary_data
    a = fit_logistic_regression(x, y, reg=0.01, mesh=make_mesh(data=1, model=1))
    b = fit_logistic_regression(x, y, reg=0.01, mesh=make_mesh(data=8, model=1))
    np.testing.assert_allclose(a.coefficients, b.coefficients, atol=1e-8)


def test_estimator_api_and_persistence(binary_data, mesh8, tmp_path):
    x, y = binary_data
    ds = {"features": x, "label": y}
    model = LogisticRegression(mesh=mesh8).setRegParam(0.01).fit(ds)
    assert model.numClasses == 2
    out = model.transform(ds)
    assert np.mean(out["prediction"] == y) > 0.7
    proba = model.predict_proba(x)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-9)
    path = str(tmp_path / "logreg")
    model.save(path)
    loaded = LogisticRegressionModel.load(path)
    np.testing.assert_allclose(loaded.coefficients, model.coefficients, atol=1e-12)
    np.testing.assert_array_equal(loaded.predict(x), model.predict(x))


def test_label_validation(mesh8, rng):
    x = rng.normal(size=(20, 3))
    with pytest.raises(ValueError, match="at least 2"):
        fit_logistic_regression(x, np.zeros(20), mesh=mesh8)
    with pytest.raises(ValueError, match="labels must be"):
        fit_logistic_regression(x, np.where(rng.uniform(size=20) < 0.5, 1.0, 5.0), mesh=mesh8)


def test_streaming_matches_batch(rng, mesh8):
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_logistic_regression,
        fit_logistic_stream,
    )

    w_true = rng.normal(size=6)
    x = rng.normal(size=(2000, 6))
    y = (x @ w_true + 0.5 + rng.normal(size=2000) * 0.3 > 0).astype(np.float64)

    sol_b = fit_logistic_regression(
        x, y, reg=1e-3, max_iter=30, tol=1e-8, mesh=mesh8
    )

    def source():
        for i in range(0, 2000, 512):
            yield x[i : i + 512], y[i : i + 512]

    sol_s = fit_logistic_stream(
        source, n_cols=6, reg=1e-3, max_iter=30, tol=1e-8, mesh=mesh8
    )
    assert sol_s.n_rows == 2000
    np.testing.assert_allclose(sol_s.coefficients, sol_b.coefficients, atol=1e-4)
    np.testing.assert_allclose(sol_s.intercept, sol_b.intercept, atol=1e-4)
    assert np.isfinite(sol_s.loss)


def test_streaming_rejects_nonbinary(mesh8, rng):
    from spark_rapids_ml_tpu.models.logistic_regression import fit_logistic_stream

    x = rng.normal(size=(64, 4))
    y = rng.integers(0, 3, size=64).astype(np.float64)  # 3 classes

    def source():
        yield x, y

    with pytest.raises(ValueError, match="binary"):
        fit_logistic_stream(source, n_cols=4, max_iter=2, mesh=mesh8)


def test_streaming_checkpoint_resume(rng, mesh8, tmp_path):
    from spark_rapids_ml_tpu.models.logistic_regression import fit_logistic_stream

    w_true = rng.normal(size=5)
    x = rng.normal(size=(1024, 5))
    y = (x @ w_true > 0).astype(np.float64)
    ck = str(tmp_path / "lr.ckpt")

    def source():
        for i in range(0, 1024, 256):
            yield x[i : i + 256], y[i : i + 256]

    full = fit_logistic_stream(
        source, n_cols=5, reg=1e-3, max_iter=25, tol=1e-10, mesh=mesh8
    )

    class Stop(Exception):
        pass

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 3:
            raise Stop()
        return iter((x[i : i + 256], y[i : i + 256]) for i in range(0, 1024, 256))

    try:
        fit_logistic_stream(
            lambda: flaky(), n_cols=5, reg=1e-3, max_iter=25, tol=1e-10,
            mesh=mesh8, checkpoint_path=ck,
        )
    except Stop:
        pass
    import os

    assert os.path.exists(ck)
    resumed = fit_logistic_stream(
        source, n_cols=5, reg=1e-3, max_iter=25, tol=1e-10,
        mesh=mesh8, checkpoint_path=ck,
    )
    assert not os.path.exists(ck)
    np.testing.assert_allclose(resumed.coefficients, full.coefficients, atol=1e-5)


def test_pcg_solve_matches_direct(rng):
    """_pcg_solve (the TPU-path inner solver) vs numpy direct solve — SPD
    well-conditioned, warm/cold starts, and indefinite breakdown safety."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.models.logistic_regression import _pcg_solve

    d = 96
    a = rng.normal(size=(d, d)).astype(np.float32)
    h = a @ a.T / d + np.eye(d, dtype=np.float32)
    g = rng.normal(size=(d,)).astype(np.float32)
    ref = np.linalg.solve(h, g)
    cold = np.asarray(_pcg_solve(jnp.asarray(h), jnp.asarray(g), jnp.zeros(d), rtol=1e-6))
    np.testing.assert_allclose(cold, ref, rtol=1e-3, atol=1e-4)
    warm = np.asarray(
        _pcg_solve(jnp.asarray(h), jnp.asarray(g), jnp.asarray(ref * 0.9), rtol=1e-6)
    )
    np.testing.assert_allclose(warm, ref, rtol=1e-3, atol=1e-4)
    # Indefinite matrix: must stay finite (terminates on negative curvature)
    hbad = h - 3.0 * np.eye(d, dtype=np.float32)
    out = np.asarray(_pcg_solve(jnp.asarray(hbad), jnp.asarray(g), jnp.zeros(d)))
    assert np.all(np.isfinite(out))


# ---------------------------------------------------------------------------
# Streamed multinomial (MM-Newton) — VERDICT r2 missing #3
# ---------------------------------------------------------------------------


def _batched(x, y, size=200):
    def src():
        return iter(
            [(x[i : i + size], y[i : i + size]) for i in range(0, len(x), size)]
        )

    return src


def test_multinomial_stream_matches_sklearn(multi_data, mesh8):
    """Differential oracle at 1e-4 (the round-2 bar): the streamed
    MM-Newton multinomial converges to sklearn's softmax optimum."""
    from oracles import logreg
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_multinomial_stream,
    )

    x, y = multi_data
    lam = 0.01
    sol = fit_multinomial_stream(
        _batched(x, y), x.shape[1], 3, reg=lam, max_iter=300, tol=1e-10,
        mesh=mesh8,
    )
    ref = logreg(x, y, C=1.0 / (len(x) * lam), tol=1e-12, max_iter=8000)
    # identifiable up to a per-feature constant shift across classes
    ours = sol.coefficients - sol.coefficients.mean(axis=0, keepdims=True)
    theirs = ref.coef_ - ref.coef_.mean(axis=0, keepdims=True)
    np.testing.assert_allclose(ours, theirs, atol=1e-4)
    np.testing.assert_allclose(
        sol.intercept - sol.intercept.mean(),
        ref.intercept_ - ref.intercept_.mean(),
        atol=1e-4,
    )


def test_multinomial_stream_batch_invariance(multi_data, mesh8):
    """Same optimum whatever the batching — the additive-statistics
    property the daemon protocol rides on."""
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_multinomial_stream,
    )

    x, y = multi_data
    a = fit_multinomial_stream(
        _batched(x, y, 150), x.shape[1], 3, reg=0.02, max_iter=60, mesh=mesh8
    )
    b = fit_multinomial_stream(
        _batched(x, y, 600), x.shape[1], 3, reg=0.02, max_iter=60, mesh=mesh8
    )
    np.testing.assert_allclose(a.coefficients, b.coefficients, atol=1e-10)
    np.testing.assert_allclose(a.intercept, b.intercept, atol=1e-10)


def test_multinomial_stream_checkpoint_resume(multi_data, mesh8, tmp_path):
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_multinomial_stream,
    )

    x, y = multi_data
    ckpt = str(tmp_path / "mm.ckpt")
    full = fit_multinomial_stream(
        _batched(x, y), x.shape[1], 3, reg=0.01, max_iter=12, tol=0.0,
        mesh=mesh8,
    )
    # Emulate an interruption at iteration 5: a successful run deletes its
    # own checkpoint, so write the iteration-5 state through the public
    # checkpoint path and resume from it.
    from spark_rapids_ml_tpu.core import checkpoint as ck

    half = fit_multinomial_stream(
        _batched(x, y), x.shape[1], 3, reg=0.01, max_iter=5, tol=0.0,
        mesh=mesh8,
    )
    ck.save_state(
        ckpt,
        {"W": half.coefficients.T, "b": half.intercept},
        {"it": 5, "n_cols": x.shape[1], "n_classes": 3},
    )
    resumed = fit_multinomial_stream(
        _batched(x, y), x.shape[1], 3, reg=0.01, max_iter=12, tol=0.0,
        mesh=mesh8, checkpoint_path=ckpt,
    )
    np.testing.assert_allclose(
        resumed.coefficients, full.coefficients, atol=1e-9
    )
    assert resumed.n_iter == 12


def test_multinomial_stream_rejects_bad_labels(mesh8, rng):
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_multinomial_stream,
    )

    x = rng.normal(size=(100, 4))
    y = np.full((100,), 5.0)  # out of range for n_classes=3
    with pytest.raises(ValueError, match="labels"):
        fit_multinomial_stream(_batched(x, y), 4, 3, max_iter=2, mesh=mesh8)


def test_multinomial_unregularized_one_hot_features_stay_finite(mesh8, rng):
    """ADVICE r5(a) regression: regParam=0 with one-hot features makes
    the per-class MM Hessian singular — one-hot columns plus the
    intercept add an exact shift-invariance null direction to the
    bordered [w; b] system, and a duplicated (collinear) or dead column
    kills h_ww itself. A bare Cholesky then returns NaN coefficients on
    the second step (the first step's curvature at W=0 is benign; the
    fitted-probability curvature is not). The floored solve must keep
    every iterate finite AND still separate the (perfectly predictable)
    classes."""
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_multinomial_stream,
    )

    n = 600
    cat = rng.integers(0, 3, n)
    x = np.zeros((n, 5), np.float64)
    x[np.arange(n), cat] = 1.0       # one-hot: rows sum to 1 (= intercept)
    x[:, 3] = x[:, 0]                # exactly collinear duplicate
    x[:, 4] = 0.0                    # dead column: zero curvature row/col
    y = cat.astype(np.float64)

    sol = fit_multinomial_stream(
        _batched(x, y), 5, 3, reg=0.0, max_iter=50, tol=1e-8, mesh=mesh8
    )
    assert np.isfinite(sol.coefficients).all(), "NaN coefficients at reg=0"
    assert np.isfinite(sol.intercept).all()
    pred = (x @ sol.coefficients.T + sol.intercept).argmax(axis=1)
    assert (pred == cat).mean() == 1.0

    # The intercept-free solve floors h_ww alone — same contract.
    free = fit_multinomial_stream(
        _batched(x, y), 5, 3, reg=0.0, max_iter=50, tol=1e-8, mesh=mesh8,
        fit_intercept=False,
    )
    assert np.isfinite(free.coefficients).all()
    assert ((x @ free.coefficients.T).argmax(axis=1) == cat).mean() == 1.0


def test_binomial_unregularized_one_hot_features_stay_finite(mesh8, rng):
    """The binomial Newton shares ADVICE r5(a)'s failure class one
    function above the multinomial fix: same one-hot ⊕ intercept null
    direction, same collinear/dead-column h_ww singularity, previously
    an unfloored LU solve. Both binomial paths (in-memory direct solve,
    streaming step) must stay finite and separate the classes."""
    from spark_rapids_ml_tpu.models.logistic_regression import (
        fit_logistic_stream,
    )

    n = 600
    cat = rng.integers(0, 3, n)
    x = np.zeros((n, 5), np.float64)
    x[np.arange(n), cat] = 1.0
    x[:, 3] = x[:, 0]
    x[:, 4] = 0.0
    y = (cat == 0).astype(np.float64)

    sol = fit_logistic_regression(x, y, reg=0.0, max_iter=30, mesh=mesh8)
    assert np.isfinite(sol.coefficients).all() and np.isfinite(sol.intercept)
    pred = x @ sol.coefficients.ravel() + sol.intercept > 0
    assert (pred == (y > 0.5)).mean() == 1.0

    stream = fit_logistic_stream(
        _batched(x, y), n_cols=5, reg=0.0, max_iter=30, mesh=mesh8
    )
    assert np.isfinite(stream.coefficients).all()
    pred = x @ stream.coefficients.ravel() + stream.intercept > 0
    assert (pred == (y > 0.5)).mean() == 1.0


def test_binomial_unregularized_one_hot_cg_branch_stays_finite(mesh8, rng):
    """The accelerator (non-CPU) in-memory Newton solves by CG, not
    direct factorization — it needs the same reg=0 floor or it diverges
    along the one-hot ⊕ intercept null direction on exactly the inputs
    the Cholesky path survives. The branch choice reads
    jax.default_backend() at closure-build time (a unique max_iter
    defeats the lru_cache), so mock it to force the CG path on CPU."""
    from unittest import mock

    import jax

    n = 600
    cat = rng.integers(0, 3, n)
    x = np.zeros((n, 5), np.float64)
    x[np.arange(n), cat] = 1.0
    x[:, 3] = x[:, 0]
    x[:, 4] = 0.0
    y = (cat == 0).astype(np.float64)

    with mock.patch.object(jax, "default_backend", return_value="tpu"):
        sol = fit_logistic_regression(x, y, reg=0.0, max_iter=29, mesh=mesh8)
    assert np.isfinite(sol.coefficients).all() and np.isfinite(sol.intercept)
    pred = x @ sol.coefficients.ravel() + sol.intercept > 0
    assert (pred == (y > 0.5)).mean() == 1.0
