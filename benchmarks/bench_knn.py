"""IVF-Flat approximate-KNN query throughput — BASELINE.json config #5
(10M×768 SBERT-class embeddings; scaled to one chip's HBM here).

Data is CLUSTERED (a 4096-component gaussian mixture, within-cluster
spread 0.35) — the embedding-like regime IVF exists for; isotropic random
data has no inverted-list structure and makes recall meaningless. The
index build uses the capacity-balanced quantizer (balanced-Lloyd
refinement + next-nearest spill, models/knn.py) which bounds the padded
layout's maxlen AND is what keeps recall high on clustered data.

Recall@10 is measured against exact chunked brute-force ground truth and
reported in the SAME JSON line; the query path runs with
``ann_rerank=off`` (residual-identity scores answer directly — measured
~1.8× q/s for ~0.015 recall on this workload, still ≥ 0.95).

Baseline: an A100 IVF-Flat at this recall point sustains ~2e5 q/s
(RAFT-class, bandwidth-limited — rough published ballpark; the reference
repo itself publishes nothing, BASELINE.md).
"""

import os
import sys

if __package__ in (None, ""):  # direct script run: python benchmarks/bench_*.py
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

D = int(os.environ.get("SRML_BENCH_D", 768))
N_BASE = int(os.environ.get("SRML_BENCH_BASE_ROWS", 1 << 20))  # 1M×768 = 3.2 GB
N_QUERY = int(os.environ.get("SRML_BENCH_QUERIES", 4096))
K = int(os.environ.get("SRML_BENCH_K", 10))
NLIST = int(os.environ.get("SRML_BENCH_NLIST", 1024))
# nprobe 20 / slack 1.4: the round-3 measured frontier point — with the
# fused kernel's EXACT per-slot selection, probe count (not selection
# loss) sets recall, and the same-run sweep showed recall@10 *rising* as
# nprobe fell (smaller final-merge pool -> less PartialReduce loss) while
# q/s plateaued below nprobe 20 (other stages dominate). 32/1.5 was the
# approx-selection round-2 point; both sweeps are in benchmarks/README.md.
NPROBE = int(os.environ.get("SRML_BENCH_NPROBE", 20))
NCLUST = int(os.environ.get("SRML_BENCH_CLUSTERS", 4096))
SLACK = float(os.environ.get("SRML_BENCH_SLACK", 1.4))

A100_QUERIES_PER_SEC = 2e5


def main() -> None:
    from benchmarks import emit, setup_platform

    setup_platform()
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.knn import (
        _ivf_query_fn,
        _residual_index_data,
        build_ivf_flat_device,
        sq_euclidean,
    )

    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")
    config.set("use_pallas", True)  # fused Lloyd step for the coarse quantizer
    config.set("ann_rerank", False)  # see module docstring

    n_chips = len(jax.devices())
    # Clustered base + queries generated on device (the host CPU is far too
    # slow for 1M×768 draws).
    cc = jax.random.normal(jax.random.key(7), (NCLUST, D), jnp.float32)
    assign = jax.random.randint(jax.random.key(8), (N_BASE,), 0, NCLUST)
    base = cc[assign] + 0.35 * jax.random.normal(
        jax.random.key(9), (N_BASE, D), jnp.float32
    )
    qassign = jax.random.randint(jax.random.key(10), (N_QUERY,), 0, NCLUST)
    queries = cc[qassign] + 0.35 * jax.random.normal(
        jax.random.key(11), (N_QUERY, D), jnp.float32
    )

    # Exact ground truth: chunked brute force (f32 accumulation).
    @jax.jit
    def gt_chunk(qc, bchunk, lo):
        d2 = sq_euclidean(qc, bchunk, accum_dtype=jnp.float32)
        neg, pos = jax.lax.top_k(-d2, K)
        return -neg, pos + lo

    bs = -(-N_BASE // 8)  # ceil: the last chunk may be short, no tail drop
    best_d = np.full((N_QUERY, K), np.inf, np.float32)
    best_i = np.full((N_QUERY, K), -1, np.int64)
    for lo in range(0, N_BASE, bs):
        bchunk = jax.lax.slice_in_dim(base, lo, min(lo + bs, N_BASE))
        dd, ii = gt_chunk(queries, bchunk, lo)
        cat_d = np.concatenate([best_d, np.asarray(dd)], axis=1)
        cat_i = np.concatenate([best_i, np.asarray(ii)], axis=1)
        sel = np.argsort(cat_d, axis=1)[:, :K]
        best_d = np.take_along_axis(cat_d, sel, axis=1)
        best_i = np.take_along_axis(cat_i, sel, axis=1)
    gt = best_i

    index = build_ivf_flat_device(base, nlist=NLIST, seed=0)
    del base  # free 3 GB of HBM — the index alone serves the queries
    dev = [
        jnp.asarray(index.centroids, dtype=jnp.float32),
        jnp.asarray(index.lists, dtype=jnp.float32),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
    ]
    from benchmarks import slope_dt, sync

    # Residual norms + the bf16 residual scan copy are index data:
    # precompute once like a serving deployment would (the model path
    # caches them on device via _ensure_dev_index).
    norms, lists_lo = _residual_index_data(dev[1], dev[0], jnp.bfloat16)
    reps = int(os.environ.get("SRML_BENCH_REPS", 8))

    def measure(rerank: bool, slack: float = SLACK, nprobe: int = NPROBE,
                rerank_width: int = 0, extract: str = "auto"):
        """(q/s, recall@10) at one operating point — BOTH points are
        emitted every run (r2 review: the default config ships
        rerank=on, the headline ran rerank=off; report both always)."""
        query = _ivf_query_fn(
            K, nprobe, "bfloat16", "float32", rerank=rerank, slack=slack,
            fused=str(config.get("ann_fused_scan")),
            rerank_width=rerank_width, extract=extract,
        )
        ids0 = np.asarray(
            query(*dev, queries, resid_norms=norms, lists_lo=lists_lo)[1]
        )
        recall = float(
            np.mean([len(set(ids0[i]) & set(gt[i])) / K for i in range(N_QUERY)])
        )

        # Host-driven rep loop, one jitted call per batch: successive
        # independent batches PIPELINE across the query's probe/scan/
        # select stages on device, which is exactly how a serving host
        # issues them (a lax.scan rep loop serializes the stages and
        # measured ~35% lower — an under-estimate of serving throughput,
        # recorded in benchmarks/README.md). The dev tunnel's per-call
        # dispatch overhead pushes the other way; the slope over reps
        # removes its fixed component.
        def run(n):
            ids = None
            for _ in range(n):
                _, ids = query(
                    *dev, queries, resid_norms=norms, lists_lo=lists_lo
                )
            sync(ids)  # one sync; calls queue on device
            return ids

        # MEDIAN of 5 slopes: single slopes on the shared dev chip have
        # produced 2× outliers in both directions (same discipline as
        # bench_kmeans; the r2 review flagged single-sample spreads).
        run(reps)
        run(3 * reps)
        lats = [slope_dt(run, reps, 3 * reps, warm=False) for _ in range(5)]
        dt = float(np.median(lats))
        return N_QUERY / dt / n_chips, recall

    ab = os.environ.get("SRML_BENCH_AB_FUSED")
    if ab:
        # Same-run interleaved A/B arms (within-session chip drift
        # forbids cross-run comparison — benchmarks/README.md): one extra
        # JSON line per arm, then the normal headline (auto = fused).
        # SRML_BENCH_AB_FUSED=1 → the fused-off/on pair; or a
        # semicolon-separated list of arm specs, e.g.
        # "fused=off;fused=on;fused=on,slack=1.25,nprobe=28".
        specs = (
            ["fused=off", "fused=on"]
            if ab == "1"
            else [a for a in ab.split(";") if a]
        )
        for spec in specs:
            kv = dict(p.split("=") for p in spec.split(","))
            config.set("ann_fused_scan", kv.get("fused", "auto"))
            qps, rec = measure(
                rerank=kv.get("rerank", "off") == "on",
                slack=float(kv.get("slack", SLACK)),
                nprobe=int(kv.get("nprobe", NPROBE)),
                rerank_width=int(kv.get("rw", 0)),
                extract=kv.get("extract", "auto"),
            )
            emit(
                "ivfflat_ab_" + spec.replace("=", "").replace(",", "_"),
                qps, "queries/s/chip",
                qps / A100_QUERIES_PER_SEC, recall_at_10=round(rec, 4),
            )
        config.set("ann_fused_scan", "auto")

    qps_off, recall_off = measure(rerank=False)
    qps_on, recall_on = measure(rerank=True)
    # Third point: rerank with NARROW kernel extraction (config
    # ann_extract) — the round-4 speed/recall dial between the two.
    qps_nar, recall_nar = measure(rerank=True, extract="narrow")
    emit(
        f"ivfflat_queries_per_sec_per_chip_n{N_BASE}_d{D}"
        f"_k{K}_nprobe{NPROBE}_clustered",
        qps_off,
        "queries/s/chip",
        qps_off / A100_QUERIES_PER_SEC,
        recall_at_10=round(recall_off, 4),
        rerank_on_qps=round(qps_on, 1),
        rerank_on_recall=round(recall_on, 4),
        rerank_on_vs_baseline=round(qps_on / A100_QUERIES_PER_SEC, 4),
        rerank_narrow_qps=round(qps_nar, 1),
        rerank_narrow_recall=round(recall_nar, 4),
    )


if __name__ == "__main__":
    main()
