"""Pallas TPU kernels for the hot ops.

The reference's hand-written device code is a Thrust sign-flip kernel and
cuBLAS GEMM calls (rapidsml_jni.cu). On TPU, XLA already fuses the
mask-multiply + GEMM + accumulate chain well, so Pallas here targets the
places hand-tiling pays:

* ``gram_pallas`` / ``gram_colsum_pallas`` — tiled XᵀX with the mask (or
  n_valid boundary) fused into the load, accumulators VMEM-resident.
* ``assign_min_dist_pallas`` / ``lloyd_step_pallas`` — KMeans assignment
  (+ fused centroid-sum update): distance tile + argmin fused, never
  materializing the (m, k) distance matrix in HBM.
* ``newton_stats_pallas`` — one-HBM-pass binomial Newton statistics.
* ``ivf_scan_select_pallas`` — IVF bucketed scan: per-list residual GEMM
  + exact packed-key top-k selection, scores VMEM-resident (gated by
  ``config.ann_fused_scan``, not ``use_pallas``).

All are gated with the XLA path as the default/fallback; parity is tested
in interpret mode on CPU (tests/test_pallas.py) so the kernels stay
correct even when no TPU is attached.

See /opt/skills/guides/pallas_guide.md for the tiling constraints used
here (f32 min tile (8, 128); MXU 128×128).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spark_rapids_ml_tpu.utils.xprof import ledgered_jit


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _dot_prec(dt):
    """Mosaic, like XLA, defaults f32 dots to single-pass bf16 mantissas on
    TPU; request full precision for f32 operands. bf16 operands pin
    DEFAULT explicitly — an ambient ``mm_precision`` HIGHEST context would
    otherwise make Mosaic attempt an f32x3 decomposition of a bf16 lhs
    ("Bad lhs type")."""
    return (
        jax.lax.Precision.HIGHEST
        if jnp.dtype(dt) == jnp.float32
        else jax.lax.Precision.DEFAULT
    )


# ---------------------------------------------------------------------------
# Tiled Gram: G = (X·mask)ᵀ (X·mask), accumulated in float32
# ---------------------------------------------------------------------------


def _gram_kernel(x_i_ref, x_j_ref, mask_ref, o_ref):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    m = mask_ref[:]  # (bn, 1) — 2-D: 1-D operands trip an XLA↔Mosaic
    # layout mismatch on real TPUs (T(1024) vs T(512) tiling)
    xi = x_i_ref[:] * m
    xj = x_j_ref[:] * m
    o_ref[:] += jax.lax.dot_general(
        xi, xj, (((0,), (0,)), ((), ())), preferred_element_type=o_ref.dtype,
        precision=_dot_prec(xi.dtype),
    )


@functools.partial(
    ledgered_jit, "pallas.gram_pallas", static_argnames=("block_n", "block_d", "interpret")
)
def gram_pallas(
    x: jax.Array,
    mask: jax.Array,
    block_n: int = 512,
    block_d: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Masked Gram XᵀX of an (n, d) block, float32 accumulate.

    n must divide block_n and d divide block_d (callers pad; shard_rows
    already zero-pads rows and the mask kills padding contributions).
    """
    n, d = x.shape
    bn = min(block_n, n)
    bd = min(block_d, d)
    if n % bn or d % bd:
        raise ValueError(f"shape ({n},{d}) not divisible by blocks ({bn},{bd})")
    grid = (d // bd, d // bd, n // bn)
    return pl.pallas_call(
        _gram_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, bd), lambda i, j, kk: (kk, i)),
            pl.BlockSpec((bn, bd), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bn, 1), lambda i, j, kk: (kk, 0)),
        ],
        out_specs=pl.BlockSpec((bd, bd), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((d, d), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
        if not interpret
        else None,
        interpret=interpret,
    )(x, x, mask.reshape(n, 1))  # x twice: (kk, i) and (kk, j) row-tile views


# ---------------------------------------------------------------------------
# Single-pass fused Gram + column-sum with a VMEM-resident accumulator
# ---------------------------------------------------------------------------


# Defaults shared with the streaming-path applicability gate (ops/gram.py).
GRAM_COLSUM_BLOCK_N = 512
GRAM_COLSUM_VMEM_BUDGET = 64 * 2**20  # max (d, d) f32 resident accumulator


def _gram_colsum_kernel(nvalid_ref, x_ref, *refs, block_n, seeded):
    if seeded:
        g0_ref, cs0_ref, c0_ref, g_ref, cs_ref, c_ref = refs
    else:
        g_ref, cs_ref, c_ref = refs

    @pl.when(pl.program_id(0) == 0)
    def _init():
        if seeded:
            # Accumulators start from the caller's streaming state, so the
            # whole per-batch update (state + batch stats) is ONE dispatch
            # with no separate add kernel reading the (d, d) state again.
            g_ref[:] = g0_ref[:]
            cs_ref[:] = cs0_ref[:]
            c_ref[:] = c0_ref[:]
        else:
            g_ref[:] = jnp.zeros_like(g_ref)
            cs_ref[:] = jnp.zeros_like(cs_ref)
            c_ref[:] = jnp.zeros_like(c_ref)

    row0 = pl.program_id(0) * block_n
    nv = nvalid_ref[0]

    # Blocks entirely past n_valid contribute nothing — skip their GEMM
    # (power-of-two bucketing can make half the blocks pure padding).
    @pl.when(row0 < nv)
    def _accumulate():
        # Only the one block straddling the n_valid boundary pays the mask;
        # full blocks skip the iota/select VPU pass entirely.
        @pl.when(row0 + block_n > nv)
        def _mask_boundary():
            rows = jax.lax.broadcasted_iota(jnp.int32, x_ref.shape, 0) + row0
            x_ref[:] = jnp.where(rows < nv, x_ref[:], jnp.zeros_like(x_ref))

        xb = x_ref[:]
        g_ref[:] += jax.lax.dot_general(
            xb, xb, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=_dot_prec(xb.dtype),
        )
        cs_ref[:] += jnp.sum(xb.astype(jnp.float32), axis=0, keepdims=True)
        lane = jax.lax.broadcasted_iota(jnp.int32, c_ref.shape, 1)
        valid = jnp.minimum(nv - row0, block_n).astype(jnp.float32)
        c_ref[:] += jnp.where(lane == 0, valid, 0.0)


@functools.partial(
    ledgered_jit, "pallas.gram_colsum_pallas", static_argnames=("block_n", "interpret")
)
def gram_colsum_pallas(
    x: jax.Array,
    n_valid: jax.Array,
    block_n: int = GRAM_COLSUM_BLOCK_N,
    state=None,
    interpret: bool = False,
):
    """One-HBM-pass fused count + column sum + XᵀX of the first ``n_valid``
    rows — the full streaming-moment statistic in a single kernel.

    x: (n, d) in the compute dtype (bfloat16 engages the MXU at full rate;
    the GEMM accumulates in float32 either way). Rows ≥ n_valid are treated
    as absent — this replaces the (n,) mask array of ``gram_pallas`` with a
    scalar, so no mask ever touches HBM and only the boundary block pays
    any select cost. The (d, d) accumulator lives in VMEM across the whole
    row-grid (grid is 1-D over row blocks), so X is read exactly once —
    the streaming equivalent of the reference's dgemmCov hot loop
    (rapidsml_jni.cu:109-127) with its mean-stats pass fused in.

    ``state``: optional ``(gram, colsum, count)`` f32 streaming state the
    accumulators are SEEDED from (loaded into VMEM at the first grid step),
    so the per-batch ``state += batch_stats`` of the streaming fit is this
    one dispatch — the separate XLA add that re-read and re-wrote the
    (d, d) state per batch is gone (ops/gram.streaming_update_rows consumes
    this under donation on single-data-device meshes).

    Returns (gram (d, d) float32, colsum (d,) float32, count () float32 —
    exact up to 2^24 rows per accumulator lifetime).
    """
    n, d = x.shape
    bn = min(block_n, n)
    if n % bn:
        raise ValueError(f"n={n} not divisible by block_n={bn}")
    if d * d * 4 > GRAM_COLSUM_VMEM_BUDGET:
        raise ValueError(f"d={d}: (d, d) f32 accumulator exceeds the VMEM budget")
    nv = jnp.asarray(n_valid, jnp.int32).reshape((1,))
    seeded = state is not None
    extra_in = []
    extra_specs = []
    if seeded:
        g0, cs0, c0 = state
        extra_in = [
            g0.astype(jnp.float32),
            cs0.astype(jnp.float32).reshape(1, d),
            jnp.zeros((1, 128), jnp.float32)
            .at[0, 0].set(jnp.asarray(c0, jnp.float32)),
        ]
        extra_specs = [
            pl.BlockSpec((d, d), lambda i, nv: (0, 0)),
            pl.BlockSpec((1, d), lambda i, nv: (0, 0)),
            pl.BlockSpec((1, 128), lambda i, nv: (0, 0)),
        ]
    gram, colsum, count = pl.pallas_call(
        functools.partial(_gram_colsum_kernel, block_n=bn, seeded=seeded),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, d), lambda i, nv: (i, 0))] + extra_specs,
            out_specs=[
                pl.BlockSpec((d, d), lambda i, nv: (0, 0)),
                pl.BlockSpec((1, d), lambda i, nv: (0, 0)),
                pl.BlockSpec((1, 128), lambda i, nv: (0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            # (d, d) f32 accumulator + double-buffered input blocks; the
            # default 16M scoped limit rejects d ≥ 1448.
            vmem_limit_bytes=100 * 2**20,
        )
        if not interpret
        else None,
        interpret=interpret,
    )(nv, x, *extra_in)
    return gram, colsum[0], count[0, 0]


# ---------------------------------------------------------------------------
# Fused KMeans Lloyd step: assign + centroid-sum update in one HBM pass
# ---------------------------------------------------------------------------


def _lloyd_step_kernel(
    nvalid_ref, x_ref, c_ref, c2h_ref, sums_ref, counts_ref, *, block_n, dead_lane
):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)

    row0 = pl.program_id(0) * block_n
    nv = nvalid_ref[0]

    @pl.when(row0 < nv)
    def _accumulate():
        xb = x_ref[:]  # (bn, d) compute dtype
        c = c_ref[:]  # (k_pad, d) compute dtype; padded rows are zeros
        # TRANSPOSED distance layout (k_pad, bn): the argmin then reduces
        # over the SUBLANE axis instead of the 128-lane axis — sublane
        # reductions are the cheap direction on the VPU, and the profile
        # at d=256/k=100 was assignment(VPU)-bound, not MXU-bound.
        xc = jax.lax.dot_general(
            c, xb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=_dot_prec(xb.dtype),
        )  # (k_pad, bn)
        # ½‖x−c‖² up to the row-constant ½‖x‖²: argmin-invariant; the ½c²
        # is precomputed host-side (one VPU subtract per element here).
        # Padded centers carry c2h = LLOYD_PAD_D2 so they never win.
        d2 = c2h_ref[:] - xc  # (k_pad, bn); c2h is (k_pad, 1)
        assign = jnp.argmin(d2, axis=0).astype(jnp.int32)[None, :]  # (1, bn)
        cols = jax.lax.broadcasted_iota(jnp.int32, assign.shape, 1) + row0
        ks = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 0)
        if dead_lane is not None:
            # Padded rows (x = 0) would argmin to the min-norm REAL center
            # and pollute counts; with k < k_pad a spare center row exists
            # — route them there ((1, bn) compare) and skip the
            # (k_pad, bn) row-mask pass entirely (sums[k:] are discarded
            # by the caller).
            assign = jnp.where(cols < nv, assign, dead_lane)
            onehot = (ks == assign).astype(xb.dtype)  # (k_pad, bn)
        else:
            onehot = ((ks == assign) & (cols < nv)).astype(xb.dtype)
        sums_ref[:] += jax.lax.dot_general(
            onehot, xb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=_dot_prec(xb.dtype),
        )
        counts_ref[:] += jnp.sum(onehot.astype(jnp.float32), axis=1)[None, :]


LLOYD_PAD_D2 = 1e30  # finite sentinel: padded centers never win the argmin
LLOYD_STEP_BLOCK_N = 4096


@functools.partial(
    ledgered_jit, "pallas.lloyd_step_pallas", static_argnames=("k", "block_n", "interpret")
)
def lloyd_step_pallas(
    x: jax.Array,
    centers: jax.Array,
    n_valid: jax.Array,
    k: int,
    block_n: int = LLOYD_STEP_BLOCK_N,
    interpret: bool = False,
):
    """One fused Lloyd iteration's statistics in a single HBM pass over x.

    x: (n, d) compute dtype; centers: (k_pad, d) compute dtype whose rows
    beyond the true ``k`` are padding — they are excluded from the argmin
    via a LLOYD_PAD_D2 distance sentinel. Whole blocks past n_valid skip
    their GEMMs entirely; invalid rows of the boundary block are routed
    to the DEAD LANE ``k`` when k < k_pad (cheaper than a (bn, k_pad)
    row mask), so **sums[k]/counts[k] carry their garbage and callers
    MUST slice [:k]** (counts.sum() is NOT the valid-row count; lanes
    k+1.. stay zero). When k == k_pad the row-mask path runs instead and
    all lanes are exact.

    Per block: pairwise-distance GEMM → argmin → one-hot → centroid-sum
    GEMM, with the (k_pad, d) sums and (1, k_pad) counts accumulators
    VMEM-resident across the row grid. Nothing of size (n, k) or (n, d)
    is ever written back to HBM — the fusion the XLA path can't express
    (it materializes both the distance matrix and the one-hot matrix).

    Returns (sums (k_pad, d) float32, counts (k_pad,) float32).
    """
    n, d = x.shape
    k_pad = centers.shape[0]
    bn = min(block_n, n)
    if n % bn:
        raise ValueError(f"n={n} not divisible by block_n={bn}")
    if k_pad % 128:
        raise ValueError(f"k_pad={k_pad} must be a multiple of 128 lanes")
    c2h = 0.5 * jnp.sum(
        jnp.square(centers.astype(jnp.float32)), axis=1, keepdims=True
    )  # (k_pad, 1) — column vector for the transposed (k_pad, bn) layout
    ks = jax.lax.broadcasted_iota(jnp.int32, c2h.shape, 0)
    c2h = jnp.where(ks < k, c2h, LLOYD_PAD_D2)
    nv = jnp.asarray(n_valid, jnp.int32).reshape((1,))
    sums, counts = pl.pallas_call(
        functools.partial(
            _lloyd_step_kernel, block_n=bn,
            dead_lane=k if k < k_pad else None,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // bn,),
            in_specs=[
                pl.BlockSpec((bn, d), lambda i, nv: (i, 0)),
                pl.BlockSpec((k_pad, d), lambda i, nv: (0, 0)),
                pl.BlockSpec((k_pad, 1), lambda i, nv: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((k_pad, d), lambda i, nv: (0, 0)),
                pl.BlockSpec((1, k_pad), lambda i, nv: (0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), vmem_limit_bytes=100 * 2**20
        )
        if not interpret
        else None,
        interpret=interpret,
    )(nv, x, centers, c2h)
    return sums, counts[0]


# ---------------------------------------------------------------------------
# Fused binomial Newton statistics: one HBM pass per IRLS iteration
# ---------------------------------------------------------------------------


NEWTON_STATS_BLOCK_N = 512
NEWTON_STATS_VMEM_BUDGET = 64 * 2**20  # max (d, d) f32 resident Hessian


def _newton_stats_kernel(b_ref, x_ref, y_ref, m_ref, w_ref, gw_ref, h_ref, s_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        gw_ref[:] = jnp.zeros_like(gw_ref)
        h_ref[:] = jnp.zeros_like(h_ref)
        s_ref[:] = jnp.zeros_like(s_ref)

    xb = x_ref[:]  # (bn, d) compute dtype
    y = y_ref[:]  # (bn, 1) f32
    m = m_ref[:]  # (bn, 1) f32
    w = w_ref[:]  # (128, d) compute dtype; row 0 = w, rest zeros
    hp = _dot_prec(xb.dtype)
    # Row-local IRLS quantities: z → p → (residual, weight). This is why the
    # whole iteration fits in one pass — nothing couples rows except the
    # final sums. Two Mosaic shape/fusion constraints shape the matvec:
    # the MXU pads N to 128 lanes anyway but rejects bf16 dots with a
    # literal N=1, so w arrives pre-padded to (128, d); and the scalar
    # `+ b` must come after the lane slice — fusing an add into a matmul
    # accumulator is rejected ("Only constant accumulator supported").
    z128 = jax.lax.dot_general(
        xb, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hp,
    )  # (bn, 128); only lane 0 is live
    z = z128[:, :1] + b_ref[0]  # (bn, 1)
    p = jax.nn.sigmoid(z)
    r = (p - y) * m
    wgt = jnp.maximum(p * (1.0 - p), 1e-10) * m
    # One (128, bn)×(bn, d) GEMM yields both vector statistics: row 0 the
    # gradient Xᵀr, row 1 the intercept border Xᵀwgt (M is MXU-padded to
    # 128 regardless, and M=2 trips the same Mosaic shape limit as N=1).
    lane = jax.lax.broadcasted_iota(jnp.int32, (xb.shape[0], 128), 1)
    rw = (
        jnp.where(lane == 0, r, 0.0) + jnp.where(lane == 1, wgt, 0.0)
    ).astype(xb.dtype)  # (bn, 128)
    gw_ref[:] += jax.lax.dot_general(
        rw, xb, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=hp,
    )
    # Hessian Xᵀdiag(wgt)X at fast DEFAULT precision: it is a
    # preconditioner, not the answer (see models/logistic_regression.py) —
    # the gradient above sets the fixed point.
    xw = xb * wgt.astype(xb.dtype)
    h_ref[:] += jax.lax.dot_general(
        xw, xb, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=jax.lax.Precision.DEFAULT,
    )
    slane = jax.lax.broadcasted_iota(jnp.int32, s_ref.shape, 1)
    s_ref[:] += jnp.where(slane == 0, jnp.sum(r), 0.0) + jnp.where(
        slane == 1, jnp.sum(wgt), 0.0
    )


@functools.partial(
    ledgered_jit, "pallas.newton_stats_pallas", static_argnames=("block_n", "interpret")
)
def newton_stats_pallas(
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    w: jax.Array,
    b: jax.Array,
    block_n: int = NEWTON_STATS_BLOCK_N,
    interpret: bool = False,
):
    """One binomial Newton-IRLS iteration's statistics in a single HBM pass.

    The XLA lowering of the IRLS body reads x ~4× per iteration (z matvec,
    gradient GEMM, weighted copy x·wgt, Hessian GEMM) — at d=1024 the step
    is HBM-bound, not MXU-bound. Here z, p, and the per-row
    residual/weight are computed in VMEM per row block and x feeds both
    GEMMs from the same resident tile, so x streams through HBM exactly
    once per Newton step. The (d, d) Hessian accumulator stays VMEM-
    resident across the whole row grid (same design as
    :func:`gram_colsum_pallas`).

    x: (n, d) in the compute dtype — bfloat16 streams half the HBM bytes
    and runs every dot single-pass on the MXU (the intended speed mode);
    float32 keeps full-precision gradients. y/mask: (n,) f32 (mask
    multiplies both residual and weight, so arbitrary row masks work, not
    just valid-prefixes); w: (d,) f32; b: scalar f32 (prefetched to SMEM).

    Returns raw (unnormalized, pre-psum) sums:
    (grad_w (d,), grad_b (), h_ww (d, d), h_wb (d,), h_bb ()), all f32 —
    the caller divides by the global row count, adds ridge terms, and
    psums across the data axis.
    """
    n, d = x.shape
    bn = min(block_n, n)
    if n % bn:
        raise ValueError(f"n={n} not divisible by block_n={bn}")
    if d * d * 4 > NEWTON_STATS_VMEM_BUDGET:
        raise ValueError(f"d={d}: (d, d) f32 Hessian exceeds the VMEM budget")
    bvec = jnp.asarray(b, jnp.float32).reshape((1,))
    wpad = jnp.zeros((128, d), x.dtype).at[0].set(w.astype(x.dtype))
    gw, h, s = pl.pallas_call(
        _newton_stats_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // bn,),
            in_specs=[
                pl.BlockSpec((bn, d), lambda i, b: (i, 0)),
                pl.BlockSpec((bn, 1), lambda i, b: (i, 0)),
                pl.BlockSpec((bn, 1), lambda i, b: (i, 0)),
                pl.BlockSpec((128, d), lambda i, b: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((128, d), lambda i, b: (0, 0)),
                pl.BlockSpec((d, d), lambda i, b: (0, 0)),
                pl.BlockSpec((1, 128), lambda i, b: (0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((128, d), jnp.float32),
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), vmem_limit_bytes=100 * 2**20
        )
        if not interpret
        else None,
        interpret=interpret,
    )(
        bvec,
        x,
        y.astype(jnp.float32).reshape(n, 1),
        mask.astype(jnp.float32).reshape(n, 1),
        wpad,
    )
    return gw[0], s[0, 0], h, gw[1], s[0, 1]


# ---------------------------------------------------------------------------
# Fused KMeans assignment: argmin_k ||x - c_k||² without an (m, k) HBM array
# ---------------------------------------------------------------------------


def _assign_kernel(x_ref, c_ref, c2_ref, best_d_ref, best_i_ref):
    kk = pl.program_id(1)

    @pl.when(kk == 0)
    def _init():
        best_d_ref[:] = jnp.full_like(best_d_ref, jnp.inf)
        best_i_ref[:] = jnp.zeros_like(best_i_ref)

    x = x_ref[:]  # (bm, d)
    c = c_ref[:]  # (bk, d)
    c2 = c2_ref[:]  # (bk,)
    # ||x-c||² up to the query-constant ||x||²: c² − 2xc (argmin-invariant).
    xc = jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        precision=_dot_prec(x.dtype),
    )
    d2 = c2[None, :] - 2.0 * xc  # (bm, bk)
    local_best = jnp.min(d2, axis=1)
    bk = c.shape[0]
    local_idx = jnp.argmin(d2, axis=1).astype(jnp.int32) + kk * bk
    improved = local_best < best_d_ref[:]
    best_i_ref[:] = jnp.where(improved, local_idx, best_i_ref[:])
    best_d_ref[:] = jnp.where(improved, local_best, best_d_ref[:])


@functools.partial(
    ledgered_jit, "pallas.assign_min_dist_pallas", static_argnames=("block_m", "block_k", "interpret")
)
def assign_min_dist_pallas(
    x: jax.Array,
    centers: jax.Array,
    block_m: int = 1024,
    block_k: int = 128,
    interpret: bool = False,
):
    """(assignments (m,), partial_min_d2 (m,)) for KMeans, fused tile-wise.

    Returned distances omit the +‖x‖² query constant (argmin-invariant);
    callers needing true distances add it back.
    """
    m, d = x.shape
    k = centers.shape[0]
    bm = min(block_m, m)
    bk = min(block_k, k)
    if m % bm or k % bk:
        raise ValueError(f"shape m={m},k={k} not divisible by blocks ({bm},{bk})")
    c2 = jnp.sum(jnp.square(centers.astype(jnp.float32)), axis=1)
    grid = (m // bm, k // bk)
    best_d, best_i = pl.pallas_call(
        _assign_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, d), lambda i, kk: (i, 0)),
            pl.BlockSpec((bk, d), lambda i, kk: (kk, 0)),
            pl.BlockSpec((bk,), lambda i, kk: (kk,)),
        ],
        out_specs=[
            pl.BlockSpec((bm,), lambda i, kk: (i,)),
            pl.BlockSpec((bm,), lambda i, kk: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m,), jnp.float32),
            jax.ShapeDtypeStruct((m,), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        )
        if not interpret
        else None,
        interpret=interpret,
    )(x, centers, c2)
    return best_i, best_d


# ---------------------------------------------------------------------------
# Fused streaming distance + EXACT top-k: kneighbors without the (q, m) matrix
# ---------------------------------------------------------------------------


DIST_TOPK_BLOCK_M = 1024
DIST_TOPK_BLOCK_Q = 256
#: Extraction-pass unroll bound: each of the k selection passes is a pair
#: of sublane reduces over the (block_m + k_pad, qb) tile, statically
#: unrolled — past this, selection cost and program size outgrow the GEMM
#: and the two-step XLA path wins anyway.
DIST_TOPK_MAX_K = 64


def _dist_topk_kernel(rows_ref, r2_ref, ids_ref, qT_ref, q2_ref,
                      d_ref, i_ref, *, k):
    """One candidate block per inner grid step: distance GEMM + merge into
    the running per-query top-k, the (bm, qb) score tile never leaving VMEM.

    Layout is the round-3 selection lesson (benchmarks/README.md) applied
    to the EXACT kneighbors path: candidates ride the SUBLANES, queries the
    LANES, so every one of the k extraction passes reduces over the cheap
    VPU direction. The running (k_pad, qb) best-distance/best-id planes are
    VMEM-resident across the whole candidate grid — nothing of size (q, m)
    is ever written to HBM, the fusion the XLA ``sq_euclidean`` →
    ``lax.top_k`` two-step cannot express (it materializes the full
    distance matrix between the two ops).

    Selection is k lexicographic (distance, id) min-extraction passes over
    the concatenation of the running best and the fresh block: ids are
    globally unique for valid rows, so each pass's equality mask removes
    exactly one element, and ties resolve to the LOWEST id — the
    ``merge_topk`` host-merge contract, pinned by the duplicate-distance
    regression test so sharded and single-daemon answers stay comparable.
    Invalid/padded rows carry (+inf, -1) and sort past every real
    candidate; slots with no finite candidate emit exactly (+inf, -1).
    """
    jb = pl.program_id(1)

    @pl.when(jb == 0)
    def _init():
        d_ref[:] = jnp.full_like(d_ref, jnp.inf)
        i_ref[:] = jnp.full_like(i_ref, -1)

    rows = rows_ref[:]  # (bm, d) compute dtype; padded rows zero
    qT = qT_ref[:]  # (d, qb) compute dtype
    qr = jax.lax.dot_general(
        rows, qT, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        precision=_dot_prec(rows.dtype),
    )  # (bm, qb)
    # Same term order as ops/distances.sq_euclidean ((x²+y²) − 2xy, clipped
    # at 0) so fused and unfused distances differ only by GEMM tiling.
    d2 = jnp.maximum(q2_ref[:] + r2_ref[:] - 2.0 * qr, 0.0)  # (bm, qb)
    ids = jnp.broadcast_to(ids_ref[:], d2.shape)  # (bm, qb) int32
    cat_d = jnp.concatenate([d_ref[:], d2], axis=0)  # (k_pad + bm, qb)
    cat_i = jnp.concatenate([i_ref[:], ids], axis=0)
    for j in range(k):
        m = jnp.min(cat_d, axis=0, keepdims=True)  # (1, qb) sublane min
        mi = jnp.min(
            jnp.where(cat_d == m, cat_i, jnp.int32(0x7FFFFFFF)),
            axis=0, keepdims=True,
        )  # lowest id among distance ties: the (distance, id) order
        d_ref[j : j + 1, :] = m
        i_ref[j : j + 1, :] = jnp.where(m < jnp.inf, mi, jnp.int32(-1))
        cat_d = jnp.where((cat_d == m) & (cat_i == mi), jnp.inf, cat_d)


@functools.partial(
    ledgered_jit, "pallas.dist_topk_pallas",
    static_argnames=("k", "block_m", "block_q", "interpret"),
)
def dist_topk_pallas(
    queries: jax.Array,
    db: jax.Array,
    row_ids: jax.Array,
    mask: jax.Array,
    k: int,
    block_m: int = DIST_TOPK_BLOCK_M,
    block_q: int = DIST_TOPK_BLOCK_Q,
    interpret: bool = False,
):
    """Exact fused kneighbors core: per-query top-``k`` squared-Euclidean
    neighbors of ``queries`` (q, d) against ``db`` (m, d), streaming db
    blocks through one HBM pass with the running k-best VMEM-resident —
    the (q, m) distance matrix is never materialized (the ledger's
    ``memory_analysis`` receipt in tests/test_knn.py pins that).

    ``row_ids``: (m,) int32 global ids of the db rows (-1 on padding);
    ``mask``: (m,) {0,1} — masked rows score +inf and emit id -1, matching
    the XLA path's missing-slot contract. Ties resolve by ascending
    (distance, id) — bitwise the ``merge_topk``/``reduce_topk`` order, so
    sharded and single-daemon kneighbors stay comparable. Distances are
    true clipped f32 squared distances (not argmin-residuals).

    Returns (dists (q, k) f32 ascending, ids (q, k) int32).
    """
    q, d = queries.shape
    m = db.shape[0]
    if k > DIST_TOPK_MAX_K:
        raise ValueError(f"k={k} exceeds DIST_TOPK_MAX_K={DIST_TOPK_MAX_K}")
    if k > m:
        raise ValueError(f"k={k} exceeds database rows m={m}")
    qb = min(block_q, _ceil_to(q, 8))
    q_pad = _ceil_to(q, qb)
    bm = min(block_m, _ceil_to(m, 8))
    m_pad = _ceil_to(m, bm)
    qf = queries.astype(jnp.float32)
    q2 = jnp.sum(jnp.square(qf), axis=1)[None, :]  # (1, q) f32
    qT = jnp.swapaxes(queries, 0, 1)  # (d, q) compute dtype
    if q_pad != q:
        qT = jnp.pad(qT, ((0, 0), (0, q_pad - q)))
        q2 = jnp.pad(q2, ((0, 0), (0, q_pad - q)))
    dbf = db.astype(jnp.float32)
    r2 = jnp.where(
        mask.astype(jnp.float32) > 0,
        jnp.sum(jnp.square(dbf), axis=1),
        jnp.inf,
    )[:, None]  # (m, 1) f32; +inf never wins and decodes to id -1
    ids = jnp.asarray(row_ids, jnp.int32)[:, None]
    if m_pad != m:
        db = jnp.pad(db, ((0, m_pad - m), (0, 0)))
        r2 = jnp.pad(r2, ((0, m_pad - m), (0, 0)), constant_values=jnp.inf)
        ids = jnp.pad(ids, ((0, m_pad - m), (0, 0)), constant_values=-1)
    k_pad = _ceil_to(k, 8)
    best_d, best_i = pl.pallas_call(
        functools.partial(_dist_topk_kernel, k=k),
        name="dist_topk",
        grid=(q_pad // qb, m_pad // bm),
        in_specs=[
            pl.BlockSpec((bm, d), lambda i, j: (j, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (j, 0)),
            pl.BlockSpec((bm, 1), lambda i, j: (j, 0)),
            pl.BlockSpec((d, qb), lambda i, j: (0, i)),
            pl.BlockSpec((1, qb), lambda i, j: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, qb), lambda i, j: (0, i)),
            pl.BlockSpec((k_pad, qb), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, q_pad), jnp.float32),
            jax.ShapeDtypeStruct((k_pad, q_pad), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 2**20,
        )
        if not interpret
        else None,
        interpret=interpret,
    )(db, r2, ids, qT, q2)
    return best_d[:k, :q].T, best_i[:k, :q].T


# ---------------------------------------------------------------------------
# Fused IVF list scan + EXACT per-slot top-k selection
# ---------------------------------------------------------------------------


# Masked-winner key: int32 max — strictly above every packed finite-score
# key (a finite f32 score maps below 0x7F800000, and the position bits
# only fill the cleared low bits).
IVF_MASKED_KEY = 0x7FFFFFFF
# Emitted in the sublane-pad output rows callers slice away.
IVF_MASKED_D2 = 3.0e38


def _sortable_int(v):
    """The order-preserving f32↔int32 bijection (IEEE trick: flip the
    non-sign bits of negatives). Self-inverse; finite inputs assumed."""
    return v ^ (
        jax.lax.shift_right_arithmetic(v, jnp.int32(31)) & jnp.int32(0x7FFFFFFF)
    )


def _packed_keys(scores, pos_bits):
    """(maxlen, C) f32 scores → UNIQUE packed int32 keys: sortable value
    in the high bits, sublane position in the low ``pos_bits``. Shared by
    the scan-selection and probe-selection kernels."""
    low = jnp.int32((1 << pos_bits) - 1)
    key = _sortable_int(jax.lax.bitcast_convert_type(scores, jnp.int32))
    return (key & ~low) | jax.lax.broadcasted_iota(jnp.int32, key.shape, 0)


def _packed_extract(key, d_ref, p_ref, count, pos_bits):
    """``count`` exact ascending min-extraction passes over packed keys:
    each pass is one sublane min-reduce + one single-element equality mask
    (keys unique ⇒ ties resolve to the lowest position). Decoded values
    are floored within a relative 2^(pos_bits-24) (the packed-key mantissa
    trade). Sublane-pad output rows get the (IVF_MASKED_D2, 0) sentinel so
    the output is deterministic."""
    low = jnp.int32((1 << pos_bits) - 1)
    for j in range(count):
        m = jnp.min(key, axis=0, keepdims=True)  # (1, C) sublane min
        pos = m & low
        vkey = m ^ pos  # position bits cleared: the floored value key
        d_ref[j : j + 1, :] = jax.lax.bitcast_convert_type(
            _sortable_int(vkey), jnp.float32
        )
        p_ref[j : j + 1, :] = pos
        key = jnp.where(key == m, jnp.int32(IVF_MASKED_KEY), key)
    if count < d_ref.shape[0]:
        pad = jax.lax.broadcasted_iota(
            jnp.int32, (d_ref.shape[0] - count, key.shape[1]), 0
        )
        d_ref[count:, :] = jnp.full_like(pad, IVF_MASKED_D2, jnp.float32)
        p_ref[count:, :] = jnp.zeros_like(pad)


def _ivf_scan_select_kernel(
    qv_ref, rows_ref, r2_ref, d_ref, p_ref, *, blk_k, pos_bits
):
    """One probed list per grid step: residual-score GEMM + exact top-blk_k
    per query slot, the (maxlen, C) score tile never leaving VMEM.

    Layout is the round-3 Lloyd lesson applied to selection (see
    benchmarks/README.md): scores are computed as (maxlen, C) — candidate
    ROWS on sublanes, query SLOTS on lanes — so each extraction pass
    reduces over the SUBLANE axis, the cheap VPU direction.

    Selection runs on PACKED sortable keys: the f32 score is mapped to a
    total-order-preserving int32 (IEEE trick: flip the non-sign bits of
    negatives), its low ``pos_bits`` cleared and the row position OR-ed
    in. One int32 word then carries (value, position): each of the blk_k
    extraction passes is a pure min-reduce + one equality mask (keys are
    UNIQUE — position bits make ties impossible, so the mask removes
    exactly one element and ties resolve to the lowest position, the
    first-occurrence contract). This halves the per-pass vreg ops vs
    carrying a separate value/index pair through the reduction tree.

    The price is ``pos_bits`` of score mantissa: emitted distances (and
    the selection boundary) are floored within a relative 2^(pos_bits-24)
    (≈1.2e-4 at maxlen 2048) — an order below the bf16 scan GEMM noise
    (~4e-3) these scores already carry in the shipped configuration.
    """
    rows = rows_ref[:]  # (maxlen_pad, d) compute dtype; padded rows zero
    qv = qv_ref[:]  # (C, d) compute dtype — pre-gathered query residuals
    qr = jax.lax.dot_general(
        rows, qv, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        precision=_dot_prec(rows.dtype),
    )  # (maxlen_pad, C)
    # Within-list residual score ‖δ‖² − 2(q−c)·δ; padded rows carry the
    # caller's ≥1e30 r2 sentinel (their qr is 0: zero rows) so they sort
    # last yet stay below IVF_MASKED_KEY once packed — a list with fewer
    # than blk_k valid rows emits them, and the caller's id table maps
    # them to -1. Finite scores assumed (no ±inf/NaN reach this kernel).
    scores = r2_ref[:] - 2.0 * qr  # r2 is (maxlen_pad, 1): broadcast lanes
    _packed_extract(_packed_keys(scores, pos_bits), d_ref, p_ref, blk_k, pos_bits)


@functools.partial(
    ledgered_jit, "pallas.ivf_scan_select_pallas", static_argnames=("blk_k", "keep_pad", "interpret")
)
def ivf_scan_select_pallas(
    qv: jax.Array,
    rows: jax.Array,
    r2: jax.Array,
    blk_k: int,
    keep_pad: bool = False,
    interpret: bool = False,
):
    """Fused IVF bucketed scan: per-list residual GEMM + exact per-slot
    top-``blk_k``, one HBM pass over the index, scores VMEM-resident.

    Replaces the XLA scan's einsum → ``approx_min_k`` pipeline
    (models/knn.py `_bucketed_core`), whose measured cost was dominated by
    the selection (9.3 of 26 ms/call at the bench shape) and whose
    PartialReduce positional loss capped fast-config recall at ~0.945
    (benchmarks/README.md round-3 frontier). Exactness restores that
    recall headroom; fusion stops the (nlist, C, maxlen) score tensor
    from ever reaching HBM.

    Args:
      qv: (nlist, C, d) compute dtype — pre-gathered query residuals
        ``(queries − c_list)[bucket]`` (hoisted out of the kernel: dynamic
        per-row gathers don't belong inside; sequential HBM streaming of
        the pre-built buffer is the cheap direction).
      rows: (nlist, maxlen, d) compute dtype — residual list rows
        (index data; padded rows MUST be zero).
      r2: (nlist, maxlen) float32 — per-row ‖δ‖² with a ≥1e30 sentinel on
        invalid/padded rows (strictly below IVF_MASKED_D2).
      blk_k: per-slot selection width (≤ maxlen).

    Returns (best_d (nlist, blk_k, C) f32 ascending, best_p (nlist, blk_k,
    C) int32 row positions). Ties resolve to the lowest position; emitted
    distances are floored within a relative 2^(ceil(log2(maxlen))-24) of
    the f32 score (the packed-key mantissa trade — kernel docstring).
    """
    nlist, C, d = qv.shape
    maxlen = rows.shape[1]
    if blk_k > maxlen:
        raise ValueError(f"blk_k={blk_k} exceeds maxlen={maxlen}")
    ml_pad = _ceil_to(maxlen, 8)
    if ml_pad != maxlen:
        rows = jnp.pad(rows, ((0, 0), (0, ml_pad - maxlen), (0, 0)))
        r2 = jnp.pad(
            r2, ((0, 0), (0, ml_pad - maxlen)), constant_values=1e30
        )
    pos_bits = max(1, (ml_pad - 1).bit_length())
    if pos_bits > 16:
        raise ValueError(f"maxlen={maxlen} too large for packed selection")
    bk_pad = _ceil_to(blk_k, 8)
    best_d, best_p = pl.pallas_call(
        functools.partial(
            _ivf_scan_select_kernel, blk_k=blk_k, pos_bits=pos_bits
        ),
        name="ivf_scan_select",
        grid=(nlist,),
        in_specs=[
            pl.BlockSpec((None, C, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((None, ml_pad, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((None, ml_pad, 1), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk_pad, C), lambda i: (i, 0, 0)),
            pl.BlockSpec((None, bk_pad, C), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nlist, bk_pad, C), jnp.float32),
            jax.ShapeDtypeStruct((nlist, bk_pad, C), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), vmem_limit_bytes=100 * 2**20
        )
        if not interpret
        else None,
        interpret=interpret,
    )(qv, rows, r2[..., None].astype(jnp.float32))
    if keep_pad:
        # Callers gathering rows from the (…, blk_k_pad) output keep the
        # 8-multiple lane width: slicing BEFORE a gather materializes an
        # unaligned-row copy, and gathering 64B-aligned rows then slicing
        # after measured ~1.7× faster (benchmarks/README.md round 3).
        # Pad rows carry (IVF_MASKED_D2, 0).
        return best_d, best_p
    return best_d[:, :blk_k], best_p[:, :blk_k]


# ---------------------------------------------------------------------------
# Fused IVF probe: centroid distances + EXACT per-query top-nprobe
# ---------------------------------------------------------------------------


def _probe_select_kernel(
    cent_ref, c2h_ref, qT_ref, q2_ref, d_ref, p_ref, *, nprobe, pos_bits
):
    """One query block per grid step: ‖q−c‖² against ALL centroids + exact
    top-nprobe per query, the (nlist, qb) distance tile VMEM-resident.

    Same layout discipline and packed-key extraction as
    ``_ivf_scan_select_kernel`` — here LISTS ride the sublanes and QUERIES
    the lanes, so the per-query selection reduces over sublanes. The f32
    GEMM runs at HIGHEST precision: probe distances feed the residual
    identity's cross-list ‖q−c‖² term, where bf16-magnitude noise corrupts
    the candidate ordering (models/knn.py probe_bucketed). Replacing the
    XLA ``approx_min_k(recall_target=0.95)`` makes probing EXACT — the one
    approximation that op added to probe coverage is gone.
    """
    cq = jax.lax.dot_general(
        cent_ref[:], qT_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # (nlist_pad, qb)
    # True ‖q−c‖²: the ‖q‖² term is a per-query (lane) constant — it
    # cannot change this selection OR the downstream cross-list ranking,
    # but the emitted values ARE the user-visible distance components, so
    # keep them true distances. Padded centroid rows carry a 1e30 c2h
    # sentinel and never win (nprobe ≤ nlist enforced by callers).
    scores = c2h_ref[:] - 2.0 * cq + q2_ref[:]
    _packed_extract(
        _packed_keys(scores, pos_bits), d_ref, p_ref, nprobe, pos_bits
    )


@functools.partial(
    ledgered_jit, "pallas.probe_select_pallas", static_argnames=("nprobe", "block_q", "interpret")
)
def probe_select_pallas(
    centroids: jax.Array,
    queries: jax.Array,
    nprobe: int,
    block_q: int = 512,
    interpret: bool = False,
):
    """Exact IVF probe: (probe ids (q, nprobe) int32 ascending-by-distance,
    probe_d2 (q, nprobe) f32 true ‖q−c‖²) in one fused kernel.

    centroids: (nlist, d) — padded rows allowed if masked by the caller
    via huge norms; here rows are taken as-is and ``nprobe ≤ nlist`` is
    the caller's contract. queries: (q, d); q must divide block_q or be
    smaller. Emitted distances carry the packed-key mantissa floor
    (relative 2^(ceil(log2(nlist))-24) — see _ivf_scan_select_kernel).
    """
    nlist, d = centroids.shape
    q = queries.shape[0]
    qb = min(block_q, q)
    if q % qb:
        raise ValueError(f"q={q} not divisible by block_q={qb}")
    nl_pad = _ceil_to(nlist, 8)
    cent = jnp.asarray(centroids, jnp.float32)
    c2 = jnp.sum(jnp.square(cent), axis=1, keepdims=True)  # (nlist, 1)
    if nl_pad != nlist:
        cent = jnp.pad(cent, ((0, nl_pad - nlist), (0, 0)))
        c2 = jnp.pad(c2, ((0, nl_pad - nlist), (0, 0)), constant_values=1e30)
    pos_bits = max(1, (nl_pad - 1).bit_length())
    if pos_bits > 16:
        raise ValueError(f"nlist={nlist} too large for packed probe selection")
    qf = jnp.asarray(queries, jnp.float32)
    qT = qf.T  # (d, q)
    q2 = jnp.sum(jnp.square(qf), axis=1)[None, :]  # (1, q)
    np_pad = _ceil_to(nprobe, 8)
    best_d, best_p = pl.pallas_call(
        functools.partial(
            _probe_select_kernel, nprobe=nprobe, pos_bits=pos_bits
        ),
        name="ivf_probe_select",
        grid=(q // qb,),
        in_specs=[
            pl.BlockSpec((nl_pad, d), lambda i: (0, 0)),
            pl.BlockSpec((nl_pad, 1), lambda i: (0, 0)),
            pl.BlockSpec((d, qb), lambda i: (0, i)),
            pl.BlockSpec((1, qb), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((np_pad, qb), lambda i: (0, i)),
            pl.BlockSpec((np_pad, qb), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((np_pad, q), jnp.float32),
            jax.ShapeDtypeStruct((np_pad, q), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), vmem_limit_bytes=100 * 2**20
        )
        if not interpret
        else None,
        interpret=interpret,
    )(cent, c2, qT, q2)
    return best_p[:nprobe].T, best_d[:nprobe].T


# ---------------------------------------------------------------------------
# Fused LinearRegression normal-equation statistics: one HBM pass
# ---------------------------------------------------------------------------


def _linreg_stats_kernel(x_ref, y_ref, m_ref, g_ref, xty_ref, cs_ref, ys_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        g_ref[:] = jnp.zeros_like(g_ref)
        xty_ref[:] = jnp.zeros_like(xty_ref)
        cs_ref[:] = jnp.zeros_like(cs_ref)
        ys_ref[:] = jnp.zeros_like(ys_ref)

    m = m_ref[:]  # (bn, 1) f32 {0,1}
    xb = x_ref[:] * m.astype(x_ref.dtype)
    yf = y_ref[:] * m  # (bn, 1) f32
    g_ref[:] += jax.lax.dot_general(
        xb, xb, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        precision=_dot_prec(xb.dtype),
    )
    xf = xb.astype(jnp.float32)
    # Xᵀy on the VPU: a (1, bn)×(bn, d) MXU call would waste 127/128 of
    # the systolic array's M tiles; the row-weighted column sum is cheap
    # next to the Gram GEMM and rides the same x read.
    xty_ref[:] += jnp.sum(xf * yf, axis=0, keepdims=True)
    cs_ref[:] += jnp.sum(xf, axis=0, keepdims=True)
    lane = jax.lax.broadcasted_iota(jnp.int32, (m.shape[0], 128), 1)
    ys_ref[:] += jnp.sum(
        jnp.where(
            lane == 0, yf, jnp.where(lane == 1, yf * yf, jnp.where(lane == 2, m, 0.0))
        ),
        axis=0,
        keepdims=True,
    )


# ---------------------------------------------------------------------------
# Multinomial MM curvature: the C per-class Xᵀdiag(p_c)X blocks with x
# streamed through HBM once per class GROUP (shared tile), not once per class
# ---------------------------------------------------------------------------


#: 1024-row blocks: K=1024 per class GEMM ran 262 vs 185 TF/s for K=512 on
#: the measured config (d=1024, C=32, v5e) — deeper contractions amortize
#: the per-class accumulator switch.
SOFTMAX_CURV_BLOCK_N = 1024
#: VMEM budget for the resident (block_c, d, d) f32 accumulator stack; the
#: group width adapts to d (softmax_curv_block_c) so the budget, not the
#: class count, caps residency.
SOFTMAX_CURV_VMEM_BUDGET = 48 * 2**20


def softmax_curv_block_c(d: int, n_classes: int) -> int:
    """Class-group width: largest POWER OF TWO whose (Cb, d, d) f32
    accumulator stack fits the VMEM budget (≥1; measured: 8 beats the
    non-power 12 at d=1024 — Mosaic tiles power-of-two stacks better)."""
    cap = max(1, min(n_classes, SOFTMAX_CURV_VMEM_BUDGET // (4 * d * d)))
    return 1 << (cap.bit_length() - 1)


def _softmax_curv_kernel(x_ref, p_ref, hw_ref, hwb_ref, *, block_c):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        hw_ref[:] = jnp.zeros_like(hw_ref)
        hwb_ref[:] = jnp.zeros_like(hwb_ref)

    x = x_ref[:]  # (bn, d) compute dtype — read ONCE for all block_c classes
    p = p_ref[:]  # (bn, block_c) f32 pre-masked probabilities
    for c in range(block_c):  # static unroll; accumulators stay VMEM-resident
        xw = x * p[:, c : c + 1].astype(x.dtype)
        # Curvature blocks are the MM preconditioner, not the answer (the
        # exact gradient pins the fixed point — models/logistic_regression
        # .py): fast DEFAULT precision, f32 accumulate.
        hw_ref[c] += jax.lax.dot_general(
            xw, x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT,
        )
        # The intercept border Xᵀp_c rides the same tile on the VPU.
        hwb_ref[c : c + 1, :] += jnp.sum(
            xw.astype(jnp.float32), axis=0, keepdims=True
        )


@functools.partial(
    ledgered_jit, "pallas.softmax_curvature_pallas", static_argnames=("block_n", "block_c", "interpret")
)
def softmax_curvature_pallas(
    x: jax.Array,
    p: jax.Array,
    block_n: int = SOFTMAX_CURV_BLOCK_N,
    block_c: int = 8,
    interpret: bool = False,
):
    """Per-class curvature hw[c] = Xᵀdiag(p_c)X and border hwb[c] = Xᵀp_c
    for every class, with x read from HBM once per class GROUP.

    The XLA lowering of the per-class loop
    (models/logistic_regression._stream_softmax_stats) re-reads the (n, d)
    operand for every one of the C classes — at C=32, d=1024 bf16 that
    traffic caps the multinomial MM pass at ~0.85× the A100 convention
    (benchmarks/README.md). Here each VMEM-resident x tile feeds block_c
    class GEMMs before the next tile loads, dividing x traffic by block_c
    (the one-HBM-pass partition-kernel idiom of ``linreg_stats_pallas`` /
    the reference's dgemmCov, rapidsml_jni.cu:109-127, extended over a
    class axis). One ``pallas_call`` per class group — the group's p
    columns arrive as their own (n, block_c) operand, whose full last dim
    keeps every block shape legal under Mosaic's lane tiling for ANY
    block_c.

    x: (n, d) compute dtype (bfloat16 = the intended speed mode);
    p: (n, C) f32 — softmax probabilities ALREADY masked (p · row_mask).
    The last group may be narrower than block_c.
    Returns (hw (C, d, d) f32, hwb (C, d) f32).
    """
    n, d = x.shape
    n_classes = p.shape[1]
    bn = min(block_n, n)
    if n % bn:
        raise ValueError(f"n={n} not divisible by block_n={bn}")
    bc = min(block_c, n_classes)
    if bc * d * d * 4 > SOFTMAX_CURV_VMEM_BUDGET:
        raise ValueError(
            f"block_c={bc}, d={d}: accumulator stack exceeds the VMEM budget"
        )
    pf = jnp.asarray(p, jnp.float32)
    hw_parts, hwb_parts = [], []
    for g0 in range(0, n_classes, bc):
        gc = min(bc, n_classes - g0)
        hw_g, hwb_g = pl.pallas_call(
            functools.partial(_softmax_curv_kernel, block_c=gc),
            grid=(n // bn,),
            in_specs=[
                pl.BlockSpec((bn, d), lambda i: (i, 0)),
                pl.BlockSpec((bn, gc), lambda i: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((gc, d, d), lambda i: (0, 0, 0)),
                pl.BlockSpec((gc, d), lambda i: (0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((gc, d, d), jnp.float32),
                jax.ShapeDtypeStruct((gc, d), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
                vmem_limit_bytes=100 * 2**20,
            )
            if not interpret
            else None,
            interpret=interpret,
        )(x, jax.lax.slice_in_dim(pf, g0, g0 + gc, axis=1))
        hw_parts.append(hw_g)
        hwb_parts.append(hwb_g)
    if len(hw_parts) == 1:
        return hw_parts[0], hwb_parts[0]
    return jnp.concatenate(hw_parts), jnp.concatenate(hwb_parts)


@functools.partial(
    ledgered_jit, "pallas.linreg_stats_pallas", static_argnames=("block_n", "interpret")
)
def linreg_stats_pallas(
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    block_n: int = GRAM_COLSUM_BLOCK_N,
    interpret: bool = False,
):
    """One-HBM-pass fused (XᵀX, Xᵀy, Σx, Σy, Σy², n) over masked rows —
    the LinearRegression analogue of ``gram_colsum_pallas`` (SURVEY §7.6:
    "literally the PCA reduction with an extra Xᵀy"). The XLA path's
    separate dots re-read X for Xᵀy and the sums (+30% wall measured at
    1M×1024 bf16); here every statistic rides the Gram's single read with
    the accumulators VMEM-resident.

    x: (n, d) compute dtype; y: (n,) any float; mask: (n,) {0,1}.
    Returns (xtx (d, d) f32, xty (d,) f32, sx (d,) f32, sy, syy, n — all
    f32 scalars; exact row counts up to 2^24 rows per call).
    """
    n, d = x.shape
    bn = min(block_n, n)
    if n % bn:
        raise ValueError(f"n={n} not divisible by block_n={bn}")
    if d * d * 4 > GRAM_COLSUM_VMEM_BUDGET:
        raise ValueError(f"d={d}: (d, d) f32 accumulator exceeds the VMEM budget")
    y2 = jnp.asarray(y, jnp.float32).reshape(n, 1)
    m2 = jnp.asarray(mask, jnp.float32).reshape(n, 1)
    g, xty, cs, ys = pl.pallas_call(
        _linreg_stats_kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((d, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, 128), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",), vmem_limit_bytes=100 * 2**20
        )
        if not interpret
        else None,
        interpret=interpret,
    )(x, y2, m2)
    return g, xty[0], cs[0], ys[0, 0], ys[0, 1], ys[0, 2]
