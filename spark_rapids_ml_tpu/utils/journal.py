"""Structured run journal: one JSON line per run/phase event.

Where ``utils/metrics.py`` answers "how is the system doing in aggregate",
the journal answers "what did THIS fit do": every ``trace_span`` phase
(gram fold, eigensolve, Lloyd pass, solve, transform …) becomes one line
carrying ``run_id`` / ``span_id`` / ``parent_id``, so a fit's per-phase
breakdown is a one-liner of ``jq`` away — the queryable form of the
reference's NVTX ranges, which only a profiler GUI could read.

Activation: set the env ``SRML_RUN_JOURNAL=/path/to/journal.jsonl``
(deployment-facing, so no ``SRML_TPU_`` prefix — same family as
``SRML_DAEMON_ADDRESS`` / ``SRML_FAULT_PLAN``), or programmatically
``config.set("run_journal", path)``. Unset, every hook is one config read
and an early return — no event dict, no JSON encoding, no I/O ("zero
allocation of journal lines", the production state).

Line schema (all events)::

    {"ts": <unix seconds, event START>, "pid": int, "tid": int,
     "event": "run_start" | "run_end" | "phase" | "mark",
     "run_id": hex, "span_id": hex, "parent_id": hex | null,
     "name": str, ...}

``tid`` (additive) is the OS thread id — ``tools/trace.py`` lays spans
out on (pid, tid) tracks when emitting Chrome-trace JSON.

``run_end`` and ``phase`` additionally carry ``duration_s``. Extra
keyword fields pass through verbatim (estimator class, algo, job name).
Nesting is per-thread: spans opened inside a ``run()`` (or inside another
span) parent to it; a span on a thread with no open run becomes its own
root (fresh ``run_id``, ``parent_id`` null) — daemon-side phases journal
standalone. Files are opened append-mode and written one line per event
under a lock, so daemon threads (and multiple processes on a shared
file, via O_APPEND line writes) interleave whole lines, never halves.

Every event additionally carries ``seq`` (additive): a per-process
monotonic sequence number, so merge tools order same-timestamp events
deterministically (sort key ``(ts, pid, seq)``) instead of by file
order. ``seq`` restarts at 1 per process — it is only meaningful within
one ``pid``.

**In-memory ring (additive).** ``ring_arm(cap)`` turns on a bounded
in-process event buffer that captures every event the journal hooks see
— with or without a file configured. The daemon arms it at start
(``telemetry_trace_buffer`` events) so the ``trace_pull`` wire op and
the flight recorder (utils/flight.py) can export recent spans with zero
filesystem dependency; ``tail(since_seq)`` drains it cursor-style.
Arming is refcounted (several daemons in one test process share the
ring); an unarmed process with no journal path keeps the original
zero-allocation early-return contract.

**Rotation (additive).** ``run_journal_max_bytes`` > 0 rotates the
journal file logrotate-style when the next line would cross the cap:
``path`` → ``path.1`` → … → ``path.K`` (``run_journal_keep`` segments
retained, oldest deleted). ``read()`` concatenates rotated segments
oldest-first, so consumers see one continuous stream. Rotation is
single-writer: multiple PROCESSES sharing one journal path should leave
the cap at 0 (unbounded append) — a rotating writer would pull the file
out from under its peers' O_APPEND handles.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Deque, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "enabled", "active", "run", "span", "mark", "read", "close", "adopt",
    "trace_ctx", "ring_arm", "ring_disarm", "tail", "last_seq", "segments",
]

_lock = threading.Lock()
_files: Dict[str, Any] = {}  # path -> [open append handle, bytes written]
_tls = threading.local()
#: Latched True after a write failure (bad path, disk full, read-only
#: FS): telemetry must NEVER take the workload down — the journal logs
#: one warning, disables itself for the process, and every fit keeps
#: running. close() re-arms (a fresh path can be configured after).
_broken = False
#: Per-process monotonic event sequence (under ``_lock``): the merge
#: tiebreaker for same-``ts`` events and the ``trace_pull`` cursor.
_seq = 0
#: Bounded in-memory event buffer; captures only while ``_ring_arms`` > 0.
_ring: Deque[Dict[str, Any]] = deque()
_ring_arms = 0
_ring_cap = 0


def _path() -> Optional[str]:
    if _broken:
        return None
    from spark_rapids_ml_tpu import config

    p = config.peek("run_journal")
    return str(p) if p else None


def enabled() -> bool:
    """True when a journal path is configured for this process."""
    return _path() is not None


def active() -> bool:
    """True when ANY sink would record an event: a journal file is
    configured or the in-memory ring is armed."""
    return _path() is not None or _ring_on()


def ring_arm(cap: int) -> None:
    """Enable the in-memory event ring (≤ ``cap`` most-recent events).
    Refcounted: each ``ring_arm`` needs a matching ``ring_disarm``; the
    largest requested cap wins while any holder is armed."""
    global _ring_arms, _ring_cap
    cap = int(cap)
    with _lock:
        _ring_arms += 1
        _ring_cap = max(_ring_cap, cap)
        while len(_ring) > _ring_cap:
            _ring.popleft()


def ring_disarm() -> None:
    """Drop one arm; the ring empties when the last holder disarms."""
    global _ring_arms, _ring_cap
    with _lock:
        _ring_arms = max(0, _ring_arms - 1)
        if _ring_arms == 0:
            _ring.clear()
            _ring_cap = 0


def _ring_on() -> bool:
    return _ring_arms > 0 and _ring_cap > 0


def tail(since_seq: int = 0) -> Tuple[List[Dict[str, Any]], int]:
    """(events with ``seq`` > ``since_seq`` still in the ring, current
    last seq). The ``trace_pull`` primitive: a caller holding the
    returned seq as its cursor streams without duplication; events that
    aged out of the bounded ring before a pull are simply gone."""
    with _lock:
        events = [dict(e) for e in _ring if e.get("seq", 0) > since_seq]
        return events, _seq


def last_seq() -> int:
    """Current per-process sequence number (0 before any event)."""
    with _lock:
        return _seq


def _stack() -> List[Tuple[str, str]]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current() -> Tuple[Optional[str], Optional[str]]:
    """(run_id, span_id) of this thread's innermost open frame."""
    s = _stack()
    return s[-1] if s else (None, None)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def _rotation() -> Tuple[int, int]:
    from spark_rapids_ml_tpu import config

    return (
        int(config.peek("run_journal_max_bytes") or 0),
        max(1, int(config.peek("run_journal_keep") or 1)),
    )


def _rotate_locked(path: str) -> None:
    """Shift ``path`` → ``path.1`` → … under ``_lock`` (handle already
    closed by the caller). Best-effort: a missing segment is fine."""
    _, keep = _rotation()
    for i in range(keep, 0, -1):
        src = path if i == 1 else f"{path}.{i - 1}"
        dst = f"{path}.{i}"
        if os.path.exists(src):
            os.replace(src, dst)
    extra = f"{path}.{keep + 1}"
    if os.path.exists(extra):  # keep shrank between rotations
        os.remove(extra)


def _write(path: str, line: str) -> None:
    global _broken
    try:
        with _lock:
            entry = _files.get(path)
            if entry is None:
                f = open(path, "a", encoding="utf-8")
                entry = _files[path] = [f, f.tell()]
            max_bytes, _ = _rotation()
            nbytes = len(line.encode("utf-8"))
            if max_bytes > 0 and entry[1] + nbytes > max_bytes and entry[1] > 0:
                entry[0].close()
                del _files[path]
                _rotate_locked(path)
                f = open(path, "a", encoding="utf-8")
                entry = _files[path] = [f, f.tell()]
            entry[0].write(line)
            entry[0].flush()
            entry[1] += nbytes
    except (OSError, ValueError) as e:  # ValueError: write on closed file
        # Emitted from finally blocks (span/run exits): raising here would
        # MASK the workload's own in-flight exception — and an unwritable
        # journal path must not fail fits. Warn once, self-disable.
        _broken = True
        from spark_rapids_ml_tpu.utils.logging import get_logger

        get_logger("utils.journal").warning(
            "run journal disabled: cannot write %s (%s)", path, e
        )


def _active() -> Tuple[Optional[str], bool]:
    """(journal path or None, ring armed?) — an event is emitted when
    either sink is on; neither on is the zero-allocation early return."""
    return _path(), _ring_on()


def _event(
    path: Optional[str],
    event: str,
    name: str,
    run_id: str,
    span_id: str,
    parent_id: Optional[str],
    ts: float,
    fields: Dict[str, Any],
    duration_s: Optional[float] = None,
) -> None:
    global _seq
    obj: Dict[str, Any] = {
        "ts": ts,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "event": event,
        "run_id": run_id,
        "span_id": span_id,
        "parent_id": parent_id,
        "name": name,
    }
    if duration_s is not None:
        obj["duration_s"] = duration_s
    obj.update(fields)
    with _lock:
        _seq += 1
        obj["seq"] = _seq
        if _ring_on():
            _ring.append(obj)
            while len(_ring) > _ring_cap:
                _ring.popleft()
    if path is not None:
        _write(path, json.dumps(obj, separators=(",", ":"), default=str) + "\n")


@contextlib.contextmanager
def run(name: str, **fields: Any) -> Iterator[Optional[str]]:
    """Open a named run (one estimator fit, one bench iteration): emits
    ``run_start`` now and ``run_end`` (with ``duration_s``) on exit;
    spans on this thread inside the block parent to it. Yields the
    run_id (None when the journal is off)."""
    path, ring = _active()
    if path is None and not ring:
        yield None
        return
    run_id = _new_id()
    span_id = _new_id()
    _, parent = current()
    ts = time.time()
    t0 = time.perf_counter()
    _event(path, "run_start", name, run_id, span_id, parent, ts, fields)
    stack = _stack()
    stack.append((run_id, span_id))
    try:
        yield run_id
    finally:
        stack.pop()
        _event(
            path, "run_end", name, run_id, span_id, parent, ts, fields,
            duration_s=time.perf_counter() - t0,
        )


@contextlib.contextmanager
def span(name: str, **fields: Any) -> Iterator[Optional[str]]:
    """One phase: emits a single ``phase`` line on exit (ts = phase
    start). ``trace_span`` routes here, so every instrumented phase in
    the package journals for free when the journal is on."""
    path, ring = _active()
    if path is None and not ring:
        yield None
        return
    stack = _stack()
    if stack:
        run_id, parent = stack[-1]
    else:
        run_id, parent = _new_id(), None
    span_id = _new_id()
    ts = time.time()
    t0 = time.perf_counter()
    stack.append((run_id, span_id))
    try:
        yield span_id
    finally:
        stack.pop()
        _event(
            path, "phase", name, run_id, span_id, parent, ts, fields,
            duration_s=time.perf_counter() - t0,
        )


def trace_ctx() -> Optional[Dict[str, str]]:
    """This thread's innermost open frame as an over-the-wire context:
    ``{"run": run_id, "span": span_id}``, or None outside any run/span.
    The data-plane client stamps it on every request (additive
    ``trace_ctx`` field, docs/protocol.md) and the estimator captures it
    into executor-side task closures — how one fit's journal lines from
    driver, executors, and N daemons stitch into a single tree
    (``tools/trace.py``)."""
    run_id, span_id = current()
    if run_id is None:
        return None
    return {"run": run_id, "span": span_id}


@contextlib.contextmanager
def adopt(
    run_id: Optional[str], span_id: Optional[str] = None
) -> Iterator[None]:
    """Parent this thread's subsequent spans under a FOREIGN frame — a
    ``trace_ctx`` that arrived over the wire (daemon side) or through a
    task closure (executor side). Emits no event itself; spans opened
    inside the block carry the adopted ``run_id`` and parent to
    ``span_id``. No-op when ``run_id`` is falsy, so callers can pass a
    request's (possibly absent) context straight through."""
    if not run_id:
        yield
        return
    stack = _stack()
    stack.append((str(run_id), str(span_id) if span_id else None))
    try:
        yield
    finally:
        stack.pop()


def mark(name: str, **fields: Any) -> None:
    """One-shot event (no duration) under the current run, if any."""
    path, ring = _active()
    if path is None and not ring:
        return
    run_id, parent = current()
    _event(
        path, "mark", name, run_id or _new_id(), _new_id(), parent,
        time.time(), fields,
    )


def segments(path: str) -> List[str]:
    """Existing on-disk segments of a journal, OLDEST first:
    ``path.K … path.2 path.1 path`` (rotation shifts upward, so higher
    suffixes are older). The live file is last even when absent peers
    leave suffix gaps."""
    out: List[str] = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        out.append(f"{path}.{i}")
        i += 1
    out.reverse()
    if os.path.exists(path) or not out:
        out.append(path)
    return out


def read(path: str) -> List[Dict[str, Any]]:
    """Parse a journal file back into event dicts (tools and tests),
    transparently concatenating rotated segments oldest-first. Blank
    lines are skipped; a torn final line (killed process) raises — the
    journal's whole-line write discipline makes that a real error."""
    out: List[Dict[str, Any]] = []
    for seg in segments(path):
        with open(seg, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    return out


def close() -> None:
    """Flush and close every open journal handle (tests; idempotent —
    the next event reopens append-mode). Also re-arms a journal that
    self-disabled after a write failure."""
    global _broken
    with _lock:
        files = [entry[0] for entry in _files.values()]
        _files.clear()
        _broken = False
    for f in files:
        try:
            f.close()
        except OSError:
            pass
