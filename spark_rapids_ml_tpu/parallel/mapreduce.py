"""On-mesh MapReduce primitives — the package's one collective layer.

DrJAX-style ``map_fn``/``reduce`` building blocks (PAPERS.md: DrJAX
2403.07128) over the data×model mesh (parallel/mesh.py): mapped
per-shard compute composes with named-axis reductions that lower to
``psum``/``all_gather``/``ppermute`` over ICI/DCN inside one compiled
SPMD program — the device-plane replacement for the reference's
JVM-serialized ``RDD.reduce`` hop (RapidsRowMatrix.scala:139).

EVERY collective in the package goes through these wrappers (test_lint's
``test_no_bare_collectives_outside_parallel`` enforces it, the mirror of
the bare-``jax.jit`` gate): a collective that bypasses this module is
invisible to the booking below and to anyone auditing what a program
moves over the interconnect. Booking happens at TRACE time — the
wrappers run once per compiled program, not per dispatch — so the
``srml_parallel_collective_traces_total`` counter reads as "collective
call sites traced, by kind and axis" (per-dispatch device cost lives in
the jit ledger, utils/xprof.py, which covers the whole program).

Not here: host-side cross-process gathers (``multihost_utils`` in
parallel/sharding.py) — those are control-plane allgathers of scalars,
not device-plane collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.parallel.compat import shard_map
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from spark_rapids_ml_tpu.utils import metrics as metrics_mod

__all__ = [
    "map_fn",
    "reduce_sum",
    "all_concat",
    "ring_shift",
    "reduce_topk",
]

_M_COLLECTIVE_TRACES = metrics_mod.counter(
    "srml_parallel_collective_traces_total",
    "Collective call sites traced into compiled programs, by kind "
    "(psum|all_gather|ppermute) and mesh axis",
)


def _book(kind: str, axis_name: str) -> None:
    _M_COLLECTIVE_TRACES.inc(kind=kind, axis=str(axis_name))


def map_fn(fn, mesh, in_specs, out_specs, check_vma: Optional[bool] = None):
    """Map ``fn`` over mesh shards (the DrJAX ``map_fn``): a named-axis
    SPMD region whose body may call the reduce primitives below. Thin
    veneer over the version-compat ``shard_map`` so call sites read as
    map/reduce pairs rather than sharding plumbing."""
    kwargs = {} if check_vma is None else {"check_vma": check_vma}
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def reduce_sum(x, axis_name: str = DATA_AXIS):
    """Cross-shard sum over a mesh axis (lowers to ``psum`` on ICI/DCN).

    The workhorse reduce: Gram/moment partials, k-means statistics,
    Newton gradient/Hessian blocks all combine through this."""
    _book("psum", axis_name)
    return jax.lax.psum(x, axis_name)


def all_concat(x, axis_name: str = DATA_AXIS, *, axis: int = 0,
               tiled: bool = True):
    """Concatenate every shard's block along tensor dim ``axis`` (lowers
    to ``all_gather``): each device ends up holding the full axis —
    feature blocks for the 2-D Gram, per-shard top-k candidate pools."""
    _book("all_gather", axis_name)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ring_shift(x, axis_name: str, perm: Sequence[Tuple[int, int]]):
    """Rotate blocks around a mesh-axis ring (lowers to ``ppermute``):
    the pipelined alternative to ``all_concat`` when the gathered buffer
    would not fit — one block in flight per step (gram ring variant)."""
    _book("ppermute", axis_name)
    return jax.lax.ppermute(x, axis_name, perm)


def reduce_topk(dists, ids, k: int, axis_name: str = DATA_AXIS):
    """Merge per-shard ascending top-k candidate lists into the global
    top-k on every device: gather the (q, k_local) pools along the mesh
    axis, re-select k. Exact as long as each shard contributed its local
    top-min(k, shard_rows) — the union then contains the global winners
    (the knn merge property, models/knn.merge_topk's device-plane twin).
    Returns ``(dists (q, k) ascending, ids (q, k))``."""
    cand_d = all_concat(dists, axis_name, axis=1)
    cand_i = all_concat(ids, axis_name, axis=1)
    neg, pos = jax.lax.top_k(-cand_d, k)
    return -neg, jnp.take_along_axis(cand_i, pos, axis=1)
