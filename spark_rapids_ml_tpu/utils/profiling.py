"""Phase-named tracing spans — the NVTX-range idiom, TPU-native.

The reference wraps its two fit phases in NVTX ranges so they show up in
Nsight (``NvtxRange("compute cov", RED)`` / ``NvtxRange("cuSolver SVD",
BLUE)``, RapidsRowMatrix.scala:62,70, closed in ``finally``). The TPU
equivalent is ``jax.profiler.TraceAnnotation``, which names the span in
xprof/Perfetto traces. ``trace_span`` keeps the same phase-named-span idiom
and degrades to a no-op timer when tracing is disabled.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.utils.logging import get_logger

_logger = get_logger(__name__)


class Timer:
    """Wall-clock timer with a monotonic clock; used by spans and benches."""

    def __init__(self) -> None:
        self.start = time.perf_counter()
        self.elapsed: Optional[float] = None

    def stop(self) -> float:
        self.elapsed = time.perf_counter() - self.start
        return self.elapsed


@contextlib.contextmanager
def trace_span(name: str, log: bool = False) -> Iterator[Timer]:
    """Context manager naming a phase in the JAX profiler timeline.

    Usage mirrors the reference's try/finally NvtxRange pattern::

        with trace_span("compute cov"):
            gram = compute_gram(...)
    """
    timer = Timer()
    if config.get("tracing"):
        import jax.profiler

        cm: contextlib.AbstractContextManager = jax.profiler.TraceAnnotation(name)
    else:
        cm = contextlib.nullcontext()
    with cm:
        try:
            yield timer
        finally:
            timer.stop()
            if log or config.get("tracing"):
                _logger.debug("phase %s: %.3fs", name, timer.elapsed)
