"""LogisticRegression — distributed full-batch Newton (IRLS) / GD.

BASELINE.json config #4 pairs LogisticRegression with the normal-equations
family. TPU-first shape: every Newton iteration is two sharded GEMMs
(gradient Xᵀr and Hessian XᵀDX) + psum over ICI, then a d×d Cholesky solve
on device — the same partition-kernel + collective + finalize frame as PCA
(SURVEY.md §7 step 6). The whole optimization loop runs inside ONE
``lax.while_loop`` under ``shard_map``: data stays sharded on device for
all iterations, nothing returns to the host until convergence.

Objective (Spark ML LogisticRegression, ``standardization=False``):

    min_w 1/n Σ log(1 + exp(−ŷᵢ·(xᵢw + b))) + λ/2·‖w‖₂²   (binary, L2)

Binary labels are {0, 1}. Multinomial (softmax) runs MM-Newton: the exact
gradient with per-class upper-bound curvature blocks (the (C·d)² Hessian is
never materialized — see _stream_softmax_stats_fn). Intercept is
unpenalized, as in Spark.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_ml_tpu import config
from spark_rapids_ml_tpu.core.dataset import as_column, as_matrix, with_column
from spark_rapids_ml_tpu.core.params import (
    Estimator,
    HasFeaturesCol,
    HasFitIntercept,
    HasLabelCol,
    HasMaxIter,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasRegParam,
    HasTol,
    Model,
)
from spark_rapids_ml_tpu.core.persistence import MLReadable, MLWritable
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, default_mesh
from spark_rapids_ml_tpu.parallel import mapreduce as mr
from spark_rapids_ml_tpu.parallel.sharding import shard_rows
from spark_rapids_ml_tpu.utils.profiling import trace_span
from spark_rapids_ml_tpu.parallel.compat import shard_map
from spark_rapids_ml_tpu.utils.xprof import ledgered_jit


class LogisticTrainingSummary(NamedTuple):
    """Final objective + iterations, Spark's training-summary shape."""

    loss: Optional[float]
    numIter: int
    n_rows: int


class LogisticSolution(NamedTuple):
    coefficients: np.ndarray  # (d,) binary or (c, d) multinomial
    intercept: np.ndarray  # scalar (binary) or (c,)
    n_iter: int
    n_rows: int
    loss: Optional[float] = None  # final training objective (binary path)


def _pcg_solve(h, g, x0, max_iter: Optional[int] = None, rtol: float = 1e-2):
    """Jacobi-preconditioned CG on the SPD Newton system ``h @ x = g``.

    XLA's direct LU/Cholesky for a single d×d system is a sequential
    blocked factorization — ~10 ms at d=1024 on a v5e chip, MORE than the
    whole fused statistics pass over 2^19 rows — so the TPU path solves
    iteratively. CG is pure matvec/axpy (MXU/VPU-friendly) and this is an
    inexact-Newton inner solve: a 1e-2 relative-residual direction
    preserves outer convergence (the gradient sets the fixed point, the
    Hessian only preconditions), and the previous iteration's direction
    warm-starts the next. Terminates on negative-curvature breakdown
    (truncated-Newton style: fast-precision Hessians of near-separable
    unregularized fits can be numerically indefinite); if breakdown hits
    before any CG step succeeds, returns the preconditioned gradient
    instead of the stale warm start (Steihaug convention).
    """
    d = h.shape[0]
    if max_iter is None:
        # CG is exact at d iterations, but past ~128 the sequential
        # latency of the tiny matvecs rivals the direct solve's cost —
        # at that point the inexact-Newton outer loop is the cheaper way
        # to buy accuracy, so truncate (forcing-term philosophy).
        max_iter = min(d, 128)
    dinv = 1.0 / jnp.maximum(jnp.diagonal(h), 1e-30)
    gnorm = jnp.linalg.norm(g)

    r0 = g - h @ x0
    z0 = dinv * r0

    def cond(c):
        _, r, _, _, it, _ = c
        return jnp.logical_and(it < max_iter, jnp.linalg.norm(r) > rtol * gnorm)

    def body(c):
        x, r, p, rz, it, nstep = c
        hp = h @ p
        php = p @ hp
        broke = php <= 0.0
        alpha = jnp.where(broke, 0.0, rz / jnp.where(broke, 1.0, php))
        x = x + alpha * p
        r = r - alpha * hp
        z = dinv * r
        rz2 = r @ z
        p = z + (rz2 / jnp.where(rz != 0.0, rz, 1.0)) * p
        # On breakdown, force the loop to exit (it = max_iter) rather than
        # spinning out the remaining matvecs on a frozen residual.
        return (
            x, r, p, rz2,
            jnp.where(broke, max_iter, it + 1),
            nstep + jnp.where(broke, 0, 1),
        )

    x, _, _, _, _, nstep = jax.lax.while_loop(
        cond, body, (x0, r0, z0, r0 @ z0, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
    )
    # nstep == 0 means either the warm start already satisfied the
    # tolerance (keep it — it IS the solution) or the very first curvature
    # was non-positive (x is then the stale warm start, unrelated to the
    # CURRENT gradient: fall back to the preconditioned gradient,
    # Steihaug convention).
    warm_ok = jnp.linalg.norm(r0) <= rtol * gnorm
    return jnp.where((nstep > 0) | warm_ok, x, dinv * g)


def _pallas_newton_applicable(shape, cd, ad, use_pallas: Optional[bool] = None) -> bool:
    """Fused single-HBM-pass Newton step (ops/pallas_kernels.newton_stats_pallas):
    TPU backend, bfloat16 compute (the speed mode the kernel exists for —
    at float32 the fusion saves no wall-clock over XLA's lowering), f32
    accumulate, lane-aligned d, block-divisible rows, VMEM-resident (d, d)
    Hessian."""
    from spark_rapids_ml_tpu.ops.gram import _pallas_backend_ok
    from spark_rapids_ml_tpu.ops.pallas_kernels import (
        NEWTON_STATS_BLOCK_N,
        NEWTON_STATS_VMEM_BUDGET,
    )

    if not _pallas_backend_ok(use_pallas):
        return False
    n, d = shape
    return (
        jnp.dtype(cd) == jnp.bfloat16
        and jnp.dtype(ad) == jnp.float32
        and n % NEWTON_STATS_BLOCK_N == 0
        and d % 128 == 0
        and d * d * 4 <= NEWTON_STATS_VMEM_BUDGET
    )


def _solve_newton_system(h_ww, h_wb, h_bb, grad_w, grad_b, reg, fit_intercept,
                         accum):
    """Direct solve of the (optionally bordered) Newton system → (dw, db).

    reg > 0: h_ww is symmetric PD — block elimination with LU solves,
    kept bit-identical to the historical path. reg == 0: the Hessian is
    only PSD — collinear/one-hot/constant columns make h_ww singular,
    and one-hot features plus an intercept add a shift-invariance null
    direction that lives in the BORDERED [w; b] system (its Schur
    complement is exactly 0), so flooring h_ww alone still lets the
    intercept step blow up (ADVICE r5(a) — the multinomial finding; the
    binomial Newton shares the failure class). Floor the diagonal of the
    whole system being solved: the floor must clear the accumulation
    noise of the summed statistics — measured negative eigenvalues reach
    a few ulps of the trace — so scale machine epsilon by a 1e3 margin.
    Still a minimum-norm-direction tiebreak, orders of magnitude below
    any statistically meaningful curvature."""
    d = h_ww.shape[0]
    if reg > 0.0:
        if fit_intercept:
            hinv_hwb = jnp.linalg.solve(h_ww, h_wb)
            hinv_gw = jnp.linalg.solve(h_ww, grad_w)
            schur = jnp.maximum(h_bb - h_wb @ hinv_hwb, 1e-12)
            db = (grad_b - h_wb @ hinv_gw) / schur
            dw = hinv_gw - hinv_hwb * db
            return dw, db
        return jnp.linalg.solve(h_ww, grad_w), jnp.zeros((), accum)
    noise = 1e3 * jnp.finfo(accum).eps
    if fit_intercept:
        joint = jnp.concatenate([
            jnp.concatenate([h_ww, h_wb[:, None]], axis=1),
            jnp.concatenate([h_wb, h_bb[None]])[None, :],
        ])
        eps = noise * jnp.trace(joint) / (d + 1) + 1e-12
        cho = jax.scipy.linalg.cho_factor(
            joint + eps * jnp.eye(d + 1, dtype=accum), lower=True
        )
        sol = jax.scipy.linalg.cho_solve(
            cho, jnp.concatenate([grad_w, grad_b[None]])
        )
        return sol[:d], sol[d]
    eps = noise * jnp.trace(h_ww) / d + 1e-12
    cho = jax.scipy.linalg.cho_factor(
        h_ww + eps * jnp.eye(d, dtype=accum), lower=True
    )
    return jax.scipy.linalg.cho_solve(cho, grad_w), jnp.zeros((), accum)


def _newton_fn(mesh: Mesh, reg: float, fit_intercept: bool, max_iter: int, tol: float, ad: str):
    # use_pallas / compute_dtype are read at build time so they participate
    # in the cache key (same snapshot pattern as ops/gram._streaming_update).
    return _newton_fn_cached(
        mesh, reg, fit_intercept, max_iter, tol, ad,
        jnp.dtype(config.get("compute_dtype")).name, bool(config.get("use_pallas")),
    )


@functools.lru_cache(maxsize=32)
def _newton_fn_cached(
    mesh: Mesh, reg: float, fit_intercept: bool, max_iter: int, tol: float, ad: str,
    cd: str, use_pallas: bool,
):
    """Binary Newton-IRLS, whole loop in one compiled SPMD program."""
    accum = jnp.dtype(ad)

    def shard(x, y, mask):
        from spark_rapids_ml_tpu.ops.gram import mm_precision

        with mm_precision(accum):  # true-f32 dots (TPU default is bf16)
            return _shard(x, y, mask)

    def _shard(x, y, mask):
        xc = x.astype(accum)
        yc = y.astype(accum)
        maskc = mask.astype(accum)
        # Integer sum: an f32 sum of ones saturates at 2^24 rows/shard.
        n = mr.reduce_sum(jnp.sum(maskc.astype(jnp.int32)).astype(accum), DATA_AXIS)
        d = x.shape[1]
        fused = _pallas_newton_applicable(x.shape, cd, ad, use_pallas)
        if fused:
            # One cast before the loop; every iteration then streams half
            # the HBM bytes and runs single-pass MXU dots.
            xb16 = x.astype(jnp.dtype(cd))
            y2 = yc.reshape(-1, 1)
            m2 = maskc.reshape(-1, 1)

        def grad_hess(w, b):
            if fused:
                # One HBM pass over x per iteration: z/residual/weight are
                # row-local, so the matvec, both vector statistics, and
                # the Hessian GEMM share one resident tile of x.
                from spark_rapids_ml_tpu.ops.pallas_kernels import newton_stats_pallas

                gw, gb, hww, hwb, hbb = newton_stats_pallas(xb16, y2, m2, w, b)
                grad_w = mr.reduce_sum(gw, DATA_AXIS) / n + reg * w
                grad_b = mr.reduce_sum(gb, DATA_AXIS) / n
                h_ww = mr.reduce_sum(hww, DATA_AXIS) / n + reg * jnp.eye(d, dtype=accum)
                h_wb = mr.reduce_sum(hwb, DATA_AXIS) / n
                h_bb = mr.reduce_sum(hbb, DATA_AXIS) / n
                return grad_w, grad_b, h_ww, h_wb, h_bb
            z = xc @ w + b
            p = jax.nn.sigmoid(z)
            r = (p - yc) * maskc  # dL/dz, masked
            grad_w = mr.reduce_sum(xc.T @ r, DATA_AXIS) / n + reg * w
            grad_b = mr.reduce_sum(jnp.sum(r), DATA_AXIS) / n
            wgt = jnp.maximum(p * (1.0 - p), 1e-10) * maskc
            xw = xc * wgt[:, None]
            # The Hessian is a preconditioner, not the answer: inexact
            # Newton converges to the same optimum (the gradient sets the
            # fixed point), so the dominant n·d² GEMM runs at fast DEFAULT
            # precision; gradients keep the surrounding full-f32 scope.
            h_ww = mr.reduce_sum(
                jax.lax.dot_general(xw, xc, (((0,), (0,)), ((), ())),
                                    preferred_element_type=accum,
                                    precision=jax.lax.Precision.DEFAULT),
                DATA_AXIS,
            ) / n + reg * jnp.eye(d, dtype=accum)
            h_wb = mr.reduce_sum(jnp.sum(xw, axis=0), DATA_AXIS) / n
            h_bb = mr.reduce_sum(jnp.sum(wgt), DATA_AXIS) / n
            return grad_w, grad_b, h_ww, h_wb, h_bb

        def loss_of(w, b):
            z = xc @ w + b
            # log(1+e^-z) for y=1, log(1+e^z) for y=0, numerically stable.
            per = (jax.nn.softplus(z) - yc * z) * maskc
            return mr.reduce_sum(jnp.sum(per), DATA_AXIS) / n + 0.5 * reg * (w @ w)

        # Trace-time solver choice: XLA's sequential LU costs ~10 ms at
        # d=1024 on TPU (more than the whole stats pass), so accelerator
        # backends solve with warm-started Jacobi-CG; on CPU LAPACK's
        # direct factorization is fast AND exact — keep it.
        direct_solve = jax.default_backend() == "cpu"

        def body(carry):
            w, b, _, it, prev_dir = carry
            grad_w, grad_b, h_ww, h_wb, h_bb = grad_hess(w, b)
            if direct_solve:
                # Bordered (d+1) system via block elimination (reg > 0)
                # or floored joint Cholesky (reg == 0, singular-safe):
                # [H_ww h_wb][dw]   [g_w]
                # [h_wbᵀ h_bb][db] = [g_b]
                dw, db = _solve_newton_system(
                    h_ww, h_wb, h_bb, grad_w, grad_b, reg, fit_intercept,
                    accum,
                )
                sol = jnp.concatenate([dw, db[None]]) if fit_intercept else dw
            elif fit_intercept:
                # The same bordered SPD system, solved whole by CG. At
                # reg == 0 it is only PSD (the same null directions the
                # direct path floors — _solve_newton_system): floor the
                # diagonal identically, or CG diverges along the null
                # space on exactly the inputs the Cholesky path survives.
                hfull = jnp.pad(h_ww, ((0, 1), (0, 1)))
                hfull = (
                    hfull.at[d, :d].set(h_wb).at[:d, d].set(h_wb).at[d, d].set(h_bb)
                )
                if reg <= 0.0:
                    eps = (1e3 * jnp.finfo(accum).eps
                           * jnp.trace(hfull) / (d + 1) + 1e-12)
                    hfull = hfull + eps * jnp.eye(d + 1, dtype=accum)
                gfull = jnp.concatenate([grad_w, grad_b[None]])
                sol = _pcg_solve(hfull, gfull, prev_dir)
                dw, db = sol[:d], sol[d]
            else:
                hmat = h_ww
                if reg <= 0.0:
                    eps = (1e3 * jnp.finfo(accum).eps
                           * jnp.trace(h_ww) / d + 1e-12)
                    hmat = h_ww + eps * jnp.eye(d, dtype=accum)
                sol = _pcg_solve(hmat, grad_w, prev_dir)
                dw, db = sol, jnp.zeros((), accum)
            new_w = w - dw
            new_b = b - db
            delta = jnp.sqrt(jnp.sum(dw * dw) + db * db)
            return new_w, new_b, delta, it + 1, sol

        def cond(carry):
            w, _, delta, it, _ = carry
            if fused and tol > 0.0:
                # (tol=0 keeps its "exactly max_iter steps" contract —
                # benchmarks and step-count-controlled callers rely on it.)
                # The bf16 rounding of x (and of w in the kernel's matvec)
                # puts a relative noise floor under the gradient — Newton
                # steps plateau around 2.5e-3·‖w‖ (measured, d=1k gaussian)
                # instead of contracting. Below 2^-8·‖w‖ steps are noise,
                # so stop there rather than burning max_iter on an
                # unreachable absolute tol.
                tol_eff = jnp.maximum(
                    jnp.asarray(tol, accum),
                    jnp.asarray(2.0**-8, accum) * jnp.linalg.norm(w),
                )
            else:
                tol_eff = tol
            return jnp.logical_and(it < max_iter, delta > tol_eff)

        w0 = jnp.zeros((d,), accum)
        b0 = jnp.zeros((), accum)
        dir0 = jnp.zeros((d + 1 if fit_intercept else d,), accum)
        w, b, _, n_iter, _ = jax.lax.while_loop(
            cond, body, (w0, b0, jnp.array(jnp.inf, accum), 0, dir0)
        )
        return w, b, n_iter, loss_of(w, b)

    f = shard_map(
        shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,  # pallas_call out_shapes carry no vma annotation
    )
    return ledgered_jit("logreg.newton_stats", f)


def fit_logistic_regression(
    x: np.ndarray,
    y: np.ndarray,
    reg: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    mesh: Optional[Mesh] = None,
) -> LogisticSolution:
    from spark_rapids_ml_tpu.parallel.sharding import require_single_process

    require_single_process("fit_logistic_regression (n_classes inferred from local labels)")
    mesh = mesh or default_mesh()
    x = np.asarray(x)
    y = np.asarray(y).reshape(-1)
    if x.shape[0] != y.shape[0]:
        raise ValueError(f"X rows {x.shape[0]} != y rows {y.shape[0]}")
    classes = np.unique(y)
    n_classes = len(classes)
    if n_classes < 2:
        raise ValueError("need at least 2 classes in the label column")
    if not np.array_equal(classes, np.arange(n_classes)):
        raise ValueError(
            f"labels must be 0..{n_classes - 1} (Spark ML convention); got {classes[:8]}"
        )
    ad = config.get("accum_dtype")
    with trace_span("logreg fit"):
        xs, mask, n_true = shard_rows(x, mesh)
        if n_classes == 2:
            ys, _, _ = shard_rows(y.astype(np.float64), mesh)
            fn = _newton_fn(mesh, float(reg), bool(fit_intercept), int(max_iter), float(tol), ad)
            w, b, n_iter, loss = jax.device_get(fn(xs, ys, mask))
            return LogisticSolution(
                coefficients=np.asarray(w, dtype=np.float64),
                intercept=np.asarray(b, dtype=np.float64),
                n_iter=int(n_iter),
                n_rows=n_true,
                loss=float(loss),
            )
        # Multinomial MM-Newton: the SAME machinery as the streaming path
        # (exact softmax gradient + per-class upper-bound curvature,
        # _stream_softmax_stats_fn) driven over the in-memory shards —
        # one device round-trip per iteration, converging in tens of
        # iterations where the round-2 Nesterov-GD sidecar needed
        # hundreds, and single source of truth for the update rule.
        accum = jnp.dtype(ad)
        state_bytes = n_classes * x.shape[1] ** 2 * accum.itemsize
        if state_bytes > 2**31:
            # The replicated (C, d, d) curvature state is the price of
            # second-order steps; past ~2 GB it would crowd out the data.
            raise ValueError(
                f"multinomial MM-Newton state is C·d² = {state_bytes / 2**30:.1f}"
                f" GiB (C={n_classes}, d={x.shape[1]}, {accum.name}) — too "
                "large for a replicated accumulator. Reduce d (feature "
                "hashing/PCA) or C, or use a float32 accum_dtype."
            )
        ys, _, _ = shard_rows(y.astype(np.float32), mesh)
        update = _stream_softmax_stats_fn(mesh, n_classes, ad)
        mm_step = _stream_multinomial_step_fn(float(reg), bool(fit_intercept), ad)
        W = jnp.zeros((x.shape[1], n_classes), accum)
        b = jnp.zeros((n_classes,), accum)
        n_iter = 0
        for it in range(max_iter):
            state = stream_softmax_zero_state(x.shape[1], n_classes, accum)
            gw, gb, hw, hwb, hbb, _, n = update(state, W, b, xs, ys, mask)
            W, b, delta = mm_step(gw, gb, hw, hwb, hbb, n, W, b)
            n_iter = it + 1
            if float(delta) <= tol:
                break
        return LogisticSolution(
            coefficients=np.asarray(
                jax.device_get(W), dtype=np.float64
            ).T,  # (c, d) Spark layout
            intercept=np.asarray(jax.device_get(b), dtype=np.float64),
            n_iter=n_iter,
            n_rows=n_true,
        )


# ---------------------------------------------------------------------------
# Streaming (out-of-HBM) Newton: one host scan per iteration
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _stream_grad_hess_fn(mesh: Mesh, ad: str):
    """Jitted donated accumulate of one batch's Newton statistics at fixed
    (w, b): (state, w, b, x, y, mask) -> state with
    state = (gw (d,), gb (), hww (d, d), hwb (d,), hbb (), loss (), n ()).

    Raw sums — normalization by n and the L2 term are applied in the
    finalize step once the scan's true row count is known.
    """
    accum = jnp.dtype(ad)

    def shard(gw, gb, hww, hwb, hbb, loss, n, w, b, x, y, mask):
        from spark_rapids_ml_tpu.ops.gram import mm_precision

        with mm_precision(accum):
            xc = x.astype(accum)
            yc = y.astype(accum)
            maskc = mask.astype(accum)
            z = xc @ w + b
            p = jax.nn.sigmoid(z)
            r = (p - yc) * maskc
            wgt = jnp.maximum(p * (1.0 - p), 1e-10) * maskc
            xw = xc * wgt[:, None]
            bloss = jnp.sum((jax.nn.softplus(z) - yc * z) * maskc)
            bn = jnp.sum(maskc.astype(jnp.int32)).astype(accum)
            return (
                gw + mr.reduce_sum(xc.T @ r, DATA_AXIS),
                gb + mr.reduce_sum(jnp.sum(r), DATA_AXIS),
                hww
                + mr.reduce_sum(
                    jax.lax.dot_general(
                        xw, xc, (((0,), (0,)), ((), ())),
                        preferred_element_type=accum,
                        # Preconditioner-only (see _newton_fn): fast path.
                        precision=jax.lax.Precision.DEFAULT,
                    ),
                    DATA_AXIS,
                ),
                hwb + mr.reduce_sum(jnp.sum(xw, axis=0), DATA_AXIS),
                hbb + mr.reduce_sum(jnp.sum(wgt), DATA_AXIS),
                loss + mr.reduce_sum(bloss, DATA_AXIS),
                n + mr.reduce_sum(bn, DATA_AXIS),
            )

    f = shard_map(
        shard,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P(), P(), P(),
                  P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(),) * 7,
    )

    @functools.partial(ledgered_jit, "logreg.streaming_update", donate_argnums=(0,))
    def update(state, w, b, x, y, mask):
        return f(*state, w, b, x, y, mask)

    return update


@functools.lru_cache(maxsize=64)
def _stream_newton_step_fn(reg: float, fit_intercept: bool, ad: str):
    """Jitted finalize: scan sums + current (w, b) -> (new_w, new_b, delta)."""
    accum = jnp.dtype(ad)

    def step(gw, gb, hww, hwb, hbb, n, w, b):
        n = jnp.maximum(n, 1.0)
        d = gw.shape[0]
        grad_w = gw / n + reg * w
        grad_b = gb / n
        h_ww = hww / n + reg * jnp.eye(d, dtype=accum)
        h_wb = hwb / n
        h_bb = hbb / n
        # Block elimination (reg > 0) or floored joint Cholesky (reg ==
        # 0, singular-safe) — same math as the in-memory _newton_fn body.
        dw, db = _solve_newton_system(
            h_ww, h_wb, h_bb, grad_w, grad_b, reg, fit_intercept, accum
        )
        delta = jnp.sqrt(jnp.sum(dw * dw) + db * db)
        return w - dw, b - db, delta

    return ledgered_jit("logreg.newton_step", step)


def _stream_softmax_stats_fn(mesh: Mesh, n_classes: int, ad: str):
    # compute_dtype / use_pallas are read at build time so they participate
    # in the cache key (the _newton_fn snapshot pattern): a config flip
    # between fits must not silently reuse a stale-curvature-dtype closure.
    return _stream_softmax_stats_cached(
        mesh, n_classes, ad, jnp.dtype(config.get("compute_dtype")).name,
        bool(config.get("use_pallas")),
    )


@functools.lru_cache(maxsize=32)
def _stream_softmax_stats_cached(
    mesh: Mesh, n_classes: int, ad: str, cd: str, use_pallas: bool = False
):
    """Jitted donated accumulate of one batch's multinomial statistics at
    fixed (W, b): (state, W, b, x, y, mask) -> state with
    state = (gw (d, C), gb (C), hw (C, d, d), hwb (C, d), hbb (C),
    loss (), n ()).

    The per-class curvature blocks are the MM/upper-bound Hessian
    Xᵀdiag(p_c)X: the softmax Hessian's class-coupling matrix satisfies
    diag(p) − ppᵀ ⪯ diag(p), so solving each class block against the
    EXACT gradient is a majorize-minimize Newton step — monotone descent
    with no line search, O(C·d²) state, one scan per iteration (the same
    streaming contract as the binary path; full-softmax coupling would
    need a (C·d)² Hessian that cannot stream)."""
    accum = jnp.dtype(ad)
    C = n_classes
    # Curvature blocks set only the MM step DIRECTION (the fixed point is
    # pinned by the exact full-precision gradient below), so their GEMM
    # operands stream at the compute dtype: on the TPU bf16 profile that
    # halves the C-GEMM loop's HBM traffic — the dominant cost at large C
    # (measured 0.69x -> parity-class at C=32, d=1024). f32/f64 accum
    # configs off the bf16 profile keep full-width operands.
    hd = (
        jnp.dtype(jnp.bfloat16)
        if accum == jnp.float32 and jnp.dtype(cd) == jnp.dtype(jnp.bfloat16)
        else accum
    )

    from spark_rapids_ml_tpu.ops.pallas_kernels import (
        SOFTMAX_CURV_BLOCK_N,
        SOFTMAX_CURV_VMEM_BUDGET,
        softmax_curv_block_c,
        softmax_curvature_pallas,
    )

    def _curv_kernel_ok(n: int, d: int) -> bool:
        """Shared-tile Pallas curvature: TPU backend + f32 accumulate +
        block-divisible shapes (the n check runs per traced shape — the
        streaming path's power-of-two row buckets satisfy it from the
        block size up, smaller buckets take the XLA loop, which is fine
        at that size) + even ONE class's (d, d) accumulator inside the
        VMEM budget (past that the XLA loop handles d, not a trace-time
        raise)."""
        from spark_rapids_ml_tpu.ops.gram import _pallas_backend_ok

        return (
            _pallas_backend_ok(use_pallas)
            and accum == jnp.float32
            and n % SOFTMAX_CURV_BLOCK_N == 0
            and d % 128 == 0
            and 4 * d * d <= SOFTMAX_CURV_VMEM_BUDGET
        )

    def shard(gw, gb, hw, hwb, hbb, loss, n, W, b, x, y, mask):
        from spark_rapids_ml_tpu.ops.gram import mm_precision

        with mm_precision(accum):
            xc = x.astype(accum)
            maskc = mask.astype(accum)
            yi = y.astype(jnp.int32)
            logits = xc @ W + b  # (n, C)
            p = jax.nn.softmax(logits, axis=1)
            yoh = jax.nn.one_hot(yi, C, dtype=accum)
            r = (p - yoh) * maskc[:, None]
            bloss = jnp.sum(
                (jax.nn.logsumexp(logits, axis=1)
                 - jnp.take_along_axis(logits, yi[:, None], axis=1)[:, 0])
                * maskc
            )
            bn = jnp.sum(maskc.astype(jnp.int32)).astype(accum)

            xh = xc.astype(hd)

            if _curv_kernel_ok(*x.shape):
                # Shared-tile kernel: each VMEM-resident x tile feeds a
                # class GROUP's GEMMs, dividing the C× HBM re-read of x —
                # the cost that capped this pass at 0.85× (see
                # ops/pallas_kernels.softmax_curvature_pallas).
                pm = (p * maskc[:, None]).astype(jnp.float32)
                bhw, bhwb = softmax_curvature_pallas(
                    xh, pm, block_c=softmax_curv_block_c(x.shape[1], C)
                )
                bhbb = jnp.sum(pm, axis=0).astype(accum)
            else:

                def per_class(c):
                    pc = p[:, c] * maskc  # (n,) full-precision probabilities
                    xw = xh * pc.astype(hd)[:, None]
                    return (
                        jax.lax.dot_general(
                            xw, xh, (((0,), (0,)), ((), ())),
                            preferred_element_type=accum,
                            # Fast-precision is safe here because these
                            # blocks only set the MM step DIRECTION; the
                            # fixed point is pinned by the exact
                            # full-precision gradient above
                            # (approximate-Hessian/exact-gradient).
                            precision=jax.lax.Precision.DEFAULT,
                        ),
                        jnp.sum(xw, axis=0, dtype=accum),
                        jnp.sum(pc),
                    )

                # Sequential over classes: a batched einsum would
                # materialize an (C, n, d) intermediate; C GEMMs stream x
                # from VMEM/HBM.
                bhw, bhwb, bhbb = jax.lax.map(per_class, jnp.arange(C))
            return (
                gw + mr.reduce_sum(
                    jax.lax.dot_general(xc, r, (((0,), (0,)), ((), ())),
                                        preferred_element_type=accum),
                    DATA_AXIS,
                ),
                gb + mr.reduce_sum(jnp.sum(r, axis=0), DATA_AXIS),
                hw + mr.reduce_sum(bhw, DATA_AXIS),
                hwb + mr.reduce_sum(bhwb, DATA_AXIS),
                hbb + mr.reduce_sum(bhbb, DATA_AXIS),
                loss + mr.reduce_sum(bloss, DATA_AXIS),
                n + mr.reduce_sum(bn, DATA_AXIS),
            )

    f = shard_map(
        shard,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P(), P(), P(),
                  P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(),) * 7,
        check_vma=False,  # pallas_call out_shapes carry no vma annotation
    )

    @functools.partial(ledgered_jit, "logreg.softmax_streaming_update", donate_argnums=(0,))
    def update(state, W, b, x, y, mask):
        return f(*state, W, b, x, y, mask)

    return update


@functools.lru_cache(maxsize=64)
def _stream_multinomial_step_fn(reg: float, fit_intercept: bool, ad: str):
    """Jitted finalize of one multinomial MM-Newton pass: scan sums +
    current (W (d, C), b (C)) -> (new_W, new_b, delta). Per-class
    bordered solves, vmapped over the class axis."""
    accum = jnp.dtype(ad)

    def step(gw, gb, hw, hwb, hbb, n, W, b):
        n = jnp.maximum(n, 1.0)
        d = gw.shape[0]
        grad_w = gw / n + reg * W  # (d, C)
        grad_b = gb / n  # (C,)
        h_w = hw / n + reg * jnp.eye(d, dtype=accum)[None, :, :]  # (C, d, d)
        h_wb = hwb / n  # (C, d)
        h_bb = hbb / n  # (C,)

        def solve_c(hww_c, hwb_c, hbb_c, gwc, gbc):
            # h_ww is Xᵀdiag(p)X/n + reg·I — symmetric PD when reg > 0:
            # ONE Cholesky per class with both right-hand sides
            # back-substituted together, where two jnp.linalg.solve calls
            # paid two LU factorizations (measured 35.9 → ~9 ms for the
            # C=32, d=1024 step).
            if reg > 0.0:
                cho = jax.scipy.linalg.cho_factor(hww_c, lower=True)
                if fit_intercept:
                    sol = jax.scipy.linalg.cho_solve(
                        cho, jnp.stack([hwb_c, gwc], axis=1)
                    )
                    hinv_hwb, hinv_gw = sol[:, 0], sol[:, 1]
                    schur = jnp.maximum(hbb_c - hwb_c @ hinv_hwb, 1e-12)
                    db = (gbc - hwb_c @ hinv_gw) / schur
                    dw = hinv_gw - hinv_hwb * db
                    return dw, db
                return (
                    jax.scipy.linalg.cho_solve(cho, gwc),
                    jnp.zeros((), accum),
                )
            # reg == 0: only PSD — the floored singular-safe solve
            # (_solve_newton_system; ADVICE r5(a)).
            return _solve_newton_system(
                hww_c, hwb_c, hbb_c, gwc, gbc, reg, fit_intercept, accum
            )

        dw, db = jax.vmap(solve_c)(h_w, h_wb, h_bb, grad_w.T, grad_b)
        new_W = W - dw.T
        new_b = b - db if fit_intercept else b
        delta = jnp.sqrt(jnp.sum(dw * dw) + jnp.sum(db * db))
        return new_W, new_b, delta

    return ledgered_jit("logreg.softmax_newton_step", step)


def stream_softmax_zero_state(n_cols: int, n_classes: int, accum_dtype) -> tuple:
    """Zero (gw, gb, hw, hwb, hbb, loss, n) accumulator for one
    multinomial pass — shared by fit_multinomial_stream and the daemon."""
    ad = jnp.dtype(accum_dtype)
    d, C = n_cols, n_classes
    return (
        jnp.zeros((d, C), ad),
        jnp.zeros((C,), ad),
        jnp.zeros((C, d, d), ad),
        jnp.zeros((C, d), ad),
        jnp.zeros((C,), ad),
        jnp.zeros((), ad),
        jnp.zeros((), ad),
    )


def stream_softmax_objective(lsum, n, reg: float, W) -> float:
    """Mean multinomial CE + L2 — the objective both the streaming fit
    and the daemon report."""
    return float(lsum / jnp.maximum(n, 1.0)) + 0.5 * float(reg) * float(
        jnp.sum(W * W)
    )


def validate_multiclass_labels(y: np.ndarray, n_classes: int) -> None:
    """Raise unless labels are integers in [0, n_classes) (Spark ML)."""
    ya = np.asarray(y)
    if ya.size == 0:
        return
    if not np.all(np.equal(np.mod(ya, 1), 0)):
        raise ValueError("labels must be integers 0..n_classes-1")
    lo, hi = ya.min(), ya.max()
    if lo < 0 or hi >= n_classes:
        raise ValueError(
            f"labels must be in [0, {n_classes}); got range [{lo}, {hi}]"
        )


def fit_multinomial_stream(
    batch_source,
    n_cols: int,
    n_classes: int,
    reg: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    mesh: Optional[Mesh] = None,
    checkpoint_path: Optional[str] = None,
) -> LogisticSolution:
    """Multinomial softmax over a re-scannable stream of host (x, y)
    batches — the multiclass peer of :func:`fit_logistic_stream` (round-2
    review: multinomial was an in-memory GD sidecar; Criteo-class
    multiclass needs the streaming/lockstep contract).

    One scan per MM-Newton iteration (see _stream_softmax_stats_fn for
    the upper-bound curvature argument); labels are integers in
    [0, n_classes). Multi-host lockstep and checkpoint/resume follow the
    binary path exactly.
    """
    from spark_rapids_ml_tpu.core import checkpoint as ckpt
    from spark_rapids_ml_tpu.parallel.sharding import lockstep_labeled_batches

    if n_classes < 2:
        raise ValueError("n_classes must be >= 2")
    multiproc = jax.process_count() > 1
    mesh = mesh or default_mesh()
    ad = config.get("accum_dtype")
    accum = jnp.dtype(ad)
    update = _stream_softmax_stats_fn(mesh, int(n_classes), ad)
    mm_step = _stream_multinomial_step_fn(float(reg), bool(fit_intercept), ad)

    W = jnp.zeros((n_cols, n_classes), accum)
    b = jnp.zeros((n_classes,), accum)
    start_iter = 0
    restored = ckpt.load_state(checkpoint_path) if checkpoint_path else None
    if checkpoint_path:
        ckpt.require_consistent_visibility(restored)
    if restored is not None:
        arrays, meta = restored
        if meta.get("n_cols") != n_cols or meta.get("n_classes") != n_classes:
            raise ValueError(
                f"checkpoint at {checkpoint_path} is for n_cols="
                f"{meta.get('n_cols')}, n_classes={meta.get('n_classes')}, "
                f"not ({n_cols}, {n_classes})"
            )
        W = jnp.asarray(arrays["W"], accum)
        b = jnp.asarray(arrays["b"], accum)
        start_iter = int(meta["it"])

    labels_checked = False

    def _check_labels(_x, y):
        if labels_checked:
            return None
        try:
            validate_multiclass_labels(y, n_classes)
        except ValueError as e:
            return str(e)
        return None

    def scan(W_dev, b_dev):
        nonlocal labels_checked
        state = stream_softmax_zero_state(n_cols, n_classes, accum)
        n_rows = 0
        for xb_host, yb_host in lockstep_labeled_batches(
            batch_source(), n_cols, check=_check_labels
        ):
            xs, ms, n_b = shard_rows(np.asarray(xb_host), mesh, dtype=np.float32)
            ys, _, _ = shard_rows(yb_host.astype(np.float32), mesh)
            n_rows += n_b
            state = update(state, W_dev, b_dev, xs, ys, ms)
        labels_checked = True
        return state, n_rows

    n_true = 0
    n_iter = start_iter
    loss = float("nan")
    with trace_span("multinomial-stream"):
        for it in range(start_iter, max_iter):
            (gw, gb, hw, hwb, hbb, lsum, n), n_true = scan(W, b)
            loss = stream_softmax_objective(lsum, n, reg, W)
            W, b, delta = mm_step(gw, gb, hw, hwb, hbb, n, W, b)
            n_iter = it + 1
            if checkpoint_path and (not multiproc or jax.process_index() == 0):
                ckpt.save_state(
                    checkpoint_path,
                    {
                        "W": np.asarray(jax.device_get(W)),
                        "b": np.asarray(jax.device_get(b)),
                    },
                    {"it": n_iter, "n_cols": n_cols, "n_classes": n_classes},
                )
            if float(delta) <= tol:
                break
        if n_true == 0:
            (_, _, _, _, _, lsum, n), n_true = scan(W, b)
            loss = stream_softmax_objective(lsum, n, reg, W)
    if checkpoint_path and (not multiproc or jax.process_index() == 0):
        import os

        if os.path.exists(checkpoint_path):
            os.unlink(checkpoint_path)
    return LogisticSolution(
        coefficients=np.asarray(jax.device_get(W), dtype=np.float64).T,  # (C, d)
        intercept=np.asarray(jax.device_get(b), dtype=np.float64),
        n_iter=n_iter,
        n_rows=n_true,
        loss=loss,
    )


def stream_zero_state(n_cols: int, accum_dtype) -> tuple:
    """Zero (gw, gb, hww, hwb, hbb, loss, n) accumulator for one Newton
    pass — shared by fit_logistic_stream and the data-plane daemon."""
    ad = jnp.dtype(accum_dtype)
    d = n_cols
    return (
        jnp.zeros((d,), ad),
        jnp.zeros((), ad),
        jnp.zeros((d, d), ad),
        jnp.zeros((d,), ad),
        jnp.zeros((), ad),
        jnp.zeros((), ad),
        jnp.zeros((), ad),
    )


def stream_objective(lsum, n, reg: float, w) -> float:
    """Training objective at the iterate a pass evaluated: mean data loss
    plus the L2 term — the single definition both streaming paths report."""
    return float(lsum / jnp.maximum(n, 1.0)) + 0.5 * float(reg) * float(
        jnp.sum(w * w)
    )


def validate_binary_labels(y: np.ndarray) -> None:
    """Raise unless labels are {0, 1} (Spark ML binary convention)."""
    bad = set(np.unique(y)) - {0, 1, 0.0, 1.0}
    if bad:
        raise ValueError(
            f"labels must be binary 0/1 for the streaming path; got {sorted(bad)[:8]}"
        )


def fit_logistic_stream(
    batch_source,
    n_cols: int,
    reg: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 100,
    tol: float = 1e-6,
    mesh: Optional[Mesh] = None,
    checkpoint_path: Optional[str] = None,
) -> LogisticSolution:
    """Binary Newton-IRLS over a re-scannable stream of host (x, y) batches
    — the capacity path for label datasets ≫ HBM (BASELINE.json config #4:
    Criteo-1TB normal-equations family).

    ``batch_source`` is a CALLABLE returning a fresh iterator of
    ``(x (rows, d), y (rows,))`` pairs; each Newton iteration consumes one
    full scan, accumulating gradient + Hessian sharded on device into a
    donated O(d²) state. Labels must be {0, 1} (binary only — multiclass
    streams through :func:`fit_multinomial_stream`). The returned
    ``loss`` is the objective at the LAST
    iterate evaluated during its final scan (one iteration stale, standard
    for streaming monitors; a converged fit has delta ≤ tol so the
    difference is below the stopping precision).

    With ``checkpoint_path``, (w, b) persist after every iteration and an
    interrupted fit resumes at the saved iteration.

    **Multi-host**: ``batch_source`` yields this process's local (x, y)
    stream; scans run in lockstep (``lockstep_labeled_batches`` — uneven
    lengths fine, label validation propagates collectively). Checkpoints
    are written by process 0 (shared filesystem to resume).
    """
    from spark_rapids_ml_tpu.core import checkpoint as ckpt
    from spark_rapids_ml_tpu.parallel.sharding import lockstep_labeled_batches

    multiproc = jax.process_count() > 1
    mesh = mesh or default_mesh()
    ad = config.get("accum_dtype")
    accum = jnp.dtype(ad)
    update = _stream_grad_hess_fn(mesh, ad)
    newton_step = _stream_newton_step_fn(float(reg), bool(fit_intercept), ad)

    w = jnp.zeros((n_cols,), accum)
    b = jnp.zeros((), accum)
    start_iter = 0
    restored = ckpt.load_state(checkpoint_path) if checkpoint_path else None
    if checkpoint_path:
        ckpt.require_consistent_visibility(restored)
    if restored is not None:
        arrays, meta = restored
        if meta.get("n_cols") != n_cols:
            raise ValueError(
                f"checkpoint at {checkpoint_path} is for n_cols="
                f"{meta.get('n_cols')}, not {n_cols}"
            )
        w = jnp.asarray(arrays["w"], accum)
        b = jnp.asarray(arrays["b"], accum)
        start_iter = int(meta["it"])

    labels_checked = False

    def _check_labels(_x, y):
        if labels_checked:  # first scan only — data is fixed across scans
            return None
        try:
            validate_binary_labels(y)
        except ValueError as e:
            return str(e)
        return None

    def scan(w_dev, b_dev):
        nonlocal labels_checked
        state = stream_zero_state(n_cols, accum)
        n_rows = 0
        for xb_host, yb_host in lockstep_labeled_batches(
            batch_source(), n_cols, check=_check_labels
        ):
            # shard_rows pads, casts f64→f32 via the threaded native bridge,
            # and places row-sharded (global assembly when multi-process).
            xs, ms, n_b = shard_rows(np.asarray(xb_host), mesh, dtype=np.float32)
            ys, _, _ = shard_rows(yb_host.astype(np.float32), mesh)
            n_rows += n_b
            state = update(state, w_dev, b_dev, xs, ys, ms)
        labels_checked = True
        return state, n_rows

    n_true = 0
    n_iter = start_iter
    loss = float("nan")
    with trace_span("logreg-stream"):
        for it in range(start_iter, max_iter):
            (gw, gb, hww, hwb, hbb, lsum, n), n_true = scan(w, b)
            # Objective at the iterate the scan evaluated (pre-update w).
            loss = stream_objective(lsum, n, reg, w)
            w, b, delta = newton_step(gw, gb, hww, hwb, hbb, n, w, b)
            n_iter = it + 1
            if checkpoint_path and (not multiproc or jax.process_index() == 0):
                ckpt.save_state(
                    checkpoint_path,
                    {
                        "w": np.asarray(jax.device_get(w)),
                        "b": np.asarray(jax.device_get(b)),
                    },
                    {"it": n_iter, "n_cols": n_cols},
                )
            if float(delta) <= tol:
                break
        if n_true == 0:
            # Resumed at/past max_iter: the loop never ran, so evaluate the
            # restored iterate once for a faithful (n_rows, loss).
            (_, _, _, _, _, lsum, n), n_true = scan(w, b)
            loss = stream_objective(lsum, n, reg, w)
    if checkpoint_path and (not multiproc or jax.process_index() == 0):
        import os

        if os.path.exists(checkpoint_path):
            os.unlink(checkpoint_path)
    return LogisticSolution(
        coefficients=np.asarray(jax.device_get(w), dtype=np.float64),
        intercept=np.asarray(jax.device_get(b), dtype=np.float64),
        n_iter=n_iter,
        n_rows=n_true,
        loss=loss,
    )


# ---------------------------------------------------------------------------
# Estimator / Model
# ---------------------------------------------------------------------------


class _LogisticRegressionParams(
    HasFeaturesCol,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasRegParam,
    HasFitIntercept,
    HasMaxIter,
    HasTol,
):
    def __init__(self, uid=None):
        super().__init__(uid=uid)
        self.setDefault(
            featuresCol="features",
            labelCol="label",
            predictionCol="prediction",
            probabilityCol="probability",
            rawPredictionCol="rawPrediction",
            regParam=0.0,
            fitIntercept=True,
            maxIter=100,
            tol=1e-6,
        )


class LogisticRegression(Estimator, _LogisticRegressionParams, MLWritable, MLReadable):
    _uid_prefix = "LogisticRegression"

    def __init__(self, uid=None, mesh: Optional[Mesh] = None):
        super().__init__(uid=uid)
        self._mesh = mesh

    def setRegParam(self, value: float) -> "LogisticRegression":
        return self._set(regParam=value)

    def setFitIntercept(self, value: bool) -> "LogisticRegression":
        return self._set(fitIntercept=value)

    def setMaxIter(self, value: int) -> "LogisticRegression":
        return self._set(maxIter=value)

    def setTol(self, value: float) -> "LogisticRegression":
        return self._set(tol=value)

    def _copy_extra_state(self, source):
        self._mesh = getattr(source, "_mesh", None)

    def _fit(self, dataset) -> "LogisticRegressionModel":
        x = as_matrix(dataset, self.getFeaturesCol())
        y = as_column(dataset, self.getLabelCol())
        sol = fit_logistic_regression(
            x,
            y,
            reg=self.getRegParam(),
            fit_intercept=self.getFitIntercept(),
            max_iter=self.getMaxIter(),
            tol=self.getTol(),
            mesh=self._mesh,
        )
        model = LogisticRegressionModel(
            coefficients=sol.coefficients, intercept=sol.intercept
        )
        model.uid = self.uid
        model._summary = LogisticTrainingSummary(
            loss=sol.loss, numIter=sol.n_iter, n_rows=sol.n_rows
        )
        self._copy_params_to(model)
        return model


class LogisticRegressionModel(Model, _LogisticRegressionParams, MLWritable, MLReadable):
    _uid_prefix = "LogisticRegressionModel"

    def __init__(self, coefficients=None, intercept=None, uid=None):
        super().__init__(uid=uid)
        self.coefficients = None if coefficients is None else np.asarray(coefficients)
        self.intercept = None if intercept is None else np.asarray(intercept)
        self._summary: Optional[LogisticTrainingSummary] = None

    @property
    def summary(self) -> Optional[LogisticTrainingSummary]:
        return self._summary

    @property
    def numClasses(self) -> int:
        if self.coefficients is None:
            return 0
        return 2 if self.coefficients.ndim == 1 else self.coefficients.shape[0]

    def _model_data(self):
        return {
            "coefficients": self.coefficients,
            "intercept": np.atleast_1d(self.intercept),
        }

    @classmethod
    def _from_model_data(cls, uid, data):
        coef = data["coefficients"]
        inter = data["intercept"]
        if coef.ndim == 1 or coef.shape[0] == 1:
            coef = coef.reshape(-1)
            inter = np.asarray(inter).reshape(-1)[0]
        return cls(coefficients=coef, intercept=inter, uid=uid)

    def _copy_extra_state(self, source):
        self.coefficients = source.coefficients
        self.intercept = source.intercept
        self._summary = getattr(source, "_summary", None)

    def predict_raw(self, x: np.ndarray) -> np.ndarray:
        """Per-class margins (logits) — Spark's rawPrediction vector.

        Binary: ``[-z, z]`` with z the log-odds, matching Spark's
        BinaryLogisticRegressionModel raw output.
        """
        x = np.asarray(x, dtype=np.float64)
        if self.coefficients.ndim == 1:
            z = x @ self.coefficients + float(np.asarray(self.intercept).reshape(-1)[0])
            return np.stack([-z, z], axis=1)
        return x @ self.coefficients.T + np.asarray(self.intercept)[None, :]

    def _raw_to_proba(self, raw: np.ndarray) -> np.ndarray:
        """Spark's raw2probability: binary -> sigmoid of the margin
        (raw = [-z, z] so softmax would wrongly give sigmoid(2z));
        multiclass -> softmax of the logits."""
        if self.coefficients.ndim == 1:
            z = raw[:, 1]
            # overflow-safe sigmoid: exp only ever sees non-positive input
            p1 = np.where(
                z >= 0,
                1.0 / (1.0 + np.exp(-np.abs(z))),
                np.exp(-np.abs(z)) / (1.0 + np.exp(-np.abs(z))),
            )
            return np.stack([1.0 - p1, p1], axis=1)
        logits = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(logits)
        return e / e.sum(axis=1, keepdims=True)

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        return self._raw_to_proba(self.predict_raw(x))

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.argmax(self.predict_proba(x), axis=1)

    # Daemon serving contract (serve/daemon.py).
    _serve_algo = "logreg"
    _serve_outputs = (
        ("rawPrediction", "rawPredictionCol", "vec"),
        ("probability", "probabilityCol", "vec"),
        ("prediction", "predictionCol", "double"),
    )

    def _serve_aot_plan(self, n_rows, n_cols, dtype="float32", k=None):
        """AOT-at-registration plan (serve/daemon.py; see PCAModel's) —
        the device half only: the raw→probability map is host
        elementwise and compiles nothing."""
        if self.coefficients is None:
            return None
        from spark_rapids_ml_tpu.parallel.sharding import bucket_rows

        c = np.asarray(self.coefficients)
        d = int(c.shape[-1] if c.ndim == 2 else c.shape[0])
        if int(n_cols) != d:
            raise ValueError(
                f"warmup n_cols={int(n_cols)} does not match the "
                f"model's fitted width {d}"
            )
        return [(
            self._raw_scorer(),
            (jax.ShapeDtypeStruct(
                (bucket_rows(int(n_rows)), d), jnp.dtype(dtype)
            ),),
        )]

    def _raw_scorer(self):
        """Jitted per-class margins with W, b device-resident — the device
        scoring path the daemon ``transform`` op serves (the reference ran
        transform on the accelerator, RapidsPCA.scala:128-161; scoring on
        executor CPUs would abandon it)."""
        cache = getattr(self, "_raw_cache", None)
        if cache is None:
            cache = self._raw_cache = {}
        from spark_rapids_ml_tpu import config

        key = (config.get("compute_dtype"), config.get("accum_dtype"))
        if key not in cache:
            import jax
            import jax.numpy as jnp

            from spark_rapids_ml_tpu.ops.gram import mm_precision

            cd, accum = jnp.dtype(key[0]), jnp.dtype(key[1])
            binary = self.coefficients.ndim == 1
            W = np.atleast_2d(self.coefficients)  # (C|1, d)
            w_dev = jnp.asarray(W, dtype=cd)
            b_dev = jnp.asarray(np.atleast_1d(self.intercept), accum)

            @ledgered_jit("logreg.raw_scores")
            def raw(x):
                with mm_precision(cd):
                    z = jax.lax.dot_general(
                        x.astype(cd), w_dev,
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=accum,
                    ) + b_dev[None, :]
                if binary:
                    # Spark's binary raw output is [-z, z] (the margin).
                    return jnp.concatenate([-z, z], axis=1)
                return z

            cache[key] = raw
        return cache[key]

    def transform_matrix(self, x: np.ndarray) -> dict:
        """Role-keyed transform of a bare matrix: margins on device, the
        elementwise raw→probability map on host (negligible next to the
        (n, d)×(d, C) GEMM)."""
        if self.coefficients is None:
            raise RuntimeError("model has no coefficients (unfitted?)")
        from spark_rapids_ml_tpu.parallel.sharding import run_bucketed

        with trace_span("logreg transform"):
            raw = run_bucketed(self._raw_scorer(), x).astype(np.float64)
            proba = self._raw_to_proba(raw)
            return {
                "rawPrediction": raw,
                "probability": proba,
                "prediction": np.argmax(proba, axis=1).astype(np.float64),
            }

    def _transform(self, dataset):
        if self.coefficients is None:
            raise RuntimeError("model has no coefficients (unfitted?)")
        x = as_matrix(dataset, self.getFeaturesCol())
        raw = self.predict_raw(x)
        proba = self._raw_to_proba(raw)
        # Emit rawPrediction + probability + prediction like Spark's
        # ProbabilisticClassificationModel (prediction last, so the
        # bare-matrix dataset path still returns hard labels).
        out = with_column(dataset, self.getRawPredictionCol(), raw)
        out = with_column(out, self.getProbabilityCol(), proba)
        return with_column(out, self.getPredictionCol(), np.argmax(proba, axis=1))
