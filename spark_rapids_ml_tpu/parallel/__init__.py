"""Distributed execution layer: device mesh, sharding, collectives.

TPU-native replacement for the reference's entire parallelism story
(SURVEY.md §2.3): where the reference runs one Spark task per partition and
combines n×n Gram partials with a JVM ``RDD.reduce`` (RapidsRowMatrix.scala:
122-139) — device→host→JVM→wire→JVM — this layer keeps partials on the
device plane: rows are sharded over the ``data`` mesh axis, features
(optionally) over ``model``, and partials combine with ``jax.lax.psum`` over
ICI/DCN inside one compiled program. This also implements the device-side
combiner the reference declared but never built (``accumulateCov``,
SURVEY.md §2.4).
"""

from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    default_mesh,
    make_mesh,
    mesh_shape,
)
from spark_rapids_ml_tpu.parallel.mapreduce import (
    all_concat,
    map_fn,
    reduce_sum,
    reduce_topk,
    ring_shift,
)
from spark_rapids_ml_tpu.parallel.membership import MeshMembership, registry
from spark_rapids_ml_tpu.parallel.sharding import (
    pad_rows,
    shard_rows,
    replicated,
    row_sharding,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "MeshMembership",
    "all_concat",
    "default_mesh",
    "make_mesh",
    "map_fn",
    "mesh_shape",
    "pad_rows",
    "reduce_sum",
    "reduce_topk",
    "registry",
    "replicated",
    "ring_shift",
    "row_sharding",
    "shard_rows",
]
