"""Wrapper integration against a REAL SparkSession (local[4]).

Skipped automatically when pyspark is absent (this repo's dev image
cannot install it — see README "Spark integration testing"); the CI
Docker image has pyspark and runs these. Mirrors the reference's
PCASuite (PCASuite.scala:42-88): ArrayType input, fit on a
multi-partition DataFrame through the executor-fed daemon path,
mapInArrow transform, CPU-oracle parity, sign-invariant 1e-5.
"""

import numpy as np
import pytest

pyspark = pytest.importorskip("pyspark")

from pyspark.sql import SparkSession  # noqa: E402

from spark_rapids_ml_tpu.models.pca import fit_pca  # noqa: E402
from spark_rapids_ml_tpu.spark.estimator import SparkPCA, SparkLinearRegression  # noqa: E402


@pytest.fixture(scope="module")
def spark():
    s = (
        SparkSession.builder.master("local[4]")
        .appName("srml-tpu-it")
        .config("spark.sql.execution.arrow.pyspark.enabled", "true")
        .getOrCreate()
    )
    yield s
    s.stop()
    from spark_rapids_ml_tpu.spark import daemon_session

    daemon_session.shutdown()


@pytest.fixture
def pca_df(spark, rng):
    n, d = 2000, 16
    basis = rng.normal(size=(d, d)) * np.logspace(0, -1.5, d)
    x = (rng.normal(size=(n, d)) @ basis).astype(np.float64)
    rows = [(row.tolist(),) for row in x]
    df = spark.createDataFrame(rows, ["features"]).repartition(4)
    return df, x


def test_real_spark_pca_fit_and_transform(pca_df, mesh8):
    df, x = pca_df
    model = SparkPCA().setInputCol("features").setK(3).fit(df)
    ref = fit_pca(x, k=3, mesh=mesh8)
    np.testing.assert_allclose(np.abs(model.pc), np.abs(ref.pc), atol=1e-5)
    out = model.transform(df)
    assert "pca_features" in out.columns
    got = np.asarray(out.select("pca_features").toPandas()["pca_features"].tolist())
    want = x @ model.pc  # Spark PCA transform does not mean-center
    # row order is not preserved across repartition; compare norms sorted
    np.testing.assert_allclose(
        np.sort(np.abs(got).sum(axis=1)), np.sort(np.abs(want).sum(axis=1)),
        atol=1e-4,
    )


def test_real_spark_linreg_fit(spark, rng, mesh8):
    n, d = 1500, 8
    x = rng.normal(size=(n, d))
    w = rng.normal(size=(d,))
    y = x @ w + 0.25
    rows = [(xi.tolist(), float(yi)) for xi, yi in zip(x, y)]
    df = spark.createDataFrame(rows, ["features", "label"]).repartition(4)
    model = SparkLinearRegression().setRegParam(1e-6).fit(df)
    np.testing.assert_allclose(model.coefficients, w, atol=1e-4)


def test_real_spark_transform_schema_is_derived(pca_df):
    """Round-3: the output schema comes from the input StructType + the
    model's declared output fields — no limit(1) probe job, and the
    declared ArrayType(Double) must match what the tasks actually emit
    (this exercises _derive_output_schema's pyspark branch, which no
    sim harness can)."""
    from pyspark.sql import types as T

    df, x = pca_df
    model = SparkPCA().setInputCol("features").setK(3).fit(df)
    out = model.transform(df)
    field = out.schema["pca_features"]
    assert isinstance(field.dataType, T.ArrayType)
    assert isinstance(field.dataType.elementType, T.DoubleType)
    assert out.count() == x.shape[0]


def test_real_spark_logreg_multiclass(spark, rng, mesh8):
    from spark_rapids_ml_tpu.spark.estimator import SparkLogisticRegression

    n, d, C = 1200, 6, 3
    x = rng.normal(size=(n, d))
    w = rng.normal(size=(d, C)) * 2
    y = np.argmax(x @ w, axis=1).astype(float)
    rows = [(xi.tolist(), float(yi)) for xi, yi in zip(x, y)]
    df = spark.createDataFrame(rows, ["features", "label"]).repartition(3)
    model = SparkLogisticRegression().setRegParam(1e-2).setMaxIter(12).fit(df)
    assert model.coefficients.shape == (C, d)
    out = model.transform(df).toPandas()
    proba = np.asarray(out["probability"].tolist())
    assert proba.shape == (n, C)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-9)
    assert (np.asarray(out["prediction"]) == np.asarray(out["label"])).mean() > 0.9


def test_real_spark_knn_daemon_fed(spark, rng):
    from spark_rapids_ml_tpu.spark.estimator import SparkNearestNeighbors

    n, d, k = 500, 8, 4
    x = rng.normal(size=(n, d)).astype(np.float64)
    df = spark.createDataFrame([(r.tolist(),) for r in x], ["features"]).repartition(3)
    model = SparkNearestNeighbors().setK(k).fit(df)
    dists, idx = model.kneighbors(x[:16])
    assert idx.shape == (16, k)
    # self-distance ~0 (ids are partition-major; repartition reorders rows,
    # so only the distance property is order-stable)
    np.testing.assert_allclose(dists[:, 0], 0.0, atol=1e-3)


def test_real_spark_transform_local_fallback(pca_df, monkeypatch):
    df, x = pca_df
    model = SparkPCA().setInputCol("features").setK(3).fit(df)
    monkeypatch.setenv("SRML_TRANSFORM_LOCAL", "1")
    out = model.transform(df)
    got = np.asarray(out.select("pca_features").toPandas()["pca_features"].tolist())
    assert got.shape == (x.shape[0], 3)
