"""Arrow list column <-> contiguous (n, d) matrix conversion.

Replaces the reference's cuDF LIST-column data path: the reference reads
training rows as a device-resident LIST column and grabs the flat child
buffer zero-copy (``lists_column_view(A).child()``, rapidsml_jni.cu:114-115),
and produces transform output as a new LIST column built from a flat result
buffer plus a stride-k offsets sequence (``cudf::sequence`` +
``make_lists_column``, rapidsml_jni.cu:98-106).

Arrow equivalents here:

* ``fixed_size_list<float32/float64>`` → zero-copy reshape of the child
  values buffer (the fast path; this is what a well-configured Spark→Arrow
  exporter produces for ML vectors).
* ragged ``list``/``large_list`` → validated gather into a contiguous matrix
  (native C++ threaded path when available, NumPy otherwise). Rows must all
  have width d; nulls are rejected — same constraint the reference's GEMM
  silently assumes of its input.
* matrix → ``fixed_size_list`` column for transform output, zero-copy over
  the result buffer (the make_lists_column equivalent).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is expected in this image
    pa = None

from spark_rapids_ml_tpu.bridge import native as _native
from spark_rapids_ml_tpu.utils import faults

_FLOAT_TYPES = ("float", "double", "halffloat")


def _require_pa():
    if pa is None:
        raise ImportError("pyarrow is required for the Arrow columnar bridge")


def list_column_to_matrix(col, n_cols: Optional[int] = None) -> np.ndarray:
    """Convert an Arrow (Chunked)Array of list type to an (n, d) ndarray.

    Zero-copy when the input is a fixed_size_list of float32/float64 with no
    nulls and an unsliced contiguous child buffer.
    """
    _require_pa()
    faults.checkpoint("bridge.to_matrix")
    if isinstance(col, pa.ChunkedArray):
        if col.num_chunks == 1:
            return _array_to_matrix(col.chunk(0), n_cols)
        mats = [_array_to_matrix(c, n_cols) for c in col.chunks if len(c)]
        if not mats:
            return np.empty((0, n_cols or 0))
        if len(mats) > 1 and mats[0].dtype == np.float64:
            out = _native.concat_chunks_f64(mats)  # threaded native assembly
            if out is not None:
                return out
        return np.concatenate(mats, axis=0)
    return _array_to_matrix(col, n_cols)


def _array_to_matrix(arr, n_cols: Optional[int]) -> np.ndarray:
    if arr.null_count:
        raise ValueError("list column contains nulls; expected dense vectors")
    t = arr.type
    if pa.types.is_fixed_size_list(t):
        d = t.list_size
        if n_cols is not None and d != n_cols:
            raise ValueError(f"fixed_size_list width {d} != expected {n_cols}")
        # flatten() accounts for slicing (arr.values would return the full
        # unsliced child buffer and misalign sliced arrays).
        flat = arr.flatten()
        if flat.null_count:
            raise ValueError("list column contains null elements; expected dense vectors")
        vals = flat.to_numpy(zero_copy_only=flat.null_count == 0)
        return vals.reshape(len(arr), d)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        offsets = np.asarray(arr.offsets)
        # arr.values of a sliced list array is the *unsliced* child; index via
        # offsets which are absolute into it.
        child = arr.values
        if child.null_count:
            # Only reject nulls inside this array's extent.
            window = child.slice(int(offsets[0]), int(offsets[-1]) - int(offsets[0]))
            if window.null_count:
                raise ValueError(
                    "list column contains null elements; expected dense vectors"
                )
        vals = child.to_numpy(zero_copy_only=child.null_count == 0)
        widths = np.diff(offsets)
        if len(widths) == 0:
            return np.empty((0, n_cols or 0), dtype=vals.dtype)
        d = int(widths[0]) if n_cols is None else n_cols
        if not np.all(widths == d):
            raise ValueError("ragged list column: rows have differing lengths")
        # Uniform widths imply the window [offsets[0], offsets[-1]) is exactly
        # len(arr)*d contiguous values — reshape is a view, no copy.
        start, stop = int(offsets[0]), int(offsets[-1])
        return vals[start:stop].reshape(len(arr), d)
    raise TypeError(f"unsupported Arrow type for vector column: {t}")


def table_column_to_matrix(table, name: str, n_cols: Optional[int] = None) -> np.ndarray:
    """Extract column ``name`` of an Arrow Table as an (n, d) matrix."""
    _require_pa()
    if name not in table.column_names:
        raise KeyError(f"column {name!r} not in table (have {table.column_names})")
    return list_column_to_matrix(table.column(name), n_cols)


def matrix_to_list_column(mat: np.ndarray):
    """Wrap an (n, d) ndarray as an Arrow fixed_size_list array, zero-copy.

    Equivalent of the reference's output construction: flat GEMM result +
    stride-d offsets → LIST column (rapidsml_jni.cu:98-106). fixed_size_list
    needs no offsets buffer at all — strictly less work than the reference.
    """
    _require_pa()
    faults.checkpoint("bridge.to_ipc")
    mat = np.ascontiguousarray(mat)
    n, d = mat.shape
    flat = pa.array(mat.reshape(-1))
    return pa.FixedSizeListArray.from_arrays(flat, d)


def matrix_from_any(col) -> Tuple[np.ndarray, int]:
    """Best-effort conversion of a column-of-vectors in any host format."""
    if pa is not None and isinstance(col, (pa.Array, pa.ChunkedArray)):
        m = list_column_to_matrix(col)
        return m, m.shape[1]
    arr = np.asarray(col)
    if arr.dtype == object:
        arr = np.stack([np.asarray(r) for r in arr])
    if arr.ndim != 2:
        raise ValueError(f"expected 2-D vector column, got shape {arr.shape}")
    return arr, arr.shape[1]
