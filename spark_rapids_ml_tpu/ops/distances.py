"""Pairwise squared-Euclidean distances via the Gram trick.

Not present in the reference (PCA-only), but required by the north-star
algorithm set (BASELINE.json: KMeans pairwise-dist kernel, approx-KNN
distance kernel). ‖x−y‖² = ‖x‖² + ‖y‖² − 2⟨x,y⟩ turns the O(m·k·d) distance
computation into one MXU GEMM plus rank-1 updates — the TPU-idiomatic form.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def sq_euclidean(
    x: jax.Array,
    y: jax.Array,
    compute_dtype=None,
    accum_dtype=jnp.float32,
) -> jax.Array:
    """(m, d) × (k, d) → (m, k) squared distances, clipped at 0."""
    from spark_rapids_ml_tpu.ops.gram import mm_precision

    xc = x.astype(compute_dtype) if compute_dtype is not None else x
    yc = y.astype(compute_dtype) if compute_dtype is not None else y
    with mm_precision(xc.dtype):
        xy = jax.lax.dot_general(
            xc, yc, (((1,), (1,)), ((), ())), preferred_element_type=accum_dtype
        )
    x2 = jnp.sum(jnp.square(x.astype(accum_dtype)), axis=1)
    y2 = jnp.sum(jnp.square(y.astype(accum_dtype)), axis=1)
    d = x2[:, None] + y2[None, :] - 2.0 * xy
    return jnp.maximum(d, 0.0)
