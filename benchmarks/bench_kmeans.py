"""KMeans Lloyd-iteration throughput — BASELINE.json config #3
(KMeans k=100 on 50M×256: pairwise-distance kernel + centroid allreduce).

Times the fused assign+update step (`models.kmeans._lloyd_fn`: distance
GEMM → argmin → one-hot update GEMM → psum) on device-resident data for a
fixed iteration count, reporting row-iterations/s/chip.

Baseline: the step is two k×d GEMMs ≈ 4·k·d flops/row·iter; an A100 at
~110 TFLOP/s sustained is ~1.07e9 row-iters/s. vs_baseline >= 0.5 matches
the north-star "within 2×".
"""

import os
import sys

if __package__ in (None, ""):  # direct script run: python benchmarks/bench_*.py
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

D = int(os.environ.get("SRML_BENCH_D", 256))
K = int(os.environ.get("SRML_BENCH_K", 100))
ROWS = int(os.environ.get("SRML_BENCH_BATCH_ROWS", 1 << 21))  # 2M × 256 f32 = 2.1 GB
ITERS = int(os.environ.get("SRML_BENCH_ITERS", 20))

A100_ROW_ITERS_PER_SEC = 110e12 / (4 * K * D)


def main() -> None:
    from benchmarks import setup_platform

    setup_platform()
    import jax
    import jax.numpy as jnp

    from benchmarks import emit
    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.kmeans import _lloyd_fn
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    config.set("compute_dtype", "bfloat16")
    config.set("accum_dtype", "float32")

    n_chips = len(jax.devices())
    mesh = make_mesh(model=1)
    x = jax.random.normal(jax.random.key(0), (ROWS, D), dtype=jnp.float32)
    if n_chips > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    mask = jnp.ones((ROWS,), dtype=jnp.float32)
    centers0 = jax.random.normal(jax.random.key(1), (K, D), dtype=jnp.float32)

    # tol=0 → exactly n iterations: a throughput measurement, not a
    # convergence race. Two iteration counts + slope_dt cancel the fixed
    # sync/dispatch overhead out of the reported rate.
    from benchmarks import slope_dt, sync

    config.set("use_pallas", True)
    fns = {
        n: _lloyd_fn(
            mesh, K, n, 0.0, "bfloat16", "float32", use_pallas=True
        )
        for n in (ITERS, 2 * ITERS)
    }

    def run(n):
        centers, cost, n_iter = fns[n](x, mask, centers0)
        sync(centers)
        assert int(n_iter) == n
        return centers

    # Median of 7 two-point slopes: single slopes on the tunneled dev chip
    # can invert or halve (documented ±25%-class jitter; a lone sample has
    # produced physically impossible >HBM-bound rates).
    run(ITERS)
    run(2 * ITERS)
    lats = [slope_dt(run, ITERS, 2 * ITERS, warm=False) for _ in range(7)]
    dt_per_iter = float(np.median(lats))
    emit(
        f"kmeans_row_iters_per_sec_per_chip_d{D}_k{K}",
        ROWS / dt_per_iter / n_chips,
        "row_iters/s/chip",
        (ROWS / dt_per_iter / n_chips) / A100_ROW_ITERS_PER_SEC,
    )


if __name__ == "__main__":
    main()
