"""ctypes loader for the native columnar library (libsrml_tpu.so).

The reference packages its native library inside the jar and extracts it at
first use (JniRAPIDSML.java:34-58). Here the .so is built from
``native/src/columnar.cpp`` (``make -C native``) and looked up next to the
package and in the repo's ``native/build`` dir; if absent or disabled via
config ``use_native_bridge``, callers fall back to the pure-NumPy path.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from spark_rapids_ml_tpu import config

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False

_SO_NAME = "libsrml_tpu.so"


def _candidate_paths():
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(os.path.dirname(here))
    return [
        # Explicit config wins over discovery.
        os.environ.get("SRML_TPU_NATIVE_LIB", ""),
        os.path.join(here, _SO_NAME),
        os.path.join(repo, "native", "build", _SO_NAME),
    ]


def get_lib() -> Optional[ctypes.CDLL]:
    """Load and memoize the native library; None if unavailable/disabled."""
    global _lib, _lib_tried
    if not config.get("use_native_bridge"):
        return None
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        for path in _candidate_paths():
            if path and os.path.exists(path):
                try:
                    lib = ctypes.CDLL(path)
                    _configure(lib)
                    _lib = lib
                    break
                except (OSError, AttributeError):
                    # AttributeError: stale .so missing a newer export —
                    # fall through to the next candidate / NumPy fallback.
                    continue
        return _lib


def _configure(lib: ctypes.CDLL) -> None:
    c_i64 = ctypes.c_int64
    c_p = ctypes.c_void_p
    # int srml_flatten_list_f64(const double* values, const int64_t* offsets,
    #                           int64_t n_rows, int64_t n_cols, double* out,
    #                           int n_threads)
    lib.srml_flatten_list_f64.restype = ctypes.c_int
    lib.srml_flatten_list_f64.argtypes = [c_p, c_p, c_i64, c_i64, c_p, ctypes.c_int]
    lib.srml_flatten_list_f32.restype = ctypes.c_int
    lib.srml_flatten_list_f32.argtypes = [c_p, c_p, c_i64, c_i64, c_p, ctypes.c_int]
    # int srml_cast_f64_to_f32(const double* src, int64_t n, float* dst, int n_threads)
    lib.srml_cast_f64_to_f32.restype = ctypes.c_int
    lib.srml_cast_f64_to_f32.argtypes = [c_p, c_i64, c_p, ctypes.c_int]
    # int srml_concat_chunks_f64(const double** chunks, const int64_t* rows,
    #                            int64_t n_chunks, int64_t n_cols, double* out,
    #                            int n_threads)
    lib.srml_concat_chunks_f64.restype = ctypes.c_int
    lib.srml_concat_chunks_f64.argtypes = [c_p, c_p, c_i64, c_i64, c_p, ctypes.c_int]
    lib.srml_abi_version.restype = ctypes.c_int
    lib.srml_abi_version.argtypes = []
    if lib.srml_abi_version() != 1:
        raise OSError("libsrml_tpu ABI version mismatch")


def _nthreads() -> int:
    return min(16, os.cpu_count() or 1)


def flatten_ragged(values: np.ndarray, offsets: np.ndarray, n_cols: int) -> Optional[np.ndarray]:
    """Native gather of a ragged list column into an (n_rows, n_cols) matrix.

    ``values`` is the flat child buffer, ``offsets`` the (n_rows+1,) int64
    offsets. Every row must have exactly ``n_cols`` elements (validated
    natively; returns None to signal fallback on any error).
    """
    lib = get_lib()
    if lib is None:
        return None
    n_rows = len(offsets) - 1
    if n_rows < 0:
        return None
    # Bounds check here on the host: the native side never sees the values
    # length, and a corrupt offsets buffer must not become an OOB memcpy.
    if n_rows > 0 and (int(offsets[0]) < 0 or int(offsets[-1]) > values.size):
        return None
    if values.dtype == np.float64:
        fn = lib.srml_flatten_list_f64
        out = np.empty((n_rows, n_cols), dtype=np.float64)
    elif values.dtype == np.float32:
        fn = lib.srml_flatten_list_f32
        out = np.empty((n_rows, n_cols), dtype=np.float32)
    else:
        return None
    values = np.ascontiguousarray(values)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    rc = fn(
        values.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        n_rows,
        n_cols,
        out.ctypes.data_as(ctypes.c_void_p),
        _nthreads(),
    )
    if rc != 0:
        return None
    return out


def cast_f64_to_f32(src: np.ndarray) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None or src.dtype != np.float64:
        return None
    src = np.ascontiguousarray(src)
    dst = np.empty(src.shape, dtype=np.float32)
    rc = lib.srml_cast_f64_to_f32(
        src.ctypes.data_as(ctypes.c_void_p),
        src.size,
        dst.ctypes.data_as(ctypes.c_void_p),
        _nthreads(),
    )
    if rc != 0:
        return None
    return dst


def concat_chunks_f64(chunks) -> Optional[np.ndarray]:
    """Threaded concat of a list of contiguous (rows_i, d) float64 blocks."""
    lib = get_lib()
    if lib is None or not chunks:
        return None
    arrs = [np.ascontiguousarray(c) for c in chunks]
    if any(a.dtype != np.float64 or a.ndim != 2 for a in arrs):
        return None
    d = arrs[0].shape[1]
    if any(a.shape[1] != d for a in arrs):
        return None
    n_total = sum(a.shape[0] for a in arrs)
    out = np.empty((n_total, d), dtype=np.float64)
    ptrs = (ctypes.c_void_p * len(arrs))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs]
    )
    rows = np.asarray([a.shape[0] for a in arrs], dtype=np.int64)
    rc = lib.srml_concat_chunks_f64(
        ctypes.cast(ptrs, ctypes.c_void_p),
        rows.ctypes.data_as(ctypes.c_void_p),
        len(arrs),
        d,
        out.ctypes.data_as(ctypes.c_void_p),
        _nthreads(),
    )
    if rc != 0:
        return None
    return out
