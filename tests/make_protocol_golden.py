"""Generate the FROZEN v1 wire-protocol transcript fixture.

Writes ``tests/fixtures/protocol_v1.bin``: the exact client→daemon byte
stream of one session exercising every v1 op (ping, feed eager, feed
partitioned, commit, seed, step, status, finalize, drop). The committed
fixture is the conformance artifact third-party clients (e.g. a JVM
implementation, README "Scala interop") are tested against:
``tests/test_protocol_golden.py`` replays these recorded bytes against a
live daemon and asserts the responses — if the daemon stops accepting
them, the frozen contract broke and PROTOCOL_VERSION must be bumped.

Run ``python -m tests.make_protocol_golden`` ONLY when deliberately
re-freezing (version bump); never regenerate to make a red test green.
"""

from __future__ import annotations

import json
import os
import struct

import numpy as np

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "protocol_v1.bin")

V = 1  # frozen: generator is pinned to v1, independent of the live code


def golden_matrix() -> np.ndarray:
    """8×3 deterministic data, two distinct 4-row partitions."""
    rng = np.random.default_rng(20260731)
    return rng.normal(size=(8, 3)).astype(np.float64)


def _ipc_bytes(x: np.ndarray) -> bytes:
    import pyarrow as pa

    col = pa.FixedSizeListArray.from_arrays(
        pa.array(np.ascontiguousarray(x).reshape(-1)), x.shape[1]
    )
    table = pa.table({"features": col})
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue().to_pybytes()


def frame_bytes(payload: bytes) -> bytes:
    return struct.pack(">I", len(payload)) + payload


def transcript_frames() -> tuple[list, list]:
    """Returns (request frames, per-request response expectations).

    Each request frame is ("json", bytes) or ("arrow", bytes) — the kind
    matters to the drift test: JSON frames are frozen byte-for-byte, Arrow
    payload frames are frozen *semantically* (any valid Arrow IPC encoding
    of the same table conforms; pyarrow version bumps may re-encode).
    Each expectation is (kind, checks) where kind is "json" or "arrays"
    and checks is a dict of response fields the replay asserts.
    """
    x = golden_matrix()
    p0, p1 = x[:4], x[4:]
    frames: list = []
    expect: list = []

    def _req(obj: dict, payload: bytes | None = None) -> None:
        frames.append(("json", json.dumps(obj).encode()))
        if payload is not None:
            frames.append(("arrow", payload))

    # 1. hello: version discovery (the one version-exempt op)
    _req({"v": V, "op": "ping"})
    expect.append(("json", {"ok": True, "v": V}))

    # 2-3. eager feeds: two batches on one job, rows accumulate immediately
    _req(
        {"v": V, "op": "feed", "job": "g-eager", "algo": "pca",
         "input_col": "features", "label_col": "label", "n_cols": None,
         "params": {}, "partition": None, "attempt": 0, "pass_id": None},
        _ipc_bytes(p0),
    )
    expect.append(("json", {"ok": True, "rows": 4}))
    _req(
        {"v": V, "op": "feed", "job": "g-eager", "algo": "pca",
         "input_col": "features", "label_col": "label", "n_cols": None,
         "params": {}, "partition": None, "attempt": 0, "pass_id": None},
        _ipc_bytes(p1),
    )
    expect.append(("json", {"ok": True, "rows": 8}))

    # 4-7. partitioned exactly-once path: feed→commit per partition;
    # rows count only after commit
    for pid, part, rows_after in ((0, p0, 4), (1, p1, 8)):
        _req(
            {"v": V, "op": "feed", "job": "g-part", "algo": "pca",
             "input_col": "features", "label_col": "label", "n_cols": None,
             "params": {}, "partition": pid, "attempt": 0, "pass_id": None},
            _ipc_bytes(part),
        )
        expect.append(("json", {"ok": True}))
        _req({"v": V, "op": "commit", "job": "g-part",
                   "partition": pid, "attempt": 0, "pass_id": None})
        expect.append(("json", {"ok": True, "rows": rows_after}))

    # 8. status
    _req({"v": V, "op": "status", "job": "g-part"})
    expect.append(("json", {"ok": True, "rows": 8, "algo": "pca", "n_cols": 3}))

    # 9-10. finalize both jobs (k=2); arrays follow the JSON header
    for job in ("g-eager", "g-part"):
        _req({"v": V, "op": "finalize", "job": job,
                   "params": {"k": 2, "mean_center": True}, "drop": True})
        expect.append(("arrays", {"ok": True, "rows": 8}))

    # 11. kmeans seed: deterministic centers, rows NOT folded
    _req(
        {"v": V, "op": "seed", "job": "g-km", "input_col": "features",
         "n_cols": None, "params": {"k": 2, "seed": 7, "init": "k-means++"}},
        _ipc_bytes(x),
    )
    expect.append(("json", {"ok": True, "rows": 0}))

    # 12-17. two Lloyd passes: feed(pass_id)→commit→step
    for pass_id in (0, 1):
        _req(
            {"v": V, "op": "feed", "job": "g-km", "algo": "kmeans",
             "input_col": "features", "label_col": "label", "n_cols": None,
             "params": {"k": 2, "seed": 7, "init": "k-means++"},
             "partition": 0, "attempt": 0, "pass_id": pass_id},
            _ipc_bytes(x),
        )
        expect.append(("json", {"ok": True}))
        _req({"v": V, "op": "commit", "job": "g-km",
                   "partition": 0, "attempt": 0, "pass_id": pass_id})
        expect.append(("json", {"ok": True, "rows": 8 * (pass_id + 1)}))
        _req({"v": V, "op": "step", "job": "g-km", "params": {}})
        expect.append(("json", {"ok": True, "iteration": pass_id + 1}))

    # 18. finalize kmeans without drop, then explicit drop
    _req({"v": V, "op": "finalize", "job": "g-km", "params": {},
               "drop": False})
    expect.append(("arrays", {"ok": True}))
    _req({"v": V, "op": "drop", "job": "g-km"})
    expect.append(("json", {"ok": True, "dropped": True}))

    return frames, expect


def transcript() -> tuple[bytes, list]:
    """(full request byte stream, response expectations)."""
    frames, expect = transcript_frames()
    return b"".join(frame_bytes(p) for _, p in frames), expect


def main() -> None:
    os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
    data, expect = transcript()
    with open(FIXTURE, "wb") as f:
        f.write(data)
    print(f"wrote {FIXTURE}: {len(data)} bytes, {len(expect)} requests")
    data_s, expect_s = serving_transcript()
    with open(FIXTURE_SERVING, "wb") as f:
        f.write(data_s)
    print(f"wrote {FIXTURE_SERVING}: {len(data_s)} bytes, {len(expect_s)} requests")
    data_m, expect_m = multihost_transcript()
    with open(FIXTURE_MULTIHOST, "wb") as f:
        f.write(data_m)
    print(f"wrote {FIXTURE_MULTIHOST}: {len(data_m)} bytes, {len(expect_m)} requests")



# ---------------------------------------------------------------------------
# v1 serving-ops transcript (additive ops: ensure_model / transform /
# model_status / kneighbors / drop_model + the knn job algo)
# ---------------------------------------------------------------------------

FIXTURE_SERVING = os.path.join(
    os.path.dirname(__file__), "fixtures", "protocol_v1_serving.bin"
)


def golden_pc() -> np.ndarray:
    """Deterministic (3, 2) projection matrix for the served-PCA leg —
    conformance needs a fixed registered model, not a real fit."""
    return np.asarray([[0.8, -0.6], [0.6, 0.8], [0.0, 0.0]], np.float64)


def serving_transcript_frames() -> tuple[list, list]:
    """Request frames + response expectations for the serving ops.

    Kinds: ("json", bytes) / ("arrow", bytes) / ("raw", bytes) — raw
    frames are the request-direction array buffers of ensure_model.
    """
    x = golden_matrix()
    pc = golden_pc()
    frames: list = []
    expect = []

    def _req(obj: dict, payloads=()) -> None:
        frames.append(("json", json.dumps(obj).encode()))
        frames.extend(payloads)

    # 1. register a PCA model: JSON carries the arrays spec, raw buffer
    # frames follow (request-direction mirror of finalize's response)
    arrays = {"pc": pc, "mean": np.zeros((3,), np.float64)}
    spec = [
        {"name": k, "dtype": str(v.dtype), "shape": list(v.shape)}
        for k, v in arrays.items()
    ]
    _req(
        {"v": V, "op": "ensure_model", "model": "g-served", "algo": "pca",
         "params": {}, "arrays": spec},
        [("raw", np.ascontiguousarray(v).tobytes()) for v in arrays.values()],
    )
    expect.append(("json", {"ok": True, "created": True}))

    # 2. idempotent re-register: first copy wins
    _req(
        {"v": V, "op": "ensure_model", "model": "g-served", "algo": "pca",
         "params": {}, "arrays": spec},
        [("raw", np.ascontiguousarray(v).tobytes()) for v in arrays.values()],
    )
    expect.append(("json", {"ok": True, "created": False}))

    # 3. model_status
    _req({"v": V, "op": "model_status", "model": "g-served"})
    expect.append(("json", {"ok": True, "exists": True, "algo": "pca"}))

    # 4. transform one batch: response carries the role-keyed arrays
    _req(
        {"v": V, "op": "transform", "model": "g-served",
         "input_col": "features", "n_cols": None},
        [("arrow", _ipc_bytes(x))],
    )
    expect.append(("arrays", {"ok": True, "rows": 8}))

    # 5-8. knn job: partitioned rows feed -> commit -> build-and-serve
    for pid, part in ((0, x[:4]), (1, x[4:])):
        _req(
            {"v": V, "op": "feed", "job": "g-knn", "algo": "knn",
             "input_col": "features", "label_col": "label", "n_cols": None,
             "params": {}, "partition": pid, "attempt": 0, "pass_id": None},
            [("arrow", _ipc_bytes(part))],
        )
        expect.append(("json", {"ok": True}))
        _req({"v": V, "op": "commit", "job": "g-knn",
              "partition": pid, "attempt": 0, "pass_id": None})
        expect.append(("json", {"ok": True}))
    _req({"v": V, "op": "finalize", "job": "g-knn",
          "params": {"mode": "exact", "register_as": "g-knn-idx"},
          "drop": True})
    expect.append(("arrays", {"ok": True, "rows": 8, "model": "g-knn-idx"}))

    # 9. kneighbors against the daemon-built index
    _req(
        {"v": V, "op": "kneighbors", "model": "g-knn-idx", "k": 2,
         "input_col": "features", "n_cols": None},
        [("arrow", _ipc_bytes(x[:3]))],
    )
    expect.append(("arrays", {"ok": True, "rows": 3}))

    # 10-11. drop both registrations
    for name in ("g-served", "g-knn-idx"):
        _req({"v": V, "op": "drop_model", "model": name})
        expect.append(("json", {"ok": True, "dropped": True}))

    return frames, expect


def serving_transcript() -> tuple[bytes, list]:
    frames, expect = serving_transcript_frames()
    return b"".join(frame_bytes(p) for _, p in frames), expect


# ---------------------------------------------------------------------------
# v1 multi-host transcript (additive ops: feed_raw / export_state /
# get_iterate / set_iterate). merge_state is deliberately NOT in a frozen
# fixture: its payload is an export_state round-trip whose array layout is
# documented as OPAQUE daemon-to-daemon state — freezing fabricated bytes
# would promote the internal state layout into the wire contract. Its
# conformance lives in live tests (tests/test_spark_multidaemon.py).
# ---------------------------------------------------------------------------

FIXTURE_MULTIHOST = os.path.join(
    os.path.dirname(__file__), "fixtures", "protocol_v1_multihost.bin"
)


def multihost_transcript_frames() -> tuple[list, list]:
    x = golden_matrix()
    frames: list = []
    expect = []

    def _req(obj: dict, payloads=()) -> None:
        frames.append(("json", json.dumps(obj).encode()))
        frames.extend(payloads)

    def _raw_spec(arrays: dict) -> tuple[list, list]:
        spec = [
            {"name": k, "dtype": str(np.asarray(v).dtype),
             "shape": list(np.asarray(v).shape)}
            for k, v in arrays.items()
        ]
        bufs = [("raw", np.ascontiguousarray(v).tobytes())
                for v in arrays.values()]
        return spec, bufs

    # 1. feed_raw eager: raw float64 buffer instead of Arrow IPC
    spec, bufs = _raw_spec({"x": x})
    _req({"v": V, "op": "feed_raw", "job": "g-raw", "algo": "pca",
          "n_cols": 3, "params": {}, "partition": None, "attempt": 0,
          "pass_id": None, "arrays": spec}, bufs)
    expect.append(("json", {"ok": True, "rows": 8}))

    # 2-5. feed_raw through the exactly-once partition/commit path
    for pid, part, rows_after in ((0, x[:4], 4), (1, x[4:], 8)):
        spec, bufs = _raw_spec({"x": part})
        _req({"v": V, "op": "feed_raw", "job": "g-raw2", "algo": "pca",
              "n_cols": 3, "params": {}, "partition": pid, "attempt": 0,
              "pass_id": None, "arrays": spec}, bufs)
        expect.append(("json", {"ok": True}))
        _req({"v": V, "op": "commit", "job": "g-raw2",
              "partition": pid, "attempt": 0, "pass_id": None})
        expect.append(("json", {"ok": True, "rows": rows_after}))

    # 6. export_state: committed partials + accounting meta (arrays are
    # opaque state — the replay checks framing + meta, not layout)
    _req({"v": V, "op": "export_state", "job": "g-raw2"})
    expect.append(("arrays", {"ok": True, "rows": 8, "pass_rows": 8,
                              "iteration": 0, "algo": "pca", "n_cols": 3}))

    # 7-8. finalize both jobs — feed_raw and Arrow-fed data are the same
    # bytes, so the replay asserts the two models are identical
    for job in ("g-raw", "g-raw2"):
        _req({"v": V, "op": "finalize", "job": job,
              "params": {"k": 2, "mean_center": True}, "drop": True})
        expect.append(("arrays", {"ok": True, "rows": 8}))

    # 9. feed_raw with labels (linreg): x + y arrays
    y = (x @ np.asarray([1.0, -2.0, 3.0])) + 0.5
    spec, bufs = _raw_spec({"x": x, "y": y})
    _req({"v": V, "op": "feed_raw", "job": "g-rawlr", "algo": "linreg",
          "n_cols": 3, "params": {}, "partition": None, "attempt": 0,
          "pass_id": None, "arrays": spec}, bufs)
    expect.append(("json", {"ok": True, "rows": 8}))
    _req({"v": V, "op": "finalize", "job": "g-rawlr",
          "params": {"reg": 0.0, "fit_intercept": True}, "drop": True})
    expect.append(("arrays", {"ok": True, "rows": 8}))

    # 10-14. iterate sync ops on a kmeans job: seed → feed → step →
    # get_iterate → set_iterate (fixed centers; resets pass stats)
    _req({"v": V, "op": "seed", "job": "g-mkm", "input_col": "features",
          "n_cols": None, "params": {"k": 2, "seed": 7, "init": "k-means++"}},
         [("arrow", _ipc_bytes(x))])
    expect.append(("json", {"ok": True, "rows": 0}))
    spec, bufs = _raw_spec({"x": x})
    _req({"v": V, "op": "feed_raw", "job": "g-mkm", "algo": "kmeans",
          "n_cols": 3, "params": {"k": 2, "seed": 7, "init": "k-means++"},
          "partition": 0, "attempt": 0, "pass_id": 0, "arrays": spec}, bufs)
    expect.append(("json", {"ok": True}))
    _req({"v": V, "op": "commit", "job": "g-mkm",
          "partition": 0, "attempt": 0, "pass_id": 0})
    expect.append(("json", {"ok": True, "rows": 8}))
    _req({"v": V, "op": "step", "job": "g-mkm", "params": {}})
    expect.append(("json", {"ok": True, "iteration": 1}))
    _req({"v": V, "op": "get_iterate", "job": "g-mkm"})
    expect.append(("arrays", {"ok": True, "iteration": 1}))
    centers = np.asarray([[0.0, 0.0, 0.0], [1.0, 1.0, 1.0]], np.float64)
    spec, bufs = _raw_spec({"centers": centers})
    _req({"v": V, "op": "set_iterate", "job": "g-mkm", "iteration": 2,
          "arrays": spec}, bufs)
    expect.append(("json", {"ok": True}))
    _req({"v": V, "op": "drop", "job": "g-mkm"})
    expect.append(("json", {"ok": True, "dropped": True}))

    # 15+. Sharded KNN build (additive, round 5 — docs/protocol.md
    # "Sharded index across daemons"): two shard jobs on this one daemon
    # stand in for two daemons. Each holds one partition; finalize
    # translates local→global ids via row_id_base; shard A returns its
    # trained quantizer (return_centroids), shard B buckets against
    # transcript-FIXED centroids (the live flow forwards A's returned
    # quantizer, but a recorded byte stream must carry fixed bytes — the
    # framing is what is frozen, not the float values).
    for pid, job in ((0, "g-shA"), (1, "g-shB")):
        part = (x[:4] if pid == 0 else x[4:]).astype(np.float32)
        spec, bufs = _raw_spec({"x": part})
        _req({"v": V, "op": "feed_raw", "job": job, "algo": "knn",
              "n_cols": 3, "params": {}, "partition": pid, "attempt": 0,
              "pass_id": None, "arrays": spec}, bufs)
        expect.append(("json", {"ok": True}))
        _req({"v": V, "op": "commit", "job": job, "partition": pid,
              "attempt": 0, "pass_id": None})
        expect.append(("json", {"ok": True, "rows": 4}))
    _req({"v": V, "op": "finalize", "job": "g-shA",
          "params": {"mode": "ivf", "register_as": "g-idxA", "nlist": 2,
                     "nprobe": 2, "seed": 0, "metric": "euclidean",
                     "row_id_base": {"0": 0}, "return_centroids": True},
          "drop": True})
    expect.append(("arrays", {"ok": True, "rows": 4, "model": "g-idxA"}))
    cent = np.asarray([[0.5, 0.0, -0.5], [-0.5, 0.5, 0.0]], np.float32)
    spec, bufs = _raw_spec({"centroids": cent})
    _req({"v": V, "op": "finalize", "job": "g-shB",
          "params": {"mode": "ivf", "register_as": "g-idxB", "nlist": 2,
                     "nprobe": 2, "seed": 0, "metric": "euclidean",
                     "row_id_base": {"1": 4}},
          "drop": True, "arrays": spec}, bufs)
    expect.append(("arrays", {"ok": True, "rows": 4, "model": "g-idxB"}))
    # Query each shard: a caller merges per-shard top-k; ids are GLOBAL.
    for model in ("g-idxA", "g-idxB"):
        _req({"v": V, "op": "kneighbors", "model": model, "k": 2,
              "input_col": "features", "n_cols": None},
             [("arrow", _ipc_bytes(x[:2]))])
        expect.append(("arrays", {"ok": True, "rows": 2}))
    for model in ("g-idxA", "g-idxB"):
        _req({"v": V, "op": "drop_model", "model": model})
        expect.append(("json", {"ok": True, "dropped": True}))

    return frames, expect


def multihost_transcript() -> tuple[bytes, list]:
    frames, expect = multihost_transcript_frames()
    return b"".join(frame_bytes(p) for _, p in frames), expect


if __name__ == "__main__":
    main()
