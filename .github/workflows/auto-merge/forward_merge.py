#!/usr/bin/env python3
"""Forward-merge release branch HEAD into BASE via a PR, auto-merging it.

Policy-CI parity with the reference's auto-merge workflow (SURVEY.md §2.5);
own implementation: stdlib-only. Flow: find-or-create the HEAD→BASE PR,
then try to merge it; a merge conflict leaves the PR open for a human and
exits non-zero so the failed run is visible.
"""

import json
import os
import sys
import urllib.error
import urllib.request


def api(method: str, url: str, token: str, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Authorization", f"Bearer {token}")
    req.add_header("Accept", "application/vnd.github+json")
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def main() -> int:
    token = os.environ["GITHUB_TOKEN"]
    repo = os.environ["REPO"]
    head, base = os.environ["HEAD"], os.environ["BASE"]
    root = f"https://api.github.com/repos/{repo}"

    status, prs = api(
        "GET", f"{root}/pulls?state=open&head={repo.split('/')[0]}:{head}&base={base}",
        token,
    )
    if status == 200 and prs:
        pr = prs[0]
        print(f"reusing open forward PR #{pr['number']}")
    else:
        status, pr = api(
            "POST",
            f"{root}/pulls",
            token,
            {
                "title": f"[auto-merge] {head} to {base}",
                "head": head,
                "base": base,
                "body": f"auto-forward of merged changes from {head} to {base}",
                "maintainer_can_modify": True,
            },
        )
        if status == 422 and "No commits between" in json.dumps(pr):
            print("nothing to forward (branches identical)")
            return 0
        if status == 422:  # other validation error (e.g. BASE missing) is real
            print(f"PR creation rejected (422): {pr.get('errors') or pr}")
            return 1
        if status != 201:
            print(f"PR creation failed ({status}): {pr}")
            return 1
        print(f"opened forward PR #{pr['number']}")

    status, merged = api(
        "PUT", f"{root}/pulls/{pr['number']}/merge", token, {"merge_method": "merge"}
    )
    if status == 200:
        print(f"merged forward PR #{pr['number']}")
        return 0
    print(
        f"could not auto-merge PR #{pr['number']} ({status}): {merged.get('message')} "
        "— resolve conflicts manually"
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
