"""Spark integration shell tests (the parts that don't require pyspark)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from spark_rapids_ml_tpu.spark import (
    SparkKMeans,
    SparkPCA,
    discovery_payload,
    tpu_session_conf,
    write_discovery_script,
)


def test_conf_builder():
    conf = tpu_session_conf(
        executor_tpus=4, tasks_per_tpu=8, discovery_script="/opt/tpu_disc.sh"
    )
    assert conf["spark.executor.resource.tpu.amount"] == "4"
    assert conf["spark.task.resource.tpu.amount"] == "0.125"
    assert conf["spark.worker.resource.tpu.discoveryScript"] == "/opt/tpu_disc.sh"
    assert conf["spark.sql.execution.arrow.pyspark.enabled"] == "true"


def test_discovery_payload_shape():
    payload = discovery_payload()
    assert payload["name"] == "tpu"
    assert isinstance(payload["addresses"], list)


def test_discovery_script_executable(tmp_path):
    path = write_discovery_script(str(tmp_path / "tpu_disc.sh"))
    assert os.access(path, os.X_OK)
    content = open(path).read()
    assert "spark_rapids_ml_tpu.spark.discovery" in content


def test_discovery_module_prints_json():
    # The script execs `python -m spark_rapids_ml_tpu.spark.discovery`;
    # its stdout must be exactly one JSON object (Spark parses it).
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_ml_tpu.spark.discovery"],
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    payload = json.loads(out.stdout.strip())
    assert payload["name"] == "tpu"


def test_wrapper_passthrough_non_spark(rng, mesh8):
    # Without pyspark, the Spark wrappers must still work on host data
    # (superset contract) and expose fluent setters + model attrs.
    x = rng.normal(size=(200, 8))
    pca = SparkPCA(mesh=mesh8).setK(2).setInputCol("features")
    model = pca.fit({"features": x})
    assert model.pc.shape == (8, 2)
    out = model.transform({"features": x})
    assert out["pca_features"].shape == (200, 2)

    km = SparkKMeans(mesh=mesh8).setK(3)
    kmodel = km.fit({"features": x})
    assert kmodel.clusterCenters().shape == (3, 8)


def test_wrapper_spark_df_requires_pyspark():
    # A Spark-shaped dataset (duck-typed) without pyspark installed must
    # produce the promised clear ImportError, not an opaque core failure.
    from spark_rapids_ml_tpu.spark import estimator as est

    if est._pyspark() is not None:  # pragma: no cover - image has no pyspark
        pytest.skip("pyspark installed; gate not triggerable")

    class FakeSparkDF:
        sparkSession = object()

    with pytest.raises(ImportError, match="pyspark"):
        SparkPCA().setK(2).fit(FakeSparkDF())
    with pytest.raises(ImportError, match="pyspark"):
        SparkPCA(). setK(2).fit({"features": np.ones((10, 4))}).transform(FakeSparkDF())


def test_ann_wrapper_host_data(rng):
    # The ANN wrapper must behave like the core estimator on host data.
    from spark_rapids_ml_tpu.spark import SparkApproximateNearestNeighbors

    centers = rng.normal(size=(8, 12)) * 8
    db = np.concatenate([c + rng.normal(size=(64, 12)) for c in centers])
    ann = (
        SparkApproximateNearestNeighbors()
        .setK(5)
        .setNlist(8)
        .setNprobe(8)
        .fit({"features": db})
    )
    dists, idx = ann.kneighbors(db[:4])
    assert idx.shape == (4, 5)
    # Self is its own nearest neighbor when probing everything.
    np.testing.assert_array_equal(idx[:, 0], np.arange(4))
