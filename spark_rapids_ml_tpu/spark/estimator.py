"""PySpark DataFrame adapters for the core estimators.

The reference's user contract: change one import, keep the Spark ML code
(`new com.nvidia.spark.ml.feature.PCA().setInputCol(...).fit(df)`,
reference PCA.scala:27-37, README.md:27-37 — with the features column as
ArrayType rather than Vector). These wrappers reproduce that contract for
PySpark: ``SparkPCA().setInputCol("features").setK(3).fit(spark_df)``.

Data path: the DataFrame's relevant columns are exchanged as Arrow
(``spark.sql.execution.arrow.*``), flattened by the columnar bridge, and
fed to the sharded TPU fit. ``transform`` runs the model on Arrow batches
via ``mapInArrow`` when available (keeps the pipeline distributed and
lazy, one batch per executor task — the analogue of the reference's
columnar UDF, RapidsPCA.scala:128-161), falling back to a collect-based
path for old PySpark.

pyspark is optional: import of this module never requires it; calling
``fit``/``transform`` with a Spark DataFrame does.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def _pyspark():
    try:
        import pyspark  # noqa: F401
        from pyspark.sql import DataFrame

        return DataFrame
    except ImportError:
        return None


def _is_spark_df(dataset: Any) -> bool:
    df_cls = _pyspark()
    return df_cls is not None and isinstance(dataset, df_cls)


def _check_not_orphan_spark_df(dataset: Any) -> None:
    """Raise the promised clear error for Spark-shaped datasets when
    pyspark is missing (instead of an opaque core-estimator failure)."""
    if _pyspark() is None and (
        hasattr(dataset, "sparkSession")
        or type(dataset).__module__.split(".")[0] == "pyspark"
    ):
        raise ImportError(
            "pyspark is not installed; Spark* estimators need it for "
            "DataFrame inputs. Use the core estimators "
            "(spark_rapids_ml_tpu.PCA etc.) with arrow/pandas/numpy data."
        )


def _df_to_arrow(df, columns):
    """Spark DataFrame -> pyarrow.Table restricted to ``columns``."""
    import pyarrow as pa

    selected = df.select(*columns)
    # Spark 4 / recent 3.x: native Arrow collect.
    if hasattr(selected, "toArrow"):
        return selected.toArrow()
    pdf = selected.toPandas()
    return pa.Table.from_pandas(pdf, preserve_index=False)


class _SparkAdapter:
    """Wraps a core estimator class with Spark DataFrame in/out.

    Non-Spark datasets pass straight through to the core estimator, so the
    Spark wrapper is a superset of the core API.
    """

    _core_cls = None  # override
    _model_attr = "model"

    def __init__(self, **kwargs):
        self._core = type(self)._core_cls(**kwargs)

    def __getattr__(self, name):
        # Fluent setters return self (the wrapper), others pass through.
        attr = getattr(self._core, name)
        if callable(attr) and name.startswith("set"):
            def fluent(*a, **kw):
                attr(*a, **kw)
                return self

            return fluent
        return attr

    def fit(self, dataset):
        if _is_spark_df(dataset):
            cols = self._input_columns()
            table = _df_to_arrow(dataset, cols)
            core_model = self._core.fit(table)
        else:
            _check_not_orphan_spark_df(dataset)
            core_model = self._core.fit(dataset)
        return _SparkModelAdapter(core_model)

    def _input_columns(self):
        cols = []
        for name in ("inputCol", "featuresCol"):
            if self._core.hasParam(name) and self._core.isDefined(
                self._core.getParam(name)
            ):
                cols.append(self._core.getOrDefault(name))
        for name in ("labelCol",):
            if self._core.hasParam(name) and self._core.isDefined(
                self._core.getParam(name)
            ):
                cols.append(self._core.getOrDefault(name))
        return cols


class _SparkModelAdapter:
    """Wraps a fitted core Model with Spark DataFrame transform."""

    def __init__(self, core_model):
        self._core = core_model

    def __getattr__(self, name):
        return getattr(self._core, name)

    def transform(self, dataset):
        if not _is_spark_df(dataset):
            _check_not_orphan_spark_df(dataset)
            return self._core.transform(dataset)
        import pyarrow as pa

        core = self._core
        out_field = None
        for name in ("outputCol", "predictionCol"):
            if core.hasParam(name) and core.isDefined(core.getParam(name)):
                out_field = core.getOrDefault(name)
                break

        if hasattr(dataset, "mapInArrow"):
            # Distributed, lazy: one Arrow batch per executor partition —
            # the columnar-UDF analogue (RapidsPCA.scala:128-161).

            def transform_batches(batches):
                for batch in batches:
                    table = pa.Table.from_batches([batch])
                    out = core.transform(table)
                    yield from out.to_batches()

            sample = _df_to_arrow(dataset.limit(1), dataset.columns)
            out_sample = core.transform(sample)
            from pyspark.sql.pandas.types import from_arrow_schema

            schema = from_arrow_schema(out_sample.schema)
            return dataset.mapInArrow(transform_batches, schema)

        # Fallback: collect → transform → recreate (local mode only).
        table = _df_to_arrow(dataset, dataset.columns)
        out = core.transform(table)
        spark = dataset.sparkSession
        return spark.createDataFrame(out.to_pandas())


def _make_wrapper(name, core_cls, doc):
    cls = type(name, (_SparkAdapter,), {"_core_cls": core_cls, "__doc__": doc})
    return cls


from spark_rapids_ml_tpu.models.kmeans import KMeans as _KMeans
from spark_rapids_ml_tpu.models.knn import (
    ApproximateNearestNeighbors as _ApproximateNearestNeighbors,
    NearestNeighbors as _NearestNeighbors,
)
from spark_rapids_ml_tpu.models.linear_regression import (
    LinearRegression as _LinearRegression,
)
from spark_rapids_ml_tpu.models.logistic_regression import (
    LogisticRegression as _LogisticRegression,
)
from spark_rapids_ml_tpu.models.pca import PCA as _PCA
from spark_rapids_ml_tpu.models.scaler import StandardScaler as _StandardScaler

SparkPCA = _make_wrapper(
    "SparkPCA", _PCA, "PCA over PySpark DataFrames (ArrayType features column)."
)
SparkKMeans = _make_wrapper(
    "SparkKMeans", _KMeans, "KMeans over PySpark DataFrames."
)
SparkLinearRegression = _make_wrapper(
    "SparkLinearRegression", _LinearRegression, "LinearRegression over PySpark DataFrames."
)
SparkLogisticRegression = _make_wrapper(
    "SparkLogisticRegression", _LogisticRegression, "LogisticRegression over PySpark DataFrames."
)
SparkNearestNeighbors = _make_wrapper(
    "SparkNearestNeighbors", _NearestNeighbors, "Exact KNN over PySpark DataFrames."
)
SparkApproximateNearestNeighbors = _make_wrapper(
    "SparkApproximateNearestNeighbors",
    _ApproximateNearestNeighbors,
    "IVF-Flat approximate KNN over PySpark DataFrames.",
)
SparkStandardScaler = _make_wrapper(
    "SparkStandardScaler", _StandardScaler,
    "StandardScaler over PySpark DataFrames (ArrayType features column).",
)
