"""Pallas kernel parity tests (interpret mode — no TPU needed)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu.ops.pallas_kernels import assign_min_dist_pallas, gram_pallas


def test_gram_parity(rng):
    n, d = 1024, 256
    x = rng.normal(size=(n, d)).astype(np.float32)
    mask = np.ones((n,), dtype=np.float32)
    mask[-37:] = 0.0  # padding rows
    out = np.asarray(gram_pallas(x, mask, block_n=256, block_d=128, interpret=True))
    xm = x * mask[:, None]
    ref = xm.T @ xm
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-2)


def test_gram_block_validation(rng):
    x = rng.normal(size=(100, 64)).astype(np.float32)
    with pytest.raises(ValueError, match="not divisible"):
        gram_pallas(x, np.ones(100, np.float32), block_n=64, block_d=64, interpret=True)


def test_gram_colsum_parity(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import gram_colsum_pallas

    n, d = 1024, 256
    x = rng.normal(size=(n, d)).astype(np.float32)
    for n_valid in (n, 700):  # full batch + boundary-straddling partial block
        g, cs, cnt = gram_colsum_pallas(x, n_valid, block_n=256, interpret=True)
        xv = x[:n_valid]
        np.testing.assert_allclose(np.asarray(g), xv.T @ xv, rtol=1e-5, atol=1e-2)
        np.testing.assert_allclose(
            np.asarray(cs), xv.sum(axis=0), rtol=1e-5, atol=1e-2
        )
        assert float(cnt) == float(n_valid)


@pytest.mark.kernels
def test_gram_colsum_seeded_state(rng):
    """The one-dispatch streaming update: accumulators SEEDED from the
    donated (gram, colsum, count) state must equal state + batch stats —
    the fusion that removes the per-batch XLA state add."""
    from spark_rapids_ml_tpu.ops.pallas_kernels import gram_colsum_pallas

    n, d = 512, 128
    x = rng.normal(size=(n, d)).astype(np.float32)
    g0 = rng.normal(size=(d, d)).astype(np.float32)
    cs0 = rng.normal(size=(d,)).astype(np.float32)
    state = (jnp.asarray(g0), jnp.asarray(cs0), jnp.asarray(37.0, jnp.float32))
    g, cs, cnt = gram_colsum_pallas(
        x, 300, block_n=256, state=state, interpret=True
    )
    xv = x[:300]
    np.testing.assert_allclose(np.asarray(g), g0 + xv.T @ xv, rtol=1e-5, atol=1e-2)
    np.testing.assert_allclose(np.asarray(cs), cs0 + xv.sum(0), rtol=1e-5, atol=1e-2)
    assert float(cnt) == 37.0 + 300


@pytest.mark.kernels
def test_gram_colsum_bf16_vs_f32_tolerance(rng):
    """bf16-input/f32-accumulate golden for the fused streaming kernel:
    the intended TPU speed mode must stay within GEMM-rounding tolerance
    of the f32 oracle on the SAME (bf16-rounded) data."""
    from spark_rapids_ml_tpu.ops.pallas_kernels import gram_colsum_pallas

    n, d = 512, 128
    x16 = jnp.asarray(rng.normal(size=(n, d)), jnp.bfloat16)
    x = np.asarray(x16, np.float32)  # the rounded values ARE the data
    g, cs, cnt = gram_colsum_pallas(x16, 300, block_n=256, interpret=True)
    xv = x[:300]
    np.testing.assert_allclose(np.asarray(g), xv.T @ xv, rtol=2e-2, atol=5e-1)
    np.testing.assert_allclose(np.asarray(cs), xv.sum(0), rtol=2e-2, atol=2e-1)
    assert float(cnt) == 300.0
    # PCA-components golden: the top-k eigenvectors of the bf16-kernel
    # centered Gram must span the f64 oracle's subspace (sign-invariant
    # |cos| per column — the PCASuite tolerance philosophy).
    k = 4
    n_v, mean = 300, xv.mean(0)
    gc = np.asarray(g, np.float64) - n_v * np.outer(mean, mean)
    ref = np.cov(xv.T.astype(np.float64))
    w1, v1 = np.linalg.eigh(gc / (n_v - 1))
    w2, v2 = np.linalg.eigh(ref)
    dots = np.abs(np.sum(v1[:, ::-1][:, :k] * v2[:, ::-1][:, :k], axis=0))
    assert np.all(dots > 1 - 5e-2), dots


def test_gram_colsum_block_validation(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import gram_colsum_pallas

    x = rng.normal(size=(100, 128)).astype(np.float32)
    with pytest.raises(ValueError, match="not divisible"):
        gram_colsum_pallas(x, 100, block_n=64, interpret=True)


def test_streaming_update_rows_matches_mask_path(rng):
    """streaming_update_rows (scalar n_valid) == streaming_update (mask array)
    on a multi-device CPU mesh, including a partial boundary batch."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops import gram as gram_ops
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(model=1)
    n_dev = mesh.shape["data"]
    m, d = 16 * n_dev, 32
    x = rng.normal(size=(m, d)).astype(np.float32)
    n_valid = m - 5  # straddles the last shard

    upd_rows = gram_ops.streaming_update_rows(mesh)
    upd_mask = gram_ops.streaming_update(mesh)
    mask = (np.arange(m) < n_valid).astype(np.float32)

    s_rows = gram_ops.init_stats(d)
    s_mask = gram_ops.init_stats(d)
    for _ in range(3):
        s_rows = upd_rows(s_rows, jnp.asarray(x), n_valid)
        s_mask = upd_mask(s_mask, jnp.asarray(x), jnp.asarray(mask))
    for a, b in zip(s_rows, s_mask):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-4)


@pytest.mark.kernels
def test_dist_topk_parity(rng):
    # Exact fused distance+top-k vs a lexsort oracle: true clipped
    # distances, ascending order, (distance, id) tie-breaking on crafted
    # duplicate rows, masked rows -> (+inf, -1), non-multiple-of-8 shapes.
    from spark_rapids_ml_tpu.ops.pallas_kernels import dist_topk_pallas

    q, m, d, k = 65, 300, 24, 7
    qs = rng.normal(size=(q, d)).astype(np.float32)
    db = rng.normal(size=(m, d)).astype(np.float32)
    db[50] = db[201]  # duplicate rows straddling blocks: exact tie
    mask = np.ones(m, np.float32)
    mask[-17:] = 0.0
    ids = np.arange(m, dtype=np.int32)
    dk, ik = dist_topk_pallas(
        jnp.asarray(qs), jnp.asarray(db), ids, mask, k,
        block_m=64, block_q=32, interpret=True,
    )
    d2 = np.maximum(
        (qs**2).sum(1)[:, None] + (db**2).sum(1)[None, :] - 2 * qs @ db.T, 0
    )
    d2[:, mask == 0] = np.inf
    order = np.lexsort((np.broadcast_to(ids, d2.shape), d2), axis=1)[:, :k]
    np.testing.assert_array_equal(
        np.asarray(ik), np.take_along_axis(np.broadcast_to(ids, d2.shape), order, 1)
    )
    np.testing.assert_allclose(
        np.asarray(dk), np.take_along_axis(d2, order, 1), rtol=1e-4, atol=1e-3
    )
    assert np.all(np.diff(np.asarray(dk), axis=1) >= 0)


@pytest.mark.kernels
def test_dist_topk_missing_slots(rng):
    # Fewer valid rows than k: the tail must carry the documented
    # (+inf, -1) missing contract, exactly like the XLA masked path.
    from spark_rapids_ml_tpu.ops.pallas_kernels import dist_topk_pallas

    qs = rng.normal(size=(8, 16)).astype(np.float32)
    db = rng.normal(size=(10, 16)).astype(np.float32)
    mask = np.zeros(10, np.float32)
    mask[:4] = 1.0
    dk, ik = dist_topk_pallas(
        jnp.asarray(qs), jnp.asarray(db), np.arange(10, dtype=np.int32),
        mask, 7, block_m=8, block_q=8, interpret=True,
    )
    assert np.all(np.asarray(ik)[:, 4:] == -1)
    assert np.all(np.isinf(np.asarray(dk)[:, 4:]))
    assert np.all(np.asarray(ik)[:, :4] >= 0)


@pytest.mark.kernels
@pytest.mark.parametrize("q", [1, 63, 64, 65])
def test_dist_topk_bucket_boundary_dtype_ladder(rng, q):
    """kneighbors-index goldens at the serve bucket ladder boundaries
    (b=64: 1, b-1, b, b+1 — the PR 5 scheduler-test shape grid), per rung
    of the compute_dtype ladder: at EACH dtype the fused kernel's indices
    must equal the unfused sq_euclidean→top_k two-step's (same rounding,
    same (distance, id) tie order), and bf16 distances must stay within
    GEMM-rounding tolerance of the f32 ones. bf16-vs-f32 INDEX swaps at
    near-ties are the documented precision trade, not a kernel bug."""
    from spark_rapids_ml_tpu.ops.distances import sq_euclidean
    from spark_rapids_ml_tpu.ops.pallas_kernels import dist_topk_pallas

    m, d, k = 96, 32, 5
    qs = rng.normal(size=(q, d)).astype(np.float32)
    db = rng.normal(size=(m, d)).astype(np.float32)
    ids = np.arange(m, dtype=np.int32)
    mask = np.ones(m, np.float32)
    by_dtype = {}
    for dt in (jnp.float32, jnp.bfloat16):
        qd, dbd = jnp.asarray(qs, dt), jnp.asarray(db, dt)
        fd, fi = dist_topk_pallas(
            qd, dbd, ids, mask, k, block_m=32, block_q=32, interpret=True
        )
        d2 = sq_euclidean(qd, dbd, accum_dtype=jnp.float32)
        neg, pos = jax.lax.top_k(-d2, k)
        np.testing.assert_array_equal(np.asarray(fi), np.asarray(pos))
        np.testing.assert_allclose(
            np.asarray(fd), np.maximum(-np.asarray(neg), 0), rtol=1e-5, atol=1e-4
        )
        by_dtype[np.dtype(dt).name] = np.asarray(fd)
    np.testing.assert_allclose(
        by_dtype["bfloat16"], by_dtype["float32"], rtol=5e-2, atol=0.5
    )


@pytest.mark.kernels
def test_streaming_update_rows_seeded_kernel_matches_mask_path(rng):
    """The donated one-dispatch streaming update (state seeded into the
    kernel, single data device) must match the XLA mask path over several
    accumulating batches — and the spy proves the seeded branch ran."""
    import jax

    from spark_rapids_ml_tpu.ops import gram as gram_ops
    from spark_rapids_ml_tpu.ops import pallas_kernels as pk
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(data=1, model=1, devices=jax.devices()[:1])
    m, d = 512, 128
    x = rng.normal(size=(m, d)).astype(np.float32)
    n_valid = m - 100
    ran = {"seeded": False}
    orig_ok = gram_ops._pallas_rows_applicable
    orig_kernel = pk.gram_colsum_pallas

    def spy(xx, nv, block_n=pk.GRAM_COLSUM_BLOCK_N, state=None,
            interpret=False):
        ran["seeded"] |= state is not None
        return orig_kernel(xx, nv, block_n=block_n, state=state,
                           interpret=True)

    gram_ops._pallas_rows_applicable = lambda shape, cd, use_pallas=None: True
    pk.gram_colsum_pallas = spy
    try:
        gram_ops._streaming_update_rows_cached.cache_clear()
        upd = gram_ops._streaming_update_rows_cached(
            mesh, "float32", "float32", True
        )
        s = gram_ops.init_stats(d, accum_dtype="float32")
        for _ in range(3):
            s = upd(s, jnp.asarray(x), n_valid)
        s = [np.asarray(v) for v in s]
    finally:
        gram_ops._pallas_rows_applicable = orig_ok
        pk.gram_colsum_pallas = orig_kernel
        gram_ops._streaming_update_rows_cached.cache_clear()
    assert ran["seeded"], "the seeded one-dispatch branch never ran"
    xv = x[:n_valid]
    np.testing.assert_allclose(s[0], 3.0 * n_valid)
    np.testing.assert_allclose(s[1], 3 * xv.sum(0), rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(s[2], 3 * (xv.T @ xv), rtol=1e-5, atol=1e-2)


def test_assign_parity(rng):
    m, d, k = 512, 32, 128
    x = rng.normal(size=(m, d)).astype(np.float32)
    centers = rng.normal(size=(k, d)).astype(np.float32)
    idx, part_d = assign_min_dist_pallas(
        x, centers, block_m=128, block_k=64, interpret=True
    )
    d2 = (
        np.sum(x**2, 1)[:, None]
        - 2 * x @ centers.T
        + np.sum(centers**2, 1)[None, :]
    )
    ref_idx = np.argmin(d2, axis=1)
    np.testing.assert_array_equal(np.asarray(idx), ref_idx)
    # partial distance + ||x||^2 == true min distance
    full = np.asarray(part_d) + np.sum(x**2, 1)
    np.testing.assert_allclose(full, d2.min(axis=1), rtol=1e-4, atol=1e-2)


def test_lloyd_step_parity(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import lloyd_step_pallas

    m, d, k, k_pad = 1024, 128, 60, 128
    # well-separated clusters: argmin margins >> f32 GEMM error
    centers = (rng.normal(size=(k, d)) * 10).astype(np.float32)
    lab = rng.integers(0, k, size=m)
    x = (centers[lab] + 0.01 * rng.normal(size=(m, d))).astype(np.float32)
    cpad = np.zeros((k_pad, d), np.float32)
    cpad[:k] = centers
    for n_valid in (m, 700):  # full + boundary-straddling partial block
        sums, counts = lloyd_step_pallas(
            x, cpad, n_valid, k=k, block_n=256, interpret=True
        )
        ref_sums = np.zeros((k, d))
        ref_counts = np.zeros(k)
        np.add.at(ref_sums, lab[:n_valid], x[:n_valid])
        np.add.at(ref_counts, lab[:n_valid], 1)
        np.testing.assert_allclose(np.asarray(counts)[:k], ref_counts)
        np.testing.assert_allclose(np.asarray(sums)[:k], ref_sums, rtol=1e-4, atol=1e-2)
        # Dead-lane contract: invalid rows of processed blocks are routed
        # to lane k (cheaper than a (bn, k_pad) row mask); that lane's
        # sums/counts carry their garbage and are DISCARDED by callers
        # (models/kmeans slices [:k]). Other padded lanes never win.
        processed = -(-min(n_valid, m) // 256) * 256
        assert float(np.asarray(counts)[k]) == float(processed - n_valid)
        assert float(np.asarray(counts)[k + 1:].sum()) == 0.0
        np.testing.assert_allclose(np.asarray(sums)[k + 1:], 0.0, atol=1e-6)


def test_lloyd_step_block_validation(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import lloyd_step_pallas

    x = rng.normal(size=(100, 128)).astype(np.float32)
    c = rng.normal(size=(128, 128)).astype(np.float32)
    with pytest.raises(ValueError, match="not divisible"):
        lloyd_step_pallas(x, c, 100, k=100, block_n=64, interpret=True)


def test_newton_stats_parity(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import newton_stats_pallas

    n, d = 1024, 256
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    mask = np.ones((n,), np.float32)
    mask[-100:] = 0.0  # arbitrary masked rows, not a block boundary
    w = (rng.normal(size=(d,)) / np.sqrt(d)).astype(np.float32)
    b = np.float32(0.3)
    gw, gb, hww, hwb, hbb = newton_stats_pallas(
        x, y, mask, w, b, block_n=256, interpret=True
    )
    z = x @ w + b
    p = 1.0 / (1.0 + np.exp(-z))
    r = (p - y) * mask
    wgt = np.maximum(p * (1.0 - p), 1e-10) * mask
    np.testing.assert_allclose(np.asarray(gw), x.T @ r, rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(float(gb), r.sum(), rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(
        np.asarray(hww), (x * wgt[:, None]).T @ x, rtol=1e-4, atol=1e-2
    )
    np.testing.assert_allclose(np.asarray(hwb), x.T @ wgt, rtol=1e-4, atol=1e-2)
    np.testing.assert_allclose(float(hbb), wgt.sum(), rtol=1e-4, atol=1e-2)


def test_newton_stats_parity_bf16(rng):
    """The production mode: the fused fit path only engages the kernel at
    compute_dtype=bfloat16 (models/logistic_regression._pallas_newton_applicable),
    so parity must hold for bf16-stored x with its own rounding."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.ops.pallas_kernels import newton_stats_pallas

    n, d = 512, 256
    x16 = jnp.asarray(rng.normal(size=(n, d)), jnp.bfloat16)
    x = np.asarray(x16, np.float32)  # the rounded values ARE the data
    y = (rng.random(n) > 0.5).astype(np.float32)
    mask = np.ones((n,), np.float32)
    mask[-60:] = 0.0
    w = (rng.normal(size=(d,)) / np.sqrt(d)).astype(np.float32)
    b = np.float32(-0.2)
    gw, gb, hww, hwb, hbb = newton_stats_pallas(
        x16, y, mask, w, b, block_n=256, interpret=True
    )
    # Oracle mirrors the kernel's bf16 rounding points: w and the
    # residual/weight operands round to bf16 before their GEMMs.
    w16 = np.asarray(jnp.asarray(w, jnp.bfloat16), np.float32)
    z = x @ w16 + b
    p = 1.0 / (1.0 + np.exp(-z))
    r16 = np.asarray(jnp.asarray((p - y) * mask, jnp.bfloat16), np.float32)
    wgt = np.maximum(p * (1.0 - p), 1e-10) * mask
    wgt16 = np.asarray(jnp.asarray(wgt, jnp.bfloat16), np.float32)
    np.testing.assert_allclose(np.asarray(gw), x.T @ r16, rtol=2e-2, atol=2e-1)
    np.testing.assert_allclose(float(gb), ((p - y) * mask).sum(), rtol=1e-3, atol=1e-2)
    np.testing.assert_allclose(
        np.asarray(hww), (x * wgt16[:, None]).T @ x, rtol=2e-2, atol=5e-1
    )
    np.testing.assert_allclose(np.asarray(hwb), x.T @ wgt16, rtol=2e-2, atol=2e-1)
    np.testing.assert_allclose(float(hbb), wgt.sum(), rtol=1e-3, atol=1e-2)


def test_newton_stats_block_validation(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import newton_stats_pallas

    x = rng.normal(size=(100, 128)).astype(np.float32)
    with pytest.raises(ValueError, match="not divisible"):
        newton_stats_pallas(
            x, np.ones(100, np.float32), np.ones(100, np.float32),
            np.zeros(128, np.float32), 0.0, block_n=64, interpret=True,
        )


def test_ivf_scan_select_parity(rng):
    # Exact per-slot top-k vs a sort-based oracle, including: ties
    # (first-occurrence/lowest-position contract), padded-row 1e30
    # sentinels, maxlen and blk_k not multiples of 8, and adversarial
    # ascending/descending score orderings.
    from spark_rapids_ml_tpu.ops.pallas_kernels import ivf_scan_select_pallas

    nlist, C, d, maxlen, blk_k = 6, 24, 32, 19, 7
    qv = rng.normal(size=(nlist, C, d)).astype(np.float32)
    rows = rng.normal(size=(nlist, maxlen, d)).astype(np.float32)
    r2 = (rows**2).sum(-1).astype(np.float32)
    r2[2, 10:] = 1e30
    rows[2, 10:] = 0  # list with fewer valid rows than... still >= blk_k
    r2[4, 3:] = 1e30
    rows[4, 3:] = 0  # FEWER valid rows than blk_k: sentinels must emit
    rows[3, 5] = rows[3, 6]
    r2[3, 5] = r2[3, 6]  # exact tie -> lowest position wins
    # Adversarial orderings: make list 5's scores monotone per slot by
    # zeroing qv (scores = r2 alone) with ascending then descending r2.
    qv[5] = 0
    r2[5] = np.linspace(1.0, 2.0, maxlen, dtype=np.float32)

    bd, bp = ivf_scan_select_pallas(
        jnp.asarray(qv), jnp.asarray(rows), jnp.asarray(r2), blk_k,
        interpret=True,
    )
    scores = r2[:, None, :] - 2 * np.einsum("lcd,lmd->lcm", qv, rows)
    ref_p = np.argsort(scores, axis=2, kind="stable")[:, :, :blk_k]
    ref_d = np.take_along_axis(scores, ref_p, axis=2)
    np.testing.assert_allclose(
        np.transpose(np.asarray(bd), (0, 2, 1)), ref_d, rtol=1e-5, atol=1e-4
    )
    np.testing.assert_array_equal(np.transpose(np.asarray(bp), (0, 2, 1)), ref_p)
    # Ascending per-slot output contract.
    assert np.all(np.diff(np.asarray(bd), axis=1) >= 0)


def test_ivf_scan_select_blk_k_validation(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import ivf_scan_select_pallas

    qv = np.zeros((2, 8, 16), np.float32)
    rows = np.zeros((2, 5, 16), np.float32)
    r2 = np.zeros((2, 5), np.float32)
    with pytest.raises(ValueError, match="blk_k"):
        ivf_scan_select_pallas(qv, rows, r2, 6, interpret=True)


def test_probe_select_parity(rng):
    # Exact per-query top-nprobe centroid probe vs a sort oracle: true
    # distances (the per-query norm term is included), ascending order,
    # first-occurrence ties, non-multiple-of-8 nlist.
    from spark_rapids_ml_tpu.ops.pallas_kernels import probe_select_pallas

    nlist, d, q, nprobe = 37, 24, 128, 5
    cent = rng.normal(size=(nlist, d)).astype(np.float32)
    qs = rng.normal(size=(q, d)).astype(np.float32)
    cent[7] = cent[11]  # duplicate centroid -> tie resolves to lower id
    ids, d2 = probe_select_pallas(
        jnp.asarray(cent), jnp.asarray(qs), nprobe, block_q=64, interpret=True
    )
    ref = ((qs[:, None, :] - cent[None]) ** 2).sum(-1)
    ref_ids = np.argsort(ref, axis=1, kind="stable")[:, :nprobe]
    np.testing.assert_array_equal(np.asarray(ids), ref_ids)
    np.testing.assert_allclose(
        np.asarray(d2), np.take_along_axis(ref, ref_ids, axis=1),
        rtol=1e-3, atol=1e-3,
    )
    assert np.all(np.diff(np.asarray(d2), axis=1) >= 0)


def test_probe_select_block_validation(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import probe_select_pallas

    cent = np.zeros((8, 16), np.float32)
    with pytest.raises(ValueError, match="divisible"):
        probe_select_pallas(
            cent, np.zeros((600, 16), np.float32), 2, block_q=512,
            interpret=True,
        )


def test_linreg_stats_parity(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import linreg_stats_pallas

    n, d = 1024, 256
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    mask = np.ones((n,), np.float32)
    mask[-100:] = 0.0
    xtx, xty, sx, sy, syy, cnt = linreg_stats_pallas(
        x, y, mask, block_n=256, interpret=True
    )
    xm = x * mask[:, None]
    ym = y * mask
    np.testing.assert_allclose(np.asarray(xtx), xm.T @ xm, rtol=1e-5, atol=1e-2)
    np.testing.assert_allclose(np.asarray(xty), xm.T @ ym, rtol=1e-5, atol=1e-2)
    np.testing.assert_allclose(np.asarray(sx), xm.sum(0), rtol=1e-5, atol=1e-2)
    np.testing.assert_allclose(float(sy), ym.sum(), rtol=1e-5)
    np.testing.assert_allclose(float(syy), (ym**2).sum(), rtol=1e-5)
    assert float(cnt) == float(mask.sum())


def test_linreg_stats_fn_pallas_matches_xla(rng):
    # The sharded stats fn with the fused kernel forced on (interpret on
    # CPU) must match the XLA path to bf16-GEMM tolerance.
    from spark_rapids_ml_tpu.models.linear_regression import _normal_eq_stats_fn
    from spark_rapids_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(data=4, model=1)
    n, d = 2048, 128
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.normal(size=(n,)).astype(np.float32)
    mask = np.ones((n,), np.float32)
    a = _normal_eq_stats_fn(mesh, "float32", "float32", False)(x, y, mask)
    b = _normal_eq_stats_fn(mesh, "float32", "float32", True)(x, y, mask)
    for va, vb in zip(a, b):
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(vb), rtol=1e-4, atol=1e-2
        )


def test_softmax_curvature_parity(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import softmax_curvature_pallas

    n, d, C = 1024, 128, 5  # C not a block_c multiple: exercises padding
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = rng.normal(size=(n, C))
    p = (np.exp(logits) / np.exp(logits).sum(1, keepdims=True)).astype(
        np.float32
    )
    mask = np.ones((n,), np.float32)
    mask[-200:] = 0.0
    pm = p * mask[:, None]
    hw, hwb = softmax_curvature_pallas(
        x, pm, block_n=256, block_c=2, interpret=True
    )
    assert hw.shape == (C, d, d) and hwb.shape == (C, d)
    for c in range(C):
        xw = x * pm[:, c : c + 1]
        np.testing.assert_allclose(
            np.asarray(hw[c]), xw.T @ x, rtol=1e-5, atol=1e-2
        )
        np.testing.assert_allclose(
            np.asarray(hwb[c]), xw.sum(0), rtol=1e-5, atol=1e-2
        )


def test_softmax_curvature_block_validation(rng):
    from spark_rapids_ml_tpu.ops.pallas_kernels import softmax_curvature_pallas

    with pytest.raises(ValueError, match="divisible"):
        softmax_curvature_pallas(
            np.zeros((600, 128), np.float32), np.zeros((600, 3), np.float32),
            block_n=512, interpret=True,
        )


def test_softmax_stats_fn_kernel_matches_xla(rng, mesh8):
    """The streamed multinomial stats with the shared-tile kernel forced
    on (interpret, CPU) must match the XLA per-class loop."""
    import jax.numpy as jnp

    from spark_rapids_ml_tpu import config
    from spark_rapids_ml_tpu.models.logistic_regression import (
        _stream_softmax_stats_cached,
        stream_softmax_zero_state,
    )
    from spark_rapids_ml_tpu.ops import pallas_kernels as pk
    from spark_rapids_ml_tpu.ops import gram as gram_ops

    n, d, C = 8192, 128, 4  # 8-way shard = 1024 rows: block-divisible
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, C, size=n).astype(np.float32)
    mask = np.ones((n,), np.float32)
    W = jnp.asarray(rng.normal(size=(d, C)) * 0.1, jnp.float32)
    b = jnp.zeros((C,), jnp.float32)
    with config.option("accum_dtype", "float32"), \
            config.option("compute_dtype", "float32"):
        ref_fn = _stream_softmax_stats_cached(
            mesh8, C, "float32", "float32", False
        )
        ref = ref_fn(
            stream_softmax_zero_state(d, C, jnp.float32), W, b,
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask),
        )
        # Force the kernel branch: pretend the backend gate passes and run
        # the kernel in interpret mode (CPU); record that it actually ran.
        ran = {"kernel": False}
        orig_ok = gram_ops._pallas_backend_ok
        orig_kernel = pk.softmax_curvature_pallas

        def spy_kernel(xx, pp, block_n=512, block_c=8, interpret=False):
            ran["kernel"] = True
            return orig_kernel(xx, pp, block_n=block_n, block_c=block_c,
                               interpret=True)

        gram_ops._pallas_backend_ok = lambda use=None: True
        pk.softmax_curvature_pallas = spy_kernel
        try:
            _stream_softmax_stats_cached.cache_clear()
            kern_fn = _stream_softmax_stats_cached(
                mesh8, C, "float32", "float32", True
            )
            got = kern_fn(
                stream_softmax_zero_state(d, C, jnp.float32), W, b,
                jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask),
            )
        finally:
            gram_ops._pallas_backend_ok = orig_ok
            pk.softmax_curvature_pallas = orig_kernel
            _stream_softmax_stats_cached.cache_clear()
    assert ran["kernel"], "gate did not select the shared-tile kernel"
    for va, vb in zip(ref, got):
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(vb), rtol=1e-4, atol=1e-2
        )
