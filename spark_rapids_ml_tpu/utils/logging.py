"""Logging setup — the Spark ``Logging`` trait equivalent.

(Reference: RapidsRowMatrix extends Logging, RapidsRowMatrix.scala:24,32,
and debug breadcrumbs marking which transform path ran,
RapidsPCA.scala:131,158.)

Library discipline: configuration attaches ONE handler to the
``spark_rapids_ml_tpu`` package logger — never ``logging.basicConfig``,
which would hijack the host application's root logger (a Spark driver or
serving process embedding this package must keep its own logging intact).
Every logger this package creates lives under the package namespace, so
``propagate=False`` on the package logger is the whole isolation story:
our records hit our handler exactly once and never double-print through
a root handler the application configured. ``SRML_TPU_LOG_LEVEL`` sets
the package level (default WARNING). Setup is idempotent and
thread-safe; host applications that want full control can remove or
replace the handler on ``logging.getLogger("spark_rapids_ml_tpu")``.
"""

from __future__ import annotations

import logging
import os
import threading

_PKG = "spark_rapids_ml_tpu"
_lock = threading.Lock()
_configured = False


def _ensure_package_handler() -> None:
    global _configured
    if _configured:
        return
    with _lock:
        if _configured:
            return
        pkg = logging.getLogger(_PKG)
        level = os.environ.get("SRML_TPU_LOG_LEVEL", "WARNING").upper()
        pkg.setLevel(getattr(logging, level, logging.WARNING))
        if not any(
            getattr(h, "_srml_handler", False) for h in pkg.handlers
        ):
            handler = logging.StreamHandler()
            handler.setFormatter(
                logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
            )
            handler._srml_handler = True  # idempotency marker
            pkg.addHandler(handler)
        pkg.propagate = False
        _configured = True


def get_logger(name: str) -> logging.Logger:
    """A logger under the package namespace (short names like
    ``"serve.daemon"`` are prefixed), with the package handler attached
    once per process."""
    _ensure_package_handler()
    if name != _PKG and not name.startswith(_PKG + "."):
        name = f"{_PKG}.{name}"
    return logging.getLogger(name)
