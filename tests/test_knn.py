"""Nearest-neighbor tests: exact vs sklearn brute force, IVF recall."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_ml_tpu import (
    ApproximateNearestNeighbors,
    NearestNeighbors,
    NearestNeighborsModel,
)
from spark_rapids_ml_tpu.parallel.mesh import make_mesh


@pytest.fixture
def db_and_queries(rng):
    db = rng.normal(size=(500, 16))
    queries = rng.normal(size=(20, 16))
    return db, queries


def _sklearn_knn(db, queries, k):
    from oracles import knn_brute

    return knn_brute(db, queries, k)


def test_exact_matches_sklearn(db_and_queries, mesh8):
    db, queries = db_and_queries
    k = 7
    model = NearestNeighbors(mesh=mesh8).setK(k).fit({"features": db})
    dists, idx = model.kneighbors(queries)
    ref_d, ref_i = _sklearn_knn(db, queries, k)
    np.testing.assert_array_equal(idx, ref_i)
    np.testing.assert_allclose(dists, ref_d, atol=1e-8)


def test_exact_shard_invariance(db_and_queries):
    db, queries = db_and_queries
    k = 5
    outs = []
    for n in (1, 8):
        model = NearestNeighbors(mesh=make_mesh(data=n, model=1)).setK(k).fit(
            {"features": db}
        )
        outs.append(model.kneighbors(queries))
    np.testing.assert_array_equal(outs[0][1], outs[1][1])
    np.testing.assert_allclose(outs[0][0], outs[1][0], atol=1e-8)


def test_exact_uneven_db_rows(mesh8, rng):
    # 101 rows: padding rows must never appear as neighbors.
    db = rng.normal(size=(101, 4))
    queries = db[:10]
    model = NearestNeighbors(mesh=mesh8).setK(3).fit({"features": db})
    dists, idx = model.kneighbors(queries)
    assert np.all(idx < 101)
    # Self is always the nearest neighbor at distance 0.
    np.testing.assert_array_equal(idx[:, 0], np.arange(10))
    # Gram-trick distances: ‖x‖²+‖y‖²−2xy is only ~eps-accurate at 0.
    np.testing.assert_allclose(dists[:, 0], 0.0, atol=1e-6)


def test_exact_k_exceeds_shard_size(mesh8, rng):
    # Regression: k larger than the per-device shard (ceil(100/8)=13) must
    # work as long as k <= total rows.
    db = rng.normal(size=(100, 6))
    queries = rng.normal(size=(5, 6))
    model = NearestNeighbors(mesh=mesh8).setK(20).fit({"features": db})
    dists, idx = model.kneighbors(queries)
    ref_d, ref_i = _sklearn_knn(db, queries, 20)
    np.testing.assert_array_equal(idx, ref_i)
    np.testing.assert_allclose(dists, ref_d, atol=1e-8)


def test_ann_k_validation(rng, mesh8):
    db = rng.normal(size=(160, 8))
    ann = (
        ApproximateNearestNeighbors(mesh=mesh8)
        .setK(5)
        .setNlist(16)
        .setNprobe(1)
        .fit({"features": db})
    )
    with pytest.raises(ValueError):
        ann.kneighbors(db[:3], k=0)
    with pytest.raises(ValueError):
        ann.kneighbors(db[:3], k=161)
    # Regression: candidate pool (nprobe*maxlen) too small for k must raise
    # with actionable advice, not crash in top_k.
    with pytest.raises(ValueError, match="nprobe"):
        ann.kneighbors(db[:3], k=100)


def test_exact_k_validation(db_and_queries, mesh8):
    db, queries = db_and_queries
    model = NearestNeighbors(mesh=mesh8).setK(5).fit({"features": db})
    with pytest.raises(ValueError):
        model.kneighbors(queries, k=0)
    with pytest.raises(ValueError):
        model.kneighbors(queries, k=len(db) + 1)


def test_exact_persistence(db_and_queries, mesh8, tmp_path):
    db, queries = db_and_queries
    model = NearestNeighbors(mesh=mesh8).setK(4).fit({"features": db})
    path = str(tmp_path / "nn")
    model.save(path)
    loaded = NearestNeighborsModel.load(path)
    a = model.kneighbors(queries)
    b = loaded.kneighbors(queries)
    np.testing.assert_array_equal(a[1], b[1])


def test_ivf_flat_recall(rng, mesh8):
    # Clustered data (IVF's favorable case): recall@10 should be high.
    centers = rng.normal(size=(16, 24)) * 8
    db = np.concatenate([c + rng.normal(size=(120, 24)) for c in centers])
    queries = np.concatenate([c + rng.normal(size=(3, 24)) for c in centers])
    k = 10
    ann = (
        ApproximateNearestNeighbors(mesh=mesh8)
        .setK(k)
        .setNlist(16)
        .setNprobe(4)
        .fit({"features": db})
    )
    dists, idx = ann.kneighbors(queries)
    ref_d, ref_i = _sklearn_knn(db, queries, k)
    recall = np.mean(
        [len(set(idx[i]) & set(ref_i[i])) / k for i in range(len(queries))]
    )
    assert recall > 0.9, f"IVF recall@{k} too low: {recall}"
    # Distances for true positives must agree.
    assert np.all(np.isfinite(dists))


def test_ivf_large_k_exceeds_block_width(rng):
    # k larger than one scan block's candidate pool (LIST_BLOCK * maxlen):
    # the per-block top-k must clamp to the block width and recover full k
    # in the cross-block merge, not crash. A hand-built index pins maxlen=2
    # so the clamp branch (blk_k = 64 < k = 100) is guaranteed to trigger —
    # a fitted quantizer can't promise that.
    from spark_rapids_ml_tpu.models.knn import IVFFlatIndex, _ivf_query_fn

    db = rng.normal(size=(256, 8)).astype(np.float32)
    queries = rng.normal(size=(5, 8)).astype(np.float32)
    k, nlist, maxlen = 100, 128, 2
    lists = db.reshape(nlist, maxlen, 8)
    list_ids = np.arange(256, dtype=np.int64).reshape(nlist, maxlen)
    index = IVFFlatIndex(
        centroids=lists.mean(axis=1),
        lists=lists,
        list_ids=list_ids,
        list_mask=np.ones((nlist, maxlen), np.float32),
    )
    query = _ivf_query_fn(k, nlist, "float32", "float32")  # probe all lists
    dists, idx = query(
        jnp.asarray(index.centroids),
        jnp.asarray(index.lists),
        jnp.asarray(index.list_ids),
        jnp.asarray(index.list_mask),
        jnp.asarray(queries),
    )
    _, ref_i = _sklearn_knn(db, queries, k)
    np.testing.assert_array_equal(np.sort(idx, axis=1), np.sort(ref_i, axis=1))


def test_ivf_nprobe_all_is_exact(rng, mesh8):
    db = rng.normal(size=(200, 8))
    queries = rng.normal(size=(10, 8))
    k = 5
    ann = (
        ApproximateNearestNeighbors(mesh=mesh8)
        .setK(k)
        .setNlist(8)
        .setNprobe(8)  # probe everything -> exact
        .fit({"features": db})
    )
    _, idx = ann.kneighbors(queries)
    _, ref_i = _sklearn_knn(db, queries, k)
    np.testing.assert_array_equal(np.sort(idx, axis=1), np.sort(ref_i, axis=1))
